"""Address book for peer discovery (reference: p2p/addrbook.go).

btcd-style bucketed book: addresses live in "new" buckets (heard about,
never connected) or "old" buckets (connected successfully). Bucket
placement is keyed by a salted hash of (address group, source group) so an
attacker feeding addresses can't fill every bucket. pick_address biases
between new/old; mark_good promotes, mark_attempt counts failures.
Persisted as JSON with periodic saves (addrbook.go:160-182).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import threading
import time

from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.netaddress import NetAddress

NEW_BUCKET_COUNT = 256
OLD_BUCKET_COUNT = 64
BUCKET_SIZE = 64
NEW_BUCKETS_PER_ADDRESS = 4
OLD_BUCKETS_PER_GROUP = 4  # informational; enforcement is per-address here
DEFAULT_SAVE_INTERVAL = 120.0
# is_bad() thresholds (addrbook.go isBad/expireNew criteria)
MAX_FAILURES = 3  # never-succeeded attempts before an address is bad
STALE_AFTER = 30 * 24 * 3600.0  # not heard from in 30 days
RECENT_ATTEMPT = 60.0  # just-tried addresses aren't judged yet
NEED_ADDRESS_THRESHOLD = 1000  # below this the book wants more (PEX asks)


class KnownAddress:
    def __init__(self, addr: NetAddress, src: NetAddress):
        self.addr = addr
        self.src = src
        self.attempts = 0
        self.added = time.time()
        self.last_attempt = 0.0
        self.last_success = 0.0
        self.bucket_type = "new"
        self.buckets: list[int] = []

    def is_old(self) -> bool:
        return self.bucket_type == "old"

    def is_bad(self, now: float | None = None) -> bool:
        """Eviction/skip criteria (addrbook.go isBad): an address is bad if
        it keeps failing without ever having worked, or nothing has been
        heard from it in STALE_AFTER. Old (proven) addresses and ones tried
        within the last minute are never judged bad."""
        if self.is_old():
            return False
        now = time.time() if now is None else now
        if self.last_attempt and now - self.last_attempt < RECENT_ATTEMPT:
            return False
        if self.attempts >= MAX_FAILURES and not self.last_success:
            return True
        last_seen = max(self.added, self.last_attempt, self.last_success)
        return now - last_seen > STALE_AFTER

    def to_json(self) -> dict:
        return {
            "addr": str(self.addr),
            "src": str(self.src),
            "attempts": self.attempts,
            "added": self.added,
            "last_attempt": self.last_attempt,
            "last_success": self.last_success,
            "bucket_type": self.bucket_type,
        }

    @classmethod
    def from_json(cls, o: dict) -> "KnownAddress":
        ka = cls(NetAddress.from_string(o["addr"]), NetAddress.from_string(o["src"]))
        ka.attempts = o.get("attempts", 0)
        ka.added = o.get("added", ka.added)
        ka.last_attempt = o.get("last_attempt", 0.0)
        ka.last_success = o.get("last_success", 0.0)
        ka.bucket_type = o.get("bucket_type", "new")
        return ka


def _group(addr: NetAddress) -> str:
    """/16 group for IPv4, string ip otherwise (addrbook.go groupKey)."""
    parts = addr.ip.split(".")
    if len(parts) == 4:
        return ".".join(parts[:2])
    return addr.ip


class AddrBook(BaseService):
    def __init__(self, file_path: str = "", routability_strict: bool = True):
        super().__init__(name="p2p.addrbook")
        self.file_path = file_path
        self.routability_strict = routability_strict
        self.key = os.urandom(24).hex()  # bucket-hash salt
        self._mtx = threading.Lock()
        self._addrs: dict[str, KnownAddress] = {}
        self._new: list[dict[str, KnownAddress]] = [
            {} for _ in range(NEW_BUCKET_COUNT)
        ]
        self._old: list[dict[str, KnownAddress]] = [
            {} for _ in range(OLD_BUCKET_COUNT)
        ]
        self._rng = random.Random()
        self.save_interval = DEFAULT_SAVE_INTERVAL
        # churn accounting (round 22, scrape-visible as p2p_addrbook_*):
        # evictions = entries expired out of full new buckets (the
        # group-domination containment actually firing), bad_dropped =
        # addresses removed via mark_bad (flooders, provably-theirs only)
        self.evictions = 0
        self.bad_dropped = 0
        if file_path and os.path.exists(file_path):
            self._load(file_path)

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        t = threading.Thread(target=self._save_routine, daemon=True, name="addrbook.save")
        t.start()

    def on_stop(self) -> None:
        self.save()

    def _save_routine(self) -> None:
        while not self.quit_event.wait(self.save_interval):
            self.save()

    # -- hashing -----------------------------------------------------------

    def _bucket_index(self, addr: NetAddress, src: NetAddress, which: str, n: int) -> int:
        h = hashlib.sha256(
            f"{self.key}:{which}:{_group(addr)}:{_group(src)}:{n}".encode()
        ).digest()
        count = NEW_BUCKET_COUNT if which == "new" else OLD_BUCKET_COUNT
        return int.from_bytes(h[:8], "big") % count

    # -- mutation ----------------------------------------------------------

    def _routable_ok(self, addr: NetAddress) -> bool:
        if not addr.valid():
            return False
        return addr.routable() or not self.routability_strict

    def add_address(self, addr: NetAddress, src: NetAddress) -> bool:
        with self._mtx:
            return self._add(addr, src)

    def _add(self, addr: NetAddress, src: NetAddress) -> bool:
        if not self._routable_ok(addr):
            return False
        key = str(addr)
        ka = self._addrs.get(key)
        if ka is not None:
            if ka.is_old():
                return False
            if len(ka.buckets) >= NEW_BUCKETS_PER_ADDRESS:
                return False
            # probabilistically avoid piling one address into many buckets
            if self._rng.random() > 1.0 / (2 ** len(ka.buckets)):
                return False
        else:
            ka = KnownAddress(addr, src)
            self._addrs[key] = ka
        for n in range(NEW_BUCKETS_PER_ADDRESS):
            idx = self._bucket_index(addr, src, "new", n)
            if idx in ka.buckets:
                continue
            bucket = self._new[idx]
            if len(bucket) >= BUCKET_SIZE:
                self._expire_one(bucket)
            bucket[key] = ka
            ka.buckets.append(idx)
            return True
        return False

    def _expire_one(self, bucket: dict[str, KnownAddress]) -> None:
        """Evict from a full new bucket: a bad entry if any (addrbook.go
        expireNew), else the stalest."""
        now = time.time()
        victim_key = next(
            (k for k, ka in bucket.items() if ka.is_bad(now)), None
        ) or min(
            bucket, key=lambda k: (bucket[k].last_success, -bucket[k].attempts)
        )
        victim = bucket.pop(victim_key)
        victim.buckets = [b for b in victim.buckets if bucket is not self._new[b]]
        if not victim.buckets and not victim.is_old():
            self._addrs.pop(victim_key, None)
        self.evictions += 1

    def remove_address(self, addr: NetAddress) -> None:
        with self._mtx:
            key = str(addr)
            ka = self._addrs.pop(key, None)
            if ka is None:
                return
            for buckets in (self._new, self._old):
                for b in buckets:
                    b.pop(key, None)

    def mark_attempt(self, addr: NetAddress) -> None:
        with self._mtx:
            ka = self._addrs.get(str(addr))
            if ka:
                ka.attempts += 1
                ka.last_attempt = time.time()

    def mark_bad(self, addr: NetAddress) -> None:
        """Drop a misbehaving peer's address (addrbook.go MarkBad — which
        the reference also implements as removal)."""
        if str(addr) in self._addrs:
            self.bad_dropped += 1
        self.remove_address(addr)

    def mark_good(self, addr: NetAddress) -> None:
        """Promote new -> old on successful connection (addrbook.go:393)."""
        with self._mtx:
            key = str(addr)
            ka = self._addrs.get(key)
            if ka is None:
                if not self._add(addr, addr):
                    return
                ka = self._addrs.get(key)
                if ka is None:
                    return
            ka.attempts = 0
            ka.last_success = time.time()
            if ka.is_old():
                return
            for idx in ka.buckets:
                self._new[idx].pop(key, None)
            ka.buckets = []
            ka.bucket_type = "old"
            idx = self._bucket_index(ka.addr, ka.src, "old", 0)
            bucket = self._old[idx]
            if len(bucket) >= BUCKET_SIZE:
                # demote the stalest old entry back to new
                demote_key = min(bucket, key=lambda k: bucket[k].last_success)
                demoted = bucket.pop(demote_key)
                demoted.bucket_type = "new"
                demoted.buckets = []
                self._addrs[demote_key] = demoted
                self._add(demoted.addr, demoted.src)
            bucket[key] = ka
            ka.buckets = [idx]

    # -- queries -----------------------------------------------------------

    def size(self) -> int:
        with self._mtx:
            return len(self._addrs)

    def stats(self) -> dict:
        """Scrape-surface shape of the book (node/telemetry.py exports
        these as p2p_addrbook_*): size split new/old, churn counters,
        and max_group — how many entries the single most-populated
        address group holds. Bucket hashing caps any one group at
        NEW_BUCKETS_PER_ADDRESS buckets, so a subnet flooding ~500
        addresses can never own more than 4*BUCKET_SIZE slots; this
        gauge is the operator's read on that containment."""
        with self._mtx:
            old = sum(1 for ka in self._addrs.values() if ka.is_old())
            groups: dict[str, int] = {}
            for ka in self._addrs.values():
                g = _group(ka.addr)
                groups[g] = groups.get(g, 0) + 1
            return {
                "size": len(self._addrs),
                "new": len(self._addrs) - old,
                "old": old,
                "max_group": max(groups.values()) if groups else 0,
                "evictions": self.evictions,
                "bad_dropped": self.bad_dropped,
            }

    def need_more_addrs(self) -> bool:
        """Should PEX keep soliciting addresses? (addrbook.go
        NeedMoreAddrs: size < 1000)."""
        return self.size() < NEED_ADDRESS_THRESHOLD

    def our_addresses(self) -> set[str]:
        return getattr(self, "_ours", set())

    def add_our_address(self, addr: NetAddress) -> None:
        self._ours = self.our_addresses() | {str(addr)}

    def pick_address(self, new_bias_pct: int = 30) -> NetAddress | None:
        """Random pick, biased between old/new (addrbook.go PickAddress)."""
        with self._mtx:
            if not self._addrs:
                return None
            now = time.time()
            olds = [ka for ka in self._addrs.values() if ka.is_old()]
            news_all = [ka for ka in self._addrs.values() if not ka.is_old()]
            # prefer not-bad new addresses, but never strand the node: if
            # everything new looks bad (e.g. after an outage burned 3
            # attempts on every address) fall back to retrying them — the
            # reference uses isBad only for bucket eviction for the same
            # reason (addrbook.go expireNew vs PickAddress)
            news = [ka for ka in news_all if not ka.is_bad(now)] or news_all
            pool = news if (self._rng.random() * 100 < new_bias_pct or not olds) else olds
            if not pool:
                pool = olds or news
            return self._rng.choice(pool).addr if pool else None

    def get_selection(self, max_count: int = 250) -> list[NetAddress]:
        """Random 23% (<=max_count) of known addrs, for PEX responses."""
        with self._mtx:
            addrs = [ka.addr for ka in self._addrs.values()]
        self._rng.shuffle(addrs)
        want = min(max_count, max(len(addrs) * 23 // 100, min(len(addrs), 8)))
        return addrs[:want]

    # -- persistence -------------------------------------------------------

    def save(self) -> None:
        if not self.file_path:
            return
        with self._mtx:
            data = {
                "key": self.key,
                "addrs": [ka.to_json() for ka in self._addrs.values()],
            }
        tmp = self.file_path + ".tmp"
        os.makedirs(os.path.dirname(self.file_path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self.file_path)

    def _load(self, path: str) -> None:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            return
        self.key = data.get("key", self.key)
        for o in data.get("addrs", []):
            try:
                ka = KnownAddress.from_json(o)
            except (KeyError, ValueError):
                continue
            if ka.is_old():
                idx = self._bucket_index(ka.addr, ka.src, "old", 0)
                self._old[idx][str(ka.addr)] = ka
                ka.buckets = [idx]
                self._addrs[str(ka.addr)] = ka
            else:
                # new entries re-enter through the REAL add path so the
                # bucket-capacity invariants hold on load too: a saved
                # book dominated by one subnet (or a crafted file) gets
                # the same group containment a live flood would —
                # overflow evicts inside the group's few buckets instead
                # of accumulating bucket-less forever-unevictable
                # entries in _addrs
                if not self._add(ka.addr, ka.src):
                    continue
                got = self._addrs.get(str(ka.addr))
                if got is not None:
                    got.attempts = ka.attempts
                    got.added = ka.added
                    got.last_attempt = ka.last_attempt
                    got.last_success = ka.last_success
