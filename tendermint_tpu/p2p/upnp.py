"""UPnP IGD client: NAT discovery, external-IP lookup, port mapping
(reference: p2p/upnp/upnp.go:35-380, probe.go).

Pure stdlib: SSDP discovery is an M-SEARCH datagram to the well-known
multicast group; the gateway answers with the LOCATION of its device
description, which names the WAN(IP|PPP)Connection control URL; mapping
calls are small SOAP envelopes POSTed there. Timeouts are short and
every failure degrades to "no NAT" — a node behind no IGD must start
instantly (node wiring gates this on p2p.skip_upnp, like the
reference's listener, p2p/listener.go:51-74).
"""

from __future__ import annotations

import socket
import urllib.request
import xml.etree.ElementTree as ET
from dataclasses import dataclass

SSDP_ADDR = ("239.255.255.250", 1900)
_SEARCH = (
    "M-SEARCH * HTTP/1.1\r\n"
    f"HOST: {SSDP_ADDR[0]}:{SSDP_ADDR[1]}\r\n"
    'MAN: "ssdp:discover"\r\n'
    "MX: 2\r\n"
    "ST: urn:schemas-upnp-org:device:InternetGatewayDevice:1\r\n"
    "\r\n"
)
_WAN_SERVICES = (
    "urn:schemas-upnp-org:service:WANIPConnection:1",
    "urn:schemas-upnp-org:service:WANPPPConnection:1",
)


class UPnPError(Exception):
    pass


@dataclass
class Capabilities:
    """probe_upnp's answer (ref probe.go UPNPCapabilities)."""

    port_mapping: bool = False
    hairpin: bool = False


class NAT:
    """One discovered IGD: a control URL + the service type to talk to."""

    def __init__(self, control_url: str, service_type: str, our_ip: str):
        self.control_url = control_url
        self.service_type = service_type
        self.our_ip = our_ip

    # -- SOAP plumbing -----------------------------------------------------

    def _soap(self, action: str, args: dict[str, str]) -> ET.Element:
        body_args = "".join(f"<{k}>{v}</{k}>" for k, v in args.items())
        envelope = (
            '<?xml version="1.0"?>'
            '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/" '
            's:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
            f'<s:Body><u:{action} xmlns:u="{self.service_type}">{body_args}'
            f"</u:{action}></s:Body></s:Envelope>"
        ).encode()
        req = urllib.request.Request(
            self.control_url,
            data=envelope,
            headers={
                "Content-Type": 'text/xml; charset="utf-8"',
                "SOAPAction": f'"{self.service_type}#{action}"',
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=3) as resp:
                return ET.fromstring(resp.read())
        except Exception as exc:  # noqa: BLE001 — one error surface
            raise UPnPError(f"SOAP {action} failed: {exc}") from exc

    @staticmethod
    def _find_text(root: ET.Element, tag: str) -> str:
        for el in root.iter():
            if el.tag.endswith(tag):
                return el.text or ""
        raise UPnPError(f"no {tag} in SOAP response")

    # -- the NAT interface (ref upnp.go NAT) --------------------------------

    def get_external_address(self) -> str:
        root = self._soap("GetExternalIPAddress", {})
        return self._find_text(root, "NewExternalIPAddress")

    def add_port_mapping(
        self,
        protocol: str,
        external_port: int,
        internal_port: int,
        description: str,
        lease_seconds: int = 0,
    ) -> int:
        self._soap(
            "AddPortMapping",
            {
                "NewRemoteHost": "",
                "NewExternalPort": str(external_port),
                "NewProtocol": protocol.upper(),
                "NewInternalPort": str(internal_port),
                "NewInternalClient": self.our_ip,
                "NewEnabled": "1",
                "NewPortMappingDescription": description,
                "NewLeaseDuration": str(lease_seconds),
            },
        )
        return external_port

    def delete_port_mapping(self, protocol: str, external_port: int) -> None:
        self._soap(
            "DeletePortMapping",
            {
                "NewRemoteHost": "",
                "NewExternalPort": str(external_port),
                "NewProtocol": protocol.upper(),
            },
        )


def _parse_ssdp_location(datagram: bytes) -> str | None:
    for line in datagram.decode(errors="replace").split("\r\n"):
        k, _, v = line.partition(":")
        if k.strip().lower() == "location":
            return v.strip()
    return None


def _control_url_from_description(location: str) -> tuple[str, str]:
    """(control_url, service_type) from the device-description XML."""
    with urllib.request.urlopen(location, timeout=3) as resp:
        root = ET.fromstring(resp.read())
    base = location.rsplit("/", 1)[0]
    services: dict[str, str] = {}
    for svc in root.iter():
        if not svc.tag.endswith("service"):
            continue
        st = ctl = ""
        for child in svc:
            if child.tag.endswith("serviceType"):
                st = (child.text or "").strip()
            elif child.tag.endswith("controlURL"):
                ctl = (child.text or "").strip()
        if st and ctl:
            services[st] = ctl
    for want in _WAN_SERVICES:
        if want in services:
            ctl = services[want]
            url = ctl if ctl.startswith("http") else base + "/" + ctl.lstrip("/")
            return url, want
    raise UPnPError("no WAN connection service in device description")


def discover(timeout: float = 3.0, ssdp_addr=SSDP_ADDR) -> NAT:
    """SSDP search for an IGD (ref upnp.go Discover)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        sock.settimeout(timeout)
        sock.sendto(_SEARCH.encode(), ssdp_addr)
        datagram, _ = sock.recvfrom(4096)
        our_ip = sock.getsockname()[0]
    except OSError as exc:
        raise UPnPError(f"SSDP discovery failed: {exc}") from exc
    finally:
        sock.close()
    location = _parse_ssdp_location(datagram)
    if not location:
        raise UPnPError("SSDP response without LOCATION")
    if our_ip in ("0.0.0.0", ""):
        our_ip = _local_ip(location)
    try:
        control_url, service_type = _control_url_from_description(location)
    except UPnPError:
        raise
    except Exception as exc:  # noqa: BLE001 — unreachable/garbage device
        # description must degrade to "no NAT", never crash node startup
        raise UPnPError(f"bad device description at {location}: {exc}") from exc
    return NAT(control_url, service_type, our_ip)


def _local_ip(reach_url: str) -> str:
    """The local interface address that routes toward the gateway."""
    from urllib.parse import urlparse

    host = urlparse(reach_url).hostname or "8.8.8.8"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((host, 9))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def probe(ext_port: int = 46656, int_port: int = 46656, timeout: float = 3.0) -> Capabilities:
    """Can this network do UPnP port mapping? (ref probe.go:87-112 minus
    the hairpin self-dial, which needs a live listener)."""
    caps = Capabilities()
    nat = discover(timeout=timeout)
    nat.get_external_address()
    nat.add_port_mapping("tcp", ext_port, int_port, "tendermint-tpu probe", 20 * 60)
    caps.port_mapping = True
    nat.delete_port_mapping("tcp", ext_port)
    return caps
