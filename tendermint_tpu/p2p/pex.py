"""Peer-exchange reactor on channel 0x00 (reference: p2p/pex_reactor.go).

Request/response gossip of known addresses; ensures a minimum number of
outbound peers every ensure_peers_period; per-peer inbound message rate
limit (pex_reactor.go:14-26: 1000 msgs / 10min window equivalent).
"""

from __future__ import annotations

import json
import random
import threading
import time

from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.addrbook import AddrBook
from tendermint_tpu.p2p.conn import ChannelDescriptor
from tendermint_tpu.p2p.netaddress import NetAddress
from tendermint_tpu.p2p.switch import Reactor

PEX_CHANNEL = 0x00
DEFAULT_ENSURE_PEERS_PERIOD = 30.0
MIN_NUM_OUTBOUND_PEERS = 10
MAX_MSG_COUNT_BY_PEER = 1000
MSG_COUNT_WINDOW = 600.0


def _encode(msg: dict) -> bytes:
    return json.dumps(msg, sort_keys=True).encode()


class PEXReactor(Reactor, BaseService):
    def __init__(self, book: AddrBook, ensure_peers_period: float = DEFAULT_ENSURE_PEERS_PERIOD):
        BaseService.__init__(self, name="p2p.pex")
        self.book = book
        self.ensure_peers_period = ensure_peers_period
        self.min_outbound = MIN_NUM_OUTBOUND_PEERS
        self._msg_counts: dict[str, list[float]] = {}
        self._mtx = threading.Lock()

    # -- Reactor interface -------------------------------------------------

    def get_channels(self) -> list[ChannelDescriptor]:
        # a pex_addrs message carries <= 250 "host:port" strings — 64 KiB
        # bounds it with an order of magnitude to spare (round-18
        # recv-ceiling right-sizing; the default was the 21 MiB block cap)
        return [ChannelDescriptor(id=PEX_CHANNEL, priority=1,
                                  send_queue_capacity=10,
                                  recv_message_capacity=1 << 16)]

    def add_peer(self, peer) -> None:
        info = peer.node_info
        if info and info.listen_addr:
            try:
                addr = NetAddress.from_string(info.listen_addr)
                if peer.outbound:
                    # we dialed them: address verified good
                    self.book.mark_good(addr)
                    if self.book.need_more_addrs():
                        self._request_addrs(peer)
                else:
                    self.book.add_address(addr, addr)
                    # learn more from inbound peers
                    self._request_addrs(peer)
            except ValueError:
                pass

    def remove_peer(self, peer, reason) -> None:
        with self._mtx:
            self._msg_counts.pop(peer.id(), None)

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        if self._flood_check(peer):
            # evict the flooder's address — but only one provably theirs:
            # listen_addr is self-reported in the handshake, so anyone
            # could otherwise claim a victim's address and have us evict
            # a proven-good entry. Require the claimed IP to match the
            # socket's actual remote IP.
            info = peer.node_info
            if info and info.listen_addr:
                try:
                    claimed = NetAddress.from_string(info.listen_addr)
                    sock_ip = str(peer.stream.remote_addr()).rsplit(":", 1)[0]
                    if claimed.ip == sock_ip:
                        self.book.mark_bad(claimed)
                except (ValueError, AttributeError):
                    pass
            self.switch.stop_peer_for_error(peer, "pex flood")
            return
        try:
            msg = json.loads(msg_bytes.decode())
            if not isinstance(msg, dict):
                raise ValueError("pex message not an object")
        except (ValueError, UnicodeDecodeError):
            self.switch.stop_peer_for_error(peer, "bad pex message")
            return
        if msg.get("type") == "pex_request":
            addrs = [str(a) for a in self.book.get_selection()]
            peer.try_send(PEX_CHANNEL, _encode({"type": "pex_addrs", "addrs": addrs}))
        elif msg.get("type") == "pex_addrs":
            src_str = peer.node_info.listen_addr if peer.node_info else ""
            try:
                src = NetAddress.from_string(src_str) if src_str else None
            except ValueError:
                src = None
            sent = msg.get("addrs", [])
            if not isinstance(sent, list):
                self.switch.stop_peer_for_error(peer, "bad pex addrs")
                return
            for s in sent[:250]:
                if not isinstance(s, str) or len(s) > 64:
                    continue  # garbage entry; the cap bounds parsing work
                try:
                    addr = NetAddress.from_string(s)
                except ValueError:
                    continue
                self.book.add_address(addr, src or addr)

    def _flood_check(self, peer) -> bool:
        now = time.monotonic()
        with self._mtx:
            times = self._msg_counts.setdefault(peer.id(), [])
            times.append(now)
            while times and now - times[0] > MSG_COUNT_WINDOW:
                times.pop(0)
            return len(times) > MAX_MSG_COUNT_BY_PEER

    def _request_addrs(self, peer) -> None:
        peer.try_send(PEX_CHANNEL, _encode({"type": "pex_request"}))

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        self.book.start()
        threading.Thread(
            target=self._ensure_peers_routine, daemon=True, name="pex.ensure"
        ).start()

    def on_stop(self) -> None:
        self.book.stop()

    def _ensure_peers_routine(self) -> None:
        # stagger startup so a fleet doesn't dial in lockstep
        time.sleep(random.random() * self.ensure_peers_period / 10)
        self._ensure_peers()
        while not self.quit_event.wait(self.ensure_peers_period):
            self._ensure_peers()

    def _ensure_peers(self) -> None:
        if not hasattr(self, "switch") or not self.switch.is_running():
            return
        outbound, _inbound, dialing = self.switch.num_peers()
        need = self.min_outbound - (outbound + dialing)
        if need <= 0:
            return
        connected = {
            p.node_info.listen_addr
            for p in self.switch.peers.list()
            if p.node_info
        }
        tried: set[str] = set()
        for _ in range(need * 3):
            addr = self.book.pick_address()
            if addr is None:
                break
            key = str(addr)
            if key in tried or key in connected or key in self.book.our_addresses():
                continue
            tried.add(key)
            self.book.mark_attempt(addr)
            threading.Thread(
                target=self._dial, args=(addr,), daemon=True, name="pex.dial"
            ).start()
            need -= 1
            if need <= 0:
                break
        # still starving: ask a random current peer for more addresses
        if need > 0:
            peers = self.switch.peers.list()
            if peers:
                self._request_addrs(random.choice(peers))

    def _dial(self, addr: NetAddress) -> None:
        try:
            self.switch.dial_peer_with_address(addr)
            self.book.mark_good(addr)
        except Exception as exc:  # noqa: BLE001
            self.logger.info("pex dial %s failed: %s", addr, exc)
