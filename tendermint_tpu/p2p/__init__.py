"""Host-side distributed communication backend.

The reference's p2p stack (p2p/switch.go, connection.go,
secret_connection.go) is a custom TCP mesh: a Switch of Reactors over
multiplexed, prioritized, encrypted connections with PEX discovery. The
consensus overlay stays host-side in the TPU framework (SURVEY.md §2.3) —
gossip is irregular, small-message, latency-bound work; only the crypto
batch plane rides the TPU. This package is therefore a clean-room,
threading-based Python implementation of the same capability surface, with
an in-memory pipe transport for deterministic in-process multi-node tests
(the net.Pipe() trick, p2p/switch.go:502-547).
"""

from tendermint_tpu.p2p.conn import ChannelDescriptor, MConnection, MConnConfig
from tendermint_tpu.p2p.netaddress import NetAddress
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.peer import Peer, PeerConfig
from tendermint_tpu.p2p.peer_set import PeerSet
from tendermint_tpu.p2p.switch import (
    Reactor,
    Switch,
    connect2_switches,
    make_connected_switches,
)

__all__ = [
    "ChannelDescriptor",
    "MConnection",
    "MConnConfig",
    "NetAddress",
    "NodeInfo",
    "Peer",
    "PeerConfig",
    "PeerSet",
    "Reactor",
    "Switch",
    "connect2_switches",
    "make_connected_switches",
]
