"""Authenticated-encryption transport (reference: p2p/secret_connection.go,
spec docs/specification/secure-p2p.rst).

Same STS-like shape as the reference, modern primitives (this framework
defines its own wire protocol, so no nacl-secretbox compatibility):

1. exchange 32-byte ephemeral X25519 pubkeys in the clear;
2. shared = X25519(eph_priv, remote_eph_pub); per-direction keys via
   HKDF-SHA256 over the sorted ephemeral pubkeys (lo||hi transcript) —
   the lexicographically-lower side sends with key1, the higher with key2;
3. all further traffic is ChaCha20-Poly1305 frames with counter nonces
   (distinct per direction via the key split);
4. challenge = SHA256(lo_eph || hi_eph); both sides send
   (node_pubkey, ed25519_sig(challenge)) over the encrypted channel and
   verify — authenticating the node identity key (secret_connection.go:49-101).

Frames: [len:2 BE][ciphertext = plaintext+16B tag], plaintext <=1024B.
"""

from __future__ import annotations

import hashlib
import json
import struct
import threading

from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

from tendermint_tpu.crypto.keys import PrivKeyEd25519, PubKeyEd25519, SignatureEd25519

DATA_MAX_SIZE = 1024
_LEN = struct.Struct(">H")


def _hkdf(secret: bytes, info: bytes, length: int = 64) -> bytes:
    """HKDF-SHA256 (extract with zero salt + expand)."""
    prk = hashlib.sha256(b"\x00" * 32 + secret).digest()
    out, t, i = b"", b"", 1
    while len(out) < length:
        t = hashlib.sha256(prk + t + info + bytes([i])).digest()
        out += t
        i += 1
    return out[:length]


class SecretConnection:
    """Wraps a stream; satisfies the stream interface itself."""

    def __init__(self, stream, priv_key: PrivKeyEd25519):
        self.stream = stream
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes_raw()

        # 1. ephemeral exchange (concurrent-safe: write then read)
        stream.write(eph_pub)
        remote_eph = self._read_exact(32)

        shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(remote_eph))
        lo, hi = sorted((eph_pub, remote_eph))
        keys = _hkdf(shared, b"TENDERMINT_TPU_SECRET_CONNECTION" + lo + hi)
        if eph_pub == lo:
            send_key, recv_key = keys[:32], keys[32:]
        else:
            send_key, recv_key = keys[32:], keys[:32]
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)
        self._send_nonce = 0
        self._recv_nonce = 0
        self._wmtx = threading.Lock()
        self._rmtx = threading.Lock()
        self._recv_buf = b""

        # 4. authenticate node keys over the encrypted channel
        challenge = hashlib.sha256(lo + hi).digest()
        auth = json.dumps(
            {
                "pub_key": priv_key.pub_key().to_json(),
                "sig": priv_key.sign(challenge).to_json(),
            }
        ).encode()
        self.write(auth)
        remote_auth = json.loads(self._read_msg().decode())
        remote_pub = PubKeyEd25519.from_json(remote_auth["pub_key"])
        remote_sig = SignatureEd25519.from_json(remote_auth["sig"])
        if not remote_pub.verify_bytes(challenge, remote_sig):
            stream.close()
            raise ConnectionError("secret connection: challenge signature invalid")
        self._remote_pubkey = remote_pub

    def remote_pubkey(self) -> PubKeyEd25519:
        return self._remote_pubkey

    # -- framing -----------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self.stream.read(n - len(buf))
            if not chunk:
                raise ConnectionError("stream closed during secret handshake/read")
            buf += chunk
        return bytes(buf)

    def _nonce12(self, counter: int) -> bytes:
        return counter.to_bytes(12, "big")

    def _write_frame(self, chunk: bytes) -> None:
        ct = self._send_aead.encrypt(self._nonce12(self._send_nonce), chunk, None)
        self._send_nonce += 1
        self.stream.write(_LEN.pack(len(ct)) + ct)

    def _read_msg(self) -> bytes:
        """One frame's plaintext."""
        (clen,) = _LEN.unpack(self._read_exact(_LEN.size))
        ct = self._read_exact(clen)
        try:
            pt = self._recv_aead.decrypt(self._nonce12(self._recv_nonce), ct, None)
        except Exception as exc:
            # tampering / desync is unrecoverable: poison the connection
            self.stream.close()
            raise ConnectionError("secret connection: frame authentication failed") from exc
        self._recv_nonce += 1
        return pt

    # -- stream interface --------------------------------------------------

    def write(self, data: bytes) -> None:
        with self._wmtx:
            for off in range(0, len(data), DATA_MAX_SIZE):
                self._write_frame(data[off : off + DATA_MAX_SIZE])
            if not data:
                self._write_frame(b"")

    def read(self, n: int) -> bytes:
        with self._rmtx:
            if not self._recv_buf:
                try:
                    self._recv_buf = self._read_msg()
                except ConnectionError:
                    return b""
            out, self._recv_buf = self._recv_buf[:n], self._recv_buf[n:]
            return out

    def close(self) -> None:
        self.stream.close()

    def remote_addr(self) -> str:
        inner = getattr(self.stream, "remote_addr", None)
        return inner() if inner else "secret"
