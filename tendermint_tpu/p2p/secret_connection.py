"""Authenticated-encryption transport (reference: p2p/secret_connection.go,
spec docs/secure-p2p.md + docs/specification/secure-p2p.rst).

Same STS-like shape as the reference, modern primitives (this framework
defines its own wire protocol, so no nacl-secretbox compatibility):

1. exchange 32-byte ephemeral X25519 pubkeys in the clear;
2. shared = X25519(eph_priv, remote_eph_pub); per-direction keys via
   HKDF-SHA256 over the sorted ephemeral pubkeys (lo||hi transcript) —
   the lexicographically-lower side sends with key1, the higher with key2;
3. all further traffic is ChaCha20-Poly1305 frames with counter nonces
   (distinct per direction via the key split);
4. challenge = SHA256(lo_eph || hi_eph); both sides send
   (node_pubkey, ed25519_sig(challenge)) over the encrypted channel and
   verify — authenticating the node identity key (secret_connection.go:49-101).

Frames: [len:2 BE][ciphertext = plaintext+16B tag], plaintext <=1024B.

The primitives are IN-REPO (crypto/x25519.py, crypto/chacha20poly1305.py
— pure-Python pinned to the RFC 7748/8439 vectors, with `cryptography`
and ctypes-libcrypto fast paths selected via TENDERMINT_SECRETCONN_BACKEND),
so the encrypted transport works on any host. The wire bytes are
backend-independent: both ends may run different backends.

Failure semantics (round 12):
- an AEAD authentication failure is TAMPERING, never EOF: the connection
  poisons itself, the stream closes, and every current/later read raises
  SecretConnectionError — a bit-flipped frame surfaces as a loud peer
  error (switch: "stopping peer for error"), not a graceful hangup;
- the handshake is deadline-bounded (TENDERMINT_SECRETCONN_HANDSHAKE_S,
  default 20 s): a stalled or byte-dribbling peer cannot pin the
  handshake thread forever;
- both families count in p2p_secretconn_* telemetry (process-wide
  instruments, materialized by node/telemetry.py like the devd
  histograms).
"""

from __future__ import annotations

import hashlib
import json
import socket
import struct
import threading
import time

from tendermint_tpu.crypto.chacha20poly1305 import ChaCha20Poly1305, InvalidTag
from tendermint_tpu.crypto.keys import PrivKeyEd25519, PubKeyEd25519, SignatureEd25519
from tendermint_tpu.crypto.x25519 import X25519PrivateKey, X25519PublicKey
from tendermint_tpu.libs import telemetry
from tendermint_tpu.libs.envknob import env_number

DATA_MAX_SIZE = 1024
_LEN = struct.Struct(">H")

DEFAULT_HANDSHAKE_S = 20.0


class SecretConnectionError(ConnectionError):
    """Cryptographic failure on the link: tampered/reordered frame,
    bad challenge signature — never a routine peer hangup."""


class HandshakeTimeout(ConnectionError):
    """The key/auth exchange did not complete within the deadline."""


def _counters() -> dict:
    """p2p_secretconn_* counter families (create-or-get from the CURRENT
    default registry each call, so instruments survive test resets —
    node/telemetry.py materializes them so the scrape family set is
    stable from the first height)."""
    reg = telemetry.default_registry()
    return {
        "handshakes": reg.counter(
            "p2p_secretconn_handshakes_total",
            "completed SecretConnection handshakes",
        ),
        "handshake_failures": reg.counter(
            "p2p_secretconn_handshake_failures_total",
            "SecretConnection handshakes failed (bad peer bytes, EOF, "
            "invalid challenge signature)",
        ),
        "handshake_timeouts": reg.counter(
            "p2p_secretconn_handshake_timeouts_total",
            "SecretConnection handshakes abandoned at the deadline",
        ),
        "auth_failures": reg.counter(
            "p2p_secretconn_auth_failures_total",
            "AEAD frame authentication failures (tamper/reorder/desync)",
        ),
        "oversized_frames": reg.counter(
            "p2p_secretconn_oversized_frames_total",
            "frames refused for an illegal length claim before any "
            "payload was buffered (oversized-frame adversary)",
        ),
    }


def _hkdf(secret: bytes, info: bytes, length: int = 64) -> bytes:
    """HKDF-SHA256 (extract with zero salt + expand)."""
    prk = hashlib.sha256(b"\x00" * 32 + secret).digest()
    out, t, i = b"", b"", 1
    while len(out) < length:
        t = hashlib.sha256(prk + t + info + bytes([i])).digest()
        out += t
        i += 1
    return out[:length]


class SecretConnection:
    """Wraps a stream; satisfies the stream interface itself."""

    def __init__(self, stream, priv_key: PrivKeyEd25519,
                 handshake_timeout_s: float | None = None):
        self.stream = stream
        if handshake_timeout_s is None:
            handshake_timeout_s = env_number(
                "TENDERMINT_SECRETCONN_HANDSHAKE_S", DEFAULT_HANDSHAKE_S
            )
        self._deadline = (
            time.monotonic() + handshake_timeout_s
            if handshake_timeout_s and handshake_timeout_s > 0 else None
        )
        # the Switch arms its own admission timeout on the socket BEFORE
        # building the peer (add_peer_from_stream); remember it so the
        # deadline bookkeeping below restores it rather than clearing it
        # — wiping it would leave the NodeInfo half of admission
        # unbounded against a peer that stalls after the secret handshake
        sock = self._sock()
        self._prior_sock_timeout = None
        if sock is not None:
            try:
                self._prior_sock_timeout = sock.gettimeout()
            except OSError:
                pass
        self._poisoned: SecretConnectionError | None = None
        try:
            self._handshake(stream, priv_key)
        except HandshakeTimeout:
            _counters()["handshake_timeouts"].inc()
            _counters()["handshake_failures"].inc()
            raise
        except socket.timeout as exc:
            # a deadline-armed WRITE tripped (sendall past the budget)
            _counters()["handshake_timeouts"].inc()
            _counters()["handshake_failures"].inc()
            raise HandshakeTimeout(
                "secret connection: handshake timed out"
            ) from exc
        except Exception:
            _counters()["handshake_failures"].inc()
            raise
        else:
            _counters()["handshakes"].inc()
        finally:
            self._deadline = None
            self._restore_sock_timeout()

    def _handshake(self, stream, priv_key: PrivKeyEd25519) -> None:
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes_raw()
        self.backend = eph_priv.backend

        # 1. ephemeral exchange (concurrent-safe: write then read)
        self._bound_to_deadline()
        stream.write(eph_pub)
        remote_eph = self._read_exact(32)

        shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(remote_eph))
        lo, hi = sorted((eph_pub, remote_eph))
        keys = _hkdf(shared, b"TENDERMINT_TPU_SECRET_CONNECTION" + lo + hi)
        if eph_pub == lo:
            send_key, recv_key = keys[:32], keys[32:]
        else:
            send_key, recv_key = keys[32:], keys[:32]
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)
        self._send_nonce = 0
        self._recv_nonce = 0
        self._wmtx = threading.Lock()
        self._rmtx = threading.Lock()
        self._recv_buf = b""

        # 4. authenticate node keys over the encrypted channel
        challenge = hashlib.sha256(lo + hi).digest()
        auth = json.dumps(
            {
                "pub_key": priv_key.pub_key().to_json(),
                "sig": priv_key.sign(challenge).to_json(),
            }
        ).encode()
        self._bound_to_deadline()
        self.write(auth)
        remote_auth = json.loads(self._read_msg().decode())
        remote_pub = PubKeyEd25519.from_json(remote_auth["pub_key"])
        remote_sig = SignatureEd25519.from_json(remote_auth["sig"])
        if not remote_pub.verify_bytes(challenge, remote_sig):
            stream.close()
            raise SecretConnectionError(
                "secret connection: challenge signature invalid"
            )
        self._remote_pubkey = remote_pub

    def remote_pubkey(self) -> PubKeyEd25519:
        return self._remote_pubkey

    # -- handshake deadline -------------------------------------------------

    def _sock(self) -> socket.socket | None:
        return getattr(self.stream, "sock", None)

    def _bound_to_deadline(self) -> None:
        """Bound the next blocking socket op by the remaining handshake
        budget (streams without a socket — in-process pipes under test
        fabrics are socketpairs, so they have one — simply stay
        unbounded). A byte-dribbling peer is covered because the
        deadline is ABSOLUTE: every read re-arms with what's left."""
        if self._deadline is None:
            return
        remaining = self._deadline - time.monotonic()
        if remaining <= 0:
            raise HandshakeTimeout("secret connection: handshake timed out")
        sock = self._sock()
        if sock is not None:
            try:
                sock.settimeout(remaining)
            except OSError:
                pass

    def _restore_sock_timeout(self) -> None:
        # put back whatever was armed before our per-read deadlines: the
        # Switch's admission timeout must keep covering the NodeInfo
        # handshake that follows (it clears it itself after admission);
        # for a direct construction this restores None, so no stray
        # timeout leaks onto the data path
        sock = self._sock()
        if sock is not None:
            try:
                sock.settimeout(self._prior_sock_timeout)
            except OSError:
                pass

    # -- framing -----------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            if self._deadline is not None:
                self._bound_to_deadline()
            try:
                chunk = self.stream.read(n - len(buf))
            except socket.timeout as exc:
                raise HandshakeTimeout(
                    "secret connection: handshake timed out"
                ) from exc
            if not chunk:
                # a SocketStream swallows OSError (incl. timeouts) into
                # b"" — distinguish deadline expiry from a peer hangup
                if self._deadline is not None and \
                        time.monotonic() >= self._deadline:
                    raise HandshakeTimeout(
                        "secret connection: handshake timed out"
                    )
                raise ConnectionError("stream closed during secret handshake/read")
            buf += chunk
        return bytes(buf)

    def _nonce12(self, counter: int) -> bytes:
        return counter.to_bytes(12, "big")

    def _write_frame(self, chunk: bytes) -> None:
        ct = self._send_aead.encrypt(self._nonce12(self._send_nonce), chunk, None)
        self._send_nonce += 1
        self.stream.write(_LEN.pack(len(ct)) + ct)

    def _read_msg(self) -> bytes:
        """One frame's plaintext."""
        (clen,) = _LEN.unpack(self._read_exact(_LEN.size))
        if clen > DATA_MAX_SIZE + 16:
            # oversized-frame adversary (round 18): our writer never
            # exceeds plaintext DATA_MAX_SIZE + the 16-byte tag, so a
            # larger claim is protocol abuse — refuse BEFORE buffering
            # the claimed payload (the old path read up to 64 KiB of
            # attacker bytes per frame just to fail the AEAD tag)
            _counters()["oversized_frames"].inc()
            _counters()["auth_failures"].inc()
            err = SecretConnectionError(
                f"secret connection: oversized frame claim ({clen} B; "
                f"legal max {DATA_MAX_SIZE + 16})"
            )
            self._poisoned = err
            self.stream.close()
            raise err
        ct = self._read_exact(clen)
        try:
            pt = self._recv_aead.decrypt(self._nonce12(self._recv_nonce), ct, None)
        except InvalidTag as exc:
            # tampering / desync is unrecoverable: poison the connection
            _counters()["auth_failures"].inc()
            err = SecretConnectionError(
                "secret connection: frame authentication failed"
            )
            self._poisoned = err
            self.stream.close()
            raise err from exc
        self._recv_nonce += 1
        return pt

    # -- stream interface --------------------------------------------------

    def write(self, data: bytes) -> None:
        with self._wmtx:
            for off in range(0, len(data), DATA_MAX_SIZE):
                self._write_frame(data[off : off + DATA_MAX_SIZE])
            if not data:
                self._write_frame(b"")

    def read(self, n: int) -> bytes:
        """Up to n plaintext bytes; b"" on clean EOF (peer hangup).
        Tampering is NOT EOF: an authentication failure raises
        SecretConnectionError — here and on every subsequent read (the
        connection is poisoned) — so the mconn recv routine drops the
        peer for cause instead of reading a quiet close."""
        with self._rmtx:
            if self._poisoned is not None:
                raise self._poisoned
            if not self._recv_buf:
                try:
                    self._recv_buf = self._read_msg()
                except SecretConnectionError:
                    raise
                except ConnectionError:
                    return b""
            out, self._recv_buf = self._recv_buf[:n], self._recv_buf[n:]
            return out

    def close(self) -> None:
        self.stream.close()

    def remote_addr(self) -> str:
        inner = getattr(self.stream, "remote_addr", None)
        return inner() if inner else "secret"
