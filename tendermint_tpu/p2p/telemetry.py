"""Per-peer / per-channel p2p instrumentation (round 15).

Before this module, ``p2p_*`` exported three aggregate peer counts — and
both PR-13 vote-gossip liveness wedges had to be found by staring at
frozen height vectors, because no per-peer gossip counter existed to
alarm on. These are the labeled ``p2p_peer_*`` families that make the
gossip plane observable per link:

    p2p_peer_send_bytes_total{peer,channel}      frame bytes written
    p2p_peer_recv_bytes_total{peer,channel}      packet bytes read
    p2p_peer_send_msgs_total{peer,channel}       whole messages sent
    p2p_peer_recv_msgs_total{peer,channel}       whole messages received
    p2p_peer_send_failures_total{peer,channel}   full-queue send/try_send
                                                 rejections at the mconn
    p2p_peer_send_queue{peer,channel}            queue depth at last enqueue
    p2p_peer_send_queue_high_water{peer,channel} max depth seen
    p2p_peer_ping_rtt_seconds{peer}              ping->pong round trip
    p2p_peer_last_recv_age_seconds{peer}         seconds since any packet
                                                 (refreshed at collect by
                                                 node/telemetry.py)
    p2p_peer_vote_gossip_picks_total{peer}       votes picked for a peer
    p2p_peer_vote_gossip_sends_total{peer}       ... that actually sent
    p2p_peer_vote_gossip_send_failures_total{peer}  ... that did NOT —
        picks persistently > sends is the exact signal that would have
        caught the PR-13 pick-marks-before-send wedge
    p2p_peer_catchup_commits_total{peer}         catchup-commit tracking
                                                 arrays engaged for a
                                                 lagging peer
    p2p_peer_vote_duplicates_total{peer}         gossiped votes already
                                                 seen (round 17: the
                                                 2NxN redundancy before-
                                                 number for gossip dedup)

Label cardinality rides the registry's ``_other`` collapse
(libs/telemetry.py): peer churn past the per-family bound
(TENDERMINT_TELEMETRY_MAX_SERIES, or the per-family
TENDERMINT_TELEMETRY_MAX_SERIES_<FAMILY> override) folds into one
overflow series — totals survive, memory stays bounded, and this holds
for the labeled HISTOGRAM exactly like the counters (tests/test_telemetry.py
asserts it under 100-peer churn).

Registry scoping: families are created on the registry passed in —
node/telemetry.py passes the NODE registry, so two nodes in one test
process (the netchaos harness) keep separate per-peer counters and each
node's scrape shows only its own links. Callers without a node (unit
tests, bare switches) default to the process-wide registry.
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.libs import telemetry

_CACHE_ATTR = "_p2p_peer_family_cache"


class RttEwma:
    """Registry-scoped EWMA over every peer's ping RTT samples (round
    21): the one-number RTT summary the RTT-adaptive lazy-relay hold
    reads (consensus/reactor.adaptive_relay_delay). Not an instrument —
    the per-peer distribution already rides the ping_rtt histogram; this
    is the cheap cross-peer smoother the hot relay path polls."""

    ALPHA = 0.2

    __slots__ = ("_mtx", "_value", "_samples")

    def __init__(self):
        self._mtx = threading.Lock()
        self._value = 0.0
        self._samples = 0

    def observe(self, rtt_s: float) -> None:
        with self._mtx:
            self._samples += 1
            if self._samples == 1:
                self._value = rtt_s
            else:
                self._value += self.ALPHA * (rtt_s - self._value)

    def value(self) -> float | None:
        """The smoothed RTT in seconds; None before any sample (the
        relay hold then keeps its constant fallback)."""
        with self._mtx:
            return self._value if self._samples else None


def peer_metrics(reg: "telemetry.Registry | None" = None) -> dict:
    """Create-or-get the p2p_peer_* families on `reg` (default: the
    process-wide registry). The built dict is cached on the registry
    object so hot paths pay one attribute read, not N create-or-get
    lookups (a racing double-build is idempotent — create-or-get returns
    the same instruments)."""
    if reg is None:
        reg = telemetry.default_registry()
    cached = getattr(reg, _CACHE_ATTR, None)
    if cached is not None:
        return cached
    pc = ("peer", "channel")
    p = ("peer",)
    fams = {
        "send_bytes": reg.counter(
            "p2p_peer_send_bytes_total",
            "mconn frame bytes written, per peer and channel",
            labelnames=pc,
        ),
        "recv_bytes": reg.counter(
            "p2p_peer_recv_bytes_total",
            "mconn packet bytes read, per peer and channel",
            labelnames=pc,
        ),
        "send_msgs": reg.counter(
            "p2p_peer_send_msgs_total",
            "whole messages sent, per peer and channel",
            labelnames=pc,
        ),
        "recv_msgs": reg.counter(
            "p2p_peer_recv_msgs_total",
            "whole messages received, per peer and channel",
            labelnames=pc,
        ),
        "send_failures": reg.counter(
            "p2p_peer_send_failures_total",
            "sends rejected by a full channel queue, per peer and channel",
            labelnames=pc,
        ),
        "send_queue": reg.gauge(
            "p2p_peer_send_queue",
            "channel send-queue depth sampled at last enqueue",
            labelnames=pc,
        ),
        "send_queue_high_water": reg.gauge(
            "p2p_peer_send_queue_high_water",
            "max channel send-queue depth seen",
            labelnames=pc,
        ),
        "ping_rtt": reg.histogram(
            "p2p_peer_ping_rtt_seconds",
            "mconn ping->pong round trip per peer",
            labelnames=p,
        ),
        "last_recv_age": reg.gauge(
            "p2p_peer_last_recv_age_seconds",
            "seconds since the last packet from the peer (refreshed at "
            "collect time)",
            labelnames=p,
        ),
        "vote_gossip_picks": reg.counter(
            "p2p_peer_vote_gossip_picks_total",
            "votes picked for a peer by the gossip routine",
            labelnames=p,
        ),
        "vote_gossip_sends": reg.counter(
            "p2p_peer_vote_gossip_sends_total",
            "picked votes whose send succeeded (the peer is then marked)",
            labelnames=p,
        ),
        "vote_gossip_send_failures": reg.counter(
            "p2p_peer_vote_gossip_send_failures_total",
            "picked votes whose send FAILED — the vote stays retryable "
            "(the PR-13 pick-marks-before-send wedge signal)",
            labelnames=p,
        ),
        "catchup_commits": reg.counter(
            "p2p_peer_catchup_commits_total",
            "catchup-commit tracking arrays engaged for a lagging peer",
            labelnames=p,
        ),
        "vote_duplicates": reg.counter(
            "p2p_peer_vote_duplicates_total",
            "gossiped votes from this peer already seen (begin_add "
            "screen) — the 2NxN redundancy the gossip-dedup work "
            "targets (round 17)",
            labelnames=p,
        ),
    }
    # not an instrument: the cross-peer RTT smoother rides the same
    # cache so reactors sharing the registry read one EWMA (round 21)
    fams["ping_rtt_ewma"] = RttEwma()
    setattr(reg, _CACHE_ATTR, fams)
    return fams


def family_totals(reg: "telemetry.Registry | None" = None) -> dict:
    """Flat per-node aggregates over the labeled families (sum across
    children, the ``_other`` overflow series included) — what the legacy
    p2p producer exports beside the three peer counts."""
    fams = peer_metrics(reg)

    def total(key: str) -> int:
        return sum(child.value for _k, child in fams[key]._items())

    return {
        "peer_send_failures": total("send_failures"),
        "peer_vote_gossip_picks": total("vote_gossip_picks"),
        "peer_vote_gossip_sends": total("vote_gossip_sends"),
        "peer_vote_gossip_send_failures": total("vote_gossip_send_failures"),
        "peer_catchup_commits": total("catchup_commits"),
        "peer_vote_duplicates": total("vote_duplicates"),
    }


def _ch_label(ch_id: int) -> str:
    return f"{ch_id:#x}"


class PeerConnMetrics:
    """Per-connection handle bundle: child series resolved ONCE at
    handshake (labels never change for a live connection), so the
    send/recv routines pay one attribute read + one child inc per event
    — no registry lookups on the hot path."""

    __slots__ = ("peer_id", "_send_bytes", "_recv_bytes", "_send_msgs",
                 "_recv_msgs", "_send_failures", "_send_queue",
                 "_send_queue_hw", "_hw", "_hw_mtx", "_ping_rtt",
                 "_rtt_ewma", "_ping_sent_at")

    def __init__(self, peer_id: str, channel_ids, reg=None):
        fams = peer_metrics(reg)
        self.peer_id = peer_id

        def children(key):
            return {
                ch: fams[key].labels(peer=peer_id, channel=_ch_label(ch))
                for ch in channel_ids
            }

        self._send_bytes = children("send_bytes")
        self._recv_bytes = children("recv_bytes")
        self._send_msgs = children("send_msgs")
        self._recv_msgs = children("recv_msgs")
        self._send_failures = children("send_failures")
        self._send_queue = children("send_queue")
        self._send_queue_hw = children("send_queue_high_water")
        self._hw = {ch: 0 for ch in channel_ids}
        self._hw_mtx = threading.Lock()
        self._ping_rtt = fams["ping_rtt"].labels(peer=peer_id)
        self._rtt_ewma = fams["ping_rtt_ewma"]
        self._ping_sent_at = 0.0

    # -- send side ---------------------------------------------------------

    def sent_frame(self, ch_id: int, nbytes: int, eof: bool) -> None:
        c = self._send_bytes.get(ch_id)
        if c is None:
            return
        c.inc(nbytes)
        if eof:
            self._send_msgs[ch_id].inc()

    def send_failure(self, ch_id: int) -> None:
        c = self._send_failures.get(ch_id)
        if c is not None:
            c.inc()

    def queue_sample(self, ch_id: int, depth: int) -> None:
        g = self._send_queue.get(ch_id)
        if g is None:
            return
        g.set(depth)
        # max-under-lock, gauge write included: concurrent senders
        # racing a check-then-set (or writing the gauge after releasing)
        # could regress the high-water gauge below the true maximum
        with self._hw_mtx:
            if depth <= self._hw[ch_id]:
                return
            self._hw[ch_id] = depth
            self._send_queue_hw[ch_id].set(depth)

    # -- recv side ---------------------------------------------------------

    def recv_packet(self, ch_id: int, nbytes: int, eof: bool) -> None:
        c = self._recv_bytes.get(ch_id)
        if c is None:
            return
        c.inc(nbytes)
        if eof:
            self._recv_msgs[ch_id].inc()

    # -- liveness ----------------------------------------------------------

    def ping_sent(self) -> None:
        self._ping_sent_at = time.monotonic()

    def pong_received(self) -> None:
        if self._ping_sent_at > 0:
            rtt = time.monotonic() - self._ping_sent_at
            self._ping_rtt.observe(rtt)
            self._rtt_ewma.observe(rtt)
            self._ping_sent_at = 0.0
