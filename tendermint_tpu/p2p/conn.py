"""Multiplexed prioritized connection (reference: p2p/connection.go).

One physical stream carries many logical channels. Outgoing messages are
chopped into <=1024-byte packets; the send scheduler picks the channel
with the least recently-sent-bytes/priority ratio (connection.go:364-399),
so high-priority channels (votes) preempt bulk ones (block parts) without
starving them. Send and recv are rate-limited with flowrate monitors;
ping/pong guards liveness; a flush throttle batches small writes.

Framing (ours, not go-wire): 1-byte packet type; msg packets are
[type=0x02][channel:1][eof:1][len:2 BE][payload]. Ping=0x01, Pong=0x03.

The stream below can be a TCP socket, a SecretConnection, or an in-memory
socketpair (tests).
"""

from __future__ import annotations

import struct
import threading
import time
from collections import deque
from dataclasses import dataclass

from tendermint_tpu.libs.flowrate import Monitor
from tendermint_tpu.libs.service import BaseService

PACKET_TYPE_PING = 0x01
PACKET_TYPE_MSG = 0x02
PACKET_TYPE_PONG = 0x03

MAX_MSG_PACKET_PAYLOAD_SIZE = 1024  # connection.go:30
_MSG_HEADER = struct.Struct(">BBBH")  # type, channel, eof, payload len


class FrameViolation(ValueError):
    """The peer broke the mconn framing contract: reassembly past a
    channel's recv ceiling, an unknown channel id, or an unknown packet
    type. Typed (round 18) so the switch's adversary accounting can
    classify it without sniffing message text."""


@dataclass
class MConnConfig:
    """Tunables (connection.go:28-36, config/config.go:245-246)."""

    send_rate: float = 512000.0  # bytes/s
    recv_rate: float = 512000.0
    flush_throttle: float = 0.1  # s
    ping_interval: float = 40.0  # s (pingTimeoutSeconds uses one knob)
    pong_timeout: float = 45.0
    send_queue_capacity: int = 1
    recv_buffer_capacity: int = 4096
    recv_message_capacity: int = 22020096  # 21MB — max block + slack
    send_timeout: float = 10.0  # Channel.sendBytes block limit


@dataclass(frozen=True)
class ChannelDescriptor:
    """Static channel registration (connection.go:510-546)."""

    id: int
    priority: int = 1
    send_queue_capacity: int = 1
    recv_buffer_capacity: int = 4096
    recv_message_capacity: int = 22020096


class _Channel:
    def __init__(self, desc: ChannelDescriptor, cfg: MConnConfig):
        self.desc = desc
        self.id = desc.id
        self.priority = max(desc.priority, 1)
        self.recently_sent = 0  # decayed by flush ticks (connection.go:544)
        self._queue: deque[bytes] = deque()
        self._queue_cap = desc.send_queue_capacity
        self._mtx = threading.Lock()
        self._not_full = threading.Condition(self._mtx)
        self._sending: bytes | None = None
        self._sent_off = 0
        self._recving = bytearray()
        self._recv_cap = desc.recv_message_capacity

    # -- send side ---------------------------------------------------------

    def send_bytes(self, msg: bytes, timeout: float) -> bool:
        """Queue a message; block up to `timeout` if the queue is full."""
        deadline = time.monotonic() + timeout
        with self._not_full:
            while len(self._queue) >= self._queue_cap:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._not_full.wait(left)
            self._queue.append(msg)
            return True

    def try_send_bytes(self, msg: bytes) -> bool:
        with self._mtx:
            if len(self._queue) >= self._queue_cap:
                return False
            self._queue.append(msg)
            return True

    def is_send_pending(self) -> bool:
        with self._mtx:
            return self._sending is not None or bool(self._queue)

    def send_queue_size(self) -> int:
        with self._mtx:
            return len(self._queue) + (1 if self._sending is not None else 0)

    def next_packet(self) -> bytes | None:
        """Pop the next <=1024B packet frame for this channel, or None."""
        with self._not_full:
            if self._sending is None:
                if not self._queue:
                    return None
                self._sending = self._queue.popleft()
                self._sent_off = 0
                self._not_full.notify()
            chunk = self._sending[self._sent_off : self._sent_off + MAX_MSG_PACKET_PAYLOAD_SIZE]
            self._sent_off += len(chunk)
            eof = 1 if self._sent_off >= len(self._sending) else 0
            if eof:
                self._sending = None
                self._sent_off = 0
            frame = _MSG_HEADER.pack(PACKET_TYPE_MSG, self.id, eof, len(chunk)) + chunk
            self.recently_sent += len(frame)
            return frame

    # -- recv side ---------------------------------------------------------

    def recv_packet(self, payload: bytes, eof: bool) -> bytes | None:
        """Reassemble; returns the full message when eof (connection.go:661-677)."""
        if len(self._recving) + len(payload) > self._recv_cap:
            raise FrameViolation(
                f"channel {self.id:#x} message exceeds {self._recv_cap} bytes"
            )
        self._recving += payload
        if eof:
            msg = bytes(self._recving)
            self._recving = bytearray()
            return msg
        return None


class MConnection(BaseService):
    """on_receive(channel_id, msg_bytes) runs on the recv thread;
    on_error(exc) fires once on the first fatal stream error."""

    def __init__(
        self,
        stream,
        channel_descs: list[ChannelDescriptor],
        on_receive,
        on_error,
        config: MConnConfig | None = None,
        name: str = "mconn",
    ):
        super().__init__(name=name)
        self.stream = stream
        self.config = config or MConnConfig()
        self.on_receive = on_receive
        self.on_error = on_error
        self.channels: dict[int, _Channel] = {
            d.id: _Channel(d, self.config) for d in channel_descs
        }
        self.send_monitor = Monitor()
        self.recv_monitor = Monitor()
        self._send_signal = threading.Event()
        self._pong_pending = threading.Event()
        self._last_pong = time.monotonic()
        self._errored = threading.Event()
        self._threads: list[threading.Thread] = []
        self._wmtx = threading.Lock()  # serializes raw stream writes
        # per-peer instrumentation (round 15): armed by set_peer_label
        # once the handshake knows who the peer is; None = uninstrumented
        # (pre-handshake traffic, raw harness mconns)
        self._pm = None
        self.last_recv = time.monotonic()

    def set_peer_label(self, peer_id: str, registry=None) -> None:
        """Arm the p2p_peer_* families for this connection. `registry`
        scopes the series (the switch passes the node registry so two
        in-process nodes keep separate counters); default process-wide."""
        from tendermint_tpu.p2p.telemetry import PeerConnMetrics

        self._pm = PeerConnMetrics(peer_id, list(self.channels), registry)

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        for fn, nm in ((self._send_routine, "send"), (self._recv_routine, "recv")):
            t = threading.Thread(target=fn, name=f"{self._name}.{nm}", daemon=True)
            t.start()
            self._threads.append(t)

    def on_stop(self) -> None:
        try:
            self.stream.close()
        except Exception:
            pass
        self._send_signal.set()

    def _fatal(self, exc: Exception) -> None:
        if not self._errored.is_set():
            self._errored.set()
            if self.is_running():
                cb = self.on_error
                if cb is not None:
                    cb(exc)

    # -- public send API ---------------------------------------------------

    def send(self, ch_id: int, msg: bytes) -> bool:
        if not self.is_running():
            return False
        ch = self.channels.get(ch_id)
        if ch is None:
            return False
        ok = ch.send_bytes(msg, self.config.send_timeout)
        if ok:
            self._send_signal.set()
        self._note_send(ch, ok)
        return ok

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        if not self.is_running():
            return False
        ch = self.channels.get(ch_id)
        if ch is None:
            return False
        ok = ch.try_send_bytes(msg)
        if ok:
            self._send_signal.set()
        self._note_send(ch, ok)
        return ok

    def _note_send(self, ch: _Channel, ok: bool) -> None:
        pm = self._pm
        if pm is None:
            return
        if ok:
            pm.queue_sample(ch.id, ch.send_queue_size())
        else:
            pm.send_failure(ch.id)

    def can_send(self, ch_id: int) -> bool:
        ch = self.channels.get(ch_id)
        return ch is not None and ch.send_queue_size() < ch.desc.send_queue_capacity

    # -- send scheduler ----------------------------------------------------

    def _least_ratio_channel(self) -> _Channel | None:
        """Fair pick: min recentlySent/priority among channels with data
        (connection.go:364-399)."""
        best, best_ratio = None, None
        for ch in self.channels.values():
            if not ch.is_send_pending():
                continue
            ratio = ch.recently_sent / ch.priority
            if best_ratio is None or ratio < best_ratio:
                best, best_ratio = ch, ratio
        return best

    def _write(self, data: bytes) -> None:
        self.send_monitor.limit(len(data), self.config.send_rate)
        with self._wmtx:
            self.stream.write(data)
        self.send_monitor.update(len(data))

    def _send_routine(self) -> None:
        cfg = self.config
        last_ping = time.monotonic()
        try:
            while self.is_running() and not self._errored.is_set():
                self._send_signal.wait(cfg.flush_throttle)
                self._send_signal.clear()
                now = time.monotonic()
                if self._pong_pending.is_set():
                    self._pong_pending.clear()
                    self._write(bytes([PACKET_TYPE_PONG]))
                if now - last_ping >= cfg.ping_interval:
                    last_ping = now
                    self._write(bytes([PACKET_TYPE_PING]))
                    if self._pm is not None:
                        self._pm.ping_sent()
                    if now - self._last_pong > cfg.ping_interval + cfg.pong_timeout:
                        raise TimeoutError("pong timeout")
                # drain up to a burst of packets, fairly
                for _ in range(64):
                    ch = self._least_ratio_channel()
                    if ch is None:
                        break
                    frame = ch.next_packet()
                    if frame is None:
                        break
                    self._write(frame)
                    if self._pm is not None:
                        # frame layout: type, channel, eof (msg done)
                        self._pm.sent_frame(frame[1], len(frame),
                                            bool(frame[2]))
                # decay fairness counters once per wakeup (connection.go:544)
                for ch in self.channels.values():
                    ch.recently_sent = int(ch.recently_sent * 0.8)
        except Exception as exc:  # noqa: BLE001 — any stream error is fatal here
            self._fatal(exc)

    # -- recv --------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self.stream.read(n - len(buf))
            if not chunk:
                raise ConnectionError("stream closed")
            buf += chunk
        return bytes(buf)

    def _recv_routine(self) -> None:
        cfg = self.config
        try:
            while self.is_running() and not self._errored.is_set():
                head = self._read_exact(1)
                self.recv_monitor.limit(1, cfg.recv_rate)
                self.recv_monitor.update(1)
                ptype = head[0]
                self.last_recv = time.monotonic()
                if ptype == PACKET_TYPE_PING:
                    self._pong_pending.set()
                    self._send_signal.set()
                elif ptype == PACKET_TYPE_PONG:
                    self._last_pong = time.monotonic()
                    if self._pm is not None:
                        self._pm.pong_received()
                elif ptype == PACKET_TYPE_MSG:
                    rest = self._read_exact(_MSG_HEADER.size - 1)
                    ch_id, eof, plen = rest[0], rest[1], (rest[2] << 8) | rest[3]
                    payload = self._read_exact(plen) if plen else b""
                    self.recv_monitor.limit(plen, cfg.recv_rate)
                    self.recv_monitor.update(plen)
                    ch = self.channels.get(ch_id)
                    if ch is None:
                        raise FrameViolation(f"unknown channel {ch_id:#x}")
                    if self._pm is not None:
                        self._pm.recv_packet(ch_id, _MSG_HEADER.size + plen,
                                             bool(eof))
                    msg = ch.recv_packet(payload, bool(eof))
                    if msg is not None and self.on_receive is not None:
                        self.on_receive(ch_id, msg)
                else:
                    raise FrameViolation(f"unknown packet type {ptype:#x}")
        except Exception as exc:  # noqa: BLE001
            self._fatal(exc)

    def status(self) -> dict:
        return {
            "send_rate": self.send_monitor.status().avg_rate,
            "recv_rate": self.recv_monitor.status().avg_rate,
            "channels": {
                f"{ch.id:#x}": ch.send_queue_size() for ch in self.channels.values()
            },
        }
