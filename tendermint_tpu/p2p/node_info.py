"""Node identity and compatibility handshake data (reference: p2p/types.go).

NodeInfo is exchanged unencrypted-length-prefixed right after the secret
handshake; CompatibleWith gates the peering (p2p/types.go:25-56).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from tendermint_tpu.crypto.keys import PubKeyEd25519
from tendermint_tpu.version import PROTOCOL_VERSION

MAX_NODE_INFO_SIZE = 10240


@dataclass
class NodeInfo:
    pub_key: PubKeyEd25519
    moniker: str
    network: str  # chain id
    version: str  # "protocol/software", compat gated on protocol part
    remote_addr: str = ""
    listen_addr: str = ""
    channels: bytes = b""  # channel ids this node serves
    other: list = field(default_factory=list)

    def id(self) -> str:
        """Peer key: hex of the node pubkey address."""
        return self.pub_key.address().hex()

    def compatible_with(self, other: "NodeInfo") -> str | None:
        """None if compatible, else a human-readable reason
        (p2p/types.go:28-56: same protocol version, same network)."""
        mine = self.version.split("/", 1)[0]
        theirs = other.version.split("/", 1)[0]
        if mine != theirs:
            return f"protocol version mismatch: {mine} vs {theirs}"
        if self.network != other.network:
            return f"network mismatch: {self.network} vs {other.network}"
        return None

    def to_json(self) -> dict:
        return {
            "pub_key": self.pub_key.to_json(),
            "moniker": self.moniker,
            "network": self.network,
            "version": self.version,
            "remote_addr": self.remote_addr,
            "listen_addr": self.listen_addr,
            "channels": self.channels.hex(),
            "other": self.other,
        }

    @classmethod
    def from_json(cls, o: dict) -> "NodeInfo":
        return cls(
            pub_key=PubKeyEd25519.from_json(o["pub_key"]),
            moniker=o["moniker"],
            network=o["network"],
            version=o["version"],
            remote_addr=o.get("remote_addr", ""),
            listen_addr=o.get("listen_addr", ""),
            channels=bytes.fromhex(o.get("channels", "")),
            other=o.get("other", []),
        )

    def encode(self) -> bytes:
        return json.dumps(self.to_json(), sort_keys=True).encode()

    @classmethod
    def decode(cls, raw: bytes) -> "NodeInfo":
        return cls.from_json(json.loads(raw.decode()))


def default_version(software_version: str) -> str:
    return f"{PROTOCOL_VERSION}/{software_version}"
