"""Node identity and compatibility handshake data (reference: p2p/types.go).

NodeInfo is exchanged unencrypted-length-prefixed right after the secret
handshake; CompatibleWith gates the peering (p2p/types.go:25-56).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from tendermint_tpu.crypto.keys import PubKeyEd25519
from tendermint_tpu.version import PROTOCOL_VERSION

MAX_NODE_INFO_SIZE = 10240


@dataclass
class NodeInfo:
    pub_key: PubKeyEd25519
    moniker: str
    network: str  # chain id
    version: str  # "protocol/software", compat gated on protocol part
    remote_addr: str = ""
    listen_addr: str = ""
    channels: bytes = b""  # channel ids this node serves
    other: list = field(default_factory=list)

    def id(self) -> str:
        """Peer key: hex of the node pubkey address."""
        return self.pub_key.address().hex()

    def _commit_format(self) -> str:
        """The genesis commit-format SCHEDULE this node runs under, from
        the `other` key/value list (round 22: `commit_schedule=` carries
        the full upgrade schedule string, e.g. "full>aggregate@100" —
        genesis.schedule_string(); two nodes agreeing on today's format
        but disagreeing on the flip height would fork AT the flip, so
        the whole schedule gates the peering). Falls back to the round-18
        `commit_format=` flag for older peers, then to "full" — exactly
        the genesis default, so homogeneous old nets stay compatible."""
        fmt = None
        for entry in self.other:
            if not isinstance(entry, str):
                continue
            if entry.startswith("commit_schedule="):
                return entry.split("=", 1)[1]
            if entry.startswith("commit_format="):
                fmt = entry.split("=", 1)[1]
        return fmt if fmt is not None else "full"

    def compatible_with(self, other: "NodeInfo") -> str | None:
        """None if compatible, else a human-readable reason
        (p2p/types.go:28-56: same protocol version, same network; round
        18 adds the genesis commit_format flag — a mixed-format net must
        refuse LOUDLY at the handshake, not wedge later when one side
        gossips commit bytes the other's decode_commit rejects,
        docs/committee.md)."""
        mine = self.version.split("/", 1)[0]
        theirs = other.version.split("/", 1)[0]
        if mine != theirs:
            return f"protocol version mismatch: {mine} vs {theirs}"
        if self.network != other.network:
            return f"network mismatch: {self.network} vs {other.network}"
        if self._commit_format() != other._commit_format():
            return (
                f"commit schedule mismatch: {self._commit_format()} vs "
                f"{other._commit_format()} (mixed-schedule nets refuse at "
                f"handshake, never wedge at decode; docs/upgrade.md)"
            )
        return None

    def to_json(self) -> dict:
        return {
            "pub_key": self.pub_key.to_json(),
            "moniker": self.moniker,
            "network": self.network,
            "version": self.version,
            "remote_addr": self.remote_addr,
            "listen_addr": self.listen_addr,
            "channels": self.channels.hex(),
            "other": self.other,
        }

    @classmethod
    def from_json(cls, o: dict) -> "NodeInfo":
        # handshake input from an unauthenticated peer: every field is
        # type- and size-checked; violations raise ValueError (-> the
        # switch drops the connection). The frame itself is already
        # capped at MAX_NODE_INFO_SIZE (peer.exchange_node_info).
        from tendermint_tpu.codec import jsonval as jv

        o = jv.require_dict(o)
        other = o.get("other", [])
        if not isinstance(other, list) or len(other) > 32 or any(
            not isinstance(x, str) or len(x) > jv.MAX_STR for x in other
        ):
            raise ValueError("bad node info 'other'")
        return cls(
            pub_key=PubKeyEd25519.from_json(o.get("pub_key")),
            moniker=jv.str_field(o, "moniker"),
            network=jv.str_field(o, "network"),
            version=jv.str_field(o, "version"),
            remote_addr=jv.str_field(o, "remote_addr") if o.get("remote_addr") else "",
            listen_addr=jv.str_field(o, "listen_addr") if o.get("listen_addr") else "",
            channels=jv.hex_field(o, "channels", max_bytes=32) if o.get("channels") else b"",
            other=other,
        )

    def encode(self) -> bytes:
        return json.dumps(self.to_json(), sort_keys=True).encode()

    @classmethod
    def decode(cls, raw: bytes) -> "NodeInfo":
        return cls.from_json(json.loads(raw.decode()))


def default_version(software_version: str) -> str:
    return f"{PROTOCOL_VERSION}/{software_version}"
