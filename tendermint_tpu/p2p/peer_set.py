"""Goroutine-safe peer registry keyed by peer id (reference: p2p/peer_set.go)."""

from __future__ import annotations

import threading


class PeerSet:
    def __init__(self):
        self._mtx = threading.Lock()
        self._by_id: dict[str, object] = {}

    def add(self, peer, cap: int = 0) -> bool:
        """Register unless duplicate — or, when cap > 0, unless the set is
        already at cap. The size check must share this lock: admission
        runs on one thread per inbound connection, and a racy pre-check
        alone would let a dial burst exceed the cap arbitrarily."""
        with self._mtx:
            if cap and len(self._by_id) >= cap:
                return False
            if peer.id() in self._by_id:
                return False
            self._by_id[peer.id()] = peer
            return True

    def has(self, peer_id: str) -> bool:
        with self._mtx:
            return peer_id in self._by_id

    def get(self, peer_id: str):
        with self._mtx:
            return self._by_id.get(peer_id)

    def remove(self, peer) -> None:
        with self._mtx:
            self._by_id.pop(peer.id(), None)

    def size(self) -> int:
        with self._mtx:
            return len(self._by_id)

    def list(self) -> list:
        with self._mtx:
            return list(self._by_id.values())
