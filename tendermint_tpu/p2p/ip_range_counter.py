"""Per-IP-range connection counting (reference: p2p/ip_range_counter.go
AddToIPRangeCounts / CheckIPRangeCounts, there left unwired — here the
switch uses it to cap inbound peers per address range).

An IPv4 address belongs to one range per prefix depth: its /8, /16 and
/24. Limits are per depth: e.g. (64, 32, 16) allows at most 64 inbound
peers sharing a first octet, 32 sharing two, 16 sharing three — a cheap
sybil dampener: one botnet subnet cannot occupy the whole inbound peer
budget.
"""

from __future__ import annotations

import threading


class IPRangeCounter:
    def __init__(self, limits: tuple[int, ...] = (64, 32, 16)):
        self.limits = limits
        self._counts: dict[str, int] = {}
        self._mtx = threading.Lock()

    @staticmethod
    def _prefixes(ip: str) -> list[str]:
        parts = ip.split(".")
        if len(parts) != 4:
            return [ip]  # non-IPv4: one bucket for the whole literal
        return [".".join(parts[: i + 1]) for i in range(3)]

    def try_add(self, ip: str) -> bool:
        """Count `ip` against its ranges; False (and no change) if any
        range is at its limit."""
        prefixes = self._prefixes(ip)
        with self._mtx:
            for i, p in enumerate(prefixes):
                limit = self.limits[min(i, len(self.limits) - 1)]
                if self._counts.get(p, 0) >= limit:
                    return False
            for p in prefixes:
                self._counts[p] = self._counts.get(p, 0) + 1
            return True

    def remove(self, ip: str) -> None:
        with self._mtx:
            for p in self._prefixes(ip):
                n = self._counts.get(p, 0) - 1
                if n <= 0:
                    self._counts.pop(p, None)
                else:
                    self._counts[p] = n

    def count(self, prefix: str) -> int:
        with self._mtx:
            return self._counts.get(prefix, 0)
