"""Network addresses (reference: p2p/netaddress.go).

Addresses are `ip:port` strings with routability classification used by
the address book to decide what to gossip.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass


@dataclass(frozen=True)
class NetAddress:
    ip: str
    port: int

    @classmethod
    def from_string(cls, s: str) -> "NetAddress":
        host, _, port = s.rpartition(":")
        if not host or not port:
            raise ValueError(f"invalid address {s!r}")
        return cls(host, int(port))

    def __str__(self) -> str:
        return f"{self.ip}:{self.port}"

    def dial_string(self) -> tuple[str, int]:
        return self.ip, self.port

    # -- classification (netaddress.go:171-252) ---------------------------

    def _addr(self):
        try:
            return ipaddress.ip_address(self.ip)
        except ValueError:
            return None

    def valid(self) -> bool:
        return self._addr() is not None and 0 < self.port < 65536

    def local(self) -> bool:
        a = self._addr()
        return a is not None and (a.is_loopback or a.is_unspecified)

    def routable(self) -> bool:
        """Globally routable: valid and not loopback/private/link-local."""
        a = self._addr()
        if a is None or not (0 < self.port < 65536):
            return False
        return not (
            a.is_loopback
            or a.is_private
            or a.is_link_local
            or a.is_multicast
            or a.is_unspecified
            or a.is_reserved
        )

    def same_network(self, other: "NetAddress", bits: int = 16) -> bool:
        a, b = self._addr(), other._addr()
        if a is None or b is None or a.version != b.version:
            return False
        net = ipaddress.ip_network(f"{self.ip}/{bits}", strict=False)
        return b in net

    def to_json(self):
        return str(self)

    @classmethod
    def from_json(cls, s: str) -> "NetAddress":
        return cls.from_string(s)
