"""Retention coordinator: bounded-disk lifecycle for a node that serves
heavy traffic forever (round 19, docs/state-sync.md § Retention).

A `[pruning]` config section (`retain_blocks`, `interval_heights`, off
by default) arms automatic pruning of the block store and the consensus
WAL on the apply executor's tail — the same post-apply hook the
snapshot producer rides, AFTER it, so a snapshot published at height H
is on disk before the prune computes its floor.

The SAFE retain height is the minimum of every plane that still needs
history:

    safe = min(head - retain_blocks + 1,          # the operator target
               min(published snapshot heights),   # statesync producer
                                                  #   must stay serviceable
               min(pending evidence heights),     # conflicts stay auditable
               min(statetree retained versions))  # proofs at retained
                                                  #   versions need headers

so an aggressive operator target silently defers to whichever subsystem
retains deeper — disk stays bounded by the LARGEST of the retention
knobs, never truncated under a plane that still serves the range. The
block-store prune itself is crash-safe (watermark-first + clean_base
resume, blockchain/store.py); WAL retention drops whole rotated chunks
below the horizon (consensus/wal.py prune_to); snapshot-store retention
stays with the producer (`snapshot_keep_recent`) whose oldest published
height is this coordinator's floor.

`maybe_prune` NEVER raises — like the snapshot hook, a retention
failure must not wedge the apply executor (and therefore the consensus
join).

Telemetry: the `pruning_*` family on both metric surfaces — enabled /
target / runs / pruned heights / last retain height / the per-plane
floors of the last run / per-plane disk gauges (block store, WAL,
snapshots; refreshed at most every DISK_GAUGE_REFRESH_S so scrapes stay
cheap) — plus `blockstore_pruned_heights_total` on the store producer.
"""

from __future__ import annotations

import logging
import os
import time

logger = logging.getLogger("node.retention")

# consensus always needs the head block's seen commit and the previous
# block's meta/commit linkage; a retain target below this is an operator
# typo, not a policy
MIN_RETAIN_BLOCKS = 2
DISK_GAUGE_REFRESH_S = 5.0
# heights pruned per pass, at most: enabling [pruning] on a deep archive
# must drain the backlog across passes, not delete the whole history
# synchronously inside one post-apply hook (in serial finalize mode that
# hook runs INLINE in consensus commit — an unbounded first pass would
# stall rounds for the O(backlog) delete)
DEFAULT_MAX_PER_PASS = 2000


def dir_bytes(path: str, prefix: str | None = None) -> int:
    """Total file bytes under `path` (0 when absent). `prefix` keeps
    only files whose NAME starts with it — the db_dir holds every
    per-name DB (blockstore, state, tx_index), and the blockstore gauge
    must count only the plane retention actually prunes."""
    total = 0
    for dirpath, _dirs, files in os.walk(path):
        for fn in files:
            if prefix is not None and not fn.startswith(prefix):
                continue
            try:
                total += os.path.getsize(os.path.join(dirpath, fn))
            except OSError:
                continue
    return total


class RetentionCoordinator:
    def __init__(
        self,
        cfg,
        block_store,
        snapshot_store=None,
        wal_fn=None,
        evidence_pool=None,
        tree_app=None,
        tx_indexer=None,
        db_dir: str = "",
        wal_dir: str = "",
        snapshot_dir: str = "",
    ):
        """cfg is a config.PruningConfig. wal_fn() returns the consensus
        WAL (None before consensus starts). tree_app is the in-process
        app carrying a VersionedTree, or None — read per run, since a
        statesync restore rebinds app.tree. tx_indexer is the kv tx
        index (round 20: the last per-height disk term on a pruned
        node), pruned on the same pass; Null/absent indexers no-op."""
        from tendermint_tpu.libs.envknob import env_number

        self.enabled = cfg.retain_blocks > 0
        self.retain_blocks = max(int(cfg.retain_blocks), MIN_RETAIN_BLOCKS)
        self.interval = max(int(cfg.interval_heights), 1)
        self.max_per_pass = max(int(env_number(
            "TENDERMINT_RETENTION_MAX_PER_PASS", DEFAULT_MAX_PER_PASS,
            cast=int,
        )), 1)
        self.block_store = block_store
        self.snapshot_store = snapshot_store
        self.wal_fn = wal_fn
        self.evidence_pool = evidence_pool
        self.tree_app = tree_app
        self.tx_indexer = tx_indexer
        self._db_dir = db_dir
        self._wal_dir = wal_dir
        self._snapshot_dir = snapshot_dir

        # gauges (pruning_* on both metric surfaces)
        self.runs = 0
        self.pruned_heights = 0
        self.wal_chunks_pruned = 0
        self.tx_index_pruned = 0
        self.last_retain_height = 0
        self.prune_failures = 0
        self._last_floors: dict[str, int] = {}
        self._disk_cache: tuple[float, dict[str, int]] | None = None

    # -- the formula -------------------------------------------------------

    def safe_retain_height(self, head: int) -> tuple[int, dict[str, int]]:
        """(safe retain height, per-plane floors actually considered).
        The floors dict is what the pruning_floor_* gauges export — an
        operator whose disk is not shrinking reads WHICH plane pinned
        retention straight off a scrape."""
        floors = {"operator": max(head - self.retain_blocks + 1, 1)}
        if self.snapshot_store is not None:
            heights = self.snapshot_store.heights()
            if heights:
                floors["snapshots"] = heights[0]
        if self.evidence_pool is not None:
            ev = self.evidence_pool.min_pending_height()
            if ev is not None:
                floors["evidence"] = ev
        tree = getattr(self.tree_app, "tree", None)
        if tree is not None:
            try:
                versions = tree.versions()
            except Exception:  # noqa: BLE001 — mid-rebind during restore
                versions = []
            if versions:
                floors["statetree"] = max(versions[0], 1)
        return min(floors.values()), floors

    # -- the hook ----------------------------------------------------------

    def maybe_prune(self, state, block=None) -> int | None:
        """The post-apply hook (runs on the executor tail, after the
        snapshot producer): prune when the just-applied height lands on
        the interval. NEVER raises. Returns heights pruned, or None when
        the check did not run."""
        if not self.enabled:
            return None
        h = state.last_block_height
        if h == 0 or h % self.interval != 0:
            return None
        try:
            return self.prune(h)
        except Exception:  # noqa: BLE001 — retention is best-effort
            self.prune_failures += 1
            logger.exception("retention prune at height %d failed", h)
            return None

    def prune(self, head: int | None = None) -> int:
        """One retention pass: compute the safe height and drive the
        block store + WAL. Returns block-store heights pruned."""
        if head is None:
            head = self.block_store.height()
        safe, floors = self.safe_retain_height(head)
        # a floor above the store head (stale snapshot listing, head=0)
        # clamps: prune_to refuses to disown heights it never had
        safe = min(safe, self.block_store.height())
        # bound the pass: a deep backlog (pruning newly enabled on an
        # archive home) drains max_per_pass heights per interval instead
        # of stalling the apply hook for the whole history at once
        base = self.block_store.base()
        if base > 0:
            safe = min(safe, base + self.max_per_pass)
        self._last_floors = floors
        pruned = 0
        if safe > self.block_store.base():
            pruned = self.block_store.prune_to(safe)
        wal = self.wal_fn() if self.wal_fn is not None else None
        wal_pruned = 0
        if wal is not None and hasattr(wal, "prune_to"):
            wal_pruned = wal.prune_to(safe)
        tx_pruned = 0
        if self.tx_indexer is not None and hasattr(self.tx_indexer, "prune_to"):
            tx_pruned = self.tx_indexer.prune_to(safe)
        self.runs += 1
        self.pruned_heights += pruned
        self.wal_chunks_pruned += wal_pruned
        self.tx_index_pruned += tx_pruned
        self.last_retain_height = max(self.last_retain_height, safe)
        if pruned or wal_pruned or tx_pruned:
            logger.info(
                "retention: pruned %d height(s) + %d WAL chunk(s) + %d "
                "indexed tx(s) below %d "
                "(floors: %s)", pruned, wal_pruned, tx_pruned, safe,
                {k: v for k, v in sorted(floors.items())},
            )
        return pruned

    # -- observability -----------------------------------------------------

    def _disk_gauges(self) -> dict[str, int]:
        """Per-plane disk byte gauges, refreshed at most every
        DISK_GAUGE_REFRESH_S (an os.walk per scrape would make GET
        /metrics O(files); the cadence is plenty for capacity alerts)."""
        now = time.monotonic()
        if self._disk_cache is not None and now - self._disk_cache[0] < DISK_GAUGE_REFRESH_S:
            return self._disk_cache[1]
        gauges = {
            # db_dir also holds the state/tx-index DBs retention never
            # touches; count only the block store's own files
            # (libs/db.py db_provider names them "blockstore.<ext>")
            "disk_blockstore_bytes": dir_bytes(
                self._db_dir, prefix="blockstore."
            ),
            "disk_txindex_bytes": dir_bytes(
                self._db_dir, prefix="tx_index."
            ),
            "disk_wal_bytes": dir_bytes(self._wal_dir),
            "disk_snapshots_bytes": dir_bytes(self._snapshot_dir),
        }
        gauges["disk_total_bytes"] = sum(gauges.values())
        self._disk_cache = (now, gauges)
        return gauges

    def stats(self) -> dict:
        out = {
            "enabled": int(self.enabled),
            "retain_blocks": self.retain_blocks if self.enabled else 0,
            "interval_heights": self.interval,
            "runs": self.runs,
            "pruned_heights": self.pruned_heights,
            "wal_chunks_pruned": self.wal_chunks_pruned,
            "tx_index_pruned": self.tx_index_pruned,
            "last_retain_height": self.last_retain_height,
            "prune_failures": self.prune_failures,
        }
        for plane in ("operator", "snapshots", "evidence", "statetree"):
            out[f"floor_{plane}"] = self._last_floors.get(plane, 0)
        out.update(self._disk_gauges())
        return out
