"""Persisted light-client trust anchor (round 20, docs/localnet.md §
Trust anchor).

A statesync restore ends with the light client's trust walked to the
restored height — state that previously lived only in memory. A node
that restored at height H, crashed, wiped its data dir, and restored
again would re-anchor at the OPERATOR's pinned `statesync.trust_height`
(often genesis), re-walking — and re-trusting — the whole range it had
already verified. Persisting the anchor in the node home closes that
regression window: the next restore starts its light walk from the
deepest height this home ever verified.

Format: one JSON file at `<home>/data/light_anchor.json` holding
{chain_id, height, validators, header}. The validators are the set
trusted AT that height (what LightClient needs to resume); the header
is the last fully verified one so validator-set changes after a restart
stay chain-linked (rpc/light.py advance() condition (c)). Writes are
atomic (tmp + rename) and best-effort — losing the anchor only costs a
re-walk, never safety. Loads are strict: a chain-id mismatch or any
malformed field returns None (the caller falls back to configured
trust) rather than seeding trust from a corrupt file.
"""

from __future__ import annotations

import json
import logging
import os

logger = logging.getLogger("node.light_anchor")

ANCHOR_FILE = os.path.join("data", "light_anchor.json")


def anchor_path(root_dir: str) -> str:
    return os.path.join(root_dir, ANCHOR_FILE)


def save_anchor(root_dir: str, light_client) -> bool:
    """Persist `light_client`'s trust state under `root_dir`. Returns
    True on write. NEVER raises — the caller is the statesync completion
    path, and a full disk must not wedge the fast-sync handoff."""
    if not root_dir or light_client is None or light_client.height < 1:
        return False
    try:
        header = light_client.trusted_header()
        doc = {
            "chain_id": light_client.chain_id,
            "height": light_client.height,
            "validators": light_client.validators.to_json(),
            "header": header.to_json() if header is not None else None,
        }
        path = anchor_path(root_dir)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return True
    except Exception:  # noqa: BLE001 — anchor loss costs a re-walk only
        logger.exception("failed to persist light-client trust anchor")
        return False


def load_anchor(root_dir: str, chain_id: str):
    """The persisted anchor for `chain_id`, as
    (height, ValidatorSet, Header | None) — or None when absent, for a
    different chain, or malformed (strict: corrupt trust state must not
    seed a light client)."""
    from tendermint_tpu.types.block import Header
    from tendermint_tpu.types.validator_set import ValidatorSet

    if not root_dir:
        return None
    try:
        with open(anchor_path(root_dir)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    try:
        if doc.get("chain_id") != chain_id:
            logger.warning(
                "light anchor is for chain %r (this node runs %r); ignoring",
                doc.get("chain_id"), chain_id,
            )
            return None
        height = doc["height"]
        if not isinstance(height, int) or isinstance(height, bool) or height < 1:
            return None
        validators = ValidatorSet.from_json(doc["validators"])
        header = None
        if doc.get("header") is not None:
            header = Header.from_json(doc["header"])
            if header.height != height or header.chain_id != chain_id:
                return None
            # the persisted header must be signed by the persisted set —
            # a file whose parts disagree is corrupt, not trustworthy
            if header.validators_hash != validators.hash():
                return None
        return height, validators, header
    except (KeyError, TypeError, ValueError):
        logger.warning("malformed light anchor at %s; ignoring",
                       anchor_path(root_dir))
        return None
