"""Node telemetry wiring: THE canonical ``<plane>_<name>`` metric map.

Every gauge the node exports — through the legacy ``metrics`` JSON RPC
(flat dict) AND the Prometheus ``GET /metrics`` endpoint — is wired
here, in one place, with DIRECT attribute reads: a renamed field on any
producer object raises at collect time instead of silently exporting a
stale default (the PR-4 loud-wiring convention; this replaces the old
handler's ``getattr(..., 0.0)`` guards and the statesync ``setdefault``
collision dance).

Canonical plane prefixes (full catalog: docs/observability.md):

    consensus_*        ConsensusState position + liveness gauges
    blockstore_*       BlockStore head/base + round-19 prune accounting
    pruning_*          round-19 retention coordinator (node/retention.py):
                       enabled/target/runs, per-plane retention floors,
                       per-plane disk gauges
    wal_*              consensus WAL durability gauges (after start)
    evidence_*         duplicate-vote evidence pool
    mempool_*          pool depth + sig-gate accounting
    p2p_*              switch peer counts + per-peer gossip aggregates
    p2p_peer_*         round-15 labeled per-peer/per-channel families
                       (p2p/telemetry.py; node-registry-scoped, so two
                       in-process nodes keep separate series)
    node_health_*      round-15 health verdict (node/health.py): status
                       0 ok / 1 degraded / 2 failing + liveness age
    txtrace_*          round-17 tx-lifecycle sampling counters
                       (libs/txtrace.py; the per-stage distributions are
                       the tx_stage_seconds / tx_commit_latency_seconds /
                       tx_visible_latency_seconds histograms)
    flightrec_*        round-17 black-box recorder ring/dump accounting
                       (node/flightrec.py; the ring itself is
                       GET /debug/flight)
    fastsync_*         BlockchainReactor progress + stage seconds
    statesync_*        reactor serving/restore + producer cadence (incl.
                       the round-13 delta counters)
    statetree_*        authenticated app-state tree commit/hash shape
                       (scrape-only; present when the app carries one)
    gateway_verify_*   Verifier counters (+ stream/breaker/faults on devd)
    gateway_hash_*     Hasher counters (+ stream/breaker/faults on devd)
    gateway_breaker_*  the shared circuit breaker, every route (scrape-only)

plus the process-wide instruments the default registry carries
(devd_stream_chunk_seconds / devd_single_shot_seconds histograms,
wal_fsync_seconds / wal_group_records, mempool_sig_gate_batch_seconds,
gateway_hash_batch_seconds, the round-14 execution-pipeline histograms
consensus_height_seconds / pipeline_join_wait_seconds /
pipeline_overlap_seconds, the round-16 vote-plane histogram
consensus_vote_verify_batch_seconds, faults_*, p2p_secretconn_*
transport counters, netfaults_* network-chaos aggregates).

``legacy=True`` producers make up the byte-compatible metrics-RPC dict;
``legacy=False`` ones are scrape-only, so the legacy flat key set never
drifts.
"""

from __future__ import annotations

from tendermint_tpu.libs import telemetry
from tendermint_tpu.ops import gateway


def build_registry(node) -> telemetry.Registry:
    """Wire `node`'s subsystems into a Registry chained to the
    process-wide default (each node in a test process keeps its own
    producer table; instruments are shared)."""
    # materialize the process-wide instrument families up front so a
    # scrape's family set is STABLE from the first height: the devd
    # latency histograms otherwise appear only after the first devd op,
    # and the faults_* producer only once ops/faults is imported (it
    # registers itself at import)
    from tendermint_tpu import devd
    from tendermint_tpu.consensus import pipeline as cpipeline
    from tendermint_tpu.consensus import trace as ctrace
    from tendermint_tpu.consensus import vote_batcher as cvb
    from tendermint_tpu.ops import faults  # noqa: F401 — import = register
    from tendermint_tpu.ops import netfaults  # noqa: F401 — import =
    # register: the scrape-only netfaults_* family set (incl. the
    # round-18 netfaults_wan_* WAN-shaping counters) is stable from the
    # first scrape, all-zero outside a chaos harness
    from tendermint_tpu.p2p import secret_connection
    from tendermint_tpu.p2p import telemetry as p2p_telemetry

    devd._latency_hists()
    secret_connection._counters()
    cpipeline.pipeline_hists()
    cvb.vote_batch_hists()

    reg = telemetry.Registry(parent=telemetry.default_registry())
    cs = node.consensus_state

    # round 15: the per-peer p2p families and the quorum-formation
    # histograms live on the NODE registry — each in-process node keeps
    # its own series (the netchaos harness runs four nodes per process),
    # and a scrape's family set is stable from the first height. The
    # switch hands the registry to every admitted peer; the trace
    # recorder feeds the arrival histograms at each finish().
    peer_fams = p2p_telemetry.peer_metrics(reg)
    ctrace.arrival_hists(reg)
    node.sw.metrics_registry = reg
    cs.trace.metrics_registry = reg

    # round 17: the tx-lifecycle histograms (tx_stage_seconds{stage} +
    # the two end-to-end latencies) live on the NODE registry like the
    # per-peer families, materialized now for a stable family set
    from tendermint_tpu.libs import txtrace as _txtrace

    _txtrace.txtrace_hists(reg)
    node.txtrace.metrics_registry = reg

    def consensus() -> dict:
        rs = cs.get_round_state()
        return {
            "height": rs.height,
            "round": rs.round_,
            "step": int(rs.step),
            # liveness (round 8): wall seconds per committed height —
            # the "did a round stall behind a sick device plane" signal
            "height_seconds_last": round(cs.height_seconds_last, 3),
            "height_seconds_max": round(cs.height_seconds_max, 3),
            "peer_msg_drops": cs.peer_msg_drops,
            # pipelined execution plane (round 14): deferred applies
            # taken, the last join wait the consensus thread paid, and
            # the last apply span hidden under the next height (full
            # distributions: the pipeline_join_wait_seconds /
            # pipeline_overlap_seconds histograms on GET /metrics)
            "pipeline_applies": cs.pipeline_applies,
            "pipeline_serial_commits": cs.pipeline_serial_commits,
            "pipeline_join_wait_seconds": round(cs.pipeline_join_wait_last, 6),
            "pipeline_overlap_seconds": round(cs.pipeline_overlap_last, 6),
            # big-committee vote plane (round 16): micro-batches the
            # receive routine dispatched, the signature lanes they
            # carried, and the verdicts that fell to the one-sig path
            # (latency distribution: consensus_vote_verify_batch_seconds
            # on GET /metrics)
            "vote_batches": cs.vote_batcher.batches,
            "vote_batched_sigs": cs.vote_batcher.batched_sigs,
            "vote_singletons": cs.vote_batcher.singletons,
            # round 17: gossiped votes screened as already-seen — the
            # 2NxN redundancy before-number for the gossip-dedup work
            # (per-peer attribution: p2p_peer_vote_duplicates_total)
            "vote_duplicates": cs.vote_duplicates,
            # round 20: gossiped votes genuinely added — the ratio
            # vote_duplicates/vote_accepted is the duplicate-vote ratio
            # BENCH_r20 reads off scrapes — plus the dedup plane's own
            # accounting: HasVotes that landed in a peer mirror, and
            # HasBlockPart announcements sent/applied
            "vote_accepted": cs.vote_accepted,
            "gossip_has_votes_applied":
                node.consensus_reactor.has_votes_applied,
            "gossip_part_announces_sent":
                node.consensus_reactor.part_announces_sent,
            "gossip_part_announces_applied":
                node.consensus_reactor.part_announces_applied,
        }

    reg.register_producer("consensus", consensus)

    reg.register_producer(
        "blockstore",
        lambda: {
            "height": node.block_store.height(),
            "base": node.block_store.base(),
            # round 19: retention accounting — base > 1 says "pruned or
            # restored"; this says how much and how often
            "pruned_heights_total": node.block_store.pruned_heights,
            "prune_runs": node.block_store.prune_runs,
        },
    )

    # round 19: the retention coordinator — enabled/target/runs, the
    # per-plane floors of the last pass (WHICH plane pinned retention),
    # and per-plane disk gauges (block store / WAL / snapshots; cached a
    # few seconds so scrapes stay cheap). Always registered — the family
    # set is stable whether or not [pruning] is armed.
    reg.register_producer("pruning", node.retention.stats)

    def wal() -> dict:
        # host durability plane (round 9): group-commit shape + repair
        # history. The WAL opens at consensus start, so the wal_* keys
        # appear once the node runs (same presence rule as pre-registry)
        w = cs.wal
        return {} if w is None else w.stats()

    reg.register_producer("wal", wal)

    reg.register_producer(
        "evidence", lambda: {"count": cs.evidence_pool.size()}
    )

    def mempool() -> dict:
        # cache_dups: already-seen txs shed at the dedup cache — under
        # a duplicate flood this is the shed counter; on a quiet net it
        # counts benign gossip redundancy (round 18)
        mp = node.mempool
        out = {
            "size": mp.size(),
            "cache_dups": mp.cache_dups,
            # priority lanes + intake sheds (round 23, docs/serving.md);
            # the labeled mempool_lane_* families carry the same data
            # per lane — these flats are the legacy-RPC/fleet view
            "lane_priority_size": mp.lane_counts["priority"],
            "lane_default_size": mp.lane_counts["default"],
            "lane_bulk_size": mp.lane_counts["bulk"],
            "lane_full_rejects": sum(mp.lane_full.values()),
            "pool_full_rejects": mp.pool_full_rejects,
            "source_limit_rejects": mp.source_limited,
            "shed_writes_rejects": mp.shed_writes,
            "sources": len(mp.source_counts),
        }
        batcher = mp.sig_batcher
        if batcher is not None:
            out["sig_gate_dropped"] = batcher.dropped
            out["sig_gate_delivered"] = batcher.delivered
            out["sig_gate_fail_open"] = batcher.fail_open
            out["sig_gate_bad_sigs"] = batcher.bad_sigs
        return out

    reg.register_producer("mempool", mempool)

    # -- overload-control plane (round 23, docs/serving.md) -----------------
    # flat views: the ingress admission counters and the ladder position
    reg.register_producer("rpc", node.rpc_admission.snapshot)
    reg.register_producer("node_overload", node.overload.snapshot)

    # collect-time refresh of the per-peer staleness gauge: an age only
    # means something at read time, so every scrape sets the labeled
    # children for the CURRENT peer set before instruments are gathered.
    # Disconnected peers must keep AGING, not freeze at their last live
    # value (the staleness alert fires exactly when a peer dies): the
    # last recv instant of every peer ever refreshed is remembered and
    # dead peers' series keep growing from it; churn-evicted peers have
    # their series REMOVED from the family (a frozen series is the bug
    # this exists to prevent). The RPC server is threading — concurrent
    # scrapes share the table under a lock.
    import threading as _threading
    import time as _time

    last_recv_instants: dict[str, float] = {}
    ages_mtx = _threading.Lock()

    def refresh_peer_ages() -> None:
        age_gauge = peer_fams["last_recv_age"]
        now = _time.monotonic()
        live = []
        for peer in node.sw.peers.list():
            try:
                live.append((peer.id(), now - peer.last_recv_age()))
            except Exception:  # noqa: BLE001 — a peer mid-teardown must
                # not fail the whole scrape
                pass
        with ages_mtx:
            for pid, instant in live:
                last_recv_instants[pid] = instant
            if len(last_recv_instants) > 4 * telemetry.family_max_series(
                age_gauge.name
            ):
                # churn bound: evict the stalest remembered peers AND
                # drop their series so they vanish from the scrape
                # instead of freezing at the last written age
                for pid in sorted(last_recv_instants,
                                  key=last_recv_instants.get)[
                        : len(last_recv_instants) // 2]:
                    del last_recv_instants[pid]
                    age_gauge.remove_labels(peer=pid)
                    # the dead peer's point-in-time queue gauges must
                    # vanish too, not freeze (counters stay: a stopped
                    # counter is correct Prometheus semantics)
                    for d in node.sw.ch_descs:
                        ch = f"{d.id:#x}"
                        peer_fams["send_queue"].remove_labels(
                            peer=pid, channel=ch)
                        peer_fams["send_queue_high_water"].remove_labels(
                            peer=pid, channel=ch)
            snapshot = list(last_recv_instants.items())
        for pid, instant in snapshot:
            age_gauge.labels(peer=pid).set(round(now - instant, 3))

    reg.on_collect(refresh_peer_ages)

    def p2p() -> dict:
        outbound, inbound, dialing = node.sw.num_peers()
        out = {
            "peers_outbound": outbound,
            "peers_inbound": inbound,
            "peers_dialing": dialing,
        }
        # round 15: flat aggregates over the labeled gossip families
        # (sums across peers, the _other overflow series included) so
        # the legacy RPC sees the wedge signal too
        out.update(p2p_telemetry.family_totals(reg))
        # round 18: defense-side adversary accounting — what hostile
        # pressure this node shed (flat on both surfaces so the
        # adversarial scenario matrix asserts on scrapes alone)
        adv = node.sw.adversary_stats()
        out["adversary_eclipse_dials_refused"] = (
            adv["ip_range_refused"] + adv["max_peers_refused"]
        )
        out["adversary_handshake_rejects"] = adv["handshake_rejects"]
        out["adversary_frame_violations"] = adv["frame_violations"]
        # round 22: commit-schedule disagreements refused at handshake —
        # THE misconfiguration alarm during a rolling upgrade (a nonzero
        # value names a peer running a different genesis schedule;
        # docs/upgrade.md)
        out["adversary_schedule_refused"] = adv["schedule_refused"]
        # gate-level sheds only: bad signatures are unambiguously
        # hostile, saturation drops are shed load. Dedup-cache hits
        # deliberately do NOT count here — honest gossip re-delivery
        # and client resubmits hit the cache too, and an operator
        # alerting on an adversary_* family must not page on normal
        # redundancy (the dup-storm arm reads mempool_cache_dups)
        flood = 0
        batcher = node.mempool.sig_batcher
        if batcher is not None:
            flood = batcher.bad_sigs + batcher.dropped
        out["adversary_flood_txs_rejected"] = flood
        # round 22: address-book shape — size/new/old, churn counters,
        # and the group-domination containment gauge (max_group), so the
        # pex_churn scenario asserts eviction off scrapes alone
        for k, v in node.addr_book.stats().items():
            out[f"addrbook_{k}"] = v
        return out

    reg.register_producer("p2p", p2p)

    # round 22: the upgrade-at-height plane — where this node stands
    # relative to the scheduled commit-format flip, and every aggregate-
    # commit verdict it has rendered. upgrade_height is 0 when no flip is
    # scheduled; upgrade_active flips 0 -> 1 when the NEXT block this
    # node commits will carry an aggregate last-commit (the operator's
    # "has the cutover happened HERE yet" gauge, docs/upgrade.md).
    def upgrade() -> dict:
        gd = node.genesis_doc
        next_height = max(node.block_store.height(), 0) + 1
        return {
            "height": gd.upgrade_height,
            "active": 1 if gd.aggregate_commits_at(next_height) else 0,
            # consensus-thread verdicts: commit proofs accepted from
            # catchup gossip, forged/stale/sub-quorum refused, and
            # proposals this node built with an aggregate last-commit
            "agg_commit_proofs": cs.agg_commit_proofs,
            "agg_commit_rejects": cs.agg_commit_rejects,
            "agg_commits_proposed": cs.agg_commits_proposed,
            # peer-thread accounting: whole aggregates shipped to lagging
            # peers, and forged ones screened before they could enqueue
            "agg_commits_sent": node.consensus_reactor.agg_commits_sent,
            "agg_commits_rejected":
                node.consensus_reactor.agg_commits_rejected,
        }

    reg.register_producer("upgrade", upgrade)

    # round 15: the health verdict as flat gauges on both surfaces —
    # alerting keys off node_health_status without the JSON endpoint
    from tendermint_tpu.node.health import health_gauges

    reg.register_producer("node_health", lambda: health_gauges(node))

    # round 17: tx-lifecycle sampling counters + the flight recorder's
    # ring/dump accounting (the distributions ride the histograms above;
    # the event ring itself is GET /debug/flight)
    reg.register_producer("txtrace", node.txtrace.stats)
    reg.register_producer("flightrec", node.flightrec.stats)

    def fastsync() -> dict:
        bc = node.blockchain_reactor
        out = {
            "active": int(bool(bc.fast_sync)),
            "blocks_synced": bc.blocks_synced,
            "rate_blocks_per_sec": round(bc.sync_rate, 3),
            # round 19: times the catchup path detected the network's
            # retained horizon above its target and armed statesync
            "below_horizon_fallbacks": bc.below_horizon_fallbacks,
        }
        for stage, secs in bc.stage_s.items():
            out[f"{stage}_s"] = round(secs, 3)
        return out

    reg.register_producer("fastsync", fastsync)

    def statesync() -> dict:
        # reactor owns the store gauges; the producer exports only its
        # own cadence keys (statesync/producer.py) — collision-free by
        # construction, so a plain merge is safe
        out = dict(node.statesync_reactor.stats())
        if node.snapshot_producer is not None:
            out.update(node.snapshot_producer.stats())
        return out

    reg.register_producer("statesync", statesync)

    # authenticated state tree (round 13): commit/hashing shape of the
    # app's commitment tree. Scrape-only — the legacy flat RPC key set
    # stays frozen; apps without a tree simply have no producer here.
    # Read app.tree per collect: a snapshot restore rebinds the tree
    # instance, and a producer pinned to the old one would freeze
    if node.app_state_tree_app is not None:
        reg.register_producer(
            "statetree",
            lambda: node.app_state_tree_app.tree.stats(),
            legacy=False,
        )

    # device plane: tpu_sigs moving is how an operator confirms the
    # device path is live; stream_*/breaker_*/faults_* fold in on the
    # devd route (ops/gateway stats contracts)
    reg.register_producer("gateway_verify", node.verifier.stats)
    reg.register_producer("gateway_hash", node.hasher.stats)

    # the shared breaker, exported UNCONDITIONALLY for scrapers (on
    # non-devd routes the verifier/hasher stats omit it, but a scrape
    # must always show the degradation plane). Scrape-only: adding it to
    # the flat RPC would change the legacy key set.
    reg.register_producer(
        "gateway", lambda: gateway.devd_breaker().stats(), legacy=False
    )

    # round 21: the sharded device plane — flat fleet aggregates on both
    # surfaces (stable key set even in single-socket mode: count=1,
    # dispatch counters at zero), plus labeled per-endpoint families
    # refreshed at collect time like the peer ages above. Counters carry
    # the repo's _total suffix; the dispatcher keeps monotonic totals
    # per endpoint, so children advance by delta-inc (an endpoint reset
    # — devd_shard.reset() in tests — restarts at zero, and a negative
    # delta is simply not applied: Prometheus counter semantics).
    from tendermint_tpu.ops import devd_shard

    reg.register_producer("gateway_endpoints", devd_shard.plane_stats)

    ep_gauges = {
        "outstanding": reg.gauge(
            "gateway_endpoint_outstanding",
            "Slices in flight on this devd endpoint right now",
            labelnames=("endpoint",),
        ),
        "breaker_state": reg.gauge(
            "gateway_endpoint_breaker_state",
            "Endpoint circuit breaker: 0 closed / 1 half-open / 2 open",
            labelnames=("endpoint",),
        ),
        "sigs_per_s": reg.gauge(
            "gateway_endpoint_sigs_per_s",
            "EWMA verify throughput of this endpoint (signature lanes/s)",
            labelnames=("endpoint",),
        ),
    }
    ep_counters = {
        "dispatched_slices": reg.counter(
            "gateway_endpoint_dispatched_slices_total",
            "Verify/hash slices this endpoint completed",
            labelnames=("endpoint",),
        ),
        "stolen_slices": reg.counter(
            "gateway_endpoint_stolen_slices_total",
            "Completed slices this endpoint stole from another's queue",
            labelnames=("endpoint",),
        ),
        "redispatches": reg.counter(
            "gateway_endpoint_redispatches_total",
            "Slices that failed on this endpoint and re-queued elsewhere",
            labelnames=("endpoint",),
        ),
    }

    def refresh_endpoint_families() -> None:
        for path, st in devd_shard.endpoint_stats().items():
            for key, fam in ep_gauges.items():
                fam.labels(endpoint=path).set(st[key])
            for key, fam in ep_counters.items():
                child = fam.labels(endpoint=path)
                delta = st[key] - child.value
                if delta > 0:
                    child.inc(delta)

    reg.on_collect(refresh_endpoint_families)

    # -- overload-control labeled families (round 23, docs/serving.md) -----
    # every shed is visible BY REASON on the scrape surface; the sources
    # are monotonic python ints, so children advance by delta-inc (the
    # endpoint-family pattern above).
    shed_counter = reg.counter(
        "rpc_shed_total",
        "RPC requests shed at the ingress admission edge, by reason",
        labelnames=("reason",),
    )
    ws_evictions_counter = reg.counter(
        "ws_evictions_total",
        "WS subscribers evicted for persistent send-queue overflow",
    )
    ws_dropped_counter = reg.counter(
        "ws_dropped_events_total",
        "Events dropped from slow WS subscribers' bounded send queues",
    )
    lane_depth_gauge = reg.gauge(
        "mempool_lane_depth",
        "Txs currently pooled in this priority lane",
        labelnames=("lane",),
    )
    lane_bytes_gauge = reg.gauge(
        "mempool_lane_bytes",
        "Bytes currently pooled in this priority lane",
        labelnames=("lane",),
    )
    lane_full_counter = reg.counter(
        "mempool_lane_full_total",
        "CheckTx-ok txs rejected because this lane was at its cap",
        labelnames=("lane",),
    )

    def refresh_overload_families() -> None:
        admission = node.rpc_admission
        for reason, total in admission.sheds.items():
            child = shed_counter.labels(reason=reason)
            delta = total - child.value
            if delta > 0:
                child.inc(delta)
        for plain, source in (
            (ws_evictions_counter, admission.ws_evictions),
            (ws_dropped_counter, admission.ws_dropped_events),
        ):
            child = plain.labels()
            delta = source - child.value
            if delta > 0:
                child.inc(delta)
        mp = node.mempool
        for lane in mp.lane_counts:
            lane_depth_gauge.labels(lane=lane).set(mp.lane_counts[lane])
            lane_bytes_gauge.labels(lane=lane).set(mp.lane_bytes[lane])
            child = lane_full_counter.labels(lane=lane)
            delta = mp.lane_full[lane] - child.value
            if delta > 0:
                child.inc(delta)

    reg.on_collect(refresh_overload_families)

    return reg


def build_replica_registry(replica) -> telemetry.Registry:
    """Wire a ReplicaDaemon into a Registry chained to the process-wide
    default (round 24): the replica_* follower/cache plane plus the same
    rpc_* ingress families a full node exports — one dashboard works for
    validators and replicas alike. Catalog rows: docs/observability.md."""
    reg = telemetry.Registry(parent=telemetry.default_registry())

    # flat views on both surfaces: replica_{height,lag_heights,cache_*,
    # proof_verify_failures,upstream_reconnects,served_reads_total,...}
    reg.register_producer("replica", replica.stats)
    reg.register_producer("rpc", replica.rpc_admission.snapshot)

    # labeled ingress families, delta-inc'd from the monotonic admission
    # counters at collect time (the node-registry pattern above)
    shed_counter = reg.counter(
        "rpc_shed_total",
        "RPC requests shed at the replica's admission edge, by reason",
        labelnames=("reason",),
    )
    ws_evictions_counter = reg.counter(
        "ws_evictions_total",
        "WS subscribers evicted for persistent send-queue overflow",
    )
    ws_dropped_counter = reg.counter(
        "ws_dropped_events_total",
        "Events dropped from slow WS subscribers' bounded send queues",
    )

    def refresh_replica_families() -> None:
        admission = replica.rpc_admission
        for reason, total in admission.sheds.items():
            child = shed_counter.labels(reason=reason)
            delta = total - child.value
            if delta > 0:
                child.inc(delta)
        for plain, source in (
            (ws_evictions_counter, admission.ws_evictions),
            (ws_dropped_counter, admission.ws_dropped_events),
        ):
            child = plain.labels()
            delta = source - child.value
            if delta > 0:
                child.inc(delta)

    reg.on_collect(refresh_replica_families)

    return reg
