"""Black-box flight recorder (round 17, docs/observability.md).

Every netchaos wedge so far (the PR-13 vote-gossip bugs, the PR-16
fast-sync flake) was debugged by manual repro, because the node keeps no
record of its recent past: by the time an operator looks, the scrape
shows the wedged END STATE and the 30 seconds that caused it are gone.
This module is the aircraft-style recorder: a lock-cheap bounded ring of
structured recent events, served live on ``GET /debug/flight`` and
auto-dumped to the node home when the node goes visibly wrong — so the
next wedge is diagnosable from the dump alone.

Event catalog (kind -> fields; sites guard a None recorder, so bare
harnesses pay nothing):

    step          height, round, step      consensus step transitions
                                           (consensus/state.new_step)
    vote_reject   height, round, type,     a vote add raised VoteError
                  err, peer                (try_add_vote)
    vote_dup      peer                     sampled already-seen-vote
                                           event (1 in 256; the full
                                           count is the
                                           consensus_vote_duplicates /
                                           p2p_peer_vote_duplicates_total
                                           counters)
    gossip_send_fail  peer                 a picked vote's send failed —
                                           picks-without-sends is the
                                           gossip-stall signature
    peer_add      peer, outbound           switch admitted a peer
    peer_drop     peer, reason             switch dropped a peer
    breaker       state                    device-plane breaker moved
    wal_endheight height                   the WAL #ENDHEIGHT fsync mark
    health        status                   health verdict CHANGED
    fastsync      event, ...               catchup-path milestones
                                           (invalid block, redo,
                                           switch-to-consensus)
    exception     thread, err              unhandled consensus-thread
                                           exception (also dumps)
    overload      level, prev, score,      load-shed ladder level
                  frac_*                   transition (round 23,
                                           node/health.OverloadMonitor)
                                           with the per-input fill
                                           fractions that drove it

Auto-dump triggers (each exactly once per episode; the latch re-arms
when the condition clears):

- health verdict transition to FAILING (note_health — driven by every
  health_report call: scrapes, probes, and the watchdog below)
- height-age wedge: the watchdog sees height_age_s past
  TENDERMINT_FLIGHTREC_WEDGE_S (default 60; waived during fast sync)
- an unhandled exception escaping the consensus receive routine

Dumps are JSON files under ``<node home>/flightrec/`` named
``dump-<utc>-<reason>.json``: the event ring, the trigger, and a
counter snapshot (p2p gossip totals + consensus position via
``counters_fn``, wired by node/node.py) so picks-vs-sends is readable
without a second artifact.

``record()`` is one enabled-check + one deque.append (GIL-atomic) — the
TENDERMINT_FLIGHTREC_DISABLE kill switch makes it a single attribute
test, which tests/test_flightrec.py asserts costs nothing on the step
path.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

from tendermint_tpu.libs.envknob import env_number as _env_number

logger = logging.getLogger("node.flightrec")


class FlightRecorder:
    def __init__(self, home: str | None = None, ring: int | None = None):
        self._enabled = os.environ.get(
            "TENDERMINT_FLIGHTREC_DISABLE", "") != "1"
        if ring is None:
            ring = max(16, int(_env_number("TENDERMINT_FLIGHTREC_RING", 4096,
                                           cast=int)))
        self._ring: deque[tuple] = deque(maxlen=ring)
        self._mtx = threading.Lock()  # dump/read snapshots; record is lock-free
        self.dump_dir = os.path.join(home, "flightrec") if home else None
        self.recorded = 0
        self.dumps = 0
        self.dump_failures = 0
        # per-reason episode latches: dump once per transition INTO the
        # bad state; re-arm when it clears
        self._latched: set[str] = set()
        self._last_health: str | None = None
        self._last_breaker: int | None = None
        self._last_endpoint_breaker: dict[str, int] = {}
        self._dup_sample = 0
        # optional counter-snapshot provider for dumps (node/node.py
        # wires p2p gossip totals + consensus position)
        self.counters_fn = None
        self._watch_stop: threading.Event | None = None

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        self._enabled = bool(on)

    # -- recording (hot paths) ---------------------------------------------

    def record(self, kind: str, **fields) -> None:
        """Append one event. Lock-free: deque.append with maxlen is
        atomic under the GIL, and readers snapshot under the lock."""
        if not self._enabled:
            return
        self.recorded += 1
        self._ring.append((time.time(), kind, fields))

    def note_vote_dup(self, peer: str) -> None:
        """Sampled duplicate-vote event: the 2Nx2 gossip redundancy at
        committee scale would evict every other event from the ring if
        each duplicate recorded — 1 in 256 lands as an event, the exact
        totals ride the counters."""
        if not self._enabled:
            return
        self._dup_sample += 1
        if self._dup_sample % 256 == 1:
            self.record("vote_dup", peer=peer)

    # -- change-driven notes + auto-dump latches ---------------------------

    def note_health(self, status: str) -> None:
        """Health verdict observation (every health_report call lands
        here). Records CHANGES only; the transition into failing dumps
        exactly once per episode."""
        if not self._enabled or status == self._last_health:
            return
        self._last_health = status
        self.record("health", status=status)
        if status == "failing":
            self._dump_once("health_failing")
        else:
            self._rearm("health_failing")

    def note_breaker(self, state: int) -> None:
        if not self._enabled or state == self._last_breaker:
            return
        if self._last_breaker is not None:
            self.record("breaker", state=int(state))
        self._last_breaker = state

    def note_endpoint_breaker(self, endpoint: str, state: int) -> None:
        """Per-endpoint breaker transition (round 21 sharded device
        plane): change-driven like note_breaker, keyed by socket path —
        a sick chip's open/half-open/close sequence reads straight off
        the ring (kind ``endpoint_breaker``)."""
        if not self._enabled:
            return
        last = self._last_endpoint_breaker.get(endpoint)
        if state == last:
            return
        if last is not None:
            self.record("endpoint_breaker", endpoint=endpoint,
                        state=int(state))
        self._last_endpoint_breaker[endpoint] = state

    def note_height_age(self, age_s: float, wedge_s: float,
                        waived: bool = False) -> None:
        """Height-age wedge trigger (watchdog-driven): one dump per
        wedge episode; commits re-arm it by shrinking the age."""
        if not self._enabled:
            return
        if not waived and age_s >= wedge_s:
            self._dump_once("height_wedge")
        elif age_s < wedge_s:
            self._rearm("height_wedge")

    def note_exception(self, thread: str, exc: BaseException) -> None:
        """An unhandled exception escaped a critical thread: record and
        dump (every such crash is its own episode). The kill switch
        silences this too — a disabled recorder must write nothing."""
        if not self._enabled:
            return
        self.record("exception", thread=thread,
                    err=f"{type(exc).__name__}: {exc}")
        self.dump(f"exception_{thread}")

    def _dump_once(self, reason: str) -> None:
        with self._mtx:
            if reason in self._latched:
                return
            self._latched.add(reason)
        self.dump(reason)

    def _rearm(self, reason: str) -> None:
        with self._mtx:
            self._latched.discard(reason)

    # -- reads + dumps -----------------------------------------------------

    def events(self, last: int | None = None) -> list[dict]:
        with self._mtx:
            items = list(self._ring)
        if last is not None:
            items = items[-max(1, int(last)):]
        return [{"t": t, "kind": kind, **fields} for t, kind, fields in items]

    def _snapshot_counters(self) -> dict:
        if self.counters_fn is None:
            return {}
        try:
            return dict(self.counters_fn())
        except Exception:  # noqa: BLE001 — a counter provider bug must
            # never cost the dump itself
            logger.exception("flightrec counter snapshot failed")
            return {}

    def dump(self, reason: str) -> str | None:
        """Write the ring + counter snapshot to the node home. Returns
        the path (None when no home is configured or the write failed —
        the recorder itself must never take its caller down)."""
        payload = {
            "reason": reason,
            "dumped_at": time.time(),
            "recorded_total": self.recorded,
            "ring_size": self._ring.maxlen,
            "counters": self._snapshot_counters(),
            "events": self.events(),
        }
        self.dumps += 1
        if self.dump_dir is None:
            return None
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            path = os.path.join(
                self.dump_dir, f"dump-{stamp}-{reason}.json"
            )
            # distinct path even for two dumps in one second
            i = 0
            while os.path.exists(path):
                i += 1
                path = os.path.join(
                    self.dump_dir, f"dump-{stamp}-{reason}.{i}.json"
                )
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1)
                f.write("\n")
            os.replace(tmp, path)
            logger.warning("flight record dumped: %s (%d events)",
                           path, len(payload["events"]))
            return path
        except OSError:
            self.dump_failures += 1
            logger.exception("flight record dump failed (%s)", reason)
            return None

    def stats(self) -> dict:
        """Flat gauges for the canonical map (flightrec_* families)."""
        with self._mtx:
            size = len(self._ring)
        return {
            "events": size,
            "recorded": self.recorded,
            "dumps": self.dumps,
            "dump_failures": self.dump_failures,
            "enabled": int(self._enabled),
        }

    # -- watchdog ----------------------------------------------------------

    def start_watchdog(self, node, interval_s: float | None = None) -> None:
        """Periodic trigger scan: breaker transitions, the health
        verdict (driving the failing-transition dump even when nothing
        scrapes), and the height-age wedge. Daemon thread; every check
        is failure-proof — a mid-shutdown attribute error costs one
        tick, never the node."""
        if not self._enabled or self._watch_stop is not None:
            return
        if interval_s is None:
            interval_s = float(_env_number("TENDERMINT_FLIGHTREC_WATCH_S",
                                           2.0))
        wedge_s = float(_env_number("TENDERMINT_FLIGHTREC_WEDGE_S", 60.0))
        stop = self._watch_stop = threading.Event()

        def watch():
            from tendermint_tpu.node.health import health_report
            from tendermint_tpu.ops import gateway

            while not stop.is_set():
                try:
                    self.note_breaker(
                        gateway.devd_breaker().stats()["breaker_state"]
                    )
                except Exception:  # noqa: BLE001
                    pass
                try:
                    # sharded plane: every endpoint breaker that EXISTS
                    # (never instantiates one — a single-socket node has
                    # only the primary above)
                    for path, st in gateway.devd_breaker_states().items():
                        self.note_endpoint_breaker(path, st)
                except Exception:  # noqa: BLE001
                    pass
                try:
                    # health_report routes through note_health itself
                    health_report(node)
                except Exception:  # noqa: BLE001
                    pass
                try:
                    cs = node.consensus_state
                    self.note_height_age(
                        cs.height_age_s(), wedge_s,
                        waived=bool(node.blockchain_reactor.fast_sync),
                    )
                except Exception:  # noqa: BLE001
                    pass
                stop.wait(interval_s)

        threading.Thread(target=watch, daemon=True,
                         name="node.flightwatch").start()

    def stop_watchdog(self) -> None:
        if self._watch_stop is not None:
            self._watch_stop.set()
            self._watch_stop = None
