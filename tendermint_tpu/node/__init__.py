from tendermint_tpu.node.node import Node, default_new_node

__all__ = ["Node", "default_new_node"]
