"""Node health plane (round 15): GET /health + node_health_* gauges.

Before this, netchaos scenarios and probes asserted liveness by reaching
into harness objects (frozen height vectors, peer sets). This module
folds the node's existing liveness signals into ONE ok/degraded/failing
verdict served on the RPC listener (rpc/server.py GET /health), so
k8s-style probes and the fleet aggregator (ops/fleet.py) assert on the
observable surface:

    height age      seconds since the current height opened vs the
                    consensus_height_seconds liveness budget (a stalled
                    chain is a growing age) — waived while fast sync is
                    active (catching up is not a stall)
    peers           connected peer count vs TENDERMINT_HEALTH_MIN_PEERS
                    (default 0 = not gated: a sole-validator devnode is
                    healthy with zero peers)
    breaker         the shared device-plane circuit breaker — OPEN means
                    the node runs on the CPU fallback (degraded, alive)
    wal             pending records with a growing sync age = the group-
                    commit flusher is stuck, not merely idle
    pipeline        a poisoned deferred apply wedges the join = FAILING
    mempool         depth beyond the backlog knob = ingress pressure

Verdict: failing if any check fails, degraded if any degrades, else ok.
HTTP: 200 for ok/degraded, 503 for failing (probes key off the status
code; the body is machine-readable either way). Every threshold is an
env knob (libs/envknob — a typo'd value keeps the default):

    TENDERMINT_HEALTH_HEIGHT_AGE_DEGRADED_S   (30)
    TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S    (120)
    TENDERMINT_HEALTH_MIN_PEERS               (0)
    TENDERMINT_HEALTH_WAL_SYNC_AGE_S          (30)
    TENDERMINT_HEALTH_MEMPOOL_DEGRADED        (50000)

Round 23 adds the load-shed ladder (OverloadMonitor below,
docs/serving.md): one pressure score folded from mempool depth, RPC
in-flight, WS queue depths, and the apply backlog, mapped to
ok -> shed-reads -> shed-writes and consulted by rpc/admission and the
mempool's lane admission. Ladder knobs:

    TENDERMINT_OVERLOAD_SHED_READS_AT         (0.75)
    TENDERMINT_OVERLOAD_SHED_WRITES_AT        (0.90)
    TENDERMINT_OVERLOAD_APPLY_BACKLOG_CAP     (8)

The flat ``node_health_*`` gauges (node/telemetry.py wires the producer)
export the same verdict numerically: status 0=ok / 1=degraded /
2=failing, so alerting needs no JSON endpoint.
"""

from __future__ import annotations

import time

from tendermint_tpu.libs.envknob import env_number

OK, DEGRADED, FAILING = "ok", "degraded", "failing"
_CODE = {OK: 0, DEGRADED: 1, FAILING: 2}


def _knobs() -> dict:
    """Read per call: the netchaos tier tightens these live via env."""
    return {
        "height_age_degraded_s": float(
            env_number("TENDERMINT_HEALTH_HEIGHT_AGE_DEGRADED_S", 30.0)
        ),
        "height_age_failing_s": float(
            env_number("TENDERMINT_HEALTH_HEIGHT_AGE_FAILING_S", 120.0)
        ),
        "min_peers": int(env_number("TENDERMINT_HEALTH_MIN_PEERS", 0,
                                    cast=int)),
        "wal_sync_age_s": float(
            env_number("TENDERMINT_HEALTH_WAL_SYNC_AGE_S", 30.0)
        ),
        "mempool_degraded": int(
            env_number("TENDERMINT_HEALTH_MEMPOOL_DEGRADED", 50_000,
                       cast=int)
        ),
    }


def _worst(a: str, b: str) -> str:
    return a if _CODE[a] >= _CODE[b] else b


def health_report(node) -> dict:
    """The /health body. Direct attribute reads (the PR-4 loud-wiring
    convention): a renamed producer field raises here and surfaces as a
    500 probe failure — which monitoring alerts on — never as a
    healthy-looking 200 with a silently missing check."""
    k = _knobs()
    cs = node.consensus_state
    checks: dict[str, dict] = {}
    status = OK

    # -- height age (liveness) --------------------------------------------
    age = cs.height_age_s()
    fast_sync = bool(node.blockchain_reactor.fast_sync)
    if fast_sync:
        hstatus = OK  # catching up, not stalled; fastsync_* gauges cover it
    elif age >= k["height_age_failing_s"]:
        hstatus = FAILING
    elif age >= k["height_age_degraded_s"]:
        hstatus = DEGRADED
    else:
        hstatus = OK
    checks["height_age"] = {
        "status": hstatus, "age_s": round(age, 3),
        "height": cs.get_round_state().height,
        "fast_sync": fast_sync,
        "degraded_at_s": k["height_age_degraded_s"],
        "failing_at_s": k["height_age_failing_s"],
    }
    status = _worst(status, hstatus)

    # -- peers -------------------------------------------------------------
    outbound, inbound, dialing = node.sw.num_peers()
    peers = outbound + inbound
    pstatus = DEGRADED if peers < k["min_peers"] else OK
    checks["peers"] = {
        "status": pstatus, "peers": peers, "dialing": dialing,
        "min_peers": k["min_peers"],
    }
    status = _worst(status, pstatus)

    # -- device-plane breaker ----------------------------------------------
    from tendermint_tpu.ops import gateway

    br = gateway.devd_breaker().stats()
    bstatus = DEGRADED if br["breaker_state"] == 2 else OK
    # sharded device plane (round 21): any OPEN endpoint breaker means
    # the fleet runs at reduced capacity — degraded, alive. Reads only
    # breakers that EXIST (devd_breaker_states never instantiates), so a
    # single-socket node sees exactly the primary-breaker check above.
    ep_states = gateway.devd_breaker_states()
    ep_open = sum(1 for s in ep_states.values() if s == 2)
    if ep_open and len(ep_states) > 1:
        bstatus = _worst(bstatus, DEGRADED)
    checks["breaker"] = {"status": bstatus, "state": br["breaker_state"],
                         "opens": br["breaker_opens"],
                         "device_endpoints": len(ep_states),
                         "device_endpoints_open": ep_open}
    status = _worst(status, bstatus)

    # -- WAL flusher -------------------------------------------------------
    wal = cs.wal
    if wal is None:
        checks["wal"] = {"status": OK, "open": False}
    else:
        ws = wal.stats()
        wstatus = (
            DEGRADED
            if ws["pending"] > 0 and ws["sync_age_s"] > k["wal_sync_age_s"]
            else OK
        )
        checks["wal"] = {
            "status": wstatus, "open": True, "pending": ws["pending"],
            "sync_age_s": ws["sync_age_s"],
        }
        status = _worst(status, wstatus)

    # -- execution pipeline ------------------------------------------------
    poisoned = cs.pipeline_poisoned()
    checks["pipeline"] = {"status": FAILING if poisoned else OK,
                          "poisoned": poisoned}
    status = _worst(status, checks["pipeline"]["status"])

    # -- mempool backlog ---------------------------------------------------
    depth = node.mempool.size()
    mstatus = DEGRADED if depth >= k["mempool_degraded"] else OK
    checks["mempool"] = {"status": mstatus, "size": depth,
                         "degraded_at": k["mempool_degraded"]}
    status = _worst(status, mstatus)

    # flight recorder (round 17): every health evaluation — scrape,
    # probe, or the watchdog — feeds the verdict to the recorder, which
    # records CHANGES and auto-dumps the event ring exactly once per
    # transition into failing (node/flightrec.py)
    fr = getattr(node, "flightrec", None)
    if fr is not None:
        fr.note_health(status)

    return {
        "status": status,
        "code": _CODE[status],
        "time": time.time(),
        "checks": checks,
    }


def health_gauges(node) -> dict:
    """Flat numeric view for the telemetry registry (node_health_*
    families on both surfaces): the verdict, the liveness age, and how
    many checks sit at each severity."""
    report = health_report(node)
    checks = report["checks"].values()
    return {
        "status": report["code"],
        "height_age_s": report["checks"]["height_age"]["age_s"],
        "peers": report["checks"]["peers"]["peers"],
        "mempool_size": report["checks"]["mempool"]["size"],
        "checks_degraded": sum(1 for c in checks if c["status"] == DEGRADED),
        "checks_failing": sum(1 for c in checks if c["status"] == FAILING),
    }


# -- load-shed ladder (round 23, docs/serving.md) ---------------------------

PRESSURE_OK = 0
PRESSURE_SHED_READS = 1
PRESSURE_SHED_WRITES = 2
PRESSURE_NAMES = {PRESSURE_OK: "ok", PRESSURE_SHED_READS: "shed_reads",
                  PRESSURE_SHED_WRITES: "shed_writes"}


def _ladder_knobs() -> dict:
    """Read per call (live-tunable). The score is the max fill fraction
    across the pressure inputs; the rungs are fractions of saturation."""
    return {
        "shed_reads_at": float(
            env_number("TENDERMINT_OVERLOAD_SHED_READS_AT", 0.75)),
        "shed_writes_at": float(
            env_number("TENDERMINT_OVERLOAD_SHED_WRITES_AT", 0.90)),
        "apply_backlog_cap": int(
            env_number("TENDERMINT_OVERLOAD_APPLY_BACKLOG_CAP", 8, cast=int)),
    }


class OverloadMonitor:
    """ONE pressure signal for every ingress layer (the tentpole's
    ladder): folds mempool depth, RPC in-flight, WS send-queue depth and
    the apply-executor backlog into a saturation score, maps the score
    to a level (ok -> shed-reads -> shed-writes), and records a
    flight-recorder ``overload`` event on every level transition.

    Consulted per request by rpc/admission and per admit by the mempool,
    so the evaluation is cached for `ttl_s` — attribute reads only, but
    thousands of requests/s shouldn't each walk the WS registry.
    Consensus lanes (p2p vote/part channels, the apply executor) never
    consult it: the ladder sheds edge traffic, never the core."""

    def __init__(self, node, ttl_s: float = 0.25):
        self.node = node
        self.ttl_s = ttl_s
        self._mtx = None  # plain attrs; races only re-evaluate the cache
        self._cached_at = 0.0
        self._level = PRESSURE_OK
        self._score = 0.0
        self._inputs: dict = {}
        self.transitions = 0

    def level(self) -> int:
        now = time.monotonic()
        if now - self._cached_at >= self.ttl_s:
            self._evaluate(now)
        return self._level

    def snapshot(self) -> dict:
        """Flat view for the node_overload_* telemetry producer."""
        self.level()
        out = {"level": self._level, "score": round(self._score, 4),
               "transitions": self.transitions}
        for k, v in self._inputs.items():
            out[f"frac_{k}"] = round(v, 4)
        return out

    def _evaluate(self, now: float) -> None:
        k = _ladder_knobs()
        node = self.node
        inputs: dict[str, float] = {}

        mp = node.mempool
        cap = mp.pool_cap
        inputs["mempool"] = (mp.size() / cap) if cap else 0.0

        admission = getattr(node, "rpc_admission", None)
        if admission is not None:
            max_inflight = admission.max_inflight()
            inputs["rpc_inflight"] = (
                admission.inflight / max_inflight if max_inflight else 0.0)
            inputs["ws_queue"] = admission.ws_queue_frac()

        cs = node.consensus_state
        backlog = (len(cs._apply_executor._queue)
                   if cs._apply_executor is not None else 0)
        inputs["apply_backlog"] = min(
            1.0, backlog / max(1, k["apply_backlog_cap"]))

        score = max(inputs.values()) if inputs else 0.0
        if score >= k["shed_writes_at"]:
            level = PRESSURE_SHED_WRITES
        elif score >= k["shed_reads_at"]:
            level = PRESSURE_SHED_READS
        else:
            level = PRESSURE_OK
        prev = self._level
        self._score = score
        self._inputs = inputs
        self._level = level
        self._cached_at = now
        if level != prev:
            self.transitions += 1
            fr = getattr(node, "flightrec", None)
            if fr is not None:
                fr.record(
                    "overload",
                    level=PRESSURE_NAMES[level],
                    prev=PRESSURE_NAMES[prev],
                    score=round(score, 4),
                    **{f"frac_{k_}": round(v, 4) for k_, v in inputs.items()},
                )
