"""Node assembly (reference: node/node.go).

Wires the whole stack in the reference's order (node.go:113-307):
DBs -> block store -> state -> proxy app (started here, with ABCI
handshake, node.go:152-158) -> tx indexer -> event switch -> reactors
(blockchain, mempool, consensus) -> p2p switch (+ optional PEX) ->
on start: listener, dial seeds, RPC.

The TPU crypto gateway (ops.gateway) is constructed once here and shared
by every verification site — consensus vote verify, commit verify in
block execution, and fast-sync — so all hot-path signatures flow through
one batching point.
"""

from __future__ import annotations

import logging
import os

from tendermint_tpu.blockchain.reactor import BlockchainReactor
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.consensus.replay import Handshaker
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.libs.db import db_provider
from tendermint_tpu.libs.events import EventSwitch
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.mempool import Mempool
from tendermint_tpu.mempool.reactor import MempoolReactor
from tendermint_tpu.ops import gateway
from tendermint_tpu.types import tx as tx_types
from tendermint_tpu.p2p import NodeInfo, PeerConfig, Switch
from tendermint_tpu.p2p.addrbook import AddrBook
from tendermint_tpu.p2p.conn import MConnConfig
from tendermint_tpu.p2p.listener import Listener
from tendermint_tpu.p2p.node_info import default_version
from tendermint_tpu.p2p.pex import PEXReactor
from tendermint_tpu.proxy.client_creator import default_client_creator
from tendermint_tpu.proxy.multi_app_conn import AppConns
from tendermint_tpu.state.state import State
from tendermint_tpu.state.txindex import KVTxIndexer, NullTxIndexer
from tendermint_tpu.types import GenesisDoc, PrivValidatorFS
from tendermint_tpu.version import VERSION

logger = logging.getLogger("node")


def _parse_laddr(laddr: str) -> str:
    """'tcp://host:port' -> 'host:port'."""
    return laddr.split("://", 1)[-1]


class _FailoverRPC:
    """Spread the statesync light client's reads over every configured
    rpc_server: each call tries the servers in order and the first
    TRANSPORT-level success wins (a server that answers with bad data
    still fails verification upstream — failover is for dead endpoints,
    not lying ones)."""

    def __init__(self, clients: list):
        self._clients = clients

    def __getattr__(self, name):
        def call(**kw):
            last_exc = None
            for c in self._clients:
                try:
                    return getattr(c, name)(**kw)
                except Exception as exc:  # noqa: BLE001 — try the next server
                    last_exc = exc
            raise last_exc

        return call


def default_new_node(config) -> "Node":
    """node/node.go:74-110: load/generate privval, default app client."""
    priv_validator = PrivValidatorFS.load_or_generate(
        config.base.priv_validator_file()
    )
    return Node(
        config,
        priv_validator,
        default_client_creator(
            config.base.proxy_app, config.base.db_dir(), transport=config.base.abci
        ),
    )


class Node(BaseService):
    def __init__(self, config, priv_validator, client_creator, genesis_doc=None):
        super().__init__(name="node")
        self.config = config

        # -- DBs + genesis (node.go:121-146) ------------------------------
        backend = config.base.db_backend
        db_dir = config.base.db_dir()
        block_store_db = db_provider("blockstore", backend, db_dir)
        state_db = db_provider("state", backend, db_dir)
        self.block_store = BlockStore(block_store_db)
        if genesis_doc is None:
            genesis_doc = GenesisDoc.from_file(config.base.genesis_file())
        self.genesis_doc = genesis_doc
        self.priv_validator = priv_validator

        # -- TPU crypto gateway: one batching point for every verify site,
        # one hashing gateway for the part/tx Merkle hot paths. The tx-tree
        # hook routes every Data.hash (block build + validate) through the
        # batched kernel (ref types/tx.go:33-46).
        # [device] config feeds the endpoint list BEFORE the gateway
        # resolves its kernel (the verifier's devd detection and the
        # sharded dispatcher both read the env). The env var wins when
        # already set — it is the operator's per-process override.
        dev_cfg = getattr(config, "device", None)
        if dev_cfg is not None and dev_cfg.socks and \
                not os.environ.get("TENDERMINT_DEVD_SOCKS"):
            os.environ["TENDERMINT_DEVD_SOCKS"] = dev_cfg.socks
        from tendermint_tpu.ops import devd_shard

        if devd_shard.enabled():
            logger.info(
                "sharded device plane: %d devd endpoints (%s)",
                len(devd_shard.endpoint_paths()),
                ", ".join(devd_shard.endpoint_paths()),
            )
        self.verifier = gateway.default_verifier()
        self.hasher = gateway.default_hasher()
        tx_types.set_batch_tx_root(self.hasher.tx_merkle_root)
        # operator visibility at startup: which device plane this node
        # runs on, and (devd route) the breaker policy that governs its
        # degradation/recovery — the runtime state lives in the metrics
        # RPC (gateway_verify_breaker_* / gateway_hash_breaker_*)
        if self.verifier._kernel == "devd":
            br = gateway.devd_breaker()
            logger.info(
                "device plane: devd IPC (breaker: open after %d failures, "
                "probe backoff %.2gs..%.2gs)",
                br.threshold, br.base_backoff_s, br.max_backoff_s,
            )
        else:
            logger.info(
                "device plane: %s",
                self.verifier._kernel or "cpu (native batch verify)",
            )
        # the host durability plane's policy, stated next to the device
        # plane's: what a power failure can cost (runtime state lives in
        # the metrics RPC wal_* rows; docs/crash-recovery.md)
        cc = config.consensus
        if getattr(cc, "wal_sync_every_write", False):
            logger.info("host durability plane: WAL fsync per record")
        else:
            logger.info(
                "host durability plane: WAL group commit (flush interval "
                "%.3gs, sync on #ENDHEIGHT; repair-on-open)",
                getattr(cc, "wal_flush_interval_s", 0.1),
            )
        # warm the native marshal/verify library off the hot path: the
        # gateway's CPU fallback only uses it when ready() (never builds
        # inline), so trigger the build/load here in the background
        import threading as _threading

        from tendermint_tpu import native as _native

        _threading.Thread(
            target=_native.available, daemon=True, name="native.warm"
        ).start()

        # -- tx index (node.go:164-176) -----------------------------------
        if config.base.tx_index == "kv":
            tx_indexer = KVTxIndexer(db_provider("tx_index", backend, db_dir))
        else:
            tx_indexer = NullTxIndexer()
        self.tx_indexer = tx_indexer

        # -- state --------------------------------------------------------
        state = State.get_state(state_db, genesis_doc)
        state.tx_indexer = tx_indexer

        # -- proxy app, started now with handshake so state/store/app are
        # in sync before anything else wires up (node.go:152-158) ---------
        self.proxy_app = AppConns(client_creator, Handshaker(state, self.block_store))
        self.proxy_app.start()

        # -- event switch (node.go:182-185) -------------------------------
        self.evsw = EventSwitch()

        # -- decide fast sync (node.go:188-196: skip if we're the sole
        # validator — we'd wait forever for peers) ------------------------
        fast_sync = config.base.fast_sync
        if state.validators.size() == 1 and priv_validator is not None:
            _addr, val = state.validators.get_by_index(0)
            if val.address == priv_validator.get_address():
                fast_sync = False
        self.fast_sync = fast_sync

        # -- mempool (node.go:206-212). A local app that publishes a tx
        # signature parser (e.g. apps/signedkv.py) gets the batched
        # signature gate: CheckTx bursts verify through the TPU gateway
        # BEFORE app dispatch (BASELINE config 5; the reference app
        # verifies per-tx on CPU, mempool/mempool.go:166-205) ------------
        # -- round-17 debugging substrate: one tx-lifecycle recorder
        # (libs/txtrace.py) stamped by mempool + reactor + consensus,
        # and one black-box flight recorder (node/flightrec.py) fed by
        # consensus/p2p/health — both constructed before the subsystems
        # that stamp them
        from tendermint_tpu.libs.txtrace import TxTraceRecorder
        from tendermint_tpu.node.flightrec import FlightRecorder

        self.txtrace = TxTraceRecorder()
        self.flightrec = FlightRecorder(home=config.base.root_dir)

        sig_batcher = None
        local_app = getattr(client_creator, "app", None)
        # round 13: apps with an authenticated state tree route their
        # commit-time dirty-node hashing through the gateway hash plane
        # (streamed devd when a daemon serves, CPU behind the breaker)
        app_tree = getattr(local_app, "tree", None)
        if app_tree is not None and hasattr(app_tree, "hasher"):
            app_tree.hasher = self.hasher
        # kept for telemetry (statetree_* gauges, scrape-only). The app
        # is what's held, not the tree instance: a full-snapshot restore
        # REBINDS app.tree to a fresh VersionedTree, and gauges pinned
        # to the old instance would freeze forever
        self.app_state_tree_app = local_app if app_tree is not None else None
        tx_parser = getattr(local_app, "tx_sig_parser", None)
        if tx_parser is not None:
            from tendermint_tpu.mempool.mempool import SigBatcher

            # the gate replaces the app's own CheckTx verification
            if hasattr(local_app, "verify_in_app"):
                local_app.verify_in_app = False
            sig_batcher = SigBatcher(self.verifier, tx_parser)
        self.mempool = Mempool(
            config.mempool, self.proxy_app.mempool(), sig_batcher=sig_batcher
        )
        self.mempool.txtrace = self.txtrace
        self.mempool.init_wal()
        self.mempool_reactor = MempoolReactor(config.mempool, self.mempool)

        # -- statesync (round 10, docs/state-sync.md): snapshot store is
        # always constructed (serving is free); the producer hooks the
        # post-apply point when an interval is configured and the local
        # app supports snapshots; restore mode arms when enabled on a
        # node that is still at genesis with an empty block store -------
        from tendermint_tpu.statesync import SnapshotProducer, SnapshotStore

        sc = config.statesync
        self.snapshot_store = SnapshotStore(sc.snapshot_dir())
        from tendermint_tpu.abci.types import Application

        self.snapshot_producer = None
        if sc.snapshot_interval > 0:
            # support probe by method identity — actually CALLING
            # snapshot() here would serialize the app's whole committed
            # state at node construction just to throw it away
            if local_app is not None and type(local_app).snapshot is not Application.snapshot:
                self.snapshot_producer = SnapshotProducer(
                    self.snapshot_store,
                    local_app,
                    self.block_store,
                    hasher=self.hasher,
                    interval=sc.snapshot_interval,
                    keep_recent=sc.snapshot_keep_recent,
                    chunk_size=sc.chunk_size,
                    full_every=sc.snapshot_full_every,
                )
            else:
                logger.warning(
                    "statesync.snapshot_interval=%d but app %s has no "
                    "snapshot support; producer disabled",
                    sc.snapshot_interval, config.base.proxy_app,
                )
        statesync_restore = (
            sc.enable
            and self.block_store.height() == 0
            and state.last_block_height == 0
        )
        if sc.enable and not statesync_restore:
            logger.info(
                "statesync enabled but node already has a chain "
                "(store height %d); using fast sync", self.block_store.height(),
            )

        # kept for statesync wiring: the runtime horizon fallback
        # (below-horizon laggard -> statesync, round 19) rebuilds a
        # Restorer with exactly what _make_restorer needs
        self._local_app = local_app
        self._state_db = state_db

        # -- consensus ----------------------------------------------------
        self.consensus_state = ConsensusState(
            config.consensus,
            state.copy(),
            self.proxy_app.consensus(),
            self.block_store,
            self.mempool,
            verifier=self.verifier,
        )
        if priv_validator is not None:
            self.consensus_state.set_priv_validator(priv_validator)
        self.consensus_state.txtrace = self.txtrace
        self.consensus_state.flightrec = self.flightrec
        self.consensus_state.set_event_switch(self.evsw)

        # -- retention coordinator (round 19, docs/state-sync.md §
        # Retention): [pruning] arms automatic block-store + WAL pruning
        # on the apply executor's tail, AFTER the snapshot producer in
        # the hook chain so a snapshot published at H is on disk before
        # the prune computes its snapshot floor. Constructed always
        # (stable pruning_* metric family); inert when retain_blocks=0.
        from tendermint_tpu.node.retention import RetentionCoordinator

        self.retention = RetentionCoordinator(
            config.pruning,
            self.block_store,
            snapshot_store=self.snapshot_store,
            wal_fn=lambda: self.consensus_state.wal,
            evidence_pool=self.consensus_state.evidence_pool,
            tree_app=self.app_state_tree_app,
            tx_indexer=self.tx_indexer,
            db_dir=config.base.db_dir(),
            wal_dir=os.path.dirname(config.consensus.wal_file()),
            snapshot_dir=sc.snapshot_dir(),
        )
        post_apply_hook = self._compose_post_apply_hooks()
        if post_apply_hook is not None:
            self.consensus_state.post_apply_hook = post_apply_hook
        self.consensus_reactor = ConsensusReactor(self.consensus_state, fast_sync)
        self.consensus_reactor.set_event_switch(self.evsw)

        # -- blockchain (fast sync) reactor -------------------------------
        self.blockchain_reactor = BlockchainReactor(
            state.copy(),
            self.proxy_app.consensus(),
            self.block_store,
            fast_sync,
            event_cache=None,
            batch_verifier=self.verifier.commit_batch_verifier(),
            async_batch_verifier=self.verifier.verify_batch_async,
            part_hasher=self.hasher.part_leaf_hashes,
            part_tree_hasher=self.hasher.part_set_tree,
            post_apply_hook=post_apply_hook,
            defer_for_statesync=statesync_restore,
            evidence_pool=self.consensus_state.evidence_pool,
        )

        # -- statesync reactor: always serves local snapshots; in restore
        # mode it also drives discovery -> light-verified restore -> the
        # fast-sync handoff (start_after_statesync picks up the tail) ----
        from tendermint_tpu.statesync.reactor import StateSyncReactor

        restorer = None
        if statesync_restore:
            restorer = self._make_restorer(sc, local_app, genesis_doc, state_db)
            statesync_restore = restorer is not None
            if not statesync_restore:
                # misconfigured restore must not strand the node: fall
                # back to plain fast sync (the reactor stays serve-only)
                self.blockchain_reactor.start_after_statesync(None)
        self.statesync_reactor = StateSyncReactor(
            self.snapshot_store,
            restorer=restorer,
            enabled=statesync_restore,
            on_complete=self._on_statesync_complete,
        )
        if statesync_restore:
            logger.info(
                "statesync: restore armed (light verify via %s, trust height %d)",
                sc.rpc_servers or "genesis", sc.trust_height,
            )
        # horizon-aware catchup (round 19): a fast-syncing node whose
        # next height EVERY peer has pruned switches to statesync at
        # runtime instead of spinning on no_block_response forever
        self.blockchain_reactor.horizon_fallback = self._on_below_horizon

        # -- p2p switch (node.go:231-245) ---------------------------------
        peer_config = PeerConfig(
            mconfig=MConnConfig(
                send_rate=float(config.p2p.send_rate),
                recv_rate=float(config.p2p.recv_rate),
                flush_throttle=config.p2p.flush_throttle_timeout,
            )
        )
        self.sw = Switch(config.p2p, peer_config)
        self.sw.flightrec = self.flightrec
        self.blockchain_reactor.flightrec = self.flightrec
        self.sw.add_reactor("MEMPOOL", self.mempool_reactor)
        self.sw.add_reactor("BLOCKCHAIN", self.blockchain_reactor)
        self.sw.add_reactor("CONSENSUS", self.consensus_reactor)
        self.sw.add_reactor("STATESYNC", self.statesync_reactor)

        self.addr_book = AddrBook(
            config.p2p.addr_book(), config.p2p.addr_book_strict
        )
        if config.p2p.pex_reactor:
            # dial-cadence knob for harness tiers (ops/localnet pex_churn
            # runs whole discovery→dial→evict cycles in seconds; the 30s
            # production default would make that scenario minutes long)
            from tendermint_tpu.libs.envknob import env_number
            from tendermint_tpu.p2p.pex import DEFAULT_ENSURE_PEERS_PERIOD
            self.pex_reactor = PEXReactor(
                self.addr_book,
                ensure_peers_period=float(env_number(
                    "TENDERMINT_PEX_ENSURE_PERIOD_S",
                    DEFAULT_ENSURE_PEERS_PERIOD,
                )),
            )
            self.sw.add_reactor("PEX", self.pex_reactor)
        else:
            self.pex_reactor = None

        # -- ABCI-query-backed peer filters (node.go:250-272) -------------
        if config.base.filter_peers:
            def filter_addr(addr):
                res = self.proxy_app.query().query_sync(
                    data=b"", path=f"/p2p/filter/addr/{addr}"
                )
                if not res.is_ok:
                    raise ConnectionError(f"filtered addr {addr}: {res.log}")

            def filter_pubkey(pubkey):
                res = self.proxy_app.query().query_sync(
                    data=b"", path=f"/p2p/filter/pubkey/{pubkey.raw.hex()}"
                )
                if not res.is_ok:
                    raise ConnectionError(f"filtered pubkey: {res.log}")

            self.sw.filter_conn_by_addr = filter_addr
            self.sw.filter_conn_by_pubkey = filter_pubkey

        self.state = state
        self.listener: Listener | None = None
        self.rpc_server = None
        self.grpc_server = None

        # -- overload-control plane (round 23, docs/serving.md): one
        # ingress admission controller shared with the RPC server (its
        # counters feed telemetry), one pressure monitor feeding the
        # load-shed ladder to both the RPC edge and the mempool's lane
        # admission. Consensus paths never consult either.
        from tendermint_tpu.node.health import OverloadMonitor
        from tendermint_tpu.rpc.admission import AdmissionController

        self.rpc_admission = AdmissionController(config.rpc)
        self.overload = OverloadMonitor(self)
        self.rpc_admission.pressure_fn = self.overload.level
        self.mempool.pressure_fn = self.overload.level

        # -- telemetry plane (round 11): one registry wires every
        # subsystem's gauges + the process-wide instrument set; the
        # metrics RPC renders its flat legacy dict and GET /metrics its
        # Prometheus text (node/telemetry.py is the canonical naming map)
        from tendermint_tpu.node.telemetry import build_registry

        self.telemetry = build_registry(self)

        # flight-dump counter snapshot: the p2p gossip totals (picks vs
        # sends vs failures vs duplicates — the wedge signature) and the
        # consensus position ride every dump, so a wedge is triaged
        # from the artifact alone (node/flightrec.py)
        from tendermint_tpu.p2p import telemetry as p2p_telemetry

        def _flight_counters() -> dict:
            rs = self.consensus_state.rs
            out = {
                "height": rs.height,
                "round": rs.round_,
                "step": int(rs.step),
                "vote_duplicates": self.consensus_state.vote_duplicates,
                "peer_msg_drops": self.consensus_state.peer_msg_drops,
            }
            out.update(p2p_telemetry.family_totals(self.telemetry))
            return out

        self.flightrec.counters_fn = _flight_counters

    # -- retention wiring --------------------------------------------------

    def _compose_post_apply_hooks(self):
        """The apply executor's tail chain: snapshot producer first (a
        snapshot at H must publish before retention reads its floor),
        then the retention coordinator. Each link keeps its own
        never-raises contract; the composition preserves it. Returns
        None when neither is armed (the pre-hook fast path)."""
        hooks = []
        if self.snapshot_producer is not None:
            hooks.append(self.snapshot_producer.maybe_snapshot)
        if self.retention.enabled:
            hooks.append(self.retention.maybe_prune)
        if not hooks:
            return None
        if len(hooks) == 1:
            return hooks[0]

        def chained(state, block=None):
            for hook in hooks:
                hook(state, block)

        return chained

    # -- statesync wiring --------------------------------------------------

    def _on_below_horizon(self, horizon: int) -> bool:
        """Blockchain-reactor fallback (round 19): fast sync proved the
        network pruned past our target. Arm a runtime statesync restore
        when this node can actually take one — a fresh node (empty store,
        app at 0) with light-client endpoints configured. Returns True
        when statesync was armed (the reactor then stops its pool)."""
        if self.statesync_reactor.restore_active:
            return False
        if self.block_store.height() != 0 or self.state.last_block_height != 0:
            logger.error(
                "node is below the network's retained horizon (%d) but "
                "already holds a chain at height %d — cannot statesync in "
                "place; wipe the home and restart with statesync, or find "
                "an archive peer", horizon, self.block_store.height(),
            )
            return False
        restorer = self._make_restorer(
            self.config.statesync, self._local_app, self.genesis_doc,
            self._state_db,
        )
        if restorer is None:
            logger.error(
                "node is below the network's retained horizon (%d) and "
                "statesync cannot arm (no in-process app or no "
                "statesync.rpc_servers configured) — fast sync will keep "
                "retrying but cannot converge", horizon,
            )
            return False
        armed = self.statesync_reactor.arm_restore(restorer)
        if armed:
            logger.warning(
                "auto-switching to statesync: network retains only "
                "heights >= %d", horizon,
            )
        return armed

    def _make_restorer(self, sc, local_app, genesis_doc, state_db):
        """Build the restore-side Restorer, or None (with a logged
        reason) when the configuration cannot support a restore."""
        from tendermint_tpu.statesync import Restorer

        if local_app is None:
            logger.warning(
                "statesync restore needs an in-process app (got %s); "
                "falling back to fast sync", self.config.base.proxy_app,
            )
            return None
        servers = [s.strip() for s in sc.rpc_servers.split(",") if s.strip()]
        if not servers:
            logger.warning(
                "statesync.enable without statesync.rpc_servers; the light "
                "client has nothing to verify against — falling back to "
                "fast sync",
            )
            return None
        from tendermint_tpu.rpc.client import HTTPClient
        from tendermint_tpu.rpc.light import LightClient
        from tendermint_tpu.types.validator import Validator
        from tendermint_tpu.types.validator_set import ValidatorSet

        vs = ValidatorSet(
            [Validator.new(v.pub_key, v.power) for v in genesis_doc.validators]
        )
        trust_height = sc.trust_height
        trusted_header = None
        # round 20: resume from the deepest trust this home ever verified
        # — a prior restore's persisted anchor beats the configured pin
        # (never the other way: an operator pin ABOVE the anchor wins)
        from tendermint_tpu.node.light_anchor import load_anchor

        anchor = load_anchor(self.config.base.root_dir, genesis_doc.chain_id)
        if anchor is not None and anchor[0] > trust_height:
            trust_height, vs, trusted_header = anchor
            logger.info(
                "light client resuming from persisted trust anchor at "
                "height %d", trust_height,
            )
        clients = [HTTPClient(s) for s in servers]
        light_client = LightClient(
            clients[0] if len(clients) == 1 else _FailoverRPC(clients),
            genesis_doc.chain_id,
            vs,
            trusted_height=trust_height,
            batch_verifier=self.verifier.commit_batch_verifier(),
        )
        light_client._trusted_header = trusted_header
        return Restorer(
            genesis_doc,
            local_app,
            state_db,
            self.block_store,
            hasher=self.hasher,
            light_client=light_client,
            batch_verifier=self.verifier.commit_batch_verifier(),
        )

    def _on_statesync_complete(self, restored_state) -> None:
        """Restore finished (or fell back with None): adopt the restored
        state everywhere that cached a genesis-height copy, then hand the
        tail to fast sync."""
        if restored_state is not None:
            # the consensus state keeps waiting in fast-sync mode: the
            # eventual switch_to_consensus (from the blockchain reactor)
            # seeds it with the fast-synced state, which now starts at
            # the restored height
            self.state = restored_state
            # round 20: the restorer's adopted walker holds the deepest
            # verified trust this home has ever reached — persist it so
            # a wipe-and-restore restart resumes there instead of
            # re-walking (and re-trusting) from the configured pin
            from tendermint_tpu.node.light_anchor import save_anchor

            restorer = getattr(self.statesync_reactor, "restorer", None)
            lc = getattr(restorer, "light_client", None)
            if lc is not None and save_anchor(self.config.base.root_dir, lc):
                logger.info(
                    "persisted light-client trust anchor at height %d",
                    lc.height,
                )
            logger.info(
                "statesync restore complete at height %d; fast-syncing the tail",
                restored_state.last_block_height,
            )
        self.blockchain_reactor.start_after_statesync(restored_state)

    # -- lifecycle (node.go:310-352) --------------------------------------

    def on_start(self) -> None:
        self.evsw.start()

        # p2p listener
        if self.config.p2p.laddr:
            self.listener = Listener(
                _parse_laddr(self.config.p2p.laddr),
                skip_upnp=self.config.p2p.skip_upnp,
            )
            self.sw.add_listener(self.listener)

        info = NodeInfo(
            pub_key=self.sw.node_priv_key.pub_key(),
            moniker=self.config.base.moniker,
            network=self.genesis_doc.chain_id,
            version=default_version(VERSION),
            listen_addr=(
                str(self.listener.external_address()) if self.listener else ""
            ),
            other=[
                "consensus_version=v1",
                f"rpc_addr={self.config.rpc.laddr}",
                # round 18: the genesis commit-format flag rides the
                # handshake so mixed-format nets refuse loudly at
                # peering (NodeInfo.compatible_with); round 22 adds the
                # full upgrade SCHEDULE — nodes disagreeing on the flip
                # height refuse here, never wedge at decode
                # (docs/upgrade.md)
                f"commit_format={self.genesis_doc.commit_format}",
                f"commit_schedule={self.genesis_doc.schedule_string()}",
            ],
        )
        self.sw.set_node_info(info)
        if self.listener:
            self.addr_book.add_our_address(self.listener.external_address())
        self.sw.start()

        if self.config.p2p.seeds:
            seeds = [s.strip() for s in self.config.p2p.seeds.split(",") if s.strip()]
            self.sw.dial_seeds(seeds, self.addr_book if self.pex_reactor else None)

        if self.config.rpc.laddr:
            self._start_rpc()
        if self.config.rpc.grpc_laddr:
            self._start_grpc()

        # flight-recorder trigger scan: breaker transitions, the health
        # verdict (the failing-transition auto-dump fires even when
        # nothing scrapes), the height-age wedge dump
        self.flightrec.start_watchdog(self)

    def on_stop(self) -> None:
        self.flightrec.stop_watchdog()
        if self.grpc_server is not None:
            self.grpc_server.stop()
        if self.rpc_server is not None:
            self.rpc_server.stop()
        self.sw.stop()
        if self.mempool.sig_batcher is not None:
            self.mempool.sig_batcher.stop()
        self.mempool.close_wal()
        self.proxy_app.stop()
        self.evsw.stop()

    def _rpc_context(self):
        from tendermint_tpu.rpc.core.pipe import RPCContext

        return RPCContext(
            event_switch=self.evsw,
            block_store=self.block_store,
            consensus_state=self.consensus_state,
            mempool=self.mempool,
            switch=self.sw,
            proxy_app_query=self.proxy_app.query(),
            genesis_doc=self.genesis_doc,
            priv_validator=self.priv_validator,
            tx_indexer=self.tx_indexer,
            state=self.state,
            node=self,
        )

    def _start_rpc(self) -> None:
        from tendermint_tpu.rpc.server import RPCServer

        self.rpc_server = RPCServer(
            _parse_laddr(self.config.rpc.laddr),
            self._rpc_context(),
            unsafe=self.config.rpc.unsafe,
        )
        self.rpc_server.start()

    def _start_grpc(self) -> None:
        """BroadcastAPI port (rpc/grpc/api.go:14; node wiring
        node.go:341-345)."""
        from tendermint_tpu.rpc.grpc import GRPCBroadcastServer

        self.grpc_server = GRPCBroadcastServer(
            _parse_laddr(self.config.rpc.grpc_laddr), self._rpc_context()
        )
        self.grpc_server.start()

    # -- introspection ------------------------------------------------------

    def rpc_port(self) -> int:
        assert self.rpc_server is not None
        return self.rpc_server.port
