"""Command-line interface (reference: cmd/tendermint/main.go:14-37 +
cmd/tendermint/commands/*).

Commands: init, node, replica, testnet, gen_validator, show_validator,
reset_all, reset_priv_validator, replay, replay_console, version.
`--home` picks the node root (config.toml + genesis + privval + data).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import time


def _load_config(home: str):
    from tendermint_tpu.config import ensure_root, load_config

    ensure_root(home)
    return load_config(home)


# -- commands -----------------------------------------------------------------


def cmd_init(args) -> int:
    """commands/init.go:19-43: privval + genesis + config.toml."""
    from tendermint_tpu.config import ensure_root
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidatorFS

    cfg = ensure_root(args.home)
    pv_file = cfg.base.priv_validator_file()
    if os.path.exists(pv_file):
        pv = PrivValidatorFS.load(pv_file)
        print(f"Found private validator: {pv_file}")
    else:
        pv = PrivValidatorFS.generate(pv_file)
        pv.save()
        print(f"Generated private validator: {pv_file}")
    gen_file = cfg.base.genesis_file()
    if os.path.exists(gen_file):
        print(f"Found genesis file: {gen_file}")
    else:
        doc = GenesisDoc(
            genesis_time_ns=time.time_ns(),
            chain_id=args.chain_id or f"test-chain-{os.urandom(3).hex()}",
            validators=[GenesisValidator(pv.get_pub_key(), 10, "")],
        )
        doc.save_as(gen_file)
        print(f"Generated genesis file: {gen_file}")
    return 0


def cmd_node(args) -> int:
    """commands/run_node.go."""
    import logging

    logging.basicConfig(
        level=getattr(logging, (args.log_level or "info").upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    cfg = _load_config(args.home)
    for attr in ("proxy_app", "moniker", "fast_sync"):
        v = getattr(args, attr, None)
        if v is not None:
            setattr(cfg.base, attr, v)
    if args.db_backend:
        cfg.base.db_backend = args.db_backend
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.grpc_laddr:
        cfg.rpc.grpc_laddr = args.grpc_laddr
    if args.rpc_unsafe:
        cfg.rpc.unsafe = True
    if args.seeds:
        cfg.p2p.seeds = args.seeds
    if args.pex:
        cfg.p2p.pex_reactor = True
    if args.addr_book_strict is not None:
        cfg.p2p.addr_book_strict = args.addr_book_strict == "true"

    # TENDERMINT_RACECHECK=1 == running the reference under `go test -race`:
    # every lock the node builds joins a process-wide order graph, reported
    # at shutdown (libs/racecheck.py). Install BEFORE node construction so
    # the reactors' locks are in scope.
    race_mon = None
    if os.environ.get("TENDERMINT_RACECHECK", "") == "1":
        from tendermint_tpu.libs import racecheck

        race_mon = racecheck.install()

    from tendermint_tpu.node import default_new_node

    node = default_new_node(cfg)
    node.start()
    print(f"Started node: moniker={cfg.base.moniker} rpc_port={node.rpc_port()}")

    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.5)
    finally:
        node.stop()
        if race_mon is not None:
            print(race_mon.report())
    return 0


def cmd_replica(args) -> int:
    """Run a verified read replica (round 24, docs/serving.md § Read
    replicas): follows --upstream with a light client and serves the
    read RPC surface from a proof-carrying cache."""
    import logging

    logging.basicConfig(
        level=getattr(logging, (args.log_level or "info").upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    cfg = _load_config(args.home)
    if args.upstream:
        cfg.replica.upstream = args.upstream
    if args.rpc_laddr:
        cfg.replica.laddr = args.rpc_laddr
    if args.max_lag_heights is not None:
        cfg.replica.max_lag_heights = args.max_lag_heights

    from tendermint_tpu.replica import ReplicaDaemon

    daemon = ReplicaDaemon(cfg)
    daemon.start()
    print(
        f"Started replica: upstream={cfg.replica.upstream} "
        f"rpc_port={daemon.rpc_port}"
    )

    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.5)
    finally:
        daemon.stop()
    return 0


def cmd_testnet(args) -> int:
    """commands/testnet.go:36-70: N validator dirs + shared genesis."""
    from tendermint_tpu.config import ensure_root
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidatorFS

    n = args.n
    gen_vals = []
    pvs = []
    for i in range(n):
        home = os.path.join(args.dir, f"mach{i}")
        cfg = ensure_root(home)
        pv = PrivValidatorFS.load_or_generate(cfg.base.priv_validator_file())
        pvs.append((home, pv, cfg))
        gen_vals.append(GenesisValidator(pv.get_pub_key(), 1, f"mach{i}"))
    doc = GenesisDoc(
        genesis_time_ns=time.time_ns(),
        chain_id=args.chain_id or "chain-" + os.urandom(3).hex(),
        validators=gen_vals,
    )
    for home, _pv, cfg in pvs:
        doc.save_as(cfg.base.genesis_file())
    print(f"Successfully initialized {n} node directories in {args.dir}")
    return 0


def cmd_gen_validator(args) -> int:
    from tendermint_tpu.types import PrivValidatorFS

    pv = PrivValidatorFS.generate(None)
    print(json.dumps(pv.to_json(), indent=2))
    return 0


def cmd_show_validator(args) -> int:
    from tendermint_tpu.config import ensure_root
    from tendermint_tpu.types import PrivValidatorFS

    cfg = ensure_root(args.home)
    pv = PrivValidatorFS.load_or_generate(cfg.base.priv_validator_file())
    print(json.dumps(pv.get_pub_key().to_json()))
    return 0


def cmd_reset_priv_validator(args) -> int:
    """commands/reset_priv_validator.go: DANGEROUS — signing state reset."""
    from tendermint_tpu.config import ensure_root
    from tendermint_tpu.types import PrivValidatorFS

    cfg = ensure_root(args.home)
    pv_file = cfg.base.priv_validator_file()
    if os.path.exists(pv_file):
        pv = PrivValidatorFS.load(pv_file)
        pv.reset()
        print(f"Reset private validator signing state: {pv_file}")
    else:
        PrivValidatorFS.generate(pv_file)
        print(f"Generated private validator: {pv_file}")
    return 0


def cmd_reset_all(args) -> int:
    """commands/reset_priv_validator.go ResetAll: wipe data/ + signing state."""
    from tendermint_tpu.config import ensure_root

    cfg = ensure_root(args.home)
    data_dir = cfg.base.db_dir()
    if os.path.isdir(data_dir):
        shutil.rmtree(data_dir, ignore_errors=True)
        os.makedirs(data_dir, exist_ok=True)
        print(f"Removed all data: {data_dir}")
    return cmd_reset_priv_validator(args)


def cmd_replay(args, console: bool = False) -> int:
    """commands/replay.go -> consensus/replay_file.go."""
    from tendermint_tpu.consensus.replay_file import run_replay_file

    cfg = _load_config(args.home)
    run_replay_file(cfg, console=console)
    return 0


def cmd_version(args) -> int:
    from tendermint_tpu.version import VERSION

    print(VERSION)
    return 0


def cmd_probe_upnp(args) -> int:
    """Probe the local network for UPnP port-mapping support
    (cmd/tendermint/main.go:29, p2p/upnp/probe.go)."""
    import json as _json

    from tendermint_tpu.p2p import upnp

    try:
        caps = upnp.probe()
        print(_json.dumps({"port_mapping": caps.port_mapping, "hairpin": caps.hairpin}))
        return 0
    except Exception as exc:  # noqa: BLE001 — a probe never tracebacks
        print(_json.dumps({"error": str(exc)}))
        return 1


# -- parser -------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tendermint-tpu",
        description="TPU-native BFT state-machine replication node",
    )
    p.add_argument(
        "--home",
        default=os.environ.get("TMHOME", os.path.expanduser("~/.tendermint_tpu")),
        help="node root directory",
    )
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("init", help="initialize a node (privval + genesis)")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("node", help="run the node")
    sp.add_argument("--proxy_app", default=None, help="app address or name (kvstore, signedkv, counter, nilapp, tcp://...)")
    sp.add_argument("--moniker", default=None)
    sp.add_argument("--fast_sync", action="store_true", default=None)
    sp.add_argument("--p2p.laddr", dest="p2p_laddr", default=None)
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default=None)
    sp.add_argument("--rpc.grpc_laddr", dest="grpc_laddr", default=None)
    sp.add_argument(
        "--rpc.unsafe", dest="rpc_unsafe", action="store_true",
        help="enable unsafe RPC routes (profiler, dial_seeds, flush "
        "mempool — rpc/core/routes.go:37-46 equivalent)",
    )
    sp.add_argument("--seeds", default=None, help="comma-separated host:port")
    sp.add_argument("--pex", action="store_true")
    sp.add_argument(
        "--p2p.addr_book_strict",
        dest="addr_book_strict",
        default=None,
        choices=["true", "false"],
        help="only store globally-routable peer addresses (turn off for "
        "loopback testnets; p2p/addrbook.py routability)",
    )
    sp.add_argument("--log_level", default="info")
    sp.add_argument("--db_backend", default=None, help="sqlite | filedb | memdb")
    sp.set_defaults(fn=cmd_node)

    sp = sub.add_parser(
        "replica",
        help="run a verified read replica following an upstream node "
        "(docs/serving.md § Read replicas)",
    )
    sp.add_argument(
        "--upstream", default=None,
        help="upstream RPC address (host:port) — a node, or another replica",
    )
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default=None)
    sp.add_argument(
        "--max_lag_heights", type=int, default=None,
        help="bounded staleness: refuse latest-reads when the verified "
        "view lags upstream by more than this many heights",
    )
    sp.add_argument("--log_level", default="info")
    sp.set_defaults(fn=cmd_replica)

    sp = sub.add_parser("testnet", help="initialize files for an N-node testnet")
    sp.add_argument("--n", type=int, default=4)
    sp.add_argument("--dir", default="mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_testnet)

    sub.add_parser("gen_validator", help="generate a new validator keypair").set_defaults(
        fn=cmd_gen_validator
    )
    sub.add_parser("show_validator", help="show this node's validator pubkey").set_defaults(
        fn=cmd_show_validator
    )
    sub.add_parser(
        "reset_priv_validator", help="reset the validator signing state (DANGEROUS)"
    ).set_defaults(fn=cmd_reset_priv_validator)
    sub.add_parser(
        "reset_all", help="wipe blockchain data and signing state (DANGEROUS)"
    ).set_defaults(fn=cmd_reset_all)
    sub.add_parser("replay", help="replay the consensus WAL against a fresh state").set_defaults(
        fn=lambda a: cmd_replay(a, console=False)
    )
    sub.add_parser("replay_console", help="interactive WAL replay").set_defaults(
        fn=lambda a: cmd_replay(a, console=True)
    )
    sub.add_parser("version", help="print the version").set_defaults(fn=cmd_version)
    sub.add_parser(
        "probe_upnp", help="probe the network for UPnP port-mapping support"
    ).set_defaults(fn=cmd_probe_upnp)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
