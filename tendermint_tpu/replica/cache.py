"""Proof-carrying read cache (round 24, docs/serving.md § Read replicas).

Entries are keyed ``(path, key, height)`` and hold an upstream
``/abci_query`` response TOGETHER with its statetree proof — verified by
the daemon against a light-verified header BEFORE insertion, so nothing
unproven is ever served. The cache itself is dumb storage plus
invalidation bookkeeping; all verification lives in the daemon.

Invalidation: each new verified block reports its txs through
``note_block``. In the default ``keys`` mode the kvstore wire format
(``key=value``, or the bare tx as its own key) is parsed and only the
touched keys lose their serve-latest eligibility; ``all`` mode
(``TENDERMINT_REPLICA_INVALIDATE=all``, for apps with opaque txs whose
write sets a replica cannot parse) invalidates every key on any
non-empty block. Either way the entries themselves stay — a
height-pinned query can still serve an old proof; only "give me the
latest" reads consult the touch log. Under-invalidation in ``keys``
mode against a non-kvstore app is bounded by the daemon's
``max_lag_heights`` staleness window, never unbounded.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from tendermint_tpu.libs.envknob import env_str


class ProofCache:
    """LRU over (path, key_hex, height) -> verified response entries."""

    def __init__(self, max_entries: int = 10_000):
        self.max_entries = max(1, int(max_entries))
        self._mtx = threading.Lock()
        self._entries: OrderedDict[tuple[str, str, int], dict] = OrderedDict()
        # (path, key) -> newest cached proof height for that key
        self._latest: dict[tuple[str, str], int] = {}
        # key -> last block height that wrote it (keys mode)
        self._touched: dict[str, int] = {}
        # last block height that invalidated EVERYTHING (all mode)
        self._touched_all_at = 0
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    @staticmethod
    def _mode() -> str:
        return env_str("TENDERMINT_REPLICA_INVALIDATE", "keys",
                       allowed=("keys", "all"))

    # -- reads ------------------------------------------------------------

    def get(self, path: str, key_hex: str, height: int) -> dict | None:
        """The exact entry proven at `height`, or None."""
        k = (path, key_hex.lower(), int(height))
        with self._mtx:
            ent = self._entries.get(k)
            if ent is None:
                self.misses += 1
                return None
            self._entries.move_to_end(k)
            self.hits += 1
            return ent

    def get_latest(self, path: str, key_hex: str, floor: int) -> dict | None:
        """The newest cached entry for (path, key) that is still a valid
        answer for "the latest value": proven at or above `floor` (the
        staleness window) AND not overwritten by any verified block since
        its proof height. None = the daemon must refetch."""
        key_hex = key_hex.lower()
        with self._mtx:
            h = self._latest.get((path, key_hex))
            if h is None or h < floor:
                self.misses += 1
                return None
            if max(self._touched.get(key_hex, 0), self._touched_all_at) > h:
                # the key changed after this proof's height: a fresh
                # proof exists upstream and serving this one would be a
                # stale read beyond the invalidation contract
                self.misses += 1
                return None
            ent = self._entries.get((path, key_hex, h))
            if ent is None:  # evicted by LRU under the _latest pointer
                self.misses += 1
                return None
            self._entries.move_to_end((path, key_hex, h))
            self.hits += 1
            return ent

    # -- writes -----------------------------------------------------------

    def put(self, path: str, key_hex: str, height: int, entry: dict) -> None:
        key_hex = key_hex.lower()
        k = (path, key_hex, int(height))
        with self._mtx:
            self._entries[k] = entry
            self._entries.move_to_end(k)
            cur = self._latest.get((path, key_hex), 0)
            if height >= cur:
                self._latest[(path, key_hex)] = int(height)
            while len(self._entries) > self.max_entries:
                (p, kh, h), _ = self._entries.popitem(last=False)
                if self._latest.get((p, kh)) == h:
                    del self._latest[(p, kh)]

    def note_block(self, height: int, txs: list[bytes]) -> None:
        """Record the write set of verified block `height` (called by the
        daemon AFTER header verification, never on raw upstream data)."""
        if not txs:
            return
        with self._mtx:
            if self._mode() == "all":
                self._touched_all_at = max(self._touched_all_at, int(height))
                self.invalidations += 1
                return
            for tx in txs:
                key = tx.partition(b"=")[0] or tx
                kh = key.hex().lower()
                if self._touched.get(kh, 0) < height:
                    self._touched[kh] = int(height)
                    self.invalidations += 1

    def prune(self, floor: int) -> None:
        """Forget touch log rows at or below `floor` — once every entry
        the daemon can still serve was proven above a touch height, the
        row carries no information (bounds memory to the live window)."""
        with self._mtx:
            self._touched = {
                k: h for k, h in self._touched.items() if h > floor
            }

    def stats(self) -> dict:
        with self._mtx:
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
            }
