"""Replica route table (round 24): the read-only RPC surface, served
off the daemon's verified state.

Same wire methods, param names, and response shapes as the node's
rpc/core/handlers.py — a light client (or another replica) pointed at a
replica cannot tell the difference until it asks for something outside
the replica's verified window, where it gets a typed error plus a
/status ``earliest_block_height`` to horizon-jump from. The ctx is an
ordinary RPCContext whose ``node`` is the ReplicaDaemon, so the shared
server machinery (admission, /metrics, /health, /websocket) works
unchanged.
"""

from __future__ import annotations


def status(ctx) -> dict:
    return ctx.node.status_view()


def genesis(ctx) -> dict:
    return ctx.node.genesis_view()


def commit(ctx, height: int) -> dict:
    return ctx.node.commit_view(height)


def validators(ctx, height: int = 0) -> dict:
    return ctx.node.validators_view(height)


def block(ctx, height: int) -> dict:
    return ctx.node.block_view(height)


def blockchain_info(ctx, min_height: int = 0, max_height: int = 0) -> dict:
    return ctx.node.blockchain_view(min_height, max_height)


def abci_query(ctx, data=b"", path: str = "", height: int = 0,
               prove: bool = False) -> dict:
    return ctx.node.query(data=data, path=path, height=height, prove=prove)


def metrics(ctx) -> dict:
    return ctx.node.telemetry.flatten()


REPLICA_ROUTES = {
    "status": (status, []),
    "genesis": (genesis, []),
    "commit": (commit, ["height"]),
    "validators": (validators, ["height"]),
    "block": (block, ["height"]),
    "blockchain": (blockchain_info, ["min_height", "max_height"]),
    "abci_query": (abci_query, ["data", "path", "height", "prove"]),
    "metrics": (metrics, []),
}
