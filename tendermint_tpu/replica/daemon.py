"""Stateless verified read replica (round 24, docs/serving.md § Read
replicas).

The daemon follows ONE upstream RPC endpoint — a full node, or another
replica (tiered fan-out; proofs compose unchanged because nothing here
can forge a validator signature) — with the existing light client,
persisting its trust anchor in the replica home. Every block the
upstream announces is verified (+2/3 commit check via ``advance``, block
bytes bound to the verified header hash) BEFORE it touches the serve
path: the recent-block window, the proof cache's invalidation log, and
the relayed NewBlock event all see only verified data.

Reads are served from a proof-carrying cache: an ``abci_query`` miss
fetches ``prove=1`` from upstream, checks the statetree proof against
the light-verified header at (proof height + 1), checks the bare value
against the proven one, and only then caches + serves. Clients re-verify
— ``LightClient.verified_query`` pointed at a replica runs the exact
same checks, so a corrupt replica is DETECTED, never trusted
(``TENDERMINT_REPLICA_TAMPER=value|proof`` exists to prove that in
benches/tests: it corrupts responses at serve time, after verification).

The listener is the ordinary rpc/server.py stack with a replica route
table, so the round-23 admission plane (connection/inflight caps, rate
limits, typed sheds) and WS bounded-queue fan-out apply unchanged: one
upstream subscription feeds N client subscriptions, and replicas shed
reads before the validator ever sees the flood.
"""

from __future__ import annotations

import copy
import json
import queue
import threading
from collections import OrderedDict

from tendermint_tpu.libs.envknob import env_number, env_str
from tendermint_tpu.libs.events import EventSwitch
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.node.light_anchor import load_anchor, save_anchor
from tendermint_tpu.rpc import admission as adm
from tendermint_tpu.rpc.client import HTTPClient, RPCClientError, WSClient
from tendermint_tpu.rpc.core.handlers import RPCError
from tendermint_tpu.rpc.core.pipe import RPCContext
from tendermint_tpu.rpc.light import LightClient
from tendermint_tpu.rpc.server import RPCServer
from tendermint_tpu.replica.cache import ProofCache
from tendermint_tpu.types import events as tev
from tendermint_tpu.types.block import Header


class _RecordingClient:
    """The light client's transport, recording every /commit response.

    A downstream replica walks ITS light client through this replica's
    ``commit`` endpoint; those responses must be the genuine upstream
    ones (a replica cannot re-sign anything), so the window of commits
    this replica can re-serve is exactly what its own walk fetched."""

    def __init__(self, inner, record):
        self._inner = inner
        self._record = record

    def commit(self, height: int = 0):
        res = self._inner.commit(height=height)
        self._record(int(height), res)
        return res

    def __getattr__(self, name):
        return getattr(self._inner, name)


class ReplicaDaemon(BaseService):
    """One replica: light-client follower + proof cache + read RPC."""

    def __init__(self, config):
        super().__init__(name="replica")
        self.config = config
        cfg = config.replica
        if not cfg.upstream:
            raise ValueError(
                "replica requires an upstream RPC address "
                "([replica] upstream, or --upstream)"
            )
        self.cfg = cfg
        self.upstream = cfg.upstream
        self.client = HTTPClient(cfg.upstream)
        self.cache = ProofCache(cfg.cache_entries)
        self.event_switch = EventSwitch()
        self.light: LightClient | None = None
        self.genesis_doc = None
        self._genesis_res: dict | None = None
        # verified serve window: height -> raw upstream /block response
        self._recent: OrderedDict[int, dict] = OrderedDict()
        # height -> raw upstream /commit response (recorded by the walk)
        self._commits: OrderedDict[int, dict] = OrderedDict()
        self._state_mtx = threading.Lock()
        self._ingest_mtx = threading.Lock()
        self._ingested = 0
        self.upstream_height = 0
        self.connected = False
        self.proof_verify_failures = 0
        self.upstream_reconnects = 0
        self.served_reads_total = 0
        self.relayed_events = 0
        # round-23 ingress plane on the replica's OWN listener
        self.rpc_admission = adm.AdmissionController(config.rpc)
        self.rpc_admission.pressure_fn = self._pressure
        self.health_fn = self.health_view
        from tendermint_tpu.node.telemetry import build_replica_registry

        self.telemetry = build_replica_registry(self)
        self._rpc: RPCServer | None = None
        self._follow: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        self.event_switch.start()
        self._bootstrap()
        self._follow = threading.Thread(
            target=self._follow_loop, daemon=True, name="replica.follow"
        )
        self._follow.start()
        ctx = RPCContext(event_switch=self.event_switch, node=self)
        from tendermint_tpu.replica.handlers import REPLICA_ROUTES

        self._rpc = RPCServer(self.cfg.laddr, ctx, routes=REPLICA_ROUTES)
        self._rpc.start()
        self.logger.info(
            "replica serving %s (upstream %s, trust at %d)",
            self.cfg.laddr, self.upstream, self.light.height,
        )

    def on_stop(self) -> None:
        if self._rpc is not None:
            self._rpc.stop()
        if self._follow is not None:
            self._follow.join(timeout=5.0)
        self.event_switch.stop()
        if self.light is not None:
            save_anchor(self.cfg.root_dir, self.light)

    @property
    def rpc_port(self) -> int:
        return self._rpc.port if self._rpc is not None else 0

    def _bootstrap(self) -> None:
        """Fetch genesis and seed trust — from the persisted anchor when
        this home has one, genesis otherwise. Retries until the upstream
        answers or the service stops: a replica booting before its
        upstream is a normal fleet ordering."""
        from tendermint_tpu.types.genesis import GenesisDoc
        from tendermint_tpu.types.validator import Validator
        from tendermint_tpu.types.validator_set import ValidatorSet

        delay = self.cfg.reconnect_backoff_s
        while True:
            try:
                self._genesis_res = self.client.genesis()
                break
            except Exception as exc:  # noqa: BLE001 — upstream not up yet
                if self._quit.is_set() or self._stopped:
                    raise
                self.logger.warning(
                    "upstream %s not answering genesis (%s); retrying",
                    self.upstream, exc,
                )
                if self._quit.wait(delay):
                    raise
                delay = min(delay * 2, self.cfg.reconnect_backoff_max_s)
        doc = GenesisDoc.from_json(self._genesis_res["genesis"])
        self.genesis_doc = doc
        rec = _RecordingClient(self.client, self._record_commit)
        anchor = load_anchor(self.cfg.root_dir, doc.chain_id)
        if anchor is not None:
            height, validators, header = anchor
            self.light = LightClient(rec, doc.chain_id, validators, height)
            self.light._trusted_header = header
        else:
            vs = ValidatorSet(
                [Validator.new(v.pub_key, v.power) for v in doc.validators]
            )
            self.light = LightClient(rec, doc.chain_id, vs, 0)
        # the memo must cover the serve window: every block/commit this
        # replica re-serves pairs with a memoized verified header
        self.light.header_memo_max = max(64, self.cfg.keep_blocks + 8)

    # -- upstream follower -------------------------------------------------

    def _record_commit(self, height: int, res: dict) -> None:
        if height < 1:
            return
        with self._state_mtx:
            self._commits[height] = res
            self._commits.move_to_end(height)
            while len(self._commits) > max(1, self.cfg.keep_blocks):
                self._commits.popitem(last=False)

    def _follow_loop(self) -> None:
        """One upstream WS subscription feeding everything: verification,
        cache invalidation, and the N-client event relay. Drops reconnect
        with doubling backoff and replay missed heights from /block."""
        backoff = self.cfg.reconnect_backoff_s
        first = True
        while not self._quit.is_set() and not self._stopped:
            ws = None
            try:
                ws = WSClient(self.upstream, timeout=10.0)
                ws.subscribe(tev.EVENT_NEW_BLOCK)
                if not first:
                    self.upstream_reconnects += 1
                first = False
                self.connected = True
                backoff = self.cfg.reconnect_backoff_s
                self._catch_up()
                while not self._quit.is_set() and not self._stopped:
                    try:
                        ev = ws.next_event(timeout=0.5)
                    except queue.Empty:
                        if not ws._recv_thread.is_alive():
                            raise ConnectionError(
                                "upstream event stream closed"
                            )
                        continue
                    data = ev.get("data") or {}
                    hdr = (data.get("block") or {}).get("header") or {}
                    h = hdr.get("height")
                    if isinstance(h, int) and not isinstance(h, bool) and h > 0:
                        self.upstream_height = max(self.upstream_height, h)
                        self._shed_paced(lambda h=h: self._ingest(h))
            except Exception as exc:  # noqa: BLE001 — any follower fault
                # (dead socket, verification failure, upstream restart)
                # re-enters through a fresh subscription + catch-up
                if self._quit.is_set() or self._stopped:
                    break
                self.connected = False
                self.logger.warning(
                    "upstream follower error (%s: %s); reconnecting in %.2fs",
                    type(exc).__name__, exc, backoff,
                )
                self._quit.wait(backoff)
                backoff = min(backoff * 2, self.cfg.reconnect_backoff_max_s)
            finally:
                if ws is not None:
                    ws.close()

    def _shed_paced(self, fn):
        """Run one follower-side upstream call, absorbing typed sheds.

        An upstream running the round-23 admission plane answers over-
        budget requests with HTTP 429/503 + `shed:<reason>`. For an
        infrastructure follower (often sharing its source IP with real
        clients, e.g. behind one NAT) that is a PACING signal, not a
        dead connection — honoring it with a short wait keeps the walk
        alive; treating it as a fault would thrash the reconnect path
        with doubling backoff while the chain pulls further ahead."""
        while True:
            try:
                return fn()
            except RPCClientError as exc:
                if (
                    not str(exc).startswith("shed:")
                    or self._quit.is_set()
                    or self._stopped
                ):
                    raise
                self._quit.wait(0.25)

    def _catch_up(self) -> None:
        """Replay heights committed while the subscription was down: poll
        /status for the upstream head, then ingest forward from trust —
        bounded by keep_blocks (older history is servable upstream; a
        replica only promises its recent window)."""
        st = self._shed_paced(self.client.status)
        latest = st.get("latest_block_height") or 0
        if not isinstance(latest, int) or latest < 1:
            return
        self.upstream_height = max(self.upstream_height, latest)
        start = max(self._ingested + 1, latest - self.cfg.keep_blocks + 1, 1)
        for h in range(start, latest + 1):
            if self._quit.is_set() or self._stopped:
                return
            self._shed_paced(lambda h=h: self._ingest(h))

    def _ingest(self, h: int) -> None:
        """Verify block `h` and admit it to the serve path. Everything
        downstream of this point — recent window, invalidation log,
        relayed events, the anchor — sees only verified data."""
        with self._ingest_mtx:
            if h <= self._ingested:
                return
            light = self.light
            light.advance(h)  # +2/3 walk; records commits along the way
            hdr = light.header_at(h)
            block_res = self.client.block(height=h)
            blk = block_res.get("block") or {}
            try:
                block_header = Header.from_json(blk.get("header"))
            except ValueError as exc:
                self.proof_verify_failures += 1
                raise RPCError(f"malformed upstream block at {h}: {exc}")
            if block_header.hash() != hdr.hash():
                # upstream served block bytes that are NOT the ones the
                # verified commit signed — refuse the whole height
                self.proof_verify_failures += 1
                raise RPCError(
                    f"upstream block {h} does not match the verified header"
                )
            txs = [
                bytes.fromhex(t)
                for t in (blk.get("data") or {}).get("txs") or []
            ]
            with self._state_mtx:
                self._recent[h] = block_res
                self._recent.move_to_end(h)
                while len(self._recent) > max(1, self.cfg.keep_blocks):
                    self._recent.popitem(last=False)
                self._ingested = h
            self.upstream_height = max(self.upstream_height, h)
            self.cache.note_block(h, txs)
            self.cache.prune(h - self.cfg.keep_blocks)
            save_anchor(self.cfg.root_dir, light)
        # relay AFTER verification, outside the ingest lock: the WS
        # fan-out (bounded per-client queues, rpc/server.py) must never
        # stall the follower
        self.relayed_events += 1
        self.event_switch.fire_event(tev.EVENT_NEW_BLOCK, {"block": blk})

    # -- verified read path ------------------------------------------------

    def lag_heights(self) -> int:
        return max(0, self.upstream_height - self._ingested)

    def max_lag(self) -> int:
        return int(env_number(
            "TENDERMINT_REPLICA_MAX_LAG_HEIGHTS", self.cfg.max_lag_heights,
            cast=int,
        ))

    def query(self, data=b"", path: str = "", height: int = 0,
              prove: bool = False) -> dict:
        """abci_query off the proof cache. `height` pins the proven
        version; 0 serves the newest height this replica has verified —
        refusing (typed) when its view lags the upstream beyond
        ``max_lag_heights`` rather than serving silently stale reads."""
        self.served_reads_total += 1
        light = self.light
        if light is None or light.height < 2:
            raise RPCError("replica_warming: no verified state yet")
        key_hex = data.hex() if isinstance(data, bytes) else str(data)
        key_hex = key_hex.lower()
        height = int(height)
        if height == 0:
            lag = self.lag_heights()
            if lag > self.max_lag():
                raise RPCError(
                    f"replica_stale: {lag} heights behind upstream "
                    f"(max_lag_heights {self.max_lag()})"
                )
            # header H commits the app state of block H-1: the newest
            # height provable against the verified walk
            target = light.height - 1
            ent = self.cache.get_latest(
                path, key_hex, max(1, target - self.max_lag())
            )
        else:
            target = height
            ent = self.cache.get(path, key_hex, target)
        if ent is None:
            ent = self._fetch_verified(path, key_hex, target)
        return self._serve_entry(ent)

    def _fetch_verified(self, path: str, key_hex: str, target: int) -> dict:
        """Cache miss: fetch prove=1 from upstream and verify the proof
        against the light-verified header BEFORE caching. This is the
        same check chain as LightClient.verified_query — run here so the
        cache can never hold an unproven byte."""
        from tendermint_tpu.merkle.statetree_proof import TreeProof

        key = bytes.fromhex(key_hex)
        res = self.client.abci_query(
            data=key_hex, path=path, height=int(target), prove=True
        )
        resp = res.get("response") if isinstance(res, dict) else None
        if not isinstance(resp, dict):
            raise RPCError("malformed upstream abci_query response")
        code = resp.get("code", 0)
        if code != 0:
            raise RPCError(
                f"query refused (code {code}): {resp.get('log', '')}"
            )
        proof_hex = resp.get("proof") or ""
        if not isinstance(proof_hex, str) or not proof_hex:
            raise RPCError("upstream returned no state proof")
        h = resp.get("height")
        if not isinstance(h, int) or isinstance(h, bool) or h < 1:
            raise RPCError("bad proof height in upstream response")
        try:
            proof = TreeProof.from_json(json.loads(bytes.fromhex(proof_hex)))
        except ValueError as exc:
            self.proof_verify_failures += 1
            raise RPCError(f"malformed upstream state proof: {exc}")
        if proof.key != key:
            self.proof_verify_failures += 1
            raise RPCError("upstream proof is for a different key")
        header = self.light.header_at(h + 1)
        if not proof.verify(header.app_hash):
            self.proof_verify_failures += 1
            raise RPCError(
                f"upstream state proof failed verification at header {h + 1}"
            )
        resp_value = bytes.fromhex(resp.get("value") or "")
        if proof.is_membership:
            if resp_value != proof.value:
                self.proof_verify_failures += 1
                raise RPCError("upstream value does not match proven value")
        elif resp_value:
            self.proof_verify_failures += 1
            raise RPCError("upstream value contradicts an absence proof")
        ent = {"response": dict(resp), "header": header.to_json()}
        self.cache.put(path, key_hex, h, ent)
        return ent

    @staticmethod
    def _serve_entry(ent: dict) -> dict:
        """Serve a cached entry: the verified response + the header it
        verified against (a convenience — clients re-verify through their
        own light client regardless). The tamper knob corrupts AT SERVE
        TIME, after verification: it exists so benches/tests can prove a
        lying replica is detected client-side, never accepted."""
        tamper = env_str("TENDERMINT_REPLICA_TAMPER", "",
                         allowed=("", "value", "proof"))
        if not tamper:
            return {"response": dict(ent["response"]),
                    "header": ent["header"]}
        out = copy.deepcopy(ent)
        resp = out["response"]
        if tamper == "value":
            flip = bytearray(bytes.fromhex(resp.get("value") or "")) or \
                bytearray(b"\x00")
            flip[-1] ^= 0x01
            resp["value"] = flip.hex().upper()
        else:  # proof: flip a byte of a step's value hash (still parses)
            raw = json.loads(bytes.fromhex(resp["proof"]))
            step = raw["steps"][-1]
            flip = bytearray(bytes.fromhex(step[1]))
            flip[0] ^= 0x01
            step[1] = flip.hex().upper()
            resp["proof"] = json.dumps(raw).encode().hex().upper()
        return {"response": resp, "header": out["header"]}

    # -- served views (replica/handlers.py routes) --------------------------

    def status_view(self) -> dict:
        light = self.light
        hdr = light.trusted_header() if light is not None else None
        with self._state_mtx:
            earliest = min(self._commits) if self._commits else 0
        return {
            # a replica's identity IS its upstream + role: downstream
            # light walks key off earliest_block_height for horizon jumps
            "node_info": {
                "moniker": f"replica({self.upstream})",
                "replica": True,
                "upstream": self.upstream,
            },
            "pub_key": None,
            "latest_block_hash":
                hdr.hash().hex().upper() if hdr is not None else "",
            "latest_app_hash":
                hdr.app_hash.hex().upper() if hdr is not None else "",
            "latest_block_height": light.height if light is not None else 0,
            "earliest_block_height": earliest,
            "latest_block_time": hdr.time_ns if hdr is not None else 0,
            "replica_lag_heights": self.lag_heights(),
            "replica": {
                "upstream": self.upstream,
                "upstream_height": self.upstream_height,
                "lag_heights": self.lag_heights(),
                "max_lag_heights": self.max_lag(),
                "connected": self.connected,
            },
        }

    def genesis_view(self) -> dict:
        if self._genesis_res is None:
            raise RPCError("replica_warming: genesis not fetched yet")
        return self._genesis_res

    def commit_view(self, height: int) -> dict:
        height = int(height)
        with self._state_mtx:
            res = self._commits.get(height)
            earliest = min(self._commits) if self._commits else 0
        if res is None:
            # downstream light walks catch this and horizon-jump via our
            # /status earliest_block_height
            raise RPCError(
                f"replica: no commit for height {height} "
                f"(window starts at {earliest})"
            )
        return res

    def validators_view(self, height: int = 0) -> dict:
        height = int(height)
        light = self.light
        if light is not None and height in (0, light.height):
            return {
                "block_height": light.height,
                "validators": light.validators.to_json(),
            }
        # historical sets pass through: the downstream verifier checks
        # the claimed set's hash against the header, so a replica cannot
        # lie here any more than the upstream could
        return self.client.validators(height=height)

    def block_view(self, height: int) -> dict:
        height = int(height)
        with self._state_mtx:
            res = self._recent.get(height)
            earliest = min(self._recent) if self._recent else 0
        if res is None:
            raise RPCError(
                f"replica: no block for height {height} "
                f"(window starts at {earliest})"
            )
        return res

    def blockchain_view(self, min_height: int = 0, max_height: int = 0) -> dict:
        min_height, max_height = int(min_height), int(max_height)
        if min_height and max_height and min_height > max_height:
            raise RPCError(
                f"min height {min_height} > max height {max_height}"
            )
        with self._state_mtx:
            heights = sorted(self._recent)
            window = {h: self._recent[h] for h in heights}
        last = heights[-1] if heights else 0
        base = heights[0] if heights else 0
        hi = min(last, max_height) if max_height else last
        lo = max(base, min_height) if min_height else max(base, hi - 20 + 1)
        metas = []
        for h in range(hi, lo - 1, -1):
            res = window.get(h)
            if res is not None and res.get("block_meta") is not None:
                metas.append(res["block_meta"])
        return {"last_height": last, "base": base, "block_metas": metas}

    # -- health / pressure / telemetry --------------------------------------

    def health_view(self) -> dict:
        light = self.light
        lag = self.lag_heights()
        checks = {
            "bootstrapped": {"ok": light is not None and light.height >= 1},
            "upstream_connected": {"ok": self.connected,
                                   "upstream": self.upstream},
            "lag": {"ok": lag <= self.max_lag(), "lag_heights": lag,
                    "max_lag_heights": self.max_lag()},
        }
        if light is None or light.height < 1:
            status, code = "failing", 2
        elif not self.connected or lag > self.max_lag():
            status, code = "degraded", 1
        else:
            status, code = "ok", 0
        return {"status": status, "code": code, "checks": checks}

    def _pressure(self) -> int:
        """The round-23 ladder on the replica's own listener: shed reads
        when the serve plane saturates (everything a replica serves is a
        read, so rung 1 is the whole ladder here)."""
        a = self.rpc_admission
        cap = a.max_inflight() or 1
        frac = max(a.inflight / cap, a.ws_queue_frac())
        if frac >= env_number("TENDERMINT_OVERLOAD_SHED_WRITES_AT", 0.90):
            return adm.PRESSURE_SHED_WRITES
        if frac >= env_number("TENDERMINT_OVERLOAD_SHED_READS_AT", 0.75):
            return adm.PRESSURE_SHED_READS
        return adm.PRESSURE_OK

    def stats(self) -> dict:
        """The replica_* flat keys (both metric surfaces; catalog rows in
        docs/observability.md)."""
        light = self.light
        cs = self.cache.stats()
        return {
            "height": light.height if light is not None else 0,
            "lag_heights": self.lag_heights(),
            "upstream_height": self.upstream_height,
            "upstream_connected": int(self.connected),
            "cache_hits": cs["hits"],
            "cache_misses": cs["misses"],
            "cache_entries": cs["entries"],
            "cache_invalidations": cs["invalidations"],
            "proof_verify_failures": self.proof_verify_failures,
            "upstream_reconnects": self.upstream_reconnects,
            "served_reads_total": self.served_reads_total,
            "relayed_events_total": self.relayed_events,
        }
