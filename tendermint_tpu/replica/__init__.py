"""Verified read-replica tier (round 24, docs/serving.md § Read
replicas): stateless proof-carrying replicas that scale the read RPC
surface horizontally while clients keep verifying every byte against
validator-signed headers."""

from tendermint_tpu.replica.cache import ProofCache
from tendermint_tpu.replica.daemon import ReplicaDaemon

__all__ = ["ProofCache", "ReplicaDaemon"]
