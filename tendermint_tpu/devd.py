"""Device-access daemon: ONE long-lived process owns the accelerator.

Why this exists (round-3 postmortem): the tunneled TPU wedges PERMANENTLY
when any process dies mid-device-op — a timeout-killed bench or test takes
the device down for every later process, and the round's official bench
silently became a CPU number. The fix is discipline, not detection:

- devd is the ONLY process that dials the device. It claims the chip,
  warms the verify kernels at production shapes, and then serves verify
  batches over a root-only unix socket forever.
- Everything else (benches, tests, live nodes) talks to devd through
  DevdClient / ops/devd_backend.py — so killing a node, a bench, or a
  test can NEVER wedge the tunnel: those processes hold no device state.
- devd itself ignores SIGTERM (set TENDERMINT_DEVD_EXIT_ON_TERM=1 to
  allow graceful exit, e.g. in tests) and is started detached (setsid)
  so an interactive session ending doesn't reap it mid-op.
- If the device is unreachable at startup, devd keeps polling in
  throwaway subprocesses (a hung in-process dial would poison the jax
  backend-init lock for the process lifetime) and claims the chip the
  moment the tunnel comes back. Status is always visible via `ping`.

The reference runs its signature checks inline per process
(types/validator_set.go:220-264); a per-host device daemon is the
TPU-native replacement: one chip, one owner, many client processes.

Wire protocol (trusted local IPC, socket mode 0600, root-only box):
4-byte big-endian length + pickled dict. Requests: {"op": "ping" |
"verify" | "verify_stream" | "hash" | "hash_stream" | "stats" |
"status" | "bench" | "shutdown", ...}. Replies: {"ok": bool, ...}.

Streaming transport (round 6 — docs/streaming-devd.md): the single-shot
"verify" op serializes the WHOLE batch into one pickle frame and blocks
for one monolithic round trip, which capped the serving path at 52k
sigs/s while the kernel sustains 119.7k (BENCHES.json r5). The
"verify_stream" op replaces that with a pipelined data plane on the same
connection:

  client -> {"op": "verify_stream", "chunks": K, "total": N}   (pickle)
  client -> K binary chunk frames (no pickle; see _pack_chunk)
  daemon -> K binary result frames, one per chunk, IN ORDER, each sent
            the moment that chunk's verdicts land on host

The daemon double-buffers: chunk N+1 is read off the socket and decoded
(np.frombuffer over contiguous pubkey/msg_len/msg/sig planes) while
chunk N is still in the device kernel (verify_batch_async), up to
TENDERMINT_DEVD_STREAM_DEPTH chunks in flight. A malformed chunk frame
answers with an error result frame (status 1) and closes the stream —
never a hang. Accept/reject semantics are lane-for-lane identical to
the single-shot op (same Verifier underneath).

Hash plane (round 7 — same doc): the "hash" / "hash_stream" ops extend
the chunked data plane to the Merkle workload that BENCHES.json
`3_partset` showed losing 90x through single monolithic round trips
(offload 2.28 vs CPU 205 MB/s). A hash chunk frame carries contiguous
leaf planes (lengths + packed bytes, np.frombuffer decode), each chunk
dispatches to the batched RIPEMD-160 kernel as it decodes, and 20-byte
digests stream back per chunk in order under the same in-flight bound
and malformed-frame semantics. With "tree": true the daemon runs the
vectorized tree kernel over the accumulated leaf digests after the last
chunk and appends ONE tree frame carrying every internal node
(postorder — merkle.simple.FlatTree slot order), so part-set proofs
cost the host zero hashing. Digests are byte-identical to
crypto.hashing.ripemd160 / merkle.simple (parity-tested).
"""

from __future__ import annotations

import logging
import os
import pickle
import queue as queuelib
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

# env-tunable deadline budgets parse via the shared defensive knob helper:
# a typo'd value must not kill the verify hot path (libs.envknob is
# stdlib-only, so the daemon's light import footprint is preserved)
from tendermint_tpu.libs.envknob import env_number as _env_timeout

logger = logging.getLogger("devd")

DEFAULT_SOCK = "/tmp/tendermint-devd.sock"

# streamed-chunk lane bound: a frame claiming more lanes than this is
# malformed by definition (1M lanes ~ 100MB+ of signatures)
_MAX_CHUNK_LANES = 1 << 20
# default chunk width when neither the daemon's claim-time tuning nor
# TENDERMINT_DEVD_CHUNK pinned one
DEFAULT_STREAM_CHUNK = 2048
# writer-thread reap budget (DevdClient._reap_writer); module-level so
# the chaos tests can shrink it without waiting out the production value
WRITER_REAP_S = 5.0


def sock_path() -> str:
    """The PRIMARY daemon socket. TENDERMINT_DEVD_SOCK pins it; without
    one, the first entry of TENDERMINT_DEVD_SOCKS (the round-21 sharded
    device plane's endpoint list, ops/devd_shard) is the primary — so a
    one-entry SOCKS deployment behaves byte-for-byte like a SOCK one."""
    explicit = os.environ.get("TENDERMINT_DEVD_SOCK")
    if explicit:
        return explicit
    for p in os.environ.get("TENDERMINT_DEVD_SOCKS", "").split(","):
        p = p.strip()
        if p:
            return p
    return DEFAULT_SOCK


# -- framing ------------------------------------------------------------------


def _send_frame(conn: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    conn.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("devd peer closed")
        buf += chunk
    return buf


def _recv_raw_frame(conn: socket.socket) -> bytes:
    """Length-prefixed frame WITHOUT unpickling — stream chunk/result
    frames are binary, not pickle."""
    (n,) = struct.unpack(">I", _recv_exact(conn, 4))
    if n > (1 << 30):
        raise ValueError(f"devd frame too large: {n}")
    return _recv_exact(conn, n)


def _recv_frame(conn: socket.socket):
    return pickle.loads(_recv_raw_frame(conn))


# -- stream chunk codec -------------------------------------------------------
#
# One chunk frame carries n verify lanes as four contiguous planes —
#   u32 n | pubkeys 32*n | sigs 64*n | msg_lens u32*n | msgs concat
# — so the daemon decodes with np.frombuffer over the received buffer
# (no per-item pickling on either side). Result frame payloads:
#   status u8 (0=ok) | index u32 | n u32 | verdicts u8*n
#   status u8 (1=err) | index u32 | utf-8 error message
# An error frame terminates the stream; the daemon closes the connection
# after sending it (framing past a malformed chunk is untrustworthy).

STREAM_OK = 0
STREAM_ERR = 1
# hash_stream only: the post-chunk frame carrying the tree's internal
# nodes (postorder) when the request asked for "tree": true
STREAM_TREE = 2

# hash modes: "part" = raw ripemd160 per item (Part.Hash), "leaf" =
# ripemd160 of the length-prefixed item (merkle.simple.leaf_hash)
HASH_MODES = ("part", "leaf")


def _pack_chunk(items) -> bytes:
    """items: [(pubkey32, msg, sig64)] -> one chunk frame payload.
    List-comprehension planes + one join each: the whole pack is C-loop
    work (measured ~8x a per-item append loop; pickling the same items
    costs more AND forces the daemon through per-item pickle decode)."""
    import numpy as np

    n = len(items)
    pks = [it[0] for it in items]
    msgs = [it[1] for it in items]
    sigs = [it[2] for it in items]
    if any(len(pk) != 32 for pk in pks) or any(len(s) != 64 for s in sigs):
        bad = next(
            i for i, it in enumerate(items)
            if len(it[0]) != 32 or len(it[2]) != 64
        )
        raise ValueError(
            f"stream lane {bad}: pubkey/sig must be 32/64 bytes "
            f"(got {len(items[bad][0])}/{len(items[bad][2])}); "
            "route non-ed25519 via CPU"
        )
    lens = np.fromiter(map(len, msgs), dtype="<u4", count=n)
    return b"".join((
        struct.pack("<I", n),
        b"".join(pks),
        b"".join(sigs),
        lens.tobytes(),
        b"".join(msgs),
    ))


def _unpack_chunk(payload: bytes) -> list:
    """Inverse of _pack_chunk; raises ValueError on any malformed frame.
    Plane-sliced decode: lens via ONE np.frombuffer, fixed-width planes
    via C-level bytes slicing — no per-item pickle, no memoryview churn
    (bytes(memoryview[...]) measured 6x slower than plane slicing)."""
    import numpy as np

    if len(payload) < 4:
        raise ValueError("chunk frame shorter than its lane count")
    (n,) = struct.unpack_from("<I", payload, 0)
    if n > _MAX_CHUNK_LANES:
        raise ValueError(f"chunk claims {n} lanes (max {_MAX_CHUNK_LANES})")
    off_sig = 4 + n * 32
    off_len = off_sig + n * 64
    fixed = off_len + n * 4
    if fixed > len(payload):
        raise ValueError(
            f"chunk truncated: {len(payload)} bytes < {fixed} fixed planes"
        )
    lens_arr = np.frombuffer(payload, dtype="<u4", count=n, offset=off_len)
    if fixed + int(lens_arr.sum()) != len(payload):
        raise ValueError(
            f"chunk size mismatch: {len(payload)} != "
            f"{fixed + int(lens_arr.sum())}"
        )
    pk_plane = payload[4:off_sig]
    sig_plane = payload[off_sig:off_len]
    pks = [pk_plane[i: i + 32] for i in range(0, n * 32, 32)]
    sigs = [sig_plane[i: i + 64] for i in range(0, n * 64, 64)]
    msgs, mo = [], fixed
    for ln in lens_arr.tolist():
        msgs.append(payload[mo: mo + ln])
        mo += ln
    return list(zip(pks, msgs, sigs))


def _send_result_frame(conn: socket.socket, index: int, oks) -> None:
    import numpy as np

    payload = struct.pack("<BII", STREAM_OK, index, len(oks)) + (
        np.asarray(oks, dtype=np.uint8).tobytes()
    )
    conn.sendall(struct.pack(">I", len(payload)) + payload)


# -- hash chunk codec ---------------------------------------------------------
#
# One hash chunk frame carries n leaf payloads as two contiguous planes —
#   u32 n | lens u32*n | payload bytes concatenated
# — decoded daemon-side with ONE np.frombuffer for the lengths plus
# C-level bytes slicing for the payloads (no per-item pickling). Digest
# result frames:
#   status u8 (0=ok) | index u32 | n u32 | digests 20*n
#   status u8 (1=err) | index u32 | utf-8 error message
#   status u8 (2=tree) | count u32 | internal nodes 20*count  (postorder;
#            sent once, after the last chunk's digests, iff "tree": true)
# Error semantics match the verify stream: an error frame terminates the
# stream and the daemon closes the connection.


def _pack_hash_chunk(items) -> bytes:
    """items: [bytes] -> one hash chunk frame payload (lengths plane +
    packed bytes; list-join C-loop work, mirroring _pack_chunk)."""
    import numpy as np

    n = len(items)
    lens = np.fromiter(map(len, items), dtype="<u4", count=n)
    return b"".join((struct.pack("<I", n), lens.tobytes(), b"".join(items)))


def _unpack_hash_chunk(payload: bytes) -> list:
    """Inverse of _pack_hash_chunk; raises ValueError on any malformed
    frame (same validation discipline as _unpack_chunk)."""
    import numpy as np

    if len(payload) < 4:
        raise ValueError("hash chunk frame shorter than its item count")
    (n,) = struct.unpack_from("<I", payload, 0)
    if n > _MAX_CHUNK_LANES:
        raise ValueError(f"hash chunk claims {n} items (max {_MAX_CHUNK_LANES})")
    fixed = 4 + n * 4
    if fixed > len(payload):
        raise ValueError(
            f"hash chunk truncated: {len(payload)} bytes < {fixed} length plane"
        )
    lens_arr = np.frombuffer(payload, dtype="<u4", count=n, offset=4)
    if fixed + int(lens_arr.sum()) != len(payload):
        raise ValueError(
            f"hash chunk size mismatch: {len(payload)} != "
            f"{fixed + int(lens_arr.sum())}"
        )
    items, off = [], fixed
    for ln in lens_arr.tolist():
        items.append(payload[off: off + ln])
        off += ln
    return items


def _send_digest_frame(conn: socket.socket, index: int, digests) -> None:
    payload = struct.pack("<BII", STREAM_OK, index, len(digests)) + b"".join(
        digests
    )
    conn.sendall(struct.pack(">I", len(payload)) + payload)


def _send_tree_frame(conn: socket.socket, nodes) -> None:
    payload = struct.pack("<BI", STREAM_TREE, len(nodes)) + b"".join(nodes)
    conn.sendall(struct.pack(">I", len(payload)) + payload)


def _send_error_frame(conn: socket.socket, index: int, msg: str) -> None:
    payload = struct.pack("<BI", STREAM_ERR, index) + msg.encode()
    conn.sendall(struct.pack(">I", len(payload)) + payload)


# -- server -------------------------------------------------------------------


class _DaemonState:
    def __init__(self):
        self.started = time.time()
        self.platform: str | None = None
        self.verifier = None  # ops.gateway.Verifier once the device is held
        self.hasher = None    # hash backend once the device is held
        self.warmed: list[int] = []
        self.status = "starting"
        self.lock = threading.Lock()
        self.stop = threading.Event()
        # claim-time-tuned streamed chunk width, advertised in ping/status
        # so clients frame at the width the held device actually likes
        self.stream_chunk = int(
            os.environ.get("TENDERMINT_DEVD_CHUNK") or "0"
        ) or DEFAULT_STREAM_CHUNK
        # serving-path observability (ISSUE 1): how the streamed data
        # plane is doing in production, not just in benches
        self.stream = {
            "streams": 0,            # verify_stream requests served
            "chunks": 0,             # chunk frames verified
            "lanes": 0,              # signatures through the stream path
            "bytes_framed": 0,       # chunk-frame payload bytes received
            "inflight": 0,           # chunks currently dispatched, unresolved
            "inflight_max": 0,       # high-water mark (proves overlap)
            "errors": 0,             # malformed/aborted streams
            "chunk_device_ms_last": 0.0,   # dispatch->verdict, last chunk
            "chunk_device_ms_avg": 0.0,    # EWMA (alpha .2) of the same
        }
        # hash-plane observability (ISSUE 2): same gauge shape as the
        # verify stream, "lanes" = leaves hashed; plus the tree-frame and
        # single-shot hash-op counters
        self.hash_stream = {
            "streams": 0,
            "chunks": 0,
            "lanes": 0,
            "bytes_framed": 0,
            "inflight": 0,
            "inflight_max": 0,
            "errors": 0,
            "trees": 0,              # tree frames served (proof-free part sets)
            "single_batches": 0,     # single-shot "hash" op requests
            "single_lanes": 0,
            "chunk_device_ms_last": 0.0,
            "chunk_device_ms_avg": 0.0,
        }

    def stream_stats(self) -> dict:
        with self.lock:
            return dict(self.stream)

    def hash_stream_stats(self) -> dict:
        with self.lock:
            return dict(self.hash_stream)


class _SimVerifier:
    """Transport-bench stand-in for the device kernel
    (TENDERMINT_DEVD_SIM_RATE=<sigs/s>, honored only with
    TENDERMINT_DEVD_ACCEPT_CPU=1 — never near real hardware).

    Models a pipelined device honestly: ONE worker drains dispatches
    FIFO (device compute serializes) at the configured rate, with
    verify_batch_async returning immediately — so transport/marshal
    overlap is real but simulated compute never parallelizes with
    itself. Verdicts are structural only (32/64-byte lanes pass): this
    exists to measure the IPC data plane with device time held constant,
    isolating exactly the single-shot-vs-streamed gap the r5 captures
    blamed on the serving path. Parity testing uses the real kernel."""

    def __init__(self, rate: float):
        self.rate = float(rate)
        self._q: queuelib.Queue = queuelib.Queue()
        self._stats = {"tpu_batches": 0, "tpu_sigs": 0, "cpu_sigs": 0}
        self._mtx = threading.Lock()
        threading.Thread(target=self._worker, daemon=True,
                         name="devd-simdev").start()

    def _worker(self) -> None:
        while True:
            n, done = self._q.get()
            time.sleep(n / self.rate)
            done.set()

    def verify_batch_async(self, items):
        items = list(items)
        oks = [len(it[0]) == 32 and len(it[2]) == 64 for it in items]
        done = threading.Event()
        self._q.put((len(items), done))
        with self._mtx:
            self._stats["tpu_batches"] += 1
            self._stats["tpu_sigs"] += len(items)

        def resolve():
            done.wait()
            return oks

        return resolve

    def verify_batch(self, items):
        return self.verify_batch_async(items)()

    def stats(self) -> dict:
        with self._mtx:
            return dict(self._stats)


class _DevdHasher:
    """In-daemon hash backend for the real (jax) daemon: the batched
    RIPEMD-160 kernel (ops/hashing) on the held device. Dispatch rides
    jax's async execution — hash_batch_async packs and enqueues NOW and
    materializes in the resolver, so the stream handler decodes chunk
    N+1 while chunk N's compressions run."""

    def hash_batch_async(self, items, mode: str):
        import jax.numpy as jnp
        import numpy as np

        from tendermint_tpu.ops import hashing as oh

        if mode == "leaf":
            from tendermint_tpu.codec.binary import encode_bytes

            msgs = [encode_bytes(it) for it in items]
        else:
            msgs = list(items)
        if not msgs:
            return lambda: []
        words, nblocks = oh.pack_messages(msgs, little_endian=True)
        out = oh.ripemd160_words(jnp.asarray(words), jnp.asarray(nblocks))

        def resolve():
            return oh.digests_to_bytes_le(np.asarray(out))

        return resolve

    def tree_internal_nodes(self, digests):
        """Postorder internal nodes over the leaf digests, via the
        vectorized tree kernel (ops/merkle) — the tree frame payload."""
        from tendermint_tpu.ops import merkle as ops_merkle

        return ops_merkle.tree_nodes_from_leaf_digests(digests)[len(digests):]


class _SimHasher:
    """Transport-bench stand-in for the hash kernel (same
    TENDERMINT_DEVD_SIM_RATE gate as _SimVerifier): ONE FIFO worker
    computes REAL digests (crypto.hashing — byte-identical, so parity
    holds even in sim mode) and charges simulated device time at
    rate items/s, so streamed-vs-single-shot isolates the transport with
    device time held constant."""

    def __init__(self, rate: float):
        self.rate = float(rate)
        self._q: queuelib.Queue = queuelib.Queue()
        threading.Thread(target=self._worker, daemon=True,
                         name="devd-simhash").start()

    def _worker(self) -> None:
        from tendermint_tpu.codec.binary import encode_bytes
        from tendermint_tpu.crypto.hashing import ripemd160

        while True:
            items, mode, box, done = self._q.get()
            try:
                if mode == "leaf":
                    box.extend(ripemd160(encode_bytes(it)) for it in items)
                else:
                    box.extend(ripemd160(it) for it in items)
                time.sleep(len(items) / self.rate)
            finally:
                done.set()

    def hash_batch_async(self, items, mode: str):
        box: list = []
        done = threading.Event()
        self._q.put((list(items), mode, box, done))

        def resolve():
            done.wait()
            return box

        return resolve

    def tree_internal_nodes(self, digests):
        from tendermint_tpu.merkle.simple import flat_tree_from_leaf_digests

        return flat_tree_from_leaf_digests(digests).internal_nodes()


def subprocess_probe(timeout_s: float) -> str | None:
    """Dial the device in a THROWAWAY subprocess; the platform name or
    None. The probe bounds itself (jitcache.probe_device daemon-thread
    dial + clean interpreter exit), so no one ever SIGKILLs a process
    mid-device-op here; if the child somehow outlives its own bound, it
    is left to finish — never killed. Use THIS (not an in-process
    probe_device) from any process that must stay usable afterwards: a
    hung in-process dial holds jax's backend-init lock forever, so even
    later CPU-only jax calls in that process would block."""
    code = (
        "from tendermint_tpu.jitcache import probe_device; import sys;"
        f"p = probe_device({timeout_s});"
        "print(p or '', end='');"
        "sys.exit(0 if p else 1)"
    )
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        try:
            out, _ = proc.communicate(timeout=timeout_s + 60)
        except subprocess.TimeoutExpired:
            logger.warning("probe subprocess overran; leaving it to exit on its own")
            return None
        if proc.returncode == 0:
            return (out or b"").decode() or "unknown"
        return None
    except Exception:
        logger.exception("probe subprocess failed")
        return None


def _device_loop(st: _DaemonState, *, accept_cpu: bool, probe_timeout: float,
                 retry_s: float, warm_shapes: tuple[int, ...]) -> None:
    """Poll for the device, claim it, warm kernels, flip state to serving."""
    sim_rate = float(os.environ.get("TENDERMINT_DEVD_SIM_RATE", "0") or 0)
    if sim_rate > 0:
        # accept_cpu enforcement lives in serve() — a SystemExit raised
        # here, inside a daemon thread, would be swallowed silently
        # pure-python daemon: no jax, no device, instant startup — exists
        # for transport benches/tests that need device time held constant
        with st.lock:
            st.platform = "cpu"
            st.verifier = _SimVerifier(sim_rate)
            st.hasher = _SimHasher(sim_rate)
            st.status = "serving"
        logger.info("sim device (%.0f sigs/s); serving", sim_rate)
        return
    from tendermint_tpu.jitcache import enable as enable_cache

    enable_cache()
    if accept_cpu:
        # a CPU daemon must never dial the tunnel; die visibly if the
        # pin cannot be applied (strict) instead of probing unpinned
        from tendermint_tpu.ops.gateway import pin_jax_cpu

        pin_jax_cpu(strict=True)
    while not st.stop.is_set():
        st.status = "probing"
        if accept_cpu:
            platform = "cpu"
        else:
            platform = subprocess_probe(probe_timeout)
        if platform is None:
            st.status = "waiting-for-device"
            logger.warning(
                "device unreachable; retrying in %.0fs (tunnel may recover)",
                retry_s,
            )
            if st.stop.wait(retry_s):
                return
            continue
        # A subprocess just proved the tunnel answers — now dial in-process
        # and hold the device for the daemon's lifetime.
        try:
            st.status = "claiming"
            from tendermint_tpu.ops import gateway

            # decide from the probe's OWN answer — going through
            # gateway.on_tpu() here would run a second redundant probe
            # (this daemon's socket isn't "held" yet), and a slow second
            # probe would mis-pin the daemon's jax to CPU while reporting
            # a TPU platform
            on_tpu = (not accept_cpu) and platform in ("tpu", "axon")
            gateway.set_platform("cpu" if accept_cpu else platform)
            # kernel choice: explicit TENDERMINT_DEVD_KERNEL wins; on TPU
            # hardware, bake off the comb kernel against the f32p ladder
            # at claim time and serve the measured winner (pinning the
            # direct kernel also keeps the gateway default from routing
            # the daemon's own verifier back through devd)
            env_k = os.environ.get("TENDERMINT_DEVD_KERNEL", "")
            if env_k:
                candidates = [env_k]
            elif on_tpu:
                candidates = ["comb", "f32p"]
            else:
                candidates = ["f32"]
            st.status = "warming"
            from tendermint_tpu.crypto import ed25519 as ed

            # 64 distinct keys cycled across lanes: enough key diversity
            # to exercise the comb pool's gather path without minutes of
            # python keygen
            seeds = [bytes([5, k]) + b"\x05" * 30 for k in range(64)]
            keys = [(s, ed.public_key(s)) for s in seeds]
            verifier = None
            best: tuple[float, str] | None = None
            for kname in candidates:
                os.environ["TENDERMINT_TPU_KERNEL"] = kname
                v = gateway.Verifier(min_tpu_batch=1, use_tpu=True)
                if not warm_shapes:
                    # warming disabled (TENDERMINT_DEVD_WARM=""): serve
                    # the first candidate unwarmed, as before round 5
                    if verifier is None:
                        verifier = v
                        best = (0.0, kname)
                    continue
                def make_full(shape: int) -> list:
                    items = [
                        (
                            keys[i % 64][1],
                            b"warm-%d" % i,
                            ed.sign(keys[i % 64][0], b"warm-%d" % i),
                        )
                        for i in range(min(shape, 256))
                    ]
                    return [items[i % len(items)] for i in range(shape)]

                for shape in warm_shapes:
                    t0 = time.time()
                    ok = v.verify_batch(make_full(shape))
                    assert all(ok), (
                        f"warm verify failed: kernel {kname} shape {shape}"
                    )
                    logger.info(
                        "kernel %s warmed shape %d in %.1fs",
                        kname, shape, time.time() - t0,
                    )
                    if shape not in st.warmed:
                        st.warmed.append(shape)
                # timed steady-state pass at the LARGEST shape. Two
                # untimed passes first: with the comb kernel's default
                # second-sight policy the first pass at a shape may still
                # route lanes to the ladder and the second pays table
                # builds + compile — neither may land inside the timed
                # region or the bake-off picks the wrong winner.
                # The timed region is PIPELINED (several batches in
                # flight via verify_batch_async): serving throughput is
                # what the daemon exists for, and a single synchronous
                # batch is dominated by the tunnel round trip — it ranks
                # kernels by RTT, not by device rate (the r5 bake-off
                # initially picked on 1-batch numbers 4-7x below the
                # pipelined rate).
                full = make_full(max(warm_shapes))
                for _ in range(2):
                    v.verify_batch(full)
                n_pipe = 6
                t0 = time.time()
                resolvers = [v.verify_batch_async(full) for _ in range(n_pipe)]
                for r in resolvers:
                    r()
                dt = time.time() - t0
                rate = n_pipe * len(full) / dt if dt > 0 else 0.0
                logger.info(
                    "kernel %s: %.0f sigs/s sustained (%d x %d pipelined)",
                    kname, rate, n_pipe, len(full),
                )
                if best is None or dt < best[0]:
                    best = (dt, kname)
                    verifier = v
            os.environ["TENDERMINT_TPU_KERNEL"] = best[1]
            logger.info("serving kernel: %s", best[1])
            if not os.environ.get("TENDERMINT_DEVD_CHUNK") and warm_shapes:
                # claim-time chunk-width bake-off, same pipelined
                # machinery as the kernel one: among widths the warm set
                # covers, serve the SMALLEST whose sustained pipelined
                # rate is within 10% of the best — finer chunks overlap
                # socket deserialize with device compute better, so ties
                # break toward granularity
                top = max(warm_shapes)
                cands = sorted(
                    {c for c in (1024, 2048, 4096) if c <= top} or {top}
                )
                rates: list[tuple[int, float]] = []
                for width in cands:
                    batch = make_full(width)
                    verifier.verify_batch(batch)  # shape warm, off-clock
                    t0 = time.time()
                    rs = [verifier.verify_batch_async(batch) for _ in range(6)]
                    for r in rs:
                        r()
                    dt = time.time() - t0
                    rates.append((width, 6 * width / dt if dt > 0 else 0.0))
                    logger.info(
                        "chunk %d: %.0f sigs/s pipelined", width, rates[-1][1]
                    )
                best_rate = max(r for _, r in rates)
                st.stream_chunk = next(
                    w for w, r in rates if r >= 0.9 * best_rate
                )
                logger.info("stream chunk width: %d", st.stream_chunk)
            with st.lock:
                st.platform = platform if not accept_cpu else "cpu"
                st.verifier = verifier
                # hash plane rides the same held device; compiles lazily
                # on the first hash op (part widths repeat, so the jit
                # cache hits from then on)
                st.hasher = _DevdHasher()
                st.status = "serving"
            logger.info("device held (%s); serving", st.platform)
            return
        except Exception:
            logger.exception("claim/warm failed; retrying in %.0fs", retry_s)
            st.status = "waiting-for-device"
            if st.stop.wait(retry_s):
                return


# one bench at a time daemon-wide (see the bench op)
_bench_gate = threading.Lock()


def _stream_depth() -> int:
    try:
        return max(2, int(os.environ.get("TENDERMINT_DEVD_STREAM_DEPTH", "4")))
    except ValueError:  # serve() validates; stay serving if it didn't run
        return 4


def _handle_verify_stream(conn: socket.socket, st: _DaemonState,
                          req: dict) -> bool:
    """Serve one verify_stream request: read chunk frames off the socket,
    dispatch each to the kernel as it decodes (verify_batch_async), and
    stream verdict frames back in order from a sender thread — so chunk
    N+1 deserializes while chunk N is in the kernel. Returns True when
    the connection stays usable (all chunks answered), False when the
    stream aborted (error frame sent; caller closes the connection)."""
    n_chunks = int(req.get("chunks", 0))
    v = st.verifier
    if v is None or n_chunks < 0:
        _send_error_frame(
            conn, 0xFFFFFFFF,
            f"device not held (status: {st.status})" if v is None
            else f"bad chunk count {n_chunks}",
        )
        return False
    with st.lock:
        st.stream["streams"] += 1
    return _serve_stream(
        conn, st, st.stream, n_chunks,
        _unpack_chunk, v.verify_batch_async, _send_result_frame,
    )


def _handle_hash_stream(conn: socket.socket, st: _DaemonState,
                        req: dict) -> bool:
    """Serve one hash_stream request on the shared stream core: hash
    chunk frames decode as they arrive, each dispatches to the batched
    RIPEMD-160 kernel, digest frames stream back per chunk in order.
    With "tree": true the leaf digests accumulate (in chunk order,
    through the sender thread) and ONE tree frame with every internal
    node follows the last digest frame — proofs come free host-side."""
    n_chunks = int(req.get("chunks", 0))
    mode = req.get("mode", "part")
    want_tree = bool(req.get("tree"))
    h = st.hasher
    if h is None or n_chunks < 0 or mode not in HASH_MODES:
        _send_error_frame(
            conn, 0xFFFFFFFF,
            f"device not held (status: {st.status})" if h is None
            else (f"bad chunk count {n_chunks}" if n_chunks < 0
                  else f"bad hash mode {mode!r}"),
        )
        return False
    with st.lock:
        st.hash_stream["streams"] += 1
    leaves: list = []
    ok = _serve_stream(
        conn, st, st.hash_stream, n_chunks,
        _unpack_hash_chunk, lambda items: h.hash_batch_async(items, mode),
        _send_digest_frame,
        on_result=(leaves.extend if want_tree else None),
    )
    if not ok:
        return False
    if want_tree:
        try:
            nodes = h.tree_internal_nodes(leaves) if len(leaves) > 1 else []
            _send_tree_frame(conn, nodes)
            with st.lock:
                st.hash_stream["trees"] += 1
        except Exception as exc:  # noqa: BLE001 — tree build/send died
            logger.exception("hash tree build failed")
            try:
                _send_error_frame(conn, n_chunks, f"{type(exc).__name__}: {exc}")
            except Exception:
                pass
            with st.lock:
                st.hash_stream["errors"] += 1
            return False
    return True


def _serve_stream(conn: socket.socket, st: _DaemonState, gauges: dict,
                  n_chunks: int, unpack, dispatch, send_result,
                  on_result=None) -> bool:
    """The chunked-stream serving core shared by verify_stream and
    hash_stream: bounded in-flight dispatch, in-order result frames from
    a sender thread, error-frame-then-close on any malformed frame.
    `gauges` is the st-owned counter dict (st.stream / st.hash_stream —
    same keys); `dispatch(items)` returns a zero-arg resolver;
    `send_result(conn, idx, result)` frames one chunk's result;
    `on_result(result)` (optional) observes results in chunk order from
    the sender thread. Returns True when the connection stays usable."""
    depth = threading.Semaphore(_stream_depth())
    results: queuelib.Queue = queuelib.Queue()
    send_ok = threading.Event()
    send_ok.set()

    def sender() -> None:
        while True:
            entry = results.get()
            if entry is None:
                return
            idx, resolver_or_err, n, t_disp = entry
            try:
                if isinstance(resolver_or_err, str):
                    _send_error_frame(conn, idx, resolver_or_err)
                    with st.lock:
                        gauges["errors"] += 1
                    send_ok.clear()
                    return
                counted = False
                res = resolver_or_err()
                dt_ms = (time.time() - t_disp) * 1000.0
                with st.lock:
                    s = gauges
                    s["inflight"] -= 1
                    counted = True
                    s["chunks"] += 1
                    s["lanes"] += n
                    s["chunk_device_ms_last"] = round(dt_ms, 3)
                    s["chunk_device_ms_avg"] = round(
                        0.8 * s["chunk_device_ms_avg"] + 0.2 * dt_ms, 3
                    ) if s["chunk_device_ms_avg"] else round(dt_ms, 3)
                if on_result is not None:
                    on_result(res)
                send_result(conn, idx, res)
            except Exception as exc:  # noqa: BLE001 — resolve/send died
                logger.exception("stream chunk %d failed", idx)
                try:
                    _send_error_frame(conn, idx, f"{type(exc).__name__}: {exc}")
                except Exception:
                    pass
                with st.lock:
                    gauges["errors"] += 1
                    # decrement exactly once per dispatched chunk: the
                    # success path may have counted it before the send
                    # died (a post-send failure must not double-count)
                    if not isinstance(resolver_or_err, str) and not counted:
                        gauges["inflight"] -= 1
                send_ok.clear()
                return
            finally:
                depth.release()

    send_thread = threading.Thread(target=sender, daemon=True,
                                   name="devd-stream-send")
    send_thread.start()

    def acquire_slot() -> bool:
        """Bound in-flight device work WITHOUT deadlocking on a dead
        sender: give up as soon as the stream is known broken."""
        while send_ok.is_set():
            if depth.acquire(timeout=0.5):
                return True
        return False

    aborted = False
    try:
        for idx in range(n_chunks):
            try:
                payload = _recv_raw_frame(conn)
                items = unpack(payload)
            except (ConnectionError, EOFError):
                aborted = True
                break
            except Exception as exc:  # noqa: BLE001 — malformed frame:
                # answer with an error frame, never hang the client
                if acquire_slot():
                    results.put((idx, f"malformed chunk: {exc}", 0, 0.0))
                aborted = True
                break
            if not acquire_slot():
                aborted = True
                break
            try:
                resolver = dispatch(items)
            except Exception as exc:  # noqa: BLE001 — dispatch failed
                results.put((idx, f"{type(exc).__name__}: {exc}", 0, 0.0))
                aborted = True
                break
            with st.lock:
                s = gauges
                s["bytes_framed"] += len(payload)
                s["inflight"] += 1
                s["inflight_max"] = max(s["inflight_max"], s["inflight"])
            results.put((idx, resolver, len(items), time.time()))
    finally:
        results.put(None)
        send_thread.join()
        # stats hygiene on abort: entries the dead sender never resolved
        # must not leave the in-flight gauge elevated forever
        leaked = 0
        while True:
            try:
                entry = results.get_nowait()
            except queuelib.Empty:
                break
            if entry is not None and not isinstance(entry[1], str):
                leaked += 1
        if leaked:
            with st.lock:
                gauges["inflight"] -= leaked
    return not aborted and send_ok.is_set()


def _handle_conn(conn: socket.socket, st: _DaemonState) -> None:
    try:
        while True:
            try:
                req = _recv_frame(conn)
            except (ConnectionError, EOFError):
                return
            op = req.get("op")

            def held_stats() -> dict:
                with st.lock:
                    return st.verifier.stats() if st.verifier else {}

            try:
                if op in ("ping", "status"):
                    rep = {
                        "ok": True,
                        "platform": st.platform,
                        "held": st.verifier is not None,
                        "status": st.status,
                        "warmed": list(st.warmed),
                        "uptime_s": round(time.time() - st.started, 1),
                        "stats": held_stats(),
                        "pid": os.getpid(),
                        "stream_chunk": st.stream_chunk,
                    }
                    if op == "status":
                        # the serving-path bottleneck, measurable in
                        # production: chunks in flight, bytes framed,
                        # per-chunk device latency (ISSUE 1 satellite;
                        # hash plane ISSUE 2)
                        rep["stream"] = st.stream_stats()
                        rep["hash_stream"] = st.hash_stream_stats()
                        rep["stream_depth"] = _stream_depth()
                    _send_frame(conn, rep)
                elif op == "verify_stream":
                    if not _handle_verify_stream(conn, st, req):
                        return  # stream aborted; framing is untrustworthy
                elif op == "hash_stream":
                    if not _handle_hash_stream(conn, st, req):
                        return  # stream aborted; framing is untrustworthy
                elif op == "hash":
                    # single-shot hash: one pickle frame each way — what
                    # small batches ride (stream setup loses below
                    # TENDERMINT_DEVD_STREAM_MIN) and the baseline the
                    # hash-stream bench row measures against
                    h = st.hasher
                    mode = req.get("mode", "part")
                    if h is None:
                        _send_frame(conn, {
                            "ok": False,
                            "error": f"device not held (status: {st.status})",
                        })
                    elif mode not in HASH_MODES:
                        _send_frame(conn, {
                            "ok": False, "error": f"bad hash mode {mode!r}",
                        })
                    else:
                        items = [bytes(b) for b in req.get("items", [])]
                        digests = h.hash_batch_async(items, mode)()
                        rep = {"ok": True, "digests": digests}
                        if req.get("tree"):
                            rep["nodes"] = (
                                h.tree_internal_nodes(digests)
                                if len(digests) > 1 else []
                            )
                        with st.lock:
                            st.hash_stream["single_batches"] += 1
                            st.hash_stream["single_lanes"] += len(items)
                        _send_frame(conn, rep)
                elif op == "verify":
                    v = st.verifier
                    if v is None:
                        _send_frame(conn, {
                            "ok": False,
                            "error": f"device not held (status: {st.status})",
                        })
                    else:
                        oks = v.verify_batch(req["items"])
                        _send_frame(conn, {"ok": True, "results": [bool(b) for b in oks]})
                elif op == "agg":
                    # aggregate-commit dual-scalar-mul lanes
                    # (ops/ed25519.dsm_batch; docs/upgrade.md): terms are
                    # (a, (px,py), b, (qx,qy)) python-int tuples, the
                    # reply the per-lane affine points. Rides the held
                    # device via the int32 kernel module directly — the
                    # only kernel with the dsm ladder.
                    if st.verifier is None:
                        _send_frame(conn, {
                            "ok": False,
                            "error": f"device not held (status: {st.status})",
                        })
                    else:
                        from tendermint_tpu.ops import ed25519 as _ops_ed

                        points = _ops_ed.dsm_batch(
                            [tuple(t) for t in req.get("items", [])]
                        )
                        _send_frame(conn, {"ok": True, "points": points})
                elif op == "stats":
                    _send_frame(conn, {
                        "ok": True,
                        "stats": held_stats(),
                        "stream": st.stream_stats(),
                        "hash_stream": st.hash_stream_stats(),
                    })
                elif op == "bench":
                    # In-daemon pipelined throughput measurement: the one
                    # number free of ALL client-side confounds (IPC
                    # marshal, socket hops, client thread scheduling) —
                    # how fast the held device verifies when its queue is
                    # kept full. Items are synthesized daemon-side with
                    # the warm-set key-reuse shape (64 keys cycled, a
                    # real commit's profile). MAINTENANCE op: it queues
                    # ~n_batches*batch lanes on the shared serving
                    # verifier, so concurrent verify traffic both stalls
                    # and skews it — benches are serialized against each
                    # other here, and callers should run it on an
                    # otherwise idle daemon.
                    v = st.verifier
                    if v is None:
                        _send_frame(conn, {
                            "ok": False,
                            "error": f"device not held (status: {st.status})",
                        })
                    elif not _bench_gate.acquire(blocking=False):
                        _send_frame(conn, {
                            "ok": False,
                            "error": "bench already running (serialized)",
                        })
                    else:
                        try:
                            batch = int(req.get("batch", 8192))
                            n_batches = int(req.get("n_batches", 8))
                            from tendermint_tpu.crypto import ed25519 as _ed

                            seeds = [
                                bytes([5, k]) + b"\x05" * 30 for k in range(64)
                            ]
                            base_items = [
                                (
                                    _ed.public_key(seeds[i % 64]),
                                    b"dbench-%d" % i,
                                    _ed.sign(seeds[i % 64], b"dbench-%d" % i),
                                )
                                for i in range(min(batch, 256))
                            ]
                            items = [
                                base_items[i % len(base_items)]
                                for i in range(batch)
                            ]
                            for _ in range(2):  # tables/compile off-clock
                                v.verify_batch(items)
                            t0 = time.time()
                            resolvers = [
                                v.verify_batch_async(items)
                                for _ in range(n_batches)
                            ]
                            # resolve EVERY batch before stopping the
                            # clock — short-circuiting on a failed batch
                            # would leave device work in flight and
                            # inflate the rate
                            results = [r() for r in resolvers]
                            dt = time.time() - t0
                            all_ok = all(all(res) for res in results)
                        finally:
                            _bench_gate.release()
                        _send_frame(conn, {
                            "ok": True,
                            "sigs_per_sec": (
                                batch * n_batches / dt if dt > 0 else 0.0
                            ),
                            "elapsed_s": dt,
                            "batch": batch,
                            "n_batches": n_batches,
                            "all_ok": all_ok,
                            "kernel": os.environ.get("TENDERMINT_TPU_KERNEL", ""),
                        })
                elif op == "shutdown":
                    _send_frame(conn, {"ok": True})
                    st.stop.set()
                    return
                else:
                    _send_frame(conn, {"ok": False, "error": f"unknown op {op!r}"})
            except Exception as exc:  # noqa: BLE001 — report, keep serving
                logger.exception("request failed")
                try:
                    _send_frame(conn, {"ok": False, "error": f"{type(exc).__name__}: {exc}"})
                except Exception:
                    return
    finally:
        try:
            conn.close()
        except Exception:
            pass


def serve(path: str | None = None) -> None:
    """Run the daemon (blocking). Env knobs:
    TENDERMINT_DEVD_SOCK          socket path (default /tmp/tendermint-devd.sock)
    TENDERMINT_DEVD_ACCEPT_CPU=1  serve the CPU backend (tests / no hardware)
    TENDERMINT_DEVD_WARM          comma-separated warm shapes (default 1024,4096,8192)
    TENDERMINT_DEVD_KERNEL        pin the served kernel (skips the claim-time
                                  comb-vs-f32p bake-off; any gateway.KERNELS
                                  name except "devd")
    TENDERMINT_DEVD_RETRY_S       device re-probe interval (default 120)
    TENDERMINT_DEVD_EXIT_ON_TERM=1  honor SIGTERM (default: ignore — device discipline)
    TENDERMINT_DEVD_CHUNK         pin the streamed chunk width (skips the
                                  claim-time width bake-off; clients pin
                                  their framing with the same var)
    TENDERMINT_DEVD_STREAM_DEPTH  max chunks in flight per stream (default 4)
    TENDERMINT_DEVD_SIM_RATE      serve a SIMULATED device at this sigs/s —
                                  transport benches only; requires ACCEPT_CPU=1
    """
    path = path or sock_path()
    env_k = os.environ.get("TENDERMINT_DEVD_KERNEL", "")
    if env_k:
        from tendermint_tpu.ops.gateway import KERNELS

        # fail fast at startup: inside the claim loop a bad name would be
        # swallowed by the retry handler and the daemon would spin forever
        if env_k not in KERNELS or env_k == "devd":
            raise SystemExit(
                f"TENDERMINT_DEVD_KERNEL={env_k!r}: expected one of "
                f"{sorted(k for k in KERNELS if k != 'devd')}"
            )
    accept_cpu = os.environ.get("TENDERMINT_DEVD_ACCEPT_CPU", "") == "1"
    # fail fast at startup on the remaining env knobs too: inside the
    # device thread a raise would be swallowed (threading ignores
    # SystemExit off the main thread) and the daemon would sit in
    # "starting" forever
    if float(os.environ.get("TENDERMINT_DEVD_SIM_RATE", "0") or 0) > 0 \
            and not accept_cpu:
        raise SystemExit(
            "TENDERMINT_DEVD_SIM_RATE requires TENDERMINT_DEVD_ACCEPT_CPU=1 "
            "(the sim verifier must never stand in front of real hardware)"
        )
    depth_env = os.environ.get("TENDERMINT_DEVD_STREAM_DEPTH", "")
    if depth_env:
        try:
            int(depth_env)
        except ValueError:
            raise SystemExit(
                f"TENDERMINT_DEVD_STREAM_DEPTH={depth_env!r}: expected an int"
            ) from None
    warm = tuple(
        int(x) for x in os.environ.get(
            "TENDERMINT_DEVD_WARM", "1024,4096,8192"
        ).split(",") if x
    )
    retry_s = float(os.environ.get("TENDERMINT_DEVD_RETRY_S", "120"))

    if os.environ.get("TENDERMINT_DEVD_EXIT_ON_TERM", "") != "1":
        def _ignore(signum, frame):
            logger.warning(
                "ignoring signal %d: killing the device owner mid-op wedges "
                "the tunnel; use the shutdown op or SIGKILL if you accept that",
                signum,
            )
        signal.signal(signal.SIGTERM, _ignore)
        signal.signal(signal.SIGINT, _ignore)

    # Bind first: refuse to start a second daemon on a live socket.
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    if os.path.exists(path):
        probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        probe.settimeout(1.0)
        try:
            probe.connect(path)
            raise SystemExit(f"devd already serving on {path}")
        except (ConnectionRefusedError, socket.timeout, FileNotFoundError):
            os.unlink(path)  # stale socket from a dead daemon
        finally:
            probe.close()
    srv.bind(path)
    os.chmod(path, 0o600)
    srv.listen(64)
    srv.settimeout(1.0)

    st = _DaemonState()
    threading.Thread(
        target=_device_loop, args=(st,),
        kwargs=dict(accept_cpu=accept_cpu, probe_timeout=60.0,
                    retry_s=retry_s, warm_shapes=warm),
        daemon=True, name="devd-device",
    ).start()

    logger.info("devd listening on %s (pid %d)", path, os.getpid())
    try:
        while not st.stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            threading.Thread(
                target=_handle_conn, args=(conn, st), daemon=True
            ).start()
    finally:
        srv.close()
        try:
            os.unlink(path)
        except OSError:
            pass
        logger.info("devd stopped")


# -- client -------------------------------------------------------------------


class DevdError(Exception):
    pass


# Sanctioned fault-injection point (ops/faults.py): when set, every NEW
# client connection passes through the wrapper (a socket-like proxy that
# injects scheduled faults). Production leaves it None; chaos tests and
# benches install it so the UNMODIFIED client/gateway triage paths are
# what gets exercised — no monkeypatching of internals.
_socket_wrapper = None


def set_socket_wrapper(wrapper) -> None:
    """Install (or clear, with None) the connection-factory wrapper
    applied by DevdClient._fresh. See ops/faults.install_client_faults."""
    global _socket_wrapper
    _socket_wrapper = wrapper


# -- client latency distributions (round 11) ----------------------------------
#
# The counters above say HOW MUCH rode each transport; these histograms
# say how LONG it took — the distributions the pipelining/sharding PRs
# will be judged against (docs/observability.md). Process-wide (the devd
# client is process-global), labeled by plane: op="verify" | "hash".

_hist_cache: dict = {}


def _latency_hists():
    """(per-chunk stream wait, single-shot round trip) histograms off
    the CURRENT default telemetry registry — re-fetched when tests swap
    the registry, cached otherwise so the hot path pays a dict probe."""
    from tendermint_tpu.libs import telemetry

    reg = telemetry.default_registry()
    if _hist_cache.get("reg") is not reg:
        _hist_cache["chunk"] = reg.histogram(
            "devd_stream_chunk_seconds",
            "per-chunk result wait on an active devd stream (writer "
            "overlap means this is the residual, not the full RTT)",
            labelnames=("op",),
        )
        _hist_cache["single"] = reg.histogram(
            "devd_single_shot_seconds",
            "single-shot devd pickle round trip (whole batch)",
            labelnames=("op",),
        )
        _hist_cache["reg"] = reg
    return _hist_cache["chunk"], _hist_cache["single"]


class DevdClient:
    """Client for the device daemon. verify_batch is synchronous;
    verify_batch_async sends on a pooled connection and returns a
    zero-arg resolver (the gateway's pipelining contract) — concurrent
    in-flight requests each ride their own connection, and the daemon
    serves connections in parallel, so the device queue stays full.

    verify_stream / verify_stream_async ride the chunked streaming
    protocol (module docstring): a writer thread packs and sends
    fixed-width chunk frames while the daemon verifies earlier chunks,
    and verdicts stream back per chunk — host marshal, IPC, and device
    compute all overlap instead of paying one monolithic round trip.

    A request that fails on a POOLED connection retries once on a fresh
    one: pooled sockets go stale whenever the daemon restarts, and a
    client must survive that without its caller seeing the flap.

    Deadline budgets (round 8): the single flat io_timeout is now only
    the default for three per-phase budgets — `connect` (dial), `claim`
    (control-plane ops: ping/status/stats/shutdown and stream headers),
    and `stream` (each frame read/write on an active stream). Data-plane
    single-shot verify/hash keep the full io budget (a first batch may
    legitimately sit behind a minutes-long kernel compile); everything
    else can and should fail faster. Env overrides:
    TENDERMINT_DEVD_CONNECT_TIMEOUT_S / _CLAIM_TIMEOUT_S /
    _STREAM_TIMEOUT_S."""

    def __init__(self, path: str | None = None,
                 connect_timeout: float | None = None,
                 io_timeout: float = 300.0, claim_timeout: float | None = None,
                 stream_timeout: float | None = None):
        self.path = path or sock_path()
        # env tunes only the DEFAULTS — an explicit constructor arg
        # always wins (devd.available builds its probe client with
        # connect_timeout=1.0 precisely so the breaker's inline health
        # probe stays bounded ~1 s; an operator's env knob must not
        # silently un-bound the verify hot path through it)
        self.connect_timeout = connect_timeout if connect_timeout is not None \
            else _env_timeout("TENDERMINT_DEVD_CONNECT_TIMEOUT_S", 2.0)
        self.io_timeout = io_timeout
        self.claim_timeout = claim_timeout if claim_timeout is not None \
            else _env_timeout("TENDERMINT_DEVD_CLAIM_TIMEOUT_S", io_timeout)
        self.stream_timeout = stream_timeout if stream_timeout is not None \
            else _env_timeout("TENDERMINT_DEVD_STREAM_TIMEOUT_S", io_timeout)
        self._pool: list[socket.socket] = []
        self._mtx = threading.Lock()
        self._adv_chunk: int | None = None  # daemon-advertised width
        # reconnects is the TOTAL; the labeled pair splits it by where
        # the stale socket surfaced — at first use of a pooled conn
        # (reconnects_connect: daemon restarted between requests) vs
        # mid-exchange (reconnects_midstream: it died under an active
        # request/stream) — so chaos tests can assert WHICH path fired
        self._stream_stats = {
            "stream_batches": 0, "stream_chunks_out": 0,
            "stream_lanes": 0, "stream_bytes_out": 0, "reconnects": 0,
            "reconnects_connect": 0, "reconnects_midstream": 0,
            "writer_abandoned": 0,
        }
        # hash-plane counters, same key shape (consumers prefix; the
        # gateway Hasher folds these in as flat stream_* gauges)
        self._hash_stats = {
            "stream_batches": 0, "stream_chunks_out": 0,
            "stream_lanes": 0, "stream_bytes_out": 0, "reconnects": 0,
            "reconnects_connect": 0, "reconnects_midstream": 0,
            "writer_abandoned": 0,
            "stream_trees": 0, "single_batches": 0, "single_lanes": 0,
        }

    def _note_reconnect(self, stats: dict, where: str) -> None:
        with self._mtx:
            stats["reconnects"] += 1
            stats[f"reconnects_{where}"] += 1

    def _acquire(self) -> tuple[socket.socket, bool]:
        """(connection, was_pooled). Pooled sockets may be stale — the
        caller retries once on a fresh one when was_pooled."""
        with self._mtx:
            if self._pool:
                return self._pool.pop(), True
        return self._fresh(), False

    def _release(self, conn: socket.socket) -> None:
        with self._mtx:
            self._pool.append(conn)

    def _discard(self, conn: socket.socket) -> None:
        try:
            conn.close()
        except Exception:
            pass

    def _kill(self, conn) -> None:
        """shutdown THEN discard: a conn being abandoned mid-stream may
        have the writer thread blocked in sendall on it, and close()
        alone never wakes a syscall pinned on the same fd — shutdown
        fails it fast, so the follow-up _reap_writer join returns
        promptly instead of burning the full reap budget."""
        try:
            conn.shutdown(socket.SHUT_RDWR)
        except Exception:  # noqa: BLE001 — already dead is fine
            pass
        self._discard(conn)

    def request(self, obj, timeout: float | None = None) -> dict:
        """One pickle round trip. The read/write budget defaults to the
        CLAIM deadline (control-plane ops fail fast); data-plane ops
        that may sit behind a kernel compile pass the io budget
        explicitly (verify_batch / hash_batch)."""
        conn, pooled = self._acquire()
        while True:
            conn.settimeout(timeout if timeout is not None
                            else self.claim_timeout)
            try:
                _send_frame(conn, obj)
                rep = _recv_frame(conn)
            except Exception as exc:
                self._discard(conn)
                # retry ONLY plausibly-stale pooled sockets (the daemon
                # restarted between requests): ConnectionError/EOF. A
                # timeout is a live-but-slow daemon — resubmitting the
                # same work would double device load exactly when it is
                # saturated (and break at-most-once for non-verify ops).
                if pooled and isinstance(exc, (ConnectionError, EOFError)):
                    self._note_reconnect(self._stream_stats, "connect")
                    conn, pooled = self._fresh(), False
                    continue
                raise
            conn.settimeout(self.io_timeout)
            self._release(conn)
            return rep

    def _fresh(self) -> socket.socket:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(self.connect_timeout)
        conn.connect(self.path)
        conn.settimeout(self.io_timeout)
        if _socket_wrapper is not None:  # chaos harness (ops/faults.py)
            conn = _socket_wrapper(conn)
        return conn

    def ping(self, timeout: float = 5.0) -> dict:
        rep = self.request({"op": "ping"}, timeout=timeout)
        if not rep.get("ok"):
            raise DevdError(rep.get("error", "ping failed"))
        return rep

    def verify_batch(self, items) -> list[bool]:
        t0 = time.perf_counter()
        rep = self.request({"op": "verify", "items": list(items)},
                           timeout=self.io_timeout)
        _latency_hists()[1].labels(op="verify").observe(
            time.perf_counter() - t0
        )
        if not rep.get("ok"):
            raise DevdError(rep.get("error", "verify failed"))
        return rep["results"]

    def agg_batch(self, terms) -> list[tuple[int, int]]:
        """Aggregate-commit dual-scalar-mul lanes (the 'agg' op): terms
        as in ops/ed25519.dsm_batch; returns per-lane affine points. A
        pre-agg daemon replies 'unknown op' -> DevdError, which
        ops/devd_backend latches into its CPU-floor fallback."""
        t0 = time.perf_counter()
        rep = self.request({"op": "agg", "items": [tuple(t) for t in terms]},
                           timeout=self.io_timeout)
        _latency_hists()[1].labels(op="agg").observe(
            time.perf_counter() - t0
        )
        if not rep.get("ok"):
            raise DevdError(rep.get("error", "agg failed"))
        return [tuple(p) for p in rep["points"]]

    def verify_batch_async(self, items):
        items = list(items)
        conn, pooled = self._acquire()
        try:
            _send_frame(conn, {"op": "verify", "items": items})
        except Exception as exc:
            self._discard(conn)
            if not (pooled and isinstance(exc, (ConnectionError, EOFError))):
                raise
            self._note_reconnect(self._stream_stats, "connect")
            conn, pooled = self._fresh(), False
            try:
                _send_frame(conn, {"op": "verify", "items": items})
            except Exception:
                self._discard(conn)
                raise

        def resolve() -> list[bool]:
            try:
                rep = _recv_frame(conn)
            except Exception as exc:
                self._discard(conn)
                if pooled and isinstance(exc, (ConnectionError, EOFError)):
                    # stale pooled socket: the daemon restarted between
                    # requests — the whole batch retries on a fresh conn
                    # (timeouts deliberately do NOT retry: see request())
                    self._note_reconnect(self._stream_stats, "midstream")
                    return self.verify_batch(items)
                raise
            self._release(conn)
            if not rep.get("ok"):
                raise DevdError(rep.get("error", "verify failed"))
            return rep["results"]

        return resolve

    # -- streaming transport ------------------------------------------------

    def stream_chunk(self) -> int:
        """Chunk width for streamed submission: TENDERMINT_DEVD_CHUNK
        pins it; otherwise the daemon's claim-time-tuned width (one ping,
        cached for the client lifetime); DEFAULT_STREAM_CHUNK failing
        both."""
        try:
            env = int(os.environ.get("TENDERMINT_DEVD_CHUNK", "0") or 0)
        except ValueError:  # a typo'd env var must not kill the verify
            # hot path (gateway would latch the CPU fallback); the
            # daemon-side serve() validation is the loud failure
            logger.warning("ignoring malformed TENDERMINT_DEVD_CHUNK")
            env = 0
        if env > 0:
            return env
        if self._adv_chunk is None:
            try:
                self._adv_chunk = int(
                    self.ping().get("stream_chunk", 0)
                ) or DEFAULT_STREAM_CHUNK
            except Exception:  # noqa: BLE001 — daemon unreachable: the
                # stream attempt itself will surface the real error
                return DEFAULT_STREAM_CHUNK
        return self._adv_chunk

    def verify_stream(self, items, chunk: int | None = None) -> list[bool]:
        """Streamed verify_batch: same verdicts, pipelined transport."""
        return self.verify_stream_async(items, chunk=chunk)()

    def verify_stream_async(self, items, chunk: int | None = None):
        """Submit `items` as fixed-width chunk frames on one connection;
        a writer thread streams frames while the daemon verifies, and
        the returned zero-arg resolver collects per-chunk verdicts in
        order. A failed attempt on a pooled connection retries once on a
        fresh one (daemon restarts must not surface to the caller)."""
        items = list(items)
        if not items:
            return lambda: []
        width = max(1, chunk or self.stream_chunk())
        spans = [items[i: i + width] for i in range(0, len(items), width)]
        header = {
            "op": "verify_stream",
            "chunks": len(spans),
            "total": sum(len(s) for s in spans),
        }
        return self._stream_resolver(
            spans, header, _pack_chunk, self._stream_stats,
            lambda conn, writer, werr: self._collect_stream(
                conn, writer, werr, len(spans)
            ),
        )

    def _stream_resolver(self, spans, header: dict, pack, stats, collect):
        """Open a chunked stream NOW and return the zero-arg resolver
        with the shared reconnect-once error triage (verify and hash
        planes): a DevdError is final; a writer error that is not an
        OSError is a deterministic client-side marshal failure (a retry
        would fail identically — surface the real cause); a transport
        failure on a POOLED connection retries once on a fresh one
        (daemon restarts must not surface to the caller)."""
        first = self._start_stream(spans, False, header, pack, stats)

        def resolve():
            conn, pooled, writer, werr = first
            try:
                return collect(conn, writer, werr)
            except DevdError:
                self._kill(conn)
                self._reap_writer(writer, stats, conn)
                raise
            except Exception as exc:
                self._kill(conn)
                self._reap_writer(writer, stats, conn)
                if werr and not isinstance(werr[0], OSError):
                    raise werr[0] from exc
                if not (pooled and isinstance(exc, (ConnectionError, EOFError))):
                    raise
                self._note_reconnect(stats, "midstream")
                conn2, _, writer2, werr2 = self._start_stream(
                    spans, True, header, pack, stats
                )
                try:
                    return collect(conn2, writer2, werr2)
                except Exception:
                    self._kill(conn2)
                    self._reap_writer(writer2, stats, conn2)
                    raise

        return resolve

    def _reap_writer(self, writer, stats: dict, conn) -> bool:
        """Join the writer thread under a bounded budget. An overrun is
        ABANDONMENT (satellite fix, round 8): the pre-r8 code silently
        walked away from a live writer wedged in sendall, leaving its
        thread and connection dangling with no trace in any counter.
        Now abandonment counts as a fault (`writer_abandoned`, surfaced
        through stream_* stats), and the connection is closed — which
        both unwedges the stuck sendall (it fails fast on the dead fd)
        and guarantees the socket can never re-enter the pool. Returns
        True when the writer had to be abandoned."""
        writer.join(timeout=WRITER_REAP_S)
        if not writer.is_alive():
            return False
        with self._mtx:
            stats["writer_abandoned"] += 1
        logger.warning(
            "stream writer abandoned after join timeout; closing its conn"
        )
        self._kill(conn)  # shutdown-then-close: unwedges a pinned sendall
        return True

    def _start_stream(self, spans, fresh: bool, header: dict, pack, stats):
        """Open one chunked stream (verify or hash plane): send the
        pickle header, then launch the writer thread that packs and
        streams chunk frames. `stats` is the client counter dict the
        writer notes its totals into (shared key shape)."""
        if fresh:
            conn, pooled = self._fresh(), False
        else:
            conn, pooled = self._acquire()
        try:
            conn.settimeout(self.claim_timeout)
            _send_frame(conn, header)
            # per-frame budget for the active stream: each chunk write
            # and each result read must make progress inside this window
            # (a stalled daemon surfaces as socket.timeout here instead
            # of sitting on the full flat io budget)
            conn.settimeout(self.stream_timeout)
        except Exception as exc:
            self._discard(conn)
            if not (pooled and isinstance(exc, (ConnectionError, EOFError))):
                raise
            self._note_reconnect(stats, "connect")
            return self._start_stream(spans, True, header, pack, stats)
        werr: list = []

        def write() -> None:
            # pack-as-you-send: marshaling chunk N+1 overlaps the
            # daemon's decode+verify of chunk N (and the resolver's
            # reads) — the client never builds the whole wire image
            try:
                sent_chunks = sent_bytes = sent_lanes = 0
                for span in spans:
                    payload = pack(span)
                    conn.sendall(struct.pack(">I", len(payload)) + payload)
                    sent_chunks += 1
                    sent_bytes += len(payload)
                    sent_lanes += len(span)
                with self._mtx:
                    stats["stream_batches"] += 1
                    stats["stream_chunks_out"] += sent_chunks
                    stats["stream_bytes_out"] += sent_bytes
                    stats["stream_lanes"] += sent_lanes
            except Exception as exc:  # noqa: BLE001 — surfaced by resolver
                werr.append(exc)
                # fail FAST on both sides: without this the daemon would
                # block reading the chunks that will never come and the
                # resolver would block on verdicts until io_timeout
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

        writer = threading.Thread(target=write, daemon=True,
                                  name="devd-stream-write")
        writer.start()
        return conn, pooled, writer, werr

    def _collect_stream(self, conn, writer, werr, n_chunks: int) -> list[bool]:
        import numpy as np

        chunk_hist = _latency_hists()[0].labels(op="verify")
        out: list[bool] = []
        for want in range(n_chunks):
            t0 = time.perf_counter()
            payload = _recv_raw_frame(conn)
            chunk_hist.observe(time.perf_counter() - t0)
            status, idx = struct.unpack_from("<BI", payload, 0)
            if status == STREAM_ERR:
                # the resolver's DevdError handler discards the conn and
                # reaps the writer (abandonment-counted) — no join here
                raise DevdError(
                    f"stream chunk {idx}: {payload[5:].decode(errors='replace')}"
                )
            if status not in (STREAM_OK, STREAM_ERR):
                if status == 0x80:  # a PICKLE frame: the daemon answered
                    # the verify_stream header with {"ok": False, ...} —
                    # it predates the streaming protocol. The marker
                    # below is what devd_backend latches single-shot on;
                    # any OTHER desync must NOT latch (it would silently
                    # disable the fast path over a transient bug).
                    raise DevdError("daemon too old for verify_stream")
                raise DevdError(
                    f"bad stream result frame (status {status}, chunk {want})"
                )
            if idx != want:
                raise DevdError(
                    f"stream result desync: got chunk {idx}, want {want}"
                )
            (n,) = struct.unpack_from("<I", payload, 5)
            if len(payload) != 9 + n:
                raise DevdError(f"result frame size mismatch for chunk {idx}")
            out.extend(
                np.frombuffer(payload, dtype=np.uint8, offset=9)
                .astype(bool).tolist()
            )
        abandoned = self._reap_writer(writer, self._stream_stats, conn)
        if werr:
            # results complete but the writer died — impossible unless
            # the daemon answered chunks it never received; be loud
            raise DevdError(f"stream writer failed: {werr[0]}")
        if not abandoned:
            conn.settimeout(self.io_timeout)  # back to pickle mode
            self._release(conn)
        return out

    # -- streamed hash transport --------------------------------------------

    def hash_batch(self, items, mode: str = "part", tree: bool = False):
        """Single-shot daemon hashing: one pickle frame each way. Digest
        list; with tree=True, (digests, postorder internal nodes)."""
        t0 = time.perf_counter()
        rep = self.request({
            "op": "hash", "mode": mode,
            "items": [bytes(b) for b in items], "tree": bool(tree),
        }, timeout=self.io_timeout)
        _latency_hists()[1].labels(op="hash").observe(
            time.perf_counter() - t0
        )
        if not rep.get("ok"):
            raise DevdError(rep.get("error", "hash failed"))
        with self._mtx:
            self._hash_stats["single_batches"] += 1
            self._hash_stats["single_lanes"] += len(rep["digests"])
        if tree:
            return rep["digests"], rep.get("nodes", [])
        return rep["digests"]

    def hash_stream(self, items, mode: str = "part", tree: bool = False,
                    chunk: int | None = None):
        """Streamed hash_batch: same digests, pipelined transport."""
        return self.hash_stream_async(items, mode=mode, tree=tree,
                                      chunk=chunk)()

    def hash_stream_async(self, items, mode: str = "part",
                          tree: bool = False, chunk: int | None = None):
        """Submit leaf payloads as chunked hash frames on one connection;
        the returned resolver collects per-chunk digest frames in order
        (plus the tree frame when tree=True → (digests, internal_nodes)).
        Reconnect-once semantics match verify_stream_async: a failed
        attempt on a pooled connection retries on a fresh one."""
        items = [bytes(b) for b in items]
        if not items:
            return (lambda: ([], [])) if tree else (lambda: [])
        width = max(1, chunk or self.stream_chunk())
        spans = [items[i: i + width] for i in range(0, len(items), width)]
        header = {
            "op": "hash_stream",
            "chunks": len(spans),
            "total": len(items),
            "mode": mode,
            "tree": bool(tree),
        }
        return self._stream_resolver(
            spans, header, _pack_hash_chunk, self._hash_stats,
            lambda conn, writer, werr: self._collect_hash_stream(
                conn, writer, werr, len(spans), tree
            ),
        )

    def _collect_hash_stream(self, conn, writer, werr, n_chunks: int,
                             want_tree: bool):
        chunk_hist = _latency_hists()[0].labels(op="hash")
        digests: list[bytes] = []
        for want in range(n_chunks):
            t0 = time.perf_counter()
            payload = _recv_raw_frame(conn)
            chunk_hist.observe(time.perf_counter() - t0)
            status, idx = struct.unpack_from("<BI", payload, 0)
            if status == STREAM_ERR:
                # resolver discards + reaps (see _collect_stream)
                raise DevdError(
                    f"hash stream chunk {idx}: "
                    f"{payload[5:].decode(errors='replace')}"
                )
            if status != STREAM_OK:
                if status == 0x80:  # pickle frame: pre-r7 daemon answered
                    # the header with {"ok": False, "error": "unknown op"}
                    raise DevdError("daemon too old for hash_stream")
                raise DevdError(
                    f"bad hash result frame (status {status}, chunk {want})"
                )
            if idx != want:
                raise DevdError(
                    f"hash stream desync: got chunk {idx}, want {want}"
                )
            (n,) = struct.unpack_from("<I", payload, 5)
            if len(payload) != 9 + 20 * n:
                raise DevdError(f"digest frame size mismatch for chunk {idx}")
            digests.extend(
                payload[9 + 20 * i: 29 + 20 * i] for i in range(n)
            )
        nodes: list[bytes] | None = None
        if want_tree:
            payload = _recv_raw_frame(conn)
            status, cnt = struct.unpack_from("<BI", payload, 0)
            if status == STREAM_ERR:
                raise DevdError(
                    f"hash stream tree: {payload[5:].decode(errors='replace')}"
                )
            if status != STREAM_TREE or len(payload) != 5 + 20 * cnt:
                raise DevdError(f"bad tree frame (status {status})")
            nodes = [payload[5 + 20 * i: 25 + 20 * i] for i in range(cnt)]
            with self._mtx:
                self._hash_stats["stream_trees"] += 1
        abandoned = self._reap_writer(writer, self._hash_stats, conn)
        if werr:
            raise DevdError(f"hash stream writer failed: {werr[0]}")
        if not abandoned:
            conn.settimeout(self.io_timeout)  # back to pickle mode
            self._release(conn)
        return (digests, nodes) if want_tree else digests

    def hash_stream_stats(self) -> dict:
        """Client-side hash-transport counters (ops/gateway.Hasher folds
        these in as flat stream_* gauges for the metrics RPC)."""
        with self._mtx:
            return dict(self._hash_stats)

    def stream_stats(self) -> dict:
        """Client-side streamed-transport counters (Verifier.stats()
        merges these under \"stream\" for the devd backend)."""
        with self._mtx:
            return dict(self._stream_stats)

    def status(self, timeout: float = 5.0) -> dict:
        """Ping plus the daemon's streamed-chunk observability counters."""
        rep = self.request({"op": "status"}, timeout=timeout)
        if not rep.get("ok"):
            raise DevdError(rep.get("error", "status failed"))
        return rep

    def stats(self) -> dict:
        rep = self.request({"op": "stats"})
        if not rep.get("ok"):
            raise DevdError(rep.get("error", "stats failed"))
        return rep["stats"]

    def bench(self, batch: int = 8192, n_batches: int = 8,
              timeout: float = 600.0) -> dict:
        """In-daemon pipelined device rate (see the bench op)."""
        rep = self.request(
            {"op": "bench", "batch": batch, "n_batches": n_batches},
            timeout=timeout,
        )
        if not rep.get("ok"):
            raise DevdError(rep.get("error", "bench failed"))
        return rep

    def shutdown(self) -> None:
        self.request({"op": "shutdown"})

    def close(self) -> None:
        with self._mtx:
            pool, self._pool = self._pool, []
        for c in pool:
            self._discard(c)


# per-path probe cache: the sharded plane (ops/devd_shard) probes every
# endpoint independently, so one entry per socket path
_avail_cache: dict[str, tuple[float, dict | None]] = {}
_avail_mtx = threading.Lock()
_AVAIL_TTL = 15.0


def bust_avail_cache(path: str | None = None) -> None:
    """Force the next available() to ping fresh — failure paths must not
    trust a TTL-cached 'held' from a daemon that just died. No-arg busts
    every endpoint's entry; a path busts just that endpoint's."""
    with _avail_mtx:
        if path is None:
            _avail_cache.clear()
        else:
            _avail_cache.pop(path, None)


def available(timeout: float = 1.0, path: str | None = None) -> dict | None:
    """Liveness probe: the daemon's ping reply if a daemon is serving AND
    holds the device, else None. Never raises. Positive AND negative
    results are cached ~15s per socket path — the gateway consults this
    per batch on its kernel-selection default, and a ping (or a failed
    connect) per batch would dominate small-batch latency. `path` probes
    one sharded-plane endpoint; default is the primary socket."""
    path = path or sock_path()
    now = time.monotonic()
    with _avail_mtx:
        hit = _avail_cache.get(path)
        if hit is not None and now - hit[0] < _AVAIL_TTL:
            return hit[1]
    rep = None
    if os.path.exists(path):
        try:
            c = DevdClient(path, connect_timeout=timeout, io_timeout=timeout)
            r = c.ping(timeout=timeout)
            c.close()
            rep = r if r.get("held") else None
        except Exception:
            rep = None
    with _avail_mtx:
        _avail_cache[path] = (now, rep)
    return rep


def main() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    serve()


if __name__ == "__main__":
    main()
