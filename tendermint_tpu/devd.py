"""Device-access daemon: ONE long-lived process owns the accelerator.

Why this exists (round-3 postmortem): the tunneled TPU wedges PERMANENTLY
when any process dies mid-device-op — a timeout-killed bench or test takes
the device down for every later process, and the round's official bench
silently became a CPU number. The fix is discipline, not detection:

- devd is the ONLY process that dials the device. It claims the chip,
  warms the verify kernels at production shapes, and then serves verify
  batches over a root-only unix socket forever.
- Everything else (benches, tests, live nodes) talks to devd through
  DevdClient / ops/devd_backend.py — so killing a node, a bench, or a
  test can NEVER wedge the tunnel: those processes hold no device state.
- devd itself ignores SIGTERM (set TENDERMINT_DEVD_EXIT_ON_TERM=1 to
  allow graceful exit, e.g. in tests) and is started detached (setsid)
  so an interactive session ending doesn't reap it mid-op.
- If the device is unreachable at startup, devd keeps polling in
  throwaway subprocesses (a hung in-process dial would poison the jax
  backend-init lock for the process lifetime) and claims the chip the
  moment the tunnel comes back. Status is always visible via `ping`.

The reference runs its signature checks inline per process
(types/validator_set.go:220-264); a per-host device daemon is the
TPU-native replacement: one chip, one owner, many client processes.

Wire protocol (trusted local IPC, socket mode 0600, root-only box):
4-byte big-endian length + pickled dict. Requests: {"op": "ping" |
"verify" | "stats" | "shutdown", ...}. Replies: {"ok": bool, ...}.
"""

from __future__ import annotations

import logging
import os
import pickle
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

logger = logging.getLogger("devd")

DEFAULT_SOCK = "/tmp/tendermint-devd.sock"


def sock_path() -> str:
    return os.environ.get("TENDERMINT_DEVD_SOCK", DEFAULT_SOCK)


# -- framing ------------------------------------------------------------------


def _send_frame(conn: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    conn.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("devd peer closed")
        buf += chunk
    return buf


def _recv_frame(conn: socket.socket):
    (n,) = struct.unpack(">I", _recv_exact(conn, 4))
    if n > (1 << 30):
        raise ValueError(f"devd frame too large: {n}")
    return pickle.loads(_recv_exact(conn, n))


# -- server -------------------------------------------------------------------


class _DaemonState:
    def __init__(self):
        self.started = time.time()
        self.platform: str | None = None
        self.verifier = None  # ops.gateway.Verifier once the device is held
        self.warmed: list[int] = []
        self.status = "starting"
        self.lock = threading.Lock()
        self.stop = threading.Event()


def subprocess_probe(timeout_s: float) -> str | None:
    """Dial the device in a THROWAWAY subprocess; the platform name or
    None. The probe bounds itself (jitcache.probe_device daemon-thread
    dial + clean interpreter exit), so no one ever SIGKILLs a process
    mid-device-op here; if the child somehow outlives its own bound, it
    is left to finish — never killed. Use THIS (not an in-process
    probe_device) from any process that must stay usable afterwards: a
    hung in-process dial holds jax's backend-init lock forever, so even
    later CPU-only jax calls in that process would block."""
    code = (
        "from tendermint_tpu.jitcache import probe_device; import sys;"
        f"p = probe_device({timeout_s});"
        "print(p or '', end='');"
        "sys.exit(0 if p else 1)"
    )
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        try:
            out, _ = proc.communicate(timeout=timeout_s + 60)
        except subprocess.TimeoutExpired:
            logger.warning("probe subprocess overran; leaving it to exit on its own")
            return None
        if proc.returncode == 0:
            return (out or b"").decode() or "unknown"
        return None
    except Exception:
        logger.exception("probe subprocess failed")
        return None


def _device_loop(st: _DaemonState, *, accept_cpu: bool, probe_timeout: float,
                 retry_s: float, warm_shapes: tuple[int, ...]) -> None:
    """Poll for the device, claim it, warm kernels, flip state to serving."""
    from tendermint_tpu.jitcache import enable as enable_cache

    enable_cache()
    if accept_cpu:
        # a CPU daemon must never dial the tunnel; die visibly if the
        # pin cannot be applied (strict) instead of probing unpinned
        from tendermint_tpu.ops.gateway import pin_jax_cpu

        pin_jax_cpu(strict=True)
    while not st.stop.is_set():
        st.status = "probing"
        if accept_cpu:
            platform = "cpu"
        else:
            platform = subprocess_probe(probe_timeout)
        if platform is None:
            st.status = "waiting-for-device"
            logger.warning(
                "device unreachable; retrying in %.0fs (tunnel may recover)",
                retry_s,
            )
            if st.stop.wait(retry_s):
                return
            continue
        # A subprocess just proved the tunnel answers — now dial in-process
        # and hold the device for the daemon's lifetime.
        try:
            st.status = "claiming"
            from tendermint_tpu.ops import gateway

            # decide from the probe's OWN answer — going through
            # gateway.on_tpu() here would run a second redundant probe
            # (this daemon's socket isn't "held" yet), and a slow second
            # probe would mis-pin the daemon's jax to CPU while reporting
            # a TPU platform
            on_tpu = (not accept_cpu) and platform in ("tpu", "axon")
            gateway.set_platform("cpu" if accept_cpu else platform)
            # kernel choice: explicit TENDERMINT_DEVD_KERNEL wins; on TPU
            # hardware, bake off the comb kernel against the f32p ladder
            # at claim time and serve the measured winner (pinning the
            # direct kernel also keeps the gateway default from routing
            # the daemon's own verifier back through devd)
            env_k = os.environ.get("TENDERMINT_DEVD_KERNEL", "")
            if env_k:
                candidates = [env_k]
            elif on_tpu:
                candidates = ["comb", "f32p"]
            else:
                candidates = ["f32"]
            st.status = "warming"
            from tendermint_tpu.crypto import ed25519 as ed

            # 64 distinct keys cycled across lanes: enough key diversity
            # to exercise the comb pool's gather path without minutes of
            # python keygen
            seeds = [bytes([5, k]) + b"\x05" * 30 for k in range(64)]
            keys = [(s, ed.public_key(s)) for s in seeds]
            verifier = None
            best: tuple[float, str] | None = None
            for kname in candidates:
                os.environ["TENDERMINT_TPU_KERNEL"] = kname
                v = gateway.Verifier(min_tpu_batch=1, use_tpu=True)
                if not warm_shapes:
                    # warming disabled (TENDERMINT_DEVD_WARM=""): serve
                    # the first candidate unwarmed, as before round 5
                    if verifier is None:
                        verifier = v
                        best = (0.0, kname)
                    continue
                def make_full(shape: int) -> list:
                    items = [
                        (
                            keys[i % 64][1],
                            b"warm-%d" % i,
                            ed.sign(keys[i % 64][0], b"warm-%d" % i),
                        )
                        for i in range(min(shape, 256))
                    ]
                    return [items[i % len(items)] for i in range(shape)]

                for shape in warm_shapes:
                    t0 = time.time()
                    ok = v.verify_batch(make_full(shape))
                    assert all(ok), (
                        f"warm verify failed: kernel {kname} shape {shape}"
                    )
                    logger.info(
                        "kernel %s warmed shape %d in %.1fs",
                        kname, shape, time.time() - t0,
                    )
                    if shape not in st.warmed:
                        st.warmed.append(shape)
                # timed steady-state pass at the LARGEST shape. Two
                # untimed passes first: with the comb kernel's default
                # second-sight policy the first pass at a shape may still
                # route lanes to the ladder and the second pays table
                # builds + compile — neither may land inside the timed
                # region or the bake-off picks the wrong winner.
                # The timed region is PIPELINED (several batches in
                # flight via verify_batch_async): serving throughput is
                # what the daemon exists for, and a single synchronous
                # batch is dominated by the tunnel round trip — it ranks
                # kernels by RTT, not by device rate (the r5 bake-off
                # initially picked on 1-batch numbers 4-7x below the
                # pipelined rate).
                full = make_full(max(warm_shapes))
                for _ in range(2):
                    v.verify_batch(full)
                n_pipe = 6
                t0 = time.time()
                resolvers = [v.verify_batch_async(full) for _ in range(n_pipe)]
                for r in resolvers:
                    r()
                dt = time.time() - t0
                rate = n_pipe * len(full) / dt if dt > 0 else 0.0
                logger.info(
                    "kernel %s: %.0f sigs/s sustained (%d x %d pipelined)",
                    kname, rate, n_pipe, len(full),
                )
                if best is None or dt < best[0]:
                    best = (dt, kname)
                    verifier = v
            os.environ["TENDERMINT_TPU_KERNEL"] = best[1]
            logger.info("serving kernel: %s", best[1])
            with st.lock:
                st.platform = platform if not accept_cpu else "cpu"
                st.verifier = verifier
                st.status = "serving"
            logger.info("device held (%s); serving", st.platform)
            return
        except Exception:
            logger.exception("claim/warm failed; retrying in %.0fs", retry_s)
            st.status = "waiting-for-device"
            if st.stop.wait(retry_s):
                return


# one bench at a time daemon-wide (see the bench op)
_bench_gate = threading.Lock()


def _handle_conn(conn: socket.socket, st: _DaemonState) -> None:
    try:
        while True:
            try:
                req = _recv_frame(conn)
            except (ConnectionError, EOFError):
                return
            op = req.get("op")

            def held_stats() -> dict:
                with st.lock:
                    return st.verifier.stats() if st.verifier else {}

            try:
                if op == "ping":
                    _send_frame(conn, {
                        "ok": True,
                        "platform": st.platform,
                        "held": st.verifier is not None,
                        "status": st.status,
                        "warmed": list(st.warmed),
                        "uptime_s": round(time.time() - st.started, 1),
                        "stats": held_stats(),
                        "pid": os.getpid(),
                    })
                elif op == "verify":
                    v = st.verifier
                    if v is None:
                        _send_frame(conn, {
                            "ok": False,
                            "error": f"device not held (status: {st.status})",
                        })
                    else:
                        oks = v.verify_batch(req["items"])
                        _send_frame(conn, {"ok": True, "results": [bool(b) for b in oks]})
                elif op == "stats":
                    _send_frame(conn, {"ok": True, "stats": held_stats()})
                elif op == "bench":
                    # In-daemon pipelined throughput measurement: the one
                    # number free of ALL client-side confounds (IPC
                    # marshal, socket hops, client thread scheduling) —
                    # how fast the held device verifies when its queue is
                    # kept full. Items are synthesized daemon-side with
                    # the warm-set key-reuse shape (64 keys cycled, a
                    # real commit's profile). MAINTENANCE op: it queues
                    # ~n_batches*batch lanes on the shared serving
                    # verifier, so concurrent verify traffic both stalls
                    # and skews it — benches are serialized against each
                    # other here, and callers should run it on an
                    # otherwise idle daemon.
                    v = st.verifier
                    if v is None:
                        _send_frame(conn, {
                            "ok": False,
                            "error": f"device not held (status: {st.status})",
                        })
                    elif not _bench_gate.acquire(blocking=False):
                        _send_frame(conn, {
                            "ok": False,
                            "error": "bench already running (serialized)",
                        })
                    else:
                        try:
                            batch = int(req.get("batch", 8192))
                            n_batches = int(req.get("n_batches", 8))
                            from tendermint_tpu.crypto import ed25519 as _ed

                            seeds = [
                                bytes([5, k]) + b"\x05" * 30 for k in range(64)
                            ]
                            base_items = [
                                (
                                    _ed.public_key(seeds[i % 64]),
                                    b"dbench-%d" % i,
                                    _ed.sign(seeds[i % 64], b"dbench-%d" % i),
                                )
                                for i in range(min(batch, 256))
                            ]
                            items = [
                                base_items[i % len(base_items)]
                                for i in range(batch)
                            ]
                            for _ in range(2):  # tables/compile off-clock
                                v.verify_batch(items)
                            t0 = time.time()
                            resolvers = [
                                v.verify_batch_async(items)
                                for _ in range(n_batches)
                            ]
                            # resolve EVERY batch before stopping the
                            # clock — short-circuiting on a failed batch
                            # would leave device work in flight and
                            # inflate the rate
                            results = [r() for r in resolvers]
                            dt = time.time() - t0
                            all_ok = all(all(res) for res in results)
                        finally:
                            _bench_gate.release()
                        _send_frame(conn, {
                            "ok": True,
                            "sigs_per_sec": (
                                batch * n_batches / dt if dt > 0 else 0.0
                            ),
                            "elapsed_s": dt,
                            "batch": batch,
                            "n_batches": n_batches,
                            "all_ok": all_ok,
                            "kernel": os.environ.get("TENDERMINT_TPU_KERNEL", ""),
                        })
                elif op == "shutdown":
                    _send_frame(conn, {"ok": True})
                    st.stop.set()
                    return
                else:
                    _send_frame(conn, {"ok": False, "error": f"unknown op {op!r}"})
            except Exception as exc:  # noqa: BLE001 — report, keep serving
                logger.exception("request failed")
                try:
                    _send_frame(conn, {"ok": False, "error": f"{type(exc).__name__}: {exc}"})
                except Exception:
                    return
    finally:
        try:
            conn.close()
        except Exception:
            pass


def serve(path: str | None = None) -> None:
    """Run the daemon (blocking). Env knobs:
    TENDERMINT_DEVD_SOCK          socket path (default /tmp/tendermint-devd.sock)
    TENDERMINT_DEVD_ACCEPT_CPU=1  serve the CPU backend (tests / no hardware)
    TENDERMINT_DEVD_WARM          comma-separated warm shapes (default 1024,4096,8192)
    TENDERMINT_DEVD_KERNEL        pin the served kernel (skips the claim-time
                                  comb-vs-f32p bake-off; any gateway.KERNELS
                                  name except "devd")
    TENDERMINT_DEVD_RETRY_S       device re-probe interval (default 120)
    TENDERMINT_DEVD_EXIT_ON_TERM=1  honor SIGTERM (default: ignore — device discipline)
    """
    path = path or sock_path()
    env_k = os.environ.get("TENDERMINT_DEVD_KERNEL", "")
    if env_k:
        from tendermint_tpu.ops.gateway import KERNELS

        # fail fast at startup: inside the claim loop a bad name would be
        # swallowed by the retry handler and the daemon would spin forever
        if env_k not in KERNELS or env_k == "devd":
            raise SystemExit(
                f"TENDERMINT_DEVD_KERNEL={env_k!r}: expected one of "
                f"{sorted(k for k in KERNELS if k != 'devd')}"
            )
    accept_cpu = os.environ.get("TENDERMINT_DEVD_ACCEPT_CPU", "") == "1"
    warm = tuple(
        int(x) for x in os.environ.get(
            "TENDERMINT_DEVD_WARM", "1024,4096,8192"
        ).split(",") if x
    )
    retry_s = float(os.environ.get("TENDERMINT_DEVD_RETRY_S", "120"))

    if os.environ.get("TENDERMINT_DEVD_EXIT_ON_TERM", "") != "1":
        def _ignore(signum, frame):
            logger.warning(
                "ignoring signal %d: killing the device owner mid-op wedges "
                "the tunnel; use the shutdown op or SIGKILL if you accept that",
                signum,
            )
        signal.signal(signal.SIGTERM, _ignore)
        signal.signal(signal.SIGINT, _ignore)

    # Bind first: refuse to start a second daemon on a live socket.
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    if os.path.exists(path):
        probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        probe.settimeout(1.0)
        try:
            probe.connect(path)
            raise SystemExit(f"devd already serving on {path}")
        except (ConnectionRefusedError, socket.timeout, FileNotFoundError):
            os.unlink(path)  # stale socket from a dead daemon
        finally:
            probe.close()
    srv.bind(path)
    os.chmod(path, 0o600)
    srv.listen(64)
    srv.settimeout(1.0)

    st = _DaemonState()
    threading.Thread(
        target=_device_loop, args=(st,),
        kwargs=dict(accept_cpu=accept_cpu, probe_timeout=60.0,
                    retry_s=retry_s, warm_shapes=warm),
        daemon=True, name="devd-device",
    ).start()

    logger.info("devd listening on %s (pid %d)", path, os.getpid())
    try:
        while not st.stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            threading.Thread(
                target=_handle_conn, args=(conn, st), daemon=True
            ).start()
    finally:
        srv.close()
        try:
            os.unlink(path)
        except OSError:
            pass
        logger.info("devd stopped")


# -- client -------------------------------------------------------------------


class DevdError(Exception):
    pass


class DevdClient:
    """Client for the device daemon. verify_batch is synchronous;
    verify_batch_async sends on a pooled connection and returns a
    zero-arg resolver (the gateway's pipelining contract) — concurrent
    in-flight requests each ride their own connection, and the daemon
    serves connections in parallel, so the device queue stays full."""

    def __init__(self, path: str | None = None, connect_timeout: float = 2.0,
                 io_timeout: float = 300.0):
        self.path = path or sock_path()
        self.connect_timeout = connect_timeout
        self.io_timeout = io_timeout
        self._pool: list[socket.socket] = []
        self._mtx = threading.Lock()

    def _acquire(self) -> socket.socket:
        with self._mtx:
            if self._pool:
                return self._pool.pop()
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(self.connect_timeout)
        conn.connect(self.path)
        conn.settimeout(self.io_timeout)
        return conn

    def _release(self, conn: socket.socket) -> None:
        with self._mtx:
            self._pool.append(conn)

    def _discard(self, conn: socket.socket) -> None:
        try:
            conn.close()
        except Exception:
            pass

    def request(self, obj, timeout: float | None = None) -> dict:
        conn = self._acquire()
        if timeout is not None:
            conn.settimeout(timeout)
        try:
            _send_frame(conn, obj)
            rep = _recv_frame(conn)
        except Exception:
            self._discard(conn)
            raise
        if timeout is not None:
            conn.settimeout(self.io_timeout)
        self._release(conn)
        return rep

    def ping(self, timeout: float = 5.0) -> dict:
        rep = self.request({"op": "ping"}, timeout=timeout)
        if not rep.get("ok"):
            raise DevdError(rep.get("error", "ping failed"))
        return rep

    def verify_batch(self, items) -> list[bool]:
        rep = self.request({"op": "verify", "items": list(items)})
        if not rep.get("ok"):
            raise DevdError(rep.get("error", "verify failed"))
        return rep["results"]

    def verify_batch_async(self, items):
        conn = self._acquire()
        try:
            _send_frame(conn, {"op": "verify", "items": list(items)})
        except Exception:
            self._discard(conn)
            raise

        def resolve() -> list[bool]:
            try:
                rep = _recv_frame(conn)
            except Exception:
                self._discard(conn)
                raise
            self._release(conn)
            if not rep.get("ok"):
                raise DevdError(rep.get("error", "verify failed"))
            return rep["results"]

        return resolve

    def stats(self) -> dict:
        rep = self.request({"op": "stats"})
        if not rep.get("ok"):
            raise DevdError(rep.get("error", "stats failed"))
        return rep["stats"]

    def bench(self, batch: int = 8192, n_batches: int = 8,
              timeout: float = 600.0) -> dict:
        """In-daemon pipelined device rate (see the bench op)."""
        rep = self.request(
            {"op": "bench", "batch": batch, "n_batches": n_batches},
            timeout=timeout,
        )
        if not rep.get("ok"):
            raise DevdError(rep.get("error", "bench failed"))
        return rep

    def shutdown(self) -> None:
        self.request({"op": "shutdown"})

    def close(self) -> None:
        with self._mtx:
            pool, self._pool = self._pool, []
        for c in pool:
            self._discard(c)


_avail_cache: dict = {"t": 0.0, "path": None, "rep": None}
_AVAIL_TTL = 15.0


def bust_avail_cache() -> None:
    """Force the next available() to ping fresh — failure paths must not
    trust a TTL-cached 'held' from a daemon that just died."""
    _avail_cache["t"] = 0.0


def available(timeout: float = 1.0) -> dict | None:
    """Liveness probe: the daemon's ping reply if a daemon is serving AND
    holds the device, else None. Never raises. Positive AND negative
    results are cached ~15s — the gateway consults this per batch on its
    kernel-selection default, and a ping (or a failed connect) per batch
    would dominate small-batch latency."""
    path = sock_path()
    now = time.monotonic()
    if _avail_cache["path"] == path and now - _avail_cache["t"] < _AVAIL_TTL:
        return _avail_cache["rep"]
    rep = None
    if os.path.exists(path):
        try:
            c = DevdClient(path, connect_timeout=timeout, io_timeout=timeout)
            r = c.ping(timeout=timeout)
            c.close()
            rep = r if r.get("held") else None
        except Exception:
            rep = None
    _avail_cache.update(t=now, path=path, rep=rep)
    return rep


def main() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    serve()


if __name__ == "__main__":
    main()
