"""AppConns: the three typed ABCI connections per app, plus the
handshake-on-start hook (reference: proxy/multi_app_conn.go:74-112 —
query, mempool, and consensus clients created in that order, then the
consensus replay handshake runs before the node serves anything)."""

from __future__ import annotations

from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.proxy.app_conn import AppConnConsensus, AppConnMempool, AppConnQuery
from tendermint_tpu.proxy.client_creator import ClientCreator


class AppConns(BaseService):
    def __init__(self, client_creator: ClientCreator, handshaker=None):
        super().__init__("proxy.AppConns")
        self._creator = client_creator
        self._handshaker = handshaker
        self._consensus: AppConnConsensus | None = None
        self._mempool: AppConnMempool | None = None
        self._query: AppConnQuery | None = None

    def consensus(self) -> AppConnConsensus:
        assert self._consensus is not None, "AppConns not started"
        return self._consensus

    def mempool(self) -> AppConnMempool:
        assert self._mempool is not None, "AppConns not started"
        return self._mempool

    def query(self) -> AppConnQuery:
        assert self._query is not None, "AppConns not started"
        return self._query

    def on_start(self) -> None:
        query_cli = self._creator.new_abci_client()
        query_cli.start()
        self._query = AppConnQuery(query_cli)

        mem_cli = self._creator.new_abci_client()
        mem_cli.start()
        self._mempool = AppConnMempool(mem_cli)

        con_cli = self._creator.new_abci_client()
        con_cli.start()
        self._consensus = AppConnConsensus(con_cli)

        if self._handshaker is not None:
            self._handshaker.handshake(self)
