"""Client creators (reference: proxy/client.go:14-76): local in-process
apps share one mutex across all three connections; remote apps get one
socket client per connection."""

from __future__ import annotations

import threading

from tendermint_tpu.abci.client import ABCIClient, LocalClient, SocketClient
from tendermint_tpu.abci.types import Application


class ClientCreator:
    def new_abci_client(self) -> ABCIClient:
        raise NotImplementedError


class LocalClientCreator(ClientCreator):
    def __init__(self, app: Application):
        self.app = app
        self._mtx = threading.RLock()

    def new_abci_client(self) -> ABCIClient:
        return LocalClient(self.app, self._mtx)


class RemoteClientCreator(ClientCreator):
    """Remote app: `transport` picks the wire — "socket" (pipelined
    JSON-lines, the fast default) or "grpc" (proxy/client.go:40-58)."""

    def __init__(self, addr: str, must_connect: bool = True, transport: str = "socket"):
        self.addr = addr
        self.must_connect = must_connect
        self.transport = transport

    def new_abci_client(self) -> ABCIClient:
        if self.transport == "grpc":
            from tendermint_tpu.abci.grpc import GRPCClient

            return GRPCClient(self.addr)
        return SocketClient(self.addr)


def default_client_creator(addr: str, db_dir: str = ".", transport: str = "socket") -> ClientCreator:
    """Name-or-address dispatch (proxy/client.go:64-76): known app names
    create in-process apps; anything else is a TCP address reached over
    `transport` (the config's `abci: socket | grpc`)."""
    from tendermint_tpu.abci.apps import CounterApp, KVStoreApp, NilApp, PersistentKVStoreApp

    if addr in ("kvstore", "dummy"):
        return LocalClientCreator(KVStoreApp())
    if addr in ("persistent_kvstore", "persistent_dummy"):
        return LocalClientCreator(PersistentKVStoreApp(db_dir))
    if addr == "signedkv":
        from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp

        return LocalClientCreator(SignedKVStoreApp())
    if addr == "counter":
        return LocalClientCreator(CounterApp())
    if addr == "counter_serial":
        return LocalClientCreator(CounterApp(serial=True))
    if addr == "nilapp":
        return LocalClientCreator(NilApp())
    return RemoteClientCreator(addr, transport=transport)
