from tendermint_tpu.proxy.app_conn import (
    AppConnConsensus,
    AppConnMempool,
    AppConnQuery,
)
from tendermint_tpu.proxy.client_creator import (
    ClientCreator,
    LocalClientCreator,
    RemoteClientCreator,
    default_client_creator,
)
from tendermint_tpu.proxy.multi_app_conn import AppConns

__all__ = [
    "AppConnConsensus",
    "AppConnMempool",
    "AppConnQuery",
    "ClientCreator",
    "LocalClientCreator",
    "RemoteClientCreator",
    "default_client_creator",
    "AppConns",
]
