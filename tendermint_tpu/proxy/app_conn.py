"""Typed per-connection ABCI views (reference: proxy/app_conn.go:11-41).

Each consumer sees only the subset of calls its connection is allowed to
make: consensus (InitChain/BeginBlock/DeliverTx/EndBlock/Commit), mempool
(CheckTx), query (Info/Query/Echo)."""

from __future__ import annotations

from tendermint_tpu.abci.client import ABCIClient, ReqRes


class AppConnConsensus:
    def __init__(self, client: ABCIClient):
        self._client = client

    def set_response_callback(self, cb) -> None:
        self._client.set_response_callback(cb)

    def error(self):
        return self._client.error()

    def init_chain_sync(self, validators) -> None:
        return self._client.init_chain_sync(validators)

    def begin_block_sync(self, block_hash: bytes, header) -> None:
        return self._client.begin_block_sync(block_hash, header)

    def deliver_tx_async(self, tx: bytes) -> ReqRes:
        return self._client.deliver_tx_async(tx)

    def deliver_txs_async(self, txs: list[bytes]) -> list[ReqRes]:
        return self._client.deliver_txs_async(txs)

    def end_block_sync(self, height: int):
        return self._client.end_block_sync(height)

    def commit_sync(self):
        return self._client.commit_sync()

    def flush_sync(self) -> None:
        self._client.flush_sync()


class AppConnMempool:
    def __init__(self, client: ABCIClient):
        self._client = client

    def set_response_callback(self, cb) -> None:
        self._client.set_response_callback(cb)

    def error(self):
        return self._client.error()

    def check_tx_async(self, tx: bytes) -> ReqRes:
        return self._client.check_tx_async(tx)

    def check_tx_many_async(self, txs: list[bytes]) -> list[ReqRes]:
        return self._client.check_tx_many_async(txs)

    def flush_async(self) -> ReqRes:
        return self._client.flush_async()

    def flush_sync(self) -> None:
        self._client.flush_sync()


class AppConnQuery:
    def __init__(self, client: ABCIClient):
        self._client = client

    def error(self):
        return self._client.error()

    def echo_sync(self, msg: str) -> str:
        return self._client.echo_sync(msg)

    def info_sync(self):
        return self._client.info_sync()

    def query_sync(self, data: bytes, path: str = "", height: int = 0, prove: bool = False):
        return self._client.query_sync(data, path, height, prove)
