"""Version constants (reference: version/version.go:3-18)."""

MAJ = "0"
MIN = "1"
FIX = "0"

__version__ = f"{MAJ}.{MIN}.{FIX}"
VERSION = __version__

# p2p wire-protocol compatibility version: peers must match on MAJ.MIN
# (reference gates on Version major via NodeInfo.CompatibleWith,
# p2p/types.go:36-44).
PROTOCOL_VERSION = f"{MAJ}.{MIN}"
