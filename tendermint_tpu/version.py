"""Version constants (reference: version/version.go:3-18)."""

MAJ = "0"
MIN = "1"
FIX = "0"

__version__ = f"{MAJ}.{MIN}.{FIX}"
VERSION = __version__
