"""Defensive env-var knob parsing, shared by every tunable surface
(gateway breaker, devd deadline budgets, WAL group-commit interval): a
typo'd value warns and falls back to the default — an operator fat-finger
must never kill node startup or a verify/commit hot path. An empty or
unset variable is simply "use the default", with no warning.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("libs.envknob")


def env_number(name: str, default, cast=float):
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return cast(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r; using %r", name, raw, default)
        return default


def env_str(name: str, default: str, allowed=()):
    """Enumerated string knob: a value outside `allowed` warns and falls
    back (same contract as env_number — a typo never kills startup)."""
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    if allowed and raw not in allowed:
        logger.warning(
            "ignoring unknown %s=%r (allowed: %s); using %r",
            name, raw, "|".join(allowed), default,
        )
        return default
    return raw
