"""Service lifecycle, the equivalent of tmlibs/common BaseService.

The reference wraps every long-lived component (Switch, reactors,
ConsensusState, Mempool WAL, ...) in a BaseService with idempotent
Start/Stop and an overridable OnStart/OnStop. We keep the same contract so
the node assembly (node/node.go:310) translates directly.
"""

from __future__ import annotations

import logging
import threading


class BaseService:
    """Idempotent start/stop lifecycle with subclass hooks.

    Contract (mirrors tmlibs BaseService):
    - start() runs on_start() exactly once; a second start() returns False.
    - stop() runs on_stop() exactly once after a successful start.
    - is_running() is True between start and stop.
    - wait() blocks until the service is stopped.
    """

    def __init__(self, name: str | None = None, logger: logging.Logger | None = None):
        self._name = name or type(self).__name__
        self.logger = logger or logging.getLogger(self._name)
        self._started = False
        self._stopped = False
        self._mtx = threading.Lock()
        self._quit = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> bool:
        with self._mtx:
            if self._stopped:
                raise RuntimeError(f"{self._name}: cannot restart a stopped service")
            if self._started:
                return False
            self._started = True
        self.logger.debug("starting %s", self._name)
        try:
            self.on_start()
        except Exception:
            with self._mtx:
                self._started = False
            raise
        return True

    def stop(self) -> bool:
        with self._mtx:
            if not self._started or self._stopped:
                return False
            self._stopped = True
        self.logger.debug("stopping %s", self._name)
        self.on_stop()
        self._quit.set()
        return True

    def is_running(self) -> bool:
        with self._mtx:
            return self._started and not self._stopped

    def wait(self, timeout: float | None = None) -> bool:
        return self._quit.wait(timeout)

    @property
    def quit_event(self) -> threading.Event:
        return self._quit

    # -- subclass hooks ----------------------------------------------------

    def on_start(self) -> None:  # pragma: no cover - trivial default
        pass

    def on_stop(self) -> None:  # pragma: no cover - trivial default
        pass

    def __repr__(self) -> str:
        state = "running" if self.is_running() else ("stopped" if self._stopped else "new")
        return f"<{self._name} [{state}]>"


class Routine:
    """A named daemon thread with a stop event — the goroutine-with-quit-channel
    pattern used throughout the reference (e.g. consensus/state.go:609
    receiveRoutine, p2p/connection.go:293 sendRoutine)."""

    def __init__(self, target, name: str, *args):
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=target, args=(*args,), name=name, daemon=True
        )

    def start(self) -> "Routine":
        self._thread.start()
        return self

    def signal_stop(self) -> None:
        self._stop.set()

    @property
    def stop_event(self) -> threading.Event:
        return self._stop

    def join(self, timeout: float | None = None) -> None:
        self._thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread.is_alive()
