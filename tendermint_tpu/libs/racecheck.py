"""Lock-order race/deadlock instrumentation — the framework's analogue of
the reference's race discipline (Go `-race` in CI, Makefile:31-34, plus the
single receiveRoutine owning RoundState, consensus/state.go:604-608).

Python's GIL hides data races Go's detector would catch, but the failure
mode that actually bites a threaded BFT node is the same one `-race`'s
happens-before graph encodes: inconsistent lock acquisition order across
threads (deadlock potential) and re-entering a non-reentrant lock. This
module instruments `threading.Lock`/`RLock` construction so a test tier —
or a live node run with TENDERMINT_RACECHECK=1 — records the process-wide
lock-order graph and reports:

- **order inversions**: thread T1 acquires site A then B while T2 acquires
  B then A — a cycle in the site graph == a latent deadlock;
- **self-deadlock**: a plain Lock acquired again by its holding thread
  (raises immediately instead of hanging the process);
- **hot-path discipline**: `assert_owner(obj)` pins a structure to the
  thread that first touched it (the receiveRoutine discipline).

Sites are keyed by the lock's construction call-site (file:line), so every
`ConsensusState` instance shares one node in the graph and cross-instance
ordering is checked structurally, not per-object. Limitation: two
same-site locks (e.g. two peers' locks) acquired in opposite orders
collapse to one node and aren't flagged — same-site nesting is exactly the
pattern the per-struct-mutex discipline forbids anyway, so treat any code
that needs to hold two sibling locks as a design smell, not a tooling gap.

Usage:
    mon = racecheck.install()
    ... run threads ...
    mon.check()        # raises LockOrderError on any finding
    racecheck.uninstall()
"""

from __future__ import annotations

import os
import sys
import threading
import traceback

_REPO_PREFIX = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class LockOrderError(AssertionError):
    pass


def _call_site() -> tuple[str, int]:
    """First stack frame outside this module: where the lock was built."""
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:  # pragma: no cover - defensive
        return ("<unknown>", 0)
    return (f.f_code.co_filename, f.f_lineno)


class Monitor:
    """Shared state for one install() window."""

    def __init__(self) -> None:
        self._mtx = threading.Lock()
        # site -> set of sites acquired while holding it
        self.edges: dict[tuple, set[tuple]] = {}
        # (a, b) -> formatted stack captured when the edge first appeared
        self.edge_stacks: dict[tuple, str] = {}
        self.violations: list[str] = []
        self._tls = threading.local()

    # -- per-thread held stack --------------------------------------------

    def _held(self) -> list:
        try:
            return self._tls.held
        except AttributeError:
            self._tls.held = []
            return self._tls.held

    def on_acquire(
        self, lock_id: int, site: tuple, reentrant: bool, blocking: bool = True
    ) -> None:
        held = self._held()
        if reentrant and any(lid == lock_id for lid, _ in held):
            # RLock re-entry never blocks, so it can neither deadlock nor
            # impose ordering — recording an edge here would report a
            # phantom cycle for `with r: with b: with r:` patterns
            held.append((lock_id, site))
            return
        if blocking and not reentrant and any(lid == lock_id for lid, _ in held):
            msg = (
                f"self-deadlock: non-reentrant Lock from {site[0]}:{site[1]} "
                f"re-acquired by its holding thread "
                f"{threading.current_thread().name}\n"
                + "".join(traceback.format_stack(limit=12))
            )
            with self._mtx:
                self.violations.append(msg)
            raise LockOrderError(msg)
        new_edges = []
        if blocking:  # a try-acquire never blocks, so it can't deadlock
            for _lid, held_site in held:
                if held_site != site:
                    new_edges.append((held_site, site))
        if new_edges:
            with self._mtx:
                for a, b in new_edges:
                    if b not in self.edges.setdefault(a, set()):
                        self.edges[a].add(b)
                        self.edge_stacks[(a, b)] = "".join(
                            traceback.format_stack(limit=10)
                        )
        held.append((lock_id, site))

    def on_release(self, lock_id: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == lock_id:
                del held[i]
                return

    # -- reporting ---------------------------------------------------------

    def _in_repo(self, site: tuple) -> bool:
        return site[0].startswith(_REPO_PREFIX)

    def cycles(self, repo_only: bool = True) -> list[list[tuple]]:
        """Cycles in the lock-order graph (each is a latent deadlock)."""
        with self._mtx:
            edges = {a: set(bs) for a, bs in self.edges.items()}
        if repo_only:
            edges = {
                a: {b for b in bs if self._in_repo(b)}
                for a, bs in edges.items()
                if self._in_repo(a)
            }
        # Tarjan-free: iterative DFS three-color cycle extraction
        WHITE, GRAY, BLACK = 0, 1, 2
        color = dict.fromkeys(edges, WHITE)
        found: list[list[tuple]] = []
        for root in edges:
            if color.get(root, WHITE) != WHITE:
                continue
            stack = [(root, iter(edges.get(root, ())))]
            color[root] = GRAY
            path = [root]
            while stack:
                node, it = stack[-1]
                adv = False
                for nxt in it:
                    c = color.get(nxt, WHITE)
                    if c == GRAY:
                        found.append(path[path.index(nxt):] + [nxt])
                    elif c == WHITE:
                        color[nxt] = GRAY
                        path.append(nxt)
                        stack.append((nxt, iter(edges.get(nxt, ()))))
                        adv = True
                        break
                if not adv:
                    color[node] = BLACK
                    path.pop()
                    stack.pop()
        return found

    def check(self, repo_only: bool = True) -> None:
        """Raise LockOrderError on any recorded violation or order cycle."""
        with self._mtx:
            viols = list(self.violations)
        cyc = self.cycles(repo_only=repo_only)
        if not viols and not cyc:
            return
        parts = viols[:]
        for c in cyc:
            desc = " -> ".join(f"{os.path.relpath(f, _REPO_PREFIX)}:{l}" for f, l in c)
            stacks = ""
            for a, b in zip(c, c[1:]):
                s = self.edge_stacks.get((a, b))
                if s:
                    stacks += f"\n  edge {a[0]}:{a[1]} -> {b[0]}:{b[1]} first seen:\n{s}"
            parts.append(f"lock-order cycle (latent deadlock): {desc}{stacks}")
        raise LockOrderError("\n\n".join(parts))

    def report(self) -> str:
        """Human summary (logged by the node at shutdown under
        TENDERMINT_RACECHECK=1)."""
        with self._mtx:
            n_sites = len(
                {s for a, bs in self.edges.items() for s in (a, *bs)}
            )
            n_edges = sum(len(bs) for bs in self.edges.values())
            viols = len(self.violations)
        cyc = self.cycles()
        return (
            f"racecheck: {n_sites} lock sites, {n_edges} order edges, "
            f"{len(cyc)} cycles, {viols} violations"
            + ("" if not cyc else f"; FIRST CYCLE: {cyc[0]}")
        )


class _TracedLock:
    """Wraps a real lock; reports acquire/release order to the Monitor."""

    __slots__ = ("_lock", "_mon", "_site", "_reentrant")

    def __init__(self, real, mon: Monitor, reentrant: bool):
        self._lock = real
        self._mon = mon
        self._site = _call_site()
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # order is recorded before blocking so a true deadlock still leaves
        # the inversion in the graph for the post-mortem
        self._mon.on_acquire(
            id(self), self._site, self._reentrant, blocking=bool(blocking)
        )
        ok = self._lock.acquire(blocking, timeout)
        if not ok:
            self._mon.on_release(id(self))
        return ok

    def release(self):
        self._lock.release()
        self._mon.on_release(id(self))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()

    # threading.Condition integration: delegate the private protocol so a
    # Condition built on a traced RLock keeps exact ownership semantics.
    # _release_save drops every recursion level at once, so pop ALL held
    # entries for this lock; _acquire_restore re-enters as one entry.
    def _is_owned(self):
        if hasattr(self._lock, "_is_owned"):
            return self._lock._is_owned()
        # plain Lock: probe directly (bypassing the monitor — a probe is
        # not an ordering event), mirroring Condition's fallback
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def _release_save(self):
        if hasattr(self._lock, "_release_save"):
            state = self._lock._release_save()
            held = self._mon._held()
            held[:] = [(lid, s) for lid, s in held if lid != id(self)]
            return state
        self.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._lock, "_acquire_restore"):
            self._lock._acquire_restore(state)
            self._mon.on_acquire(id(self), self._site, True)
        else:
            self.acquire()

    def _at_fork_reinit(self):  # pragma: no cover - fork support
        self._lock._at_fork_reinit()


_installed: Monitor | None = None
_orig_lock = threading.Lock
_orig_rlock = threading.RLock


def install() -> Monitor:
    """Patch threading.Lock/RLock to traced versions. Locks created BEFORE
    install are untouched (stdlib internals stay fast); only code paths
    constructing locks inside the window are instrumented."""
    global _installed
    if _installed is not None:
        return _installed
    mon = Monitor()

    def make_lock():
        return _TracedLock(_orig_lock(), mon, reentrant=False)

    def make_rlock():
        return _TracedLock(_orig_rlock(), mon, reentrant=True)

    threading.Lock = make_lock  # type: ignore[assignment]
    threading.RLock = make_rlock  # type: ignore[assignment]
    _installed = mon
    return mon


def uninstall() -> None:
    global _installed
    threading.Lock = _orig_lock  # type: ignore[assignment]
    threading.RLock = _orig_rlock  # type: ignore[assignment]
    _installed = None


def monitor() -> Monitor | None:
    return _installed


# -- thread-affinity assertion (receiveRoutine discipline) -------------------

_affinity: dict[int, tuple[str, str]] = {}
_aff_mtx = _orig_lock()


def assert_owner(obj, label: str = "") -> None:
    """Assert `obj` is only touched by the thread that first touched it —
    the single-receive-routine ownership discipline the reference leans on
    for RoundState. No-op cost is one dict lookup; call it at the top of
    methods that must stay on the owner thread."""
    me = threading.current_thread().name
    key = id(obj)
    with _aff_mtx:
        prev = _affinity.get(key)
        if prev is None:
            _affinity[key] = (me, label)
            return
    if prev[0] != me:
        raise LockOrderError(
            f"thread-affinity violation: {label or type(obj).__name__} "
            f"owned by thread {prev[0]!r} touched from {me!r}"
        )


def reset_affinity() -> None:
    with _aff_mtx:
        _affinity.clear()
