"""String-keyed pub/sub event bus (reference: tmlibs/events EventSwitch +
EventCache; usage at types/events.go:160-186, consensus/state.go:1316).

The consensus state machine fires events (NewBlock, Vote, NewRoundStep, ...);
the consensus reactor and the RPC WebSocket manager subscribe. An EventCache
buffers events fired during block execution and flushes them after commit.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from tendermint_tpu.libs.service import BaseService

EventCallback = Callable[[Any], None]


class Fireable:
    def fire_event(self, event: str, data: Any) -> None:  # pragma: no cover
        raise NotImplementedError


class EventSwitch(BaseService, Fireable):
    """Listener registry keyed by (event string, listener id)."""

    def __init__(self):
        super().__init__("EventSwitch")
        self._mtx = threading.RLock()
        # event -> {listener_id -> callback}
        self._cells: dict[str, dict[str, EventCallback]] = {}
        # listener_id -> set of events (for remove_listener)
        self._listeners: dict[str, set[str]] = {}

    def add_listener_for_event(self, listener_id: str, event: str, cb: EventCallback) -> None:
        with self._mtx:
            self._cells.setdefault(event, {})[listener_id] = cb
            self._listeners.setdefault(listener_id, set()).add(event)

    def remove_listener_for_event(self, event: str, listener_id: str) -> None:
        with self._mtx:
            cell = self._cells.get(event)
            if cell:
                cell.pop(listener_id, None)
                if not cell:
                    del self._cells[event]
            evs = self._listeners.get(listener_id)
            if evs:
                evs.discard(event)
                if not evs:
                    del self._listeners[listener_id]

    def remove_listener(self, listener_id: str) -> None:
        with self._mtx:
            for event in self._listeners.pop(listener_id, set()):
                cell = self._cells.get(event)
                if cell:
                    cell.pop(listener_id, None)
                    if not cell:
                        del self._cells[event]

    def fire_event(self, event: str, data: Any) -> None:
        with self._mtx:
            cbs = list(self._cells.get(event, {}).values())
        for cb in cbs:
            cb(data)


class EventCache(Fireable):
    """Buffers events; flush() fires them on the underlying switch in order.

    Used during finalizeCommit so subscribers observe a block's events only
    after the block is fully committed (consensus/state.go:1316,1338)."""

    def __init__(self, evsw: Fireable):
        self._evsw = evsw
        self._pending: list[tuple[str, Any]] = []

    def fire_event(self, event: str, data: Any) -> None:
        self._pending.append((event, data))

    def flush(self) -> None:
        pending, self._pending = self._pending, []
        for event, data in pending:
            self._evsw.fire_event(event, data)
