"""Shared plumbing for the gRPC transports (abci/grpc.py, rpc/grpc.py):
one JSON wire codec and one bind helper, so the two surfaces cannot
silently diverge."""

from __future__ import annotations

import json


def json_serializer(d: dict) -> bytes:
    return json.dumps(d).encode()


def json_deserializer(b: bytes) -> dict:
    return json.loads(b)


def bind_insecure(server, addr: str) -> str:
    """Bind `host:port` (port 0 = ephemeral); returns the bound addr."""
    host, port = addr.rsplit(":", 1)
    bound = server.add_insecure_port(f"{host}:{port}")
    return f"{host}:{bound}"
