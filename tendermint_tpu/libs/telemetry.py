"""Process-wide telemetry plane (round 11): ONE instrument set replacing
the six drifting per-subsystem stats conventions.

The reference declares a go-metrics dependency it never wires (SURVEY.md
§5); five PRs of perf/robustness work here outgrew the stand-in — every
subsystem exported a hand-rolled ``stats()`` dict that the metrics RPC
flattened into one JSON blob: counters only, no histograms, no per-height
timing, no scrapeable format. This module is the registry those planes
now hang off:

- ``Counter`` / ``Gauge`` / ``Histogram`` instruments, each optionally
  labeled. Histograms use fixed log-spaced buckets (env-tunable, see
  ``default_latency_buckets``) so a latency distribution costs one bisect
  + one lock per observation — cheap enough for the verify/hash/WAL hot
  paths the pipelining and sharding PRs will be judged against.
- A ``Registry`` that renders two ways: ``flatten()`` reproduces the
  legacy metrics-RPC flat dict byte-compatibly (producers registered
  with ``legacy=True`` only), and ``render_prometheus()`` emits valid
  text-exposition 0.0.4 (HELP/TYPE lines, histogram ``_bucket``/
  ``_sum``/``_count`` series) so real scrapers work against
  ``GET /metrics`` (rpc/server.py).
- ``register_producer(prefix, fn)`` adapts the existing ``stats()``
  dicts: each flat numeric key becomes its own gauge family under
  ``<prefix>_<key>``. The canonical ``<plane>_<name>`` catalog lives in
  tendermint_tpu/node/telemetry.py + docs/observability.md.

Concurrency: instruments take one small per-family lock per operation;
registries snapshot their tables under a registry lock and evaluate
producers outside it. Producer/callback failures PROPAGATE out of
``flatten``/``collect`` — a renamed attribute fails loudly as an RPC
error or an HTTP 500 scrape (which monitoring alerts on), never as a
silently missing plane behind a 200 (the PR-4 loud-wiring convention).

``set_enabled(False)`` (or TENDERMINT_TELEMETRY_DISABLE=1) turns every
hot-path ``inc``/``observe`` into a no-op — the lever the overhead guard
in benches/bench_telemetry.py uses to prove instrumentation costs <2%
on the mempool signed-burst gate.
"""

from __future__ import annotations

import logging
import math
import os
import threading
from bisect import bisect_left

from tendermint_tpu.libs.envknob import env_number as _env_number

logger = logging.getLogger("libs.telemetry")

# hot-path kill switch: observe()/inc() check this module flag (one
# global load) before doing any work
_ENABLED = os.environ.get("TENDERMINT_TELEMETRY_DISABLE", "") != "1"


def set_enabled(on: bool) -> None:
    """Flip hot-path instrumentation on/off process-wide (the overhead
    bench measures the delta; registration/rendering are unaffected)."""
    global _ENABLED
    _ENABLED = bool(on)


def enabled() -> bool:
    return _ENABLED


def log_buckets(lo: float, hi: float, per_decade: int) -> tuple[float, ...]:
    """Log-spaced bucket upper bounds covering [lo, hi], `per_decade`
    bounds per decade, rounded to 3 significant digits so rendered
    ``le`` labels stay stable across platforms."""
    if lo <= 0 or hi <= lo or per_decade <= 0:
        raise ValueError(f"bad bucket spec: lo={lo} hi={hi}/{per_decade}")
    n = int(math.ceil(math.log10(hi / lo) * per_decade)) + 1
    out = []
    for i in range(n):
        v = lo * 10 ** (i / per_decade)
        v = float(f"{v:.3g}")
        if not out or v > out[-1]:
            out.append(v)
    if out[-1] < hi:
        out.append(float(f"{hi:.3g}"))
    return tuple(out)


def default_latency_buckets() -> tuple[float, ...]:
    """Default histogram bounds for latency-in-seconds instruments:
    100 µs .. 30 s, 4 per decade (~23 buckets). Env-tunable (shared
    libs/envknob semantics — a typo'd value warns and keeps the
    default): TENDERMINT_TELEMETRY_HIST_MIN_S / _HIST_MAX_S /
    _HIST_PER_DECADE."""
    lo = float(_env_number("TENDERMINT_TELEMETRY_HIST_MIN_S", 1e-4))
    hi = float(_env_number("TENDERMINT_TELEMETRY_HIST_MAX_S", 30.0))
    per = int(_env_number("TENDERMINT_TELEMETRY_HIST_PER_DECADE", 4,
                          cast=int))
    try:
        return log_buckets(lo, hi, per)
    except ValueError:
        logger.warning("bad telemetry bucket knobs (%r, %r, %r); defaults",
                       lo, hi, per)
        return log_buckets(1e-4, 30.0, 4)


def size_buckets(hi: float = 65536.0) -> tuple[float, ...]:
    """Bounds for count-shaped histograms (group sizes, lane counts):
    1 .. hi, 3 per decade."""
    return log_buckets(1.0, hi, 3)


# -- instruments ---------------------------------------------------------------

# one shared overflow series per labeled family once the cardinality
# bound is hit: totals stay right, label explosions stay bounded
OVERFLOW_LABEL = "_other"


def family_max_series(name: str) -> int:
    """Cardinality bound for a labeled family: the per-family override
    ``TENDERMINT_TELEMETRY_MAX_SERIES_<NAME>`` (family name uppercased)
    wins over the process-wide ``TENDERMINT_TELEMETRY_MAX_SERIES``
    (default 64). Both parse defensively (libs/envknob) — a typo'd knob
    keeps the default, never kills instrument construction. The bound
    applies to every instrument kind, histograms included: a per-peer
    latency histogram under 100-peer churn collapses into one ``_other``
    series exactly like a counter does."""
    global_max = int(_env_number("TENDERMINT_TELEMETRY_MAX_SERIES", 64,
                                 cast=int))
    return int(_env_number(
        f"TENDERMINT_TELEMETRY_MAX_SERIES_{name.upper()}", global_max,
        cast=int,
    ))


class _Metric:
    """Base: a named family with optional labels. Unlabeled metrics are
    their own single child (label key ``()``)."""

    kind = "untyped"

    def __init__(self, name: str, help_: str, labelnames=(),
                 max_series: int | None = None):
        self.name = name
        self.help = help_ or name
        self.labelnames = tuple(labelnames)
        self._mtx = threading.Lock()
        self._children: dict = {}
        self._max_series = int(
            max_series if max_series is not None
            else family_max_series(name)
        )
        self.dropped_series = 0
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        raise NotImplementedError

    def _child(self, labelvalues: tuple):
        with self._mtx:
            c = self._children.get(labelvalues)
            if c is None:
                if len(self._children) >= self._max_series:
                    # cardinality bound: collapse into ONE overflow series
                    self.dropped_series += 1
                    overflow = (OVERFLOW_LABEL,) * len(self.labelnames)
                    c = self._children.get(overflow)
                    if c is None:
                        c = self._children[overflow] = self._new_child()
                else:
                    c = self._children[labelvalues] = self._new_child()
            return c

    def labels(self, **kv):
        """The child series for these label values. Missing/extra label
        names fail loudly (KeyError) — renames must not silently fork a
        new family."""
        if set(kv) != set(self.labelnames):
            raise KeyError(
                f"{self.name}: labels {sorted(kv)} != {sorted(self.labelnames)}"
            )
        return self._child(tuple(str(kv[k]) for k in self.labelnames))

    def remove_labels(self, **kv) -> None:
        """Drop one labeled child series — staleness cleanup: a series
        whose subject is gone (a churned-out peer) must disappear from
        the scrape, not freeze at its last value. Also frees the slot
        against the cardinality bound. Missing series is a no-op; the
        shared ``_other`` overflow series is removable like any other
        (it re-creates on the next overflow)."""
        if set(kv) != set(self.labelnames):
            raise KeyError(
                f"{self.name}: labels {sorted(kv)} != {sorted(self.labelnames)}"
            )
        with self._mtx:
            self._children.pop(
                tuple(str(kv[k]) for k in self.labelnames), None
            )

    def _own(self):
        if self.labelnames:
            raise KeyError(f"{self.name} is labeled; use .labels(...)")
        return self._children[()]

    def _items(self):
        with self._mtx:
            return list(self._children.items())

    def series_count(self) -> int:
        with self._mtx:
            return len(self._children)


class _CounterChild:
    __slots__ = ("value", "_mtx")

    def __init__(self):
        self.value = 0
        self._mtx = threading.Lock()

    def inc(self, v=1) -> None:
        # validate BEFORE the kill-switch check: a caller bug must crash
        # identically whether or not telemetry is disabled
        if v < 0:
            raise ValueError("counters only go up")
        if not _ENABLED:
            return
        with self._mtx:
            self.value += v


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, v=1) -> None:
        self._own().inc(v)

    @property
    def value(self):
        return self._own().value


class _GaugeChild:
    __slots__ = ("value", "_mtx")

    def __init__(self):
        self.value = 0.0
        self._mtx = threading.Lock()

    def set(self, v) -> None:
        with self._mtx:
            self.value = v

    def inc(self, v=1) -> None:
        with self._mtx:
            self.value += v

    def dec(self, v=1) -> None:
        with self._mtx:
            self.value -= v


class Gauge(_Metric):
    """A settable gauge, or — with ``fn`` — a callback gauge evaluated
    at collect time (how live object state exports without a shadow
    copy)."""

    kind = "gauge"

    def __init__(self, name, help_, labelnames=(), fn=None, **kw):
        if fn is not None and labelnames:
            raise ValueError("callback gauges cannot be labeled")
        super().__init__(name, help_, labelnames, **kw)
        self.fn = fn

    def _new_child(self):
        return _GaugeChild()

    def set(self, v) -> None:
        self._own().set(v)

    def inc(self, v=1) -> None:
        self._own().inc(v)

    def dec(self, v=1) -> None:
        self._own().dec(v)

    @property
    def value(self):
        if self.fn is not None:
            return self.fn()
        return self._own().value


class _HistogramChild:
    __slots__ = ("bounds", "counts", "sum", "count", "_mtx")

    def __init__(self, bounds: tuple):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self._mtx = threading.Lock()

    def observe(self, v: float) -> None:
        if not _ENABLED:
            return
        i = bisect_left(self.bounds, v)
        with self._mtx:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        with self._mtx:
            return list(self.counts), self.sum, self.count

    def quantile(self, q: float) -> float:
        """Approximate quantile from the bucket distribution (upper
        bound of the bucket holding the q-th observation) — operator
        convenience for tests/benches, not exported."""
        counts, _s, total = self.snapshot()
        if total == 0:
            return 0.0
        want = q * total
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= want:
                return self.bounds[i] if i < len(self.bounds) else float("inf")
        return float("inf")


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, labelnames=(), buckets=None, **kw):
        self.buckets = tuple(buckets) if buckets is not None \
            else default_latency_buckets()
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"{name}: buckets must be strictly increasing")
        super().__init__(name, help_, labelnames, **kw)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float) -> None:
        self._own().observe(v)

    @property
    def count(self):
        return self._own().count

    @property
    def sum(self):
        return self._own().sum

    def quantile(self, q: float) -> float:
        return self._own().quantile(q)


# -- collection + rendering ----------------------------------------------------


class Family:
    """One exposition family: samples are (suffix, labels, value)."""

    __slots__ = ("name", "kind", "help", "samples")

    def __init__(self, name, kind, help_, samples):
        self.name = name
        self.kind = kind
        self.help = help_
        self.samples = samples


def _sanitize(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        ok = ch.isascii() and (ch.isalpha() or ch == "_" or ch == ":"
                               or (ch.isdigit() and i > 0))
        out.append(ch if ok else "_")
    return "".join(out)


def _esc_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _esc_label(s: str) -> str:
    return s.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt(v) -> str:
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f == math.inf:
        return "+Inf"
    if f == -math.inf:
        return "-Inf"
    return repr(f)


def _metric_families(m: _Metric) -> Family:
    samples = []
    for labelvalues, child in m._items():
        labels = dict(zip(m.labelnames, labelvalues))
        if m.kind == "histogram":
            counts, total_sum, count = child.snapshot()
            acc = 0
            for bound, c in zip(m.buckets, counts):
                acc += c
                samples.append(("_bucket", {**labels, "le": _fmt(bound)}, acc))
            samples.append(("_bucket", {**labels, "le": "+Inf"}, count))
            samples.append(("_sum", labels, total_sum))
            samples.append(("_count", labels, count))
        elif isinstance(m, Gauge) and m.fn is not None:
            # same loud-wiring rule as producers: a broken callback is a
            # wiring bug, not something to render around
            samples.append(("", labels, m.fn()))
        else:
            samples.append(("", labels, child.value))
    return Family(m.name, m.kind, m.help, samples)


class Registry:
    """A set of instruments + legacy flat-dict producers, optionally
    chained to a parent registry (the process-wide default) whose
    families it re-exports. Per-node registries chain to the default so
    one scrape shows node gauges AND the process-global device-plane
    instruments, while two nodes in one test process keep their own
    producer tables."""

    def __init__(self, parent: "Registry | None" = None):
        self._mtx = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        # prefix -> (fn, legacy); evaluation order = registration order
        self._producers: dict[str, tuple] = {}
        # collect-time refreshers (round 15): run before instruments are
        # gathered, so point-in-time gauges (per-peer last-recv age) are
        # fresh in the SAME scrape that triggered them
        self._pre_collect: list = []
        self.parent = parent

    def on_collect(self, fn) -> None:
        """Register a hook run at the start of every collect() — the
        seam for labeled gauges whose value only means something at read
        time. Hook failures propagate (the loud-wiring convention)."""
        with self._mtx:
            self._pre_collect.append(fn)

    # -- instrument factories (create-or-get by name) ----------------------

    def _get_or_make(self, cls, name, help_, **kw):
        with self._mtx:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"{name} already registered as {m.kind}, not "
                        f"{cls.kind}"
                    )
                return m
            m = self._metrics[name] = cls(name, help_, **kw)
            return m

    def counter(self, name, help_="", labelnames=(), **kw) -> Counter:
        return self._get_or_make(Counter, name, help_,
                                 labelnames=labelnames, **kw)

    def gauge(self, name, help_="", labelnames=(), fn=None, **kw) -> Gauge:
        return self._get_or_make(Gauge, name, help_,
                                 labelnames=labelnames, fn=fn, **kw)

    def histogram(self, name, help_="", labelnames=(), buckets=None,
                  **kw) -> Histogram:
        return self._get_or_make(Histogram, name, help_,
                                 labelnames=labelnames, buckets=buckets, **kw)

    # -- legacy stats() producers ------------------------------------------

    def register_producer(self, prefix: str, fn, legacy: bool = True) -> None:
        """Adapt a flat numeric ``stats()``-style dict: each key renders
        as gauge family ``<prefix>_<key>`` (prefix "" = keys as-is).
        ``legacy=True`` producers make up the byte-compatible metrics-RPC
        dict (``flatten``); ``legacy=False`` ones are scrape-only (new
        families must not change the legacy RPC key set). Re-registering
        a prefix replaces the previous producer."""
        with self._mtx:
            self._producers[prefix] = (fn, bool(legacy))

    def unregister_producer(self, prefix: str) -> None:
        with self._mtx:
            self._producers.pop(prefix, None)

    def _producer_items(self, prefix: str, fn) -> list[tuple[str, object]]:
        # producer failures PROPAGATE (the PR-4 loud-wiring convention):
        # a renamed attribute must surface as a metrics-RPC error / an
        # HTTP 500 scrape — both of which monitoring alerts on — never
        # as a silently vanished plane behind a healthy-looking 200
        d = fn()
        out = []
        for k, v in d.items():
            if not isinstance(v, (int, float)):
                continue  # producers are flat-numeric by contract
            out.append((f"{prefix}_{k}" if prefix else str(k), v))
        return out

    def flatten(self) -> dict:
        """The legacy metrics-RPC flat dict: every ``legacy`` producer's
        keys, prefixed — byte-compatible with the pre-registry handler
        (rpc/core/handlers.py metrics)."""
        with self._mtx:
            producers = [(p, fn) for p, (fn, legacy) in
                         self._producers.items() if legacy]
        out: dict = {}
        for prefix, fn in producers:
            for k, v in self._producer_items(prefix, fn):
                out[k] = v
        return out

    def collect(self) -> list[Family]:
        """Every family this registry exports: own instruments, own
        producers (each key a gauge family), then the parent chain —
        first registration of a name wins."""
        with self._mtx:
            metrics = list(self._metrics.values())
            producers = list(self._producers.items())
            hooks = list(self._pre_collect)
        for hook in hooks:
            hook()
        fams: list[Family] = []
        seen: set[str] = set()

        def add(f: Family) -> None:
            if f.name not in seen:
                seen.add(f.name)
                fams.append(f)

        for m in metrics:
            add(_metric_families(m))
        for prefix, (fn, _legacy) in producers:
            for k, v in self._producer_items(prefix, fn):
                add(Family(k, "gauge", f"{k} ({prefix or 'flat'} plane gauge)",
                           [("", {}, v)]))
        if self.parent is not None:
            for f in self.parent.collect():
                add(f)
        return fams

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        for fam in self.collect():
            name = _sanitize(fam.name)
            lines.append(f"# HELP {name} {_esc_help(fam.help)}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for suffix, labels, value in fam.samples:
                if labels:
                    lbl = ",".join(
                        f'{_sanitize(k)}="{_esc_label(str(v))}"'
                        for k, v in labels.items()
                    )
                    lines.append(f"{name}{suffix}{{{lbl}}} {_fmt(value)}")
                else:
                    lines.append(f"{name}{suffix} {_fmt(value)}")
        return "\n".join(lines) + "\n"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_default: Registry = Registry()
_default_mtx = threading.Lock()
_install_hooks: list = []


def default_registry() -> Registry:
    """The process-wide registry: device-plane histograms (devd client),
    WAL/mempool instruments, faults counters. Per-node registries
    (node/telemetry.py) chain to it."""
    return _default


def on_default_registry(install) -> None:
    """Run ``install(registry)`` against the default registry now AND
    after every ``reset_default_registry`` — how modules (ops/faults)
    keep their producers registered across test resets."""
    with _default_mtx:
        _install_hooks.append(install)
        reg = _default
    install(reg)


def reset_default_registry() -> Registry:
    """Swap in a fresh default registry (tests), re-running the module
    install hooks. Instruments held by live objects keep counting but
    stop being exported until re-created via the factory methods."""
    global _default
    with _default_mtx:
        _default = Registry()
        reg = _default
        hooks = list(_install_hooks)
    for install in hooks:
        try:
            install(reg)
        except Exception:  # noqa: BLE001 — a bad hook must not kill reset
            logger.exception("telemetry install hook failed")
    return reg
