"""Runtime primitives: the equivalents of the reference's tmlibs foundation
(SURVEY.md section 2.2): BaseService lifecycle, BitArray, concurrent list,
event switch, KV DB, autofile/WAL group, flow-rate monitor.
"""
