"""Rotating append-only file group with reverse marker search — equivalent of
tmlibs/autofile (Group + Search), the storage layer of the consensus WAL
(consensus/wal.go:43-104) and mempool WAL (mempool/mempool.go:111-124).

Semantics kept from the reference:
- append lines to "head"; rotate to numbered chunks (path.000, path.001, ...)
  when the head exceeds a size limit;
- `search_for_end_height` scans backwards across chunks for the last
  occurrence of a marker line (the "#ENDHEIGHT: h" convention,
  consensus/replay.go:107-126) and returns a reader positioned just after it.

Round-9 additions for the framed WAL (consensus/wal.py v2 format,
docs/crash-recovery.md):
- `write_bytes` appends raw bytes (a CRC-framed record) with no newline;
  rotation only ever happens in `flush()`, i.e. BETWEEN writes, so a
  record never spans a chunk boundary — the repair scan relies on this.
- `header`: bytes stamped at offset 0 of every freshly created chunk
  (the WAL's format magic), including each new head after a rotation.
- `crash_hooks=True` routes writes and rotation through state/fail.py's
  torture points (FAIL_TEST_MODE=torn_write / rotate_crash) so a node
  subprocess can be killed at any byte offset of the append stream.  The
  env gate is checked here so un-armed processes never even import fail.
"""

from __future__ import annotations

import os
import threading


class Group:
    def __init__(
        self,
        head_path: str,
        chunk_size: int = 10 * 1024 * 1024,
        header: bytes = b"",
        crash_hooks: bool = False,
    ):
        self._head_path = head_path
        self._chunk_size = chunk_size
        self._header = header
        self._crash_hooks = crash_hooks
        self._mtx = threading.RLock()
        os.makedirs(os.path.dirname(head_path) or ".", exist_ok=True)
        self._head = open(head_path, "ab")
        # the head's directory entry may be brand new; the first synced
        # flush must also fsync the directory or a power failure can drop
        # the file (and everything fsynced into it) wholesale
        self._dir_dirty = True
        if header and self._head.tell() == 0:
            self._write_raw(header)
            self._head.flush()

    # -- writing -----------------------------------------------------------

    def _write_raw(self, data: bytes) -> None:
        if self._crash_hooks and os.environ.get("FAIL_TEST_MODE"):
            from tendermint_tpu.state import fail

            fail.wal_write(self._head, data)
        else:
            self._head.write(data)

    def write_bytes(self, data: bytes) -> None:
        """Append raw bytes to the head (no newline framing)."""
        with self._mtx:
            self._write_raw(data)

    def write_line(self, line: str) -> None:
        self.write_bytes(line.encode() + b"\n")

    def flush(self, sync: bool = False) -> None:
        fd = None
        dir_dirty = False
        with self._mtx:
            self._head.flush()
            if sync:
                # fsync OUTSIDE the lock: a concurrent writer (the
                # consensus receive hot path) must never stall behind the
                # flusher's disk round trip. dup() pins the open file so a
                # concurrent rotation closing self._head can't invalidate
                # the descriptor (a rotated-out chunk was already fsynced
                # by _rotate, so syncing the stale dup stays correct).
                # Bytes appended after the dup simply ride the next sync —
                # the WAL's group accounting already assumes that.
                fd = os.dup(self._head.fileno())
                dir_dirty, self._dir_dirty = self._dir_dirty, False
            if self._head.tell() >= self._chunk_size:
                self._rotate()
        if fd is not None:
            try:
                os.fsync(fd)
            except BaseException:
                # the obligation was consumed under the lock but never met —
                # put it back, or every later synced flush would skip the
                # directory fsync and a power failure could drop the head
                # file (with its fsynced records) wholesale
                if dir_dirty:
                    with self._mtx:
                        self._dir_dirty = True
                raise
            finally:
                os.close(fd)
            if dir_dirty:
                # file data first, then its directory entry — the head was
                # created since the last synced flush
                self._fsync_dir()

    def _fsync_dir(self) -> None:
        """fsync the chunk directory: renames (rotation) and file creation
        are durable only once the directory entry itself is journaled."""
        d = os.path.dirname(self._head_path) or "."
        try:
            dfd = os.open(d, os.O_RDONLY)
        except OSError:  # pragma: no cover - platform without dir-open
            return
        try:
            os.fsync(dfd)
        except OSError:  # pragma: no cover - fs without dir fsync
            pass
        finally:
            os.close(dfd)

    def _rotate(self) -> None:
        # the chunk being rotated out will never be written again, so make
        # it durable NOW: without this fsync a group-commit caller's later
        # sync() only covers the NEW head fd, and a power failure could
        # tear the rotated chunk's tail long after wal_pending read 0 —
        # quarantining everything after it, including fsynced #ENDHEIGHTs
        self._head.flush()
        os.fsync(self._head.fileno())
        hooked = self._crash_hooks and os.environ.get("FAIL_TEST_MODE")
        if hooked:
            from tendermint_tpu.state import fail

            fail.rotate_point("pre")
        self._head.close()
        idx = self._max_index() + 1
        os.replace(self._head_path, f"{self._head_path}.{idx:03d}")
        if hooked:
            from tendermint_tpu.state import fail

            fail.rotate_point("post")
        self._head = open(self._head_path, "ab")
        # the rename and the fresh head are directory mutations: the next
        # synced flush must journal the directory before claiming durability
        # (a lost rename still leaves the fsynced data under the OLD name,
        # so no synced record can vanish either way)
        self._dir_dirty = True
        if self._header and self._head.tell() == 0:
            self._write_raw(self._header)
            self._head.flush()

    def _max_index(self) -> int:
        indices = Group._chunk_indices(self._head_path)
        return indices[-1] if indices else -1

    def position(self) -> tuple[int, int]:
        """(index the head will take when it rotates, OS-flushed head
        size) — the clean-watermark coordinate (consensus/wal.py, round
        10). Captured under the append lock, so the offset always lands
        on a record boundary: writers append whole frames and rotation
        only happens between writes."""
        with self._mtx:
            self._head.flush()
            return self._max_index() + 1, self._head.tell()

    def close(self) -> None:
        with self._mtx:
            self._head.flush()
            self._head.close()

    # -- reading -----------------------------------------------------------

    @staticmethod
    def _chunk_indices(head_path: str) -> list[int]:
        """Numeric suffixes of the rotated chunk files, ascending — the ONE
        place the `<head>.NNN` naming scheme is parsed."""
        d = os.path.dirname(head_path) or "."
        base = os.path.basename(head_path)
        indices = []
        try:
            names = os.listdir(d)
        except FileNotFoundError:
            return []
        for fn in names:
            if fn.startswith(base + "."):
                suffix = fn[len(base) + 1 :]
                if suffix.isdigit():
                    indices.append(int(suffix))
        return sorted(indices)

    @staticmethod
    def list_chunks(head_path: str) -> list[str]:
        """Existing chunk files oldest→newest, head last — usable before a
        Group is constructed (the WAL's repair pass runs pre-open)."""
        paths = [f"{head_path}.{i:03d}" for i in Group._chunk_indices(head_path)]
        if os.path.exists(head_path):
            paths.append(head_path)
        return paths

    def _chunk_paths(self) -> list[str]:
        """All chunk files oldest→newest, head last."""
        return Group.list_chunks(self._head_path)

    def chunk_paths(self) -> list[str]:
        with self._mtx:
            self._head.flush()
            return self._chunk_paths()

    def read_all_lines(self) -> list[str]:
        with self._mtx:
            self._head.flush()
            lines: list[str] = []
            for p in self._chunk_paths():
                with open(p, "rb") as f:
                    for raw in f.read().splitlines():
                        lines.append(raw.decode(errors="replace"))
            return lines

    def search_lines_after_marker(self, marker: str) -> list[str] | None:
        """Lines strictly after the LAST line equal to `marker`; None if the
        marker never occurs (the caller then treats the whole log as fresh,
        matching autofile.Group.Search miss behavior).

        Scans chunks newest-to-oldest and stops at the first chunk containing
        the marker, so a long WAL only costs one chunk read in the common
        case (the reference's reverse Search, consensus/replay.go:107-126).
        tests/test_libs.py holds this to parity with a front-to-back scan.
        """
        with self._mtx:
            self._head.flush()
            tail: list[str] = []
            for p in reversed(self._chunk_paths()):
                with open(p, "rb") as f:
                    lines = [ln.decode(errors="replace") for ln in f.read().splitlines()]
                for i in range(len(lines) - 1, -1, -1):
                    if lines[i] == marker:
                        return lines[i + 1 :] + tail
                tail = lines + tail
            return None
