"""Rotating append-only file group with reverse marker search — equivalent of
tmlibs/autofile (Group + Search), the storage layer of the consensus WAL
(consensus/wal.go:43-104) and mempool WAL (mempool/mempool.go:111-124).

Semantics kept from the reference:
- append lines to "head"; rotate to numbered chunks (path.000, path.001, ...)
  when the head exceeds a size limit;
- `search_for_end_height` scans backwards across chunks for the last
  occurrence of a marker line (the "#ENDHEIGHT: h" convention,
  consensus/replay.go:107-126) and returns a reader positioned just after it.
"""

from __future__ import annotations

import os
import threading


class Group:
    def __init__(self, head_path: str, chunk_size: int = 10 * 1024 * 1024):
        self._head_path = head_path
        self._chunk_size = chunk_size
        self._mtx = threading.RLock()
        os.makedirs(os.path.dirname(head_path) or ".", exist_ok=True)
        self._head = open(head_path, "ab")

    # -- writing -----------------------------------------------------------

    def write_line(self, line: str) -> None:
        with self._mtx:
            self._head.write(line.encode() + b"\n")

    def flush(self, sync: bool = False) -> None:
        with self._mtx:
            self._head.flush()
            if sync:
                os.fsync(self._head.fileno())
            if self._head.tell() >= self._chunk_size:
                self._rotate()

    def _rotate(self) -> None:
        self._head.close()
        idx = self._max_index() + 1
        os.replace(self._head_path, f"{self._head_path}.{idx:03d}")
        self._head = open(self._head_path, "ab")

    def _max_index(self) -> int:
        d = os.path.dirname(self._head_path) or "."
        base = os.path.basename(self._head_path)
        mx = -1
        for fn in os.listdir(d):
            if fn.startswith(base + "."):
                suffix = fn[len(base) + 1 :]
                if suffix.isdigit():
                    mx = max(mx, int(suffix))
        return mx

    def close(self) -> None:
        with self._mtx:
            self._head.flush()
            self._head.close()

    # -- reading -----------------------------------------------------------

    def _chunk_paths(self) -> list[str]:
        """All chunk files oldest→newest, head last."""
        paths = [
            f"{self._head_path}.{i:03d}"
            for i in range(self._max_index() + 1)
            if os.path.exists(f"{self._head_path}.{i:03d}")
        ]
        if os.path.exists(self._head_path):
            paths.append(self._head_path)
        return paths

    def read_all_lines(self) -> list[str]:
        with self._mtx:
            self._head.flush()
            lines: list[str] = []
            for p in self._chunk_paths():
                with open(p, "rb") as f:
                    for raw in f.read().splitlines():
                        lines.append(raw.decode(errors="replace"))
            return lines

    def search_lines_after_marker(self, marker: str) -> list[str] | None:
        """Lines strictly after the LAST line equal to `marker`; None if the
        marker never occurs (the caller then treats the whole log as fresh,
        matching autofile.Group.Search miss behavior).

        Scans chunks newest-to-oldest and stops at the first chunk containing
        the marker, so a long WAL only costs one chunk read in the common
        case (the reference's reverse Search, consensus/replay.go:107-126).
        """
        with self._mtx:
            self._head.flush()
            tail: list[str] = []
            for p in reversed(self._chunk_paths()):
                with open(p, "rb") as f:
                    lines = [ln.decode(errors="replace") for ln in f.read().splitlines()]
                for i in range(len(lines) - 1, -1, -1):
                    if lines[i] == marker:
                        return lines[i + 1 :] + tail
                tail = lines + tail
            return None
