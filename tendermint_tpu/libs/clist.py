"""Concurrent ordered list with blocking iteration (reference: tmlibs/clist,
used by the mempool to hold good txs and by the mempool reactor's per-peer
broadcast routine which blocks on FrontWait/NextWait —
mempool/mempool.go:61, mempool/reactor.go:114-152).

Elements stay navigable after removal: a detached element's next pointers
keep working so an iterator parked on a removed element can continue.
"""

from __future__ import annotations

import threading
from typing import Any


class CElement:
    __slots__ = ("value", "_next", "_prev", "_removed", "_list", "_next_wake")

    def __init__(self, value: Any, lst: "CList"):
        self.value = value
        self._next: CElement | None = None
        self._prev: CElement | None = None
        self._removed = False
        self._list = lst
        # lazily allocated on first next_wait: a 50k-tx CheckTx burst
        # builds 50k elements but parks iterators on only a handful, and
        # Condition construction dominated the burst profile (~20%)
        self._next_wake: threading.Condition | None = None

    def next(self) -> "CElement | None":
        with self._list._mtx:
            return self._next

    def next_wait(self, timeout: float | None = None) -> "CElement | None":
        """Block until this element has a next, or it is removed (then None
        means the iterator should restart from front), or timeout."""
        with self._list._mtx:
            if self._next is None and not self._removed:
                if self._next_wake is None:
                    self._next_wake = threading.Condition(self._list._mtx)
                self._next_wake.wait(timeout)
            return self._next

    @property
    def removed(self) -> bool:
        with self._list._mtx:
            return self._removed


class CList:
    def __init__(self):
        self._mtx = threading.RLock()
        self._head: CElement | None = None
        self._tail: CElement | None = None
        self._len = 0
        self._front_wake = threading.Condition(self._mtx)

    def __len__(self) -> int:
        with self._mtx:
            return self._len

    def front(self) -> CElement | None:
        with self._mtx:
            return self._head

    def front_wait(self, timeout: float | None = None) -> CElement | None:
        with self._mtx:
            if self._head is None:
                self._front_wake.wait(timeout)
            return self._head

    def back(self) -> CElement | None:
        with self._mtx:
            return self._tail

    def push_back(self, value: Any) -> CElement:
        with self._mtx:
            el = CElement(value, self)
            el._prev = self._tail
            if self._tail is not None:
                self._tail._next = el
                if self._tail._next_wake is not None:
                    self._tail._next_wake.notify_all()
            else:
                self._head = el
                self._front_wake.notify_all()
            self._tail = el
            self._len += 1
            return el

    def remove(self, el: CElement) -> Any:
        with self._mtx:
            if el._removed:
                return el.value
            prev, nxt = el._prev, el._next
            if prev is not None:
                prev._next = nxt
            else:
                self._head = nxt
            if nxt is not None:
                nxt._prev = prev
            else:
                self._tail = prev
            el._removed = True
            self._len -= 1
            # wake any iterator blocked in next_wait on the removed element
            if el._next_wake is not None:
                el._next_wake.notify_all()
            return el.value

    def __iter__(self):
        el = self.front()
        while el is not None:
            yield el
            el = el.next()
