"""KV store abstraction (reference: tmlibs/db — LevelDB/MemDB used for the
block store, state, tx index, addr book; chosen at node/node.go:51-53).

Two implementations:
- MemDB: in-memory dict (tests, fast-path).
- FileDB: dict snapshot persisted atomically to a single file. The access
  patterns in this framework (point get/set by height-derived keys plus a
  tiny iteration surface) don't need an LSM; an append-journal + periodic
  compaction keeps restart-recovery semantics without external deps.
"""

from __future__ import annotations

import os
import struct
import threading


class DB:
    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def set_sync(self, key: bytes, value: bytes) -> None:
        self.set(key, value)

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def iterate_prefix(self, prefix: bytes):
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemDB(DB):
    def __init__(self):
        self._data: dict[bytes, bytes] = {}
        self._mtx = threading.RLock()

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._data[bytes(key)] = bytes(value)

    def delete(self, key: bytes) -> None:
        with self._mtx:
            self._data.pop(key, None)

    def iterate_prefix(self, prefix: bytes):
        with self._mtx:
            items = sorted(
                (k, v) for k, v in self._data.items() if k.startswith(prefix)
            )
        yield from items

    def __len__(self):
        with self._mtx:
            return len(self._data)


_REC = struct.Struct("<BII")  # op, klen, vlen


class FileDB(DB):
    """Append-only journal of (op, key, value) records with load-time replay
    and size-triggered compaction. fsync on set_sync for the durability the
    reference gets from LevelDB's WAL."""

    _OP_SET = 1
    _OP_DEL = 2

    def __init__(self, path: str, compact_threshold: int = 64 * 1024 * 1024):
        self._path = path
        self._mtx = threading.RLock()
        self._data: dict[bytes, bytes] = {}
        self._compact_threshold = compact_threshold
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._load()
        self._f = open(path, "ab")

    def _load(self) -> None:
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as f:
            buf = f.read()
        off = 0
        valid_end = 0
        while off + _REC.size <= len(buf):
            op, klen, vlen = _REC.unpack_from(buf, off)
            off += _REC.size
            if off + klen + vlen > len(buf):
                break  # torn tail record from a crash: drop it
            key = buf[off : off + klen]
            off += klen
            val = buf[off : off + vlen]
            off += vlen
            valid_end = off
            if op == self._OP_SET:
                self._data[key] = val
            elif op == self._OP_DEL:
                self._data.pop(key, None)
        if valid_end < len(buf):
            # truncate the torn tail so subsequent appends don't concatenate
            # onto garbage and corrupt the journal for the next restart
            with open(self._path, "r+b") as f:
                f.truncate(valid_end)

    def _append(self, op: int, key: bytes, value: bytes, sync: bool) -> None:
        rec = _REC.pack(op, len(key), len(value)) + key + value
        self._f.write(rec)
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())
        if self._f.tell() > self._compact_threshold:
            self._compact()

    def _compact(self) -> None:
        tmp = self._path + ".compact"
        with open(tmp, "wb") as f:
            for k, v in self._data.items():
                f.write(_REC.pack(self._OP_SET, len(k), len(v)) + k + v)
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self._path)
        self._f = open(self._path, "ab")

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            key, value = bytes(key), bytes(value)
            self._data[key] = value
            self._append(self._OP_SET, key, value, sync=False)

    def set_sync(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            key, value = bytes(key), bytes(value)
            self._data[key] = value
            self._append(self._OP_SET, key, value, sync=True)

    def delete(self, key: bytes) -> None:
        with self._mtx:
            if key in self._data:
                del self._data[key]
                self._append(self._OP_DEL, key, b"", sync=False)

    def iterate_prefix(self, prefix: bytes):
        with self._mtx:
            items = sorted(
                (k, v) for k, v in self._data.items() if k.startswith(prefix)
            )
        yield from items

    def close(self) -> None:
        with self._mtx:
            self._f.close()


def db_provider(name: str, backend: str, db_dir: str) -> DB:
    """node/node.go:51-53 DefaultDBProvider equivalent."""
    if backend in ("memdb", "mem"):
        return MemDB()
    return FileDB(os.path.join(db_dir, name + ".db"))
