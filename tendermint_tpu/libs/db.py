"""KV store abstraction (reference: tmlibs/db — LevelDB/MemDB used for the
block store, state, tx index, addr book; chosen at node/node.go:51-53).

Three implementations:
- MemDB: in-memory dict (tests, fast-path).
- FileDB: append-journal with an in-memory key->offset index and
  periodic compaction (the r4 default; RAM grows with the key count).
- SqliteDB: stdlib sqlite3 behind a fixed page cache — the default
  since round 5: bounded steady-state RSS regardless of chain length
  (see its docstring for the soak numbers that motivated it).
"""

from __future__ import annotations

import os
import struct
import threading


class DB:
    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def set_sync(self, key: bytes, value: bytes) -> None:
        self.set(key, value)

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def iterate_prefix(self, prefix: bytes):
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemDB(DB):
    def __init__(self):
        self._data: dict[bytes, bytes] = {}
        self._mtx = threading.RLock()

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._data[bytes(key)] = bytes(value)

    def delete(self, key: bytes) -> None:
        with self._mtx:
            self._data.pop(key, None)

    def iterate_prefix(self, prefix: bytes):
        with self._mtx:
            items = sorted(
                (k, v) for k, v in self._data.items() if k.startswith(prefix)
            )
        yield from items

    def __len__(self):
        with self._mtx:
            return len(self._data)


_REC = struct.Struct("<BII")  # op, klen, vlen


class FileDB(DB):
    """Append-only journal of (op, key, value) records with load-time replay
    and size-triggered compaction. fsync on set_sync for the durability the
    reference gets from LevelDB's WAL.

    VALUES LIVE ON DISK: memory holds only a key -> (offset, length)
    index, so a long-running node's block store costs RAM proportional to
    the KEY count (~60 B/entry), not the chain's bytes — the property the
    reference gets from LevelDB. (A 30-min soak caught the prior design
    retaining ~9 KB of RAM per block, unbounded with chain length.)
    Reads seek the journal; the block-store/state hot paths read rarely
    (serving fast sync, RPC) while writes stay append-only."""

    _OP_SET = 1
    _OP_DEL = 2

    def __init__(self, path: str, compact_threshold: int = 64 * 1024 * 1024):
        self._path = path
        self._mtx = threading.RLock()
        self._index: dict[bytes, tuple[int, int]] = {}  # key -> (off, vlen)
        self._compact_threshold = compact_threshold
        self._compactions = 0  # observable: tests must prove live reads
        # survive a compaction, not just a restart replay
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._load()
        self._f = open(path, "ab")
        self._rf = open(path, "rb")

    def _load(self) -> None:
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as f:
            buf = f.read()
        off = 0
        valid_end = 0
        while off + _REC.size <= len(buf):
            op, klen, vlen = _REC.unpack_from(buf, off)
            off += _REC.size
            if off + klen + vlen > len(buf):
                break  # torn tail record from a crash: drop it
            key = buf[off : off + klen]
            off += klen
            if op == self._OP_SET:
                self._index[key] = (off, vlen)
            elif op == self._OP_DEL:
                self._index.pop(key, None)
            off += vlen
            valid_end = off
        if valid_end < len(buf):
            # truncate the torn tail so subsequent appends don't concatenate
            # onto garbage and corrupt the journal for the next restart
            with open(self._path, "r+b") as f:
                f.truncate(valid_end)

    def _append(self, op: int, key: bytes, value: bytes, sync: bool) -> int:
        """Write one record; returns the VALUE's file offset. Compaction is
        the caller's follow-up (_maybe_compact) so the new record's index
        entry exists before the index is rewritten."""
        value_off = self._f.tell() + _REC.size + len(key)
        self._f.write(_REC.pack(op, len(key), len(value)) + key + value)
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())
        return value_off

    def _maybe_compact(self) -> None:
        if self._f.tell() > self._compact_threshold:
            self._compact()

    def _read_at(self, off: int, vlen: int) -> bytes:
        self._rf.seek(off)
        return self._rf.read(vlen)

    def _compact(self) -> None:
        tmp = self._path + ".compact"
        new_index: dict[bytes, tuple[int, int]] = {}
        with open(tmp, "wb") as f:
            for k, (off, vlen) in self._index.items():
                v = self._read_at(off, vlen)
                new_index[k] = (f.tell() + _REC.size + len(k), vlen)
                f.write(_REC.pack(self._OP_SET, len(k), vlen) + k + v)
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        self._rf.close()
        os.replace(tmp, self._path)
        self._index = new_index
        self._compactions += 1
        self._f = open(self._path, "ab")
        self._rf = open(self._path, "rb")

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            ent = self._index.get(key)
            if ent is None:
                return None
            return self._read_at(*ent)

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            key, value = bytes(key), bytes(value)
            off = self._append(self._OP_SET, key, value, sync=False)
            self._index[key] = (off, len(value))
            self._maybe_compact()

    def set_sync(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            key, value = bytes(key), bytes(value)
            off = self._append(self._OP_SET, key, value, sync=True)
            self._index[key] = (off, len(value))
            self._maybe_compact()

    def delete(self, key: bytes) -> None:
        with self._mtx:
            if key in self._index:
                self._append(self._OP_DEL, key, b"", sync=False)
                del self._index[key]
                self._maybe_compact()

    def iterate_prefix(self, prefix: bytes):
        # snapshot KEYS only (filter before sorting); read each value via
        # get() at yield time — re-resolving the index per key keeps reads
        # correct across a concurrent compaction (stored offsets go stale
        # when the journal is rewritten) and never materializes the whole
        # matching range in RAM
        with self._mtx:
            keys = sorted(k for k in self._index if k.startswith(prefix))
        for k in keys:
            v = self.get(k)
            if v is not None:  # deleted since the snapshot: skip
                yield (k, v)

    def close(self) -> None:
        with self._mtx:
            self._f.close()
            self._rf.close()


class SqliteDB(DB):
    """KV store over stdlib sqlite3 — the BOUNDED-RAM persistent backend
    (the reference's LevelDB role, node/node.go:51-53).

    Why it exists (round-5 soak): FileDB keeps its whole key->offset
    index in RAM, so a node's RSS grows with chain length forever
    (~100 B x ~8 keys/block, measured ~90 KB/min at test cadence —
    scripts/soak_rss.py). Sqlite keeps the index in B-tree pages on disk
    behind a FIXED page cache, so steady-state RSS is flat no matter how
    long the chain gets.

    Durability split mirrors FileDB's: `set` commits in WAL mode with
    synchronous=NORMAL (fast; a power cut may lose the last commits but
    never corrupts), while `set_sync` runs on a second connection with
    synchronous=FULL, which fsyncs the WAL before returning — the
    guarantee the privval last-sign and state saves require."""

    _CACHE_KB = 2048  # fixed page-cache budget per DB (bounds RSS)

    def __init__(self, path: str):
        import sqlite3

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(f"PRAGMA cache_size=-{self._CACHE_KB}")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
        )
        self._conn.commit()
        self._sync_conn = sqlite3.connect(path, check_same_thread=False)
        self._sync_conn.execute("PRAGMA synchronous=FULL")
        self._sync_conn.execute(f"PRAGMA cache_size=-{self._CACHE_KB}")
        self._mtx = threading.RLock()

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (bytes(key),)
            ).fetchone()
        return None if row is None else bytes(row[0])

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
                (bytes(key), bytes(value)),
            )
            self._conn.commit()

    def set_sync(self, key: bytes, value: bytes) -> None:
        with self._mtx:
            self._sync_conn.execute(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
                (bytes(key), bytes(value)),
            )
            self._sync_conn.commit()

    def delete(self, key: bytes) -> None:
        with self._mtx:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (bytes(key),))
            self._conn.commit()

    def iterate_prefix(self, prefix: bytes):
        # snapshot the matching KEYS (cheap), then re-read each value at
        # yield time — same concurrent-mutation semantics as FileDB's
        # iterator (deleted-since-snapshot keys are skipped)
        prefix = bytes(prefix)
        # exclusive upper bound = prefix with its last non-0xff byte
        # incremented (an all-0xff prefix has no upper bound); the range
        # is the index-friendly filter, startswith is the correctness one
        upper = None
        p = bytearray(prefix)
        for i in reversed(range(len(p))):
            if p[i] != 0xFF:
                p[i] += 1
                upper = bytes(p[: i + 1])
                break
        q = "SELECT k, v FROM kv WHERE k >= ? ORDER BY k"
        params: tuple = (prefix,)
        if upper is not None:
            q = "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k"
            params = (prefix, upper)
        # one indexed range query, materialized under the lock (MemDB
        # yields snapshot-time values too; FileDB's re-read-per-key
        # exists only because compaction invalidates its offsets)
        with self._mtx:
            items = [
                (bytes(r[0]), bytes(r[1]))
                for r in self._conn.execute(q, params)
                if bytes(r[0]).startswith(prefix)
            ]
        yield from items

    def close(self) -> None:
        with self._mtx:
            self._conn.close()
            self._sync_conn.close()


def db_provider(name: str, backend: str, db_dir: str) -> DB:
    """node/node.go:51-53 DefaultDBProvider equivalent."""
    if backend in ("memdb", "mem"):
        return MemDB()
    if backend in ("sqlite", "sqlitedb"):
        return SqliteDB(os.path.join(db_dir, name + ".sqlite"))
    if backend in ("filedb", "file"):
        return FileDB(os.path.join(db_dir, name + ".db"))
    # fail LOUDLY: a silent FileDB fallback on a typo'd backend would
    # open a fresh empty store next to the real chain data
    raise ValueError(
        f"unknown db_backend {backend!r}: expected sqlite | filedb | memdb"
    )
