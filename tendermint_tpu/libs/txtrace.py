"""Transaction-lifecycle tracing (round 17, docs/observability.md).

The per-height consensus traces (round 11) and the fleet timelines
(round 15) answer "how is the node/fleet doing"; nothing answered
"where did MY transaction spend its time". This module is the sampled
per-tx span recorder: a traced tx is stamped with a wall-clock instant
at each lifecycle stage it crosses —

    rpc_ingress     check_tx entry (RPC submit, or gossip arrival on a
                    replica — the record carries the source)
    sig_gate        the batched signature-gate verdict landed
    mempool_admit   the app's CheckTx accepted it into the pool
    p2p_broadcast   first gossip send to any peer succeeded
    proposal        reaped into our proposal, or seen in a received
                    complete proposal block (whichever node this is)
    block_commit    the block carrying it finalized (stage 1: the WAL
                    marker is down; the record learns its height here)
    apply           the block's deferred/serial apply completed
    event_delivery  the tx's DeliverTx event flushed to subscribers

Stamps are keep-first (a re-proposed round re-stamps nothing), absolute
epoch seconds — the SAME convention as the round-15 gossip arrival
marks, so `ops/txtrace` can join instants for one tx hash ACROSS nodes
into a cross-node timeline (submitted on A, committed via B's proposal).
The tx hash (types/tx.tx_hash — the natural cross-node causal id) is
computed once, at sampling time, never on the untraced hot path.

Sampling (env knobs, libs/envknob semantics):

    TENDERMINT_TXTRACE_FIRST_K     (2)   trace the first K txs entering
                                         check_tx after each commit
    TENDERMINT_TXTRACE_SAMPLE_N    (64)  plus every Nth tx (0 = off)
    TENDERMINT_TXTRACE_MAX_ACTIVE  (256) in-flight trace bound — beyond
                                         it the oldest active trace is
                                         sealed as "evicted"
    TENDERMINT_TXTRACE_RING        (256) completed-trace ring
    TENDERMINT_TXTRACE_DISABLE     (0)   kill switch

Hot-path cost discipline (the <2% bound benches/bench_txtrace.py
asserts on the signed-burst shape — the harshest denominator in the
repo, ~16 us/tx through the batched gate): an untraced tx pays ONE
inline countdown at ingress (``rec._tick -= 1`` at the check_tx call
site — no method call; both sampling arms are folded into the one
counter, re-armed by the slow path), and the sig-gate/admit stamps run
at BATCH granularity (``stamp_gate_batch``: one set build per verified
batch, then one membership probe per in-flight trace — never per-tx
method calls). Dict keys are the tx BYTES whose hash the mempool cache
already computed and the bytes object caches. Block-granularity stamp
sites (`commit`/`stamp_present`/`delivered`) cost one dict.get per
block tx only while traces are in flight.

Metrics (materialized on the node registry by node/telemetry.py):
``tx_stage_seconds{stage}`` — span from the previous stamped stage —
plus the end-to-end ``tx_commit_latency_seconds`` (rpc_ingress ->
block_commit) and ``tx_visible_latency_seconds`` (rpc_ingress ->
event_delivery) histograms, observed once per sealed trace. The spans
TELESCOPE: for any sealed trace the stamped spans through block_commit
sum EXACTLY to its commit latency (the bench asserts within 10% to
guard the stamping sites, not the arithmetic).

Served by the ``tx_trace`` RPC (completed ring + in-flight actives —
a partition-parked tx is visible mid-flight, which is exactly what the
netchaos wedge triage needs) and the ``python -m
tendermint_tpu.ops.txtrace`` cross-node CLI.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from tendermint_tpu.libs.envknob import env_number as _env_number

# canonical stage order (display + docs/observability.md diagram)
STAGES = (
    "rpc_ingress", "sig_gate", "mempool_admit", "p2p_broadcast",
    "proposal", "block_commit", "apply", "event_delivery",
)

# tick value meaning "sampling disarmed": large enough that a node
# submitting a billion tx/s would take decades to count it down
_NEVER = 1 << 60

_hist_attr = "_txtrace_family_cache"


def txtrace_hists(reg=None) -> dict:
    """Create-or-get the tx-lifecycle histogram families on `reg`
    (default: the process-wide registry). Cached on the registry object
    like p2p/telemetry.peer_metrics so seals pay one attribute read."""
    from tendermint_tpu.libs import telemetry

    if reg is None:
        reg = telemetry.default_registry()
    cached = getattr(reg, _hist_attr, None)
    if cached is not None:
        return cached
    fams = {
        "stage": reg.histogram(
            "tx_stage_seconds",
            "per-tx span from the previous stamped lifecycle stage to "
            "this one (sampled txs only)",
            labelnames=("stage",),
        ),
        "commit": reg.histogram(
            "tx_commit_latency_seconds",
            "sampled per-tx end-to-end latency: check_tx ingress to "
            "block commit",
        ),
        "visible": reg.histogram(
            "tx_visible_latency_seconds",
            "sampled per-tx end-to-end latency: check_tx ingress to "
            "DeliverTx event delivery",
        ),
    }
    setattr(reg, _hist_attr, fams)
    return fams


class TxTrace:
    """One sampled tx's lifecycle record. Mutated only through the
    recorder; published (RPC readers) as to_json snapshots. The tx HASH
    (the cross-node causal id) is computed lazily — at seal or first
    read, never on the ingress path."""

    __slots__ = ("tx", "hash", "source", "stamps", "height", "outcome",
                 "completed_at")

    def __init__(self, tx: bytes, source: str):
        self.tx = tx
        self.hash: bytes | None = None
        self.source = source
        self.stamps: dict[str, float] = {}
        self.height = 0
        self.outcome: str | None = None  # committed/rejected/evicted
        self.completed_at = 0.0

    def ensure_hash(self) -> bytes:
        h = self.hash
        if h is None:
            from tendermint_tpu.types.tx import tx_hash

            h = self.hash = tx_hash(self.tx)
        return h

    def spans(self, stamps: dict | None = None) -> dict[str, float]:
        """Span attributed to each stamped stage: seconds since the
        PREVIOUS stamped stage. Telescoping by construction — summing
        the spans through block_commit reproduces the commit latency
        exactly."""
        if stamps is None:
            stamps = self.stamps
        out: dict[str, float] = {}
        prev = None
        for stage in STAGES:
            t = stamps.get(stage)
            if t is None:
                continue
            if prev is not None:
                out[stage] = max(0.0, t - prev)
            prev = t
        return out

    def to_json(self) -> dict:
        # snapshot FIRST: an RPC reader serializes in-flight traces
        # while stamping threads insert — dict(d) is one C-level copy
        # under the GIL, where iterating the live dict could raise
        # "changed size during iteration" mid-triage
        stamps = dict(self.stamps)
        ingress = stamps.get("rpc_ingress")
        commit = stamps.get("block_commit")
        visible = stamps.get("event_delivery")
        return {
            "hash": self.ensure_hash().hex().upper(),
            "source": self.source,
            "height": self.height,
            "outcome": self.outcome,
            "stages": stamps,
            "spans": {k: round(v, 6)
                      for k, v in self.spans(stamps).items()},
            "commit_latency_s": (
                round(commit - ingress, 6)
                if ingress is not None and commit is not None else None
            ),
            "visible_latency_s": (
                round(visible - ingress, 6)
                if ingress is not None and visible is not None else None
            ),
            "completed_at": self.completed_at or None,
        }


class TxTraceRecorder:
    """Sampled per-tx lifecycle spans keyed by tx bytes in flight and
    by tx hash at rest (the ring). One recorder per node — the mempool,
    its reactor, and the consensus state all stamp the same instance
    (node/node.py wires it; sites guard None for bare-harness tests)."""

    def __init__(self, ring: int | None = None, first_k: int | None = None,
                 sample_n: int | None = None, max_active: int | None = None):
        import os

        self._enabled = os.environ.get(
            "TENDERMINT_TXTRACE_DISABLE", "") != "1"
        self.first_k = (
            first_k if first_k is not None
            else int(_env_number("TENDERMINT_TXTRACE_FIRST_K", 2, cast=int))
        )
        self.sample_n = (
            sample_n if sample_n is not None
            else int(_env_number("TENDERMINT_TXTRACE_SAMPLE_N", 64, cast=int))
        )
        self.max_active = max(1, (
            max_active if max_active is not None
            else int(_env_number("TENDERMINT_TXTRACE_MAX_ACTIVE", 256,
                                 cast=int))
        ))
        if ring is None:
            ring = max(1, int(_env_number("TENDERMINT_TXTRACE_RING", 256,
                                          cast=int)))
        self._ring: deque[TxTrace] = deque(maxlen=ring)
        self._mtx = threading.Lock()
        # insertion-ordered (py3.7 dict): the oldest active is the
        # eviction victim when the bound is hit
        self._active: dict[bytes, TxTrace] = {}
        # THE ingress fast path: one countdown folding both sampling
        # arms. Call sites run `rec._tick -= 1` inline and only enter
        # ingress() when it hits zero; ingress() re-arms it — 0 while a
        # first-K burst is open (every tx enters), sample_n between
        # 1-in-N samples, effectively-infinite when sampling is off.
        # Benign GIL races (a lost decrement under concurrent check_tx)
        # shift WHICH tx samples, never correctness.
        self._burst_left = self.first_k if self._enabled else 0
        self._tick = _NEVER
        # external countdown holders (the mempool keeps its own
        # `_trace_tick` attribute so its check_tx fast path is a pure
        # local-attribute decrement — bind_tick registers it and _rearm
        # pushes every re-arm there too)
        self._tick_holders: list = []
        if self._enabled:
            self._rearm()
        self._seen = 0          # sampling decisions taken (stats)
        # flat stats (node/telemetry.py txtrace producer)
        self.sampled = 0
        self.completed = 0
        self.rejected = 0
        self.evicted = 0
        self.gate_batches = 0  # stamp_gate_batch calls (overhead bench)
        self.metrics_registry = None

    def set_enabled(self, on: bool) -> None:
        self._enabled = bool(on)
        self._burst_left = self.first_k if on else 0
        self._rearm()

    # -- sampling decision (check_tx entry) --------------------------------

    def maybe_trace(self, tx: bytes, source: str = "rpc",
                    at: float | None = None) -> bool:
        """The ingress gate: the inline countdown + the slow path. Call
        sites that can't inline the tick (tests, non-hot paths) use
        this; mempool.check_tx runs the two-line tick itself."""
        self._tick -= 1
        if self._tick <= 0:
            return self.ingress(tx, source, at)
        return False

    def bind_tick(self, holder) -> None:
        """Register an external countdown holder: `holder._trace_tick`
        mirrors this recorder's tick so the holder's hot path can run
        the decrement on its OWN attribute (no cross-object loads)."""
        self._tick_holders.append(holder)
        holder._trace_tick = self._tick

    def _rearm(self) -> None:
        """Set the countdown for the NEXT sample (callers hold no
        invariant: burst first, then 1-in-N, else never) and push it to
        every bound holder."""
        if self._burst_left > 0:
            tick = 0
        elif self.sample_n > 0:
            tick = self.sample_n
        else:
            tick = _NEVER
        self._tick = tick
        for h in self._tick_holders:
            h._trace_tick = tick

    def ingress(self, tx: bytes, source: str = "rpc",
                at: float | None = None) -> bool:
        """The tick hit zero: sample THIS tx (stamping rpc_ingress) and
        re-arm the countdown. The tx hash is computed only here — never
        on the untraced path."""
        if not self._enabled:
            self._burst_left = 0
            self._rearm()
            return False
        victim = None
        with self._mtx:
            self._seen += 1
            if self._burst_left > 0:
                self._burst_left -= 1
            self._rearm()
            if tx in self._active:
                return True  # resubmission of a tx already in flight
            self.sampled += 1
            tr = TxTrace(tx, source)
            tr.stamps["rpc_ingress"] = at if at is not None else time.time()
            if len(self._active) >= self.max_active:
                victim = self._active.pop(next(iter(self._active)))
                self.evicted += 1
            self._active[tx] = tr
        if victim is not None:
            # seal OUTSIDE the table lock (_seal appends to the ring
            # under the same mutex)
            self._seal(victim, "evicted")
        return True

    # -- stamping (hot paths: one dict.get when anything is in flight) -----

    def stamp(self, tx: bytes, stage: str, at: float | None = None) -> None:
        """Stamp one stage for one tx (keep-first). Untraced txs pay one
        dict.get; with nothing in flight, one attribute read."""
        if not self._active:
            return
        tr = self._active.get(tx)
        if tr is not None and stage not in tr.stamps:
            tr.stamps[stage] = at if at is not None else time.time()

    def stamp_present(self, txs, stage: str, at: float | None = None) -> None:
        """Stamp `stage` for every traced tx present in `txs` (a block's
        tx list) — one dict.get per block tx, only while traces are in
        flight."""
        if not self._active:
            return
        at = at if at is not None else time.time()
        for t in txs:
            self.stamp(bytes(t), stage, at=at)

    def stamp_gate_batch(self, ok_entries, at: float | None = None) -> None:
        """Batch-granular sig-gate stamping (the <2% discipline): one
        set build over the batch's admitted (tx, ctx) entries, then one
        membership probe per IN-FLIGHT trace — zero per-untraced-tx
        method calls. Stamps sig_gate AND mempool_admit at the verdict
        instant: the app dispatch is the same grouped call, and a local
        app's CheckTx ack lands within the same millisecond (an app
        REJECT later seals the trace via the mempool's reject path, so
        the approximation never leaves a wrong committed record)."""
        active = self._active
        if not active:
            return
        self.gate_batches += 1
        at = at if at is not None else time.time()
        if not ok_entries:
            return
        # C-speed transpose: one zip(*) pass + one set() over the tx
        # column — the cheapest whole-batch set build CPython offers
        ok = set(next(zip(*ok_entries)))
        for tx, tr in list(active.items()):
            if tx in ok:
                if "sig_gate" not in tr.stamps:
                    tr.stamps["sig_gate"] = at
                if "mempool_admit" not in tr.stamps:
                    tr.stamps["mempool_admit"] = at

    def reject(self, tx: bytes, reason: str = "rejected") -> None:
        """Seal a traced tx that left the lifecycle early (bad
        signature, app CheckTx reject)."""
        if not self._active:
            return
        with self._mtx:
            tr = self._active.pop(tx, None)
        if tr is not None:
            self._seal(tr, reason)
            self.rejected += 1

    # -- commit-side stamps (consensus state) ------------------------------

    def commit(self, txs, height: int, at: float | None = None) -> None:
        """block_commit for every traced tx in the finalized block; the
        record learns its height here. Also re-opens the first-K
        sampling window — called exactly once per committed height."""
        if self._enabled and self.first_k > 0:
            with self._mtx:
                self._burst_left = self.first_k
                self._rearm()
        if not self._active:
            return
        at = at if at is not None else time.time()
        for t in txs:
            b = bytes(t)
            tr = self._active.get(b)
            if tr is not None:
                if "block_commit" not in tr.stamps:
                    tr.stamps["block_commit"] = at
                tr.height = height

    def delivered(self, txs, at: float | None = None) -> None:
        """event_delivery for every traced tx in the block, then seal —
        the trace is complete (called after the event flush, serial and
        pipelined modes both)."""
        if not self._active:
            return
        at = at if at is not None else time.time()
        done = []
        with self._mtx:
            for t in txs:
                tr = self._active.pop(bytes(t), None)
                if tr is not None:
                    if "event_delivery" not in tr.stamps:
                        tr.stamps["event_delivery"] = at
                    done.append(tr)
        for tr in done:
            self._seal(tr, "committed")
            self.completed += 1

    # -- sealing + metrics -------------------------------------------------

    def _seal(self, tr: TxTrace, outcome: str) -> None:
        tr.outcome = outcome
        tr.completed_at = time.time()
        tr.ensure_hash()  # off the ingress path by design; pin it now
        self._observe(tr)
        with self._mtx:
            self._ring.append(tr)

    def _observe(self, tr: TxTrace) -> None:
        """Feed the sealed trace into the scrape-side distributions.
        Failure-proof like the consensus trace probes — attribution must
        never break the path that sealed the trace."""
        try:
            hists = txtrace_hists(self.metrics_registry)
            for stage, span in tr.spans().items():
                hists["stage"].labels(stage=stage).observe(span)
            ingress = tr.stamps.get("rpc_ingress")
            if ingress is None:
                return
            commit = tr.stamps.get("block_commit")
            if commit is not None:
                hists["commit"].observe(max(0.0, commit - ingress))
            visible = tr.stamps.get("event_delivery")
            if visible is not None:
                hists["visible"].observe(max(0.0, visible - ingress))
        except Exception:  # noqa: BLE001
            pass

    # -- reads (RPC threads) -----------------------------------------------

    def active(self) -> list[dict]:
        """In-flight traces, oldest first — a partition-parked tx shows
        up HERE, stages frozen at wherever it stalled."""
        with self._mtx:
            return [tr.to_json() for tr in self._active.values()]

    def last(self, n: int = 20) -> list[dict]:
        """Newest-first slice of the completed ring (sliced BEFORE
        serialization — fleets poll this)."""
        n = max(1, int(n))
        with self._mtx:
            items = list(self._ring)
        return [tr.to_json() for tr in list(reversed(items))[:n]]

    def stats(self) -> dict:
        """Flat gauges for the canonical map (txtrace_* families)."""
        with self._mtx:
            active = len(self._active)
        return {
            "sampled": self.sampled,
            "completed": self.completed,
            "rejected": self.rejected,
            "evicted": self.evicted,
            "active": active,
        }
