"""CRC-32C (Castagnoli, poly 0x1EDC6F41 reflected 0x82F63B78) — the WAL
record checksum (reference: consensus/wal.go frames every record with
crc32c before length; the Castagnoli polynomial has hardware support and
strictly better burst-error detection than CRC-32/ISO, which is why both
Tendermint and every LSM WAL picked it).

The container ships `google_crc32c` (native, ~4 GB/s) — preferred.  The
pure-Python table fallback keeps the FORMAT identical (same polynomial,
same init/xorout) on hosts without it; it is byte-at-a-time (~2 MB/s) and
only the repair scan over a large WAL would notice.  The self-check below
pins both paths to the canonical check value so a wrong polynomial can
never silently frame records.
"""

from __future__ import annotations

try:  # native path (baked into the image)
    import google_crc32c as _native

    def crc32c(data: bytes) -> int:
        return _native.value(data)

    IMPL = "google_crc32c"
except ImportError:  # pragma: no cover - exercised only without the wheel
    _TABLE = []
    for _n in range(256):
        _c = _n
        for _ in range(8):
            _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
        _TABLE.append(_c)

    def crc32c(data: bytes) -> int:
        crc = 0xFFFFFFFF
        for b in data:
            crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
        return crc ^ 0xFFFFFFFF

    IMPL = "pure-python"

# canonical CRC-32C check value (RFC 3720 appendix / every test vector
# table): a wrong polynomial here would mean every framed record fails
# its own checksum on a correct reader — refuse to import instead.
# A real raise, not assert: python -O must not strip the pin.
if crc32c(b"123456789") != 0xE3069283:
    raise RuntimeError(f"CRC-32C self-check failed ({IMPL})")
