"""Flow-rate monitoring and limiting — equivalent of tmlibs/flowrate, used by
MConnection send/recv throttling (p2p/connection.go:352,410) and the
fast-sync per-peer minimum-rate check (blockchain/pool.go:100-118).
"""

from __future__ import annotations

import threading
import time


class Status:
    def __init__(self, bytes_total: int, avg_rate: float, cur_rate: float):
        self.bytes = bytes_total
        self.avg_rate = avg_rate
        self.cur_rate = cur_rate


class Monitor:
    """EWMA rate monitor with an optional limit() that sleeps to cap the
    average transfer rate."""

    def __init__(self, sample_period: float = 0.1):
        self._mtx = threading.Lock()
        self._start = time.monotonic()
        self._bytes = 0
        self._cur_rate = 0.0
        self._window_start = self._start
        self._window_bytes = 0
        self._sample_period = sample_period

    def update(self, n: int) -> None:
        with self._mtx:
            now = time.monotonic()
            self._bytes += n
            self._window_bytes += n
            dt = now - self._window_start
            if dt >= self._sample_period:
                inst = self._window_bytes / dt
                # EWMA, alpha=0.5 per sample window
                self._cur_rate = inst if self._cur_rate == 0 else (self._cur_rate + inst) / 2
                self._window_start = now
                self._window_bytes = 0

    def limit(self, want: int, rate_limit: float) -> int:
        """Sleep as needed so the *average* rate stays <= rate_limit, then
        return how many bytes the caller may transfer (always `want` here;
        pacing is purely time-based)."""
        if rate_limit <= 0:
            return want
        with self._mtx:
            elapsed = time.monotonic() - self._start
            allowed = rate_limit * elapsed
            excess = self._bytes - allowed
        if excess > 0:
            time.sleep(excess / rate_limit)
        return want

    def status(self) -> Status:
        with self._mtx:
            now = time.monotonic()
            elapsed = max(now - self._start, 1e-9)
            return Status(self._bytes, self._bytes / elapsed, self._cur_rate)
