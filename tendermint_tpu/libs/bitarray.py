"""Thread-safe bit array (reference: tmlibs/common BitArray, used for vote
bitmaps at types/vote_set.go:54 and part-set tracking at types/part_set.go).

Backed by a Python int (arbitrary precision) rather than []uint64 words —
the operations the consensus gossip needs (or/and/sub, pick-random-set-bit,
copy) are O(words) either way and Python ints vectorize them in C.
"""

from __future__ import annotations

import random
import threading


class BitArray:
    def __init__(self, bits: int):
        if bits < 0:
            raise ValueError("negative bit count")
        self._bits = bits
        self._elems = 0  # little-endian bitmask
        self._mtx = threading.Lock()

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_int(cls, bits: int, mask: int) -> "BitArray":
        ba = cls(bits)
        ba._elems = mask & ((1 << bits) - 1)
        return ba

    @classmethod
    def from_indices(cls, bits: int, indices) -> "BitArray":
        ba = cls(bits)
        for i in indices:
            ba.set_index(i, True)
        return ba

    # -- accessors ---------------------------------------------------------

    @property
    def size(self) -> int:
        return self._bits

    def get_index(self, i: int) -> bool:
        with self._mtx:
            if i >= self._bits or i < 0:
                return False
            return bool((self._elems >> i) & 1)

    def set_index(self, i: int, v: bool) -> bool:
        with self._mtx:
            if i >= self._bits or i < 0:
                return False
            if v:
                self._elems |= 1 << i
            else:
                self._elems &= ~(1 << i)
            return True

    def copy(self) -> "BitArray":
        with self._mtx:
            return BitArray.from_int(self._bits, self._elems)

    def update(self, other: "BitArray") -> None:
        """Replace this array's bits with other's (tmlibs BitArray.Update,
        used by ApplyVoteSetBitsMessage's replace semantics)."""
        mask = other.as_int()
        with self._mtx:
            self._elems = mask & ((1 << self._bits) - 1)

    def as_int(self) -> int:
        with self._mtx:
            return self._elems

    # -- set algebra (used by gossip to compute "parts the peer lacks",
    #    consensus/reactor.go:428) ----------------------------------------

    def or_(self, other: "BitArray") -> "BitArray":
        bits = max(self._bits, other._bits)
        return BitArray.from_int(bits, self.as_int() | other.as_int())

    def and_(self, other: "BitArray") -> "BitArray":
        bits = min(self._bits, other._bits)
        return BitArray.from_int(bits, self.as_int() & other.as_int())

    def not_(self) -> "BitArray":
        with self._mtx:
            return BitArray.from_int(self._bits, ~self._elems & ((1 << self._bits) - 1))

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other (beyond other's size, self wins)."""
        with self._mtx:
            bits, elems = self._bits, self._elems
        o = other.as_int() & ((1 << min(bits, other.size)) - 1)
        return BitArray.from_int(bits, elems & ~o)

    def is_empty(self) -> bool:
        return self.as_int() == 0

    def is_full(self) -> bool:
        with self._mtx:
            return self._elems == (1 << self._bits) - 1 and self._bits > 0

    def num_true_bits(self) -> int:
        return bin(self.as_int()).count("1")

    def pick_random(self) -> tuple[int, bool]:
        """Pick a uniformly random set bit; (index, ok). Used by the gossip
        routines to pick a random needed part/vote (consensus/reactor.go:919)."""
        elems = self.as_int()
        if elems == 0:
            return 0, False
        set_bits = [i for i in range(self._bits) if (elems >> i) & 1]
        return random.choice(set_bits), True

    def indices(self) -> list[int]:
        elems = self.as_int()
        return [i for i in range(self._bits) if (elems >> i) & 1]

    def __eq__(self, other) -> bool:
        if not isinstance(other, BitArray):
            return NotImplemented
        return self._bits == other._bits and self.as_int() == other.as_int()

    def __repr__(self) -> str:
        bits = "".join("x" if self.get_index(i) else "_" for i in range(min(self._bits, 64)))
        return f"BA{{{self._bits}:{bits}}}"

    def to_json(self):
        return {"bits": self._bits, "elems": f"{self.as_int():x}"}

    @classmethod
    def from_json(cls, obj) -> "BitArray":
        return cls.from_int(obj["bits"], int(obj["elems"] or "0", 16))
