"""Cross-node transaction-lifecycle timelines (round 17).

    python -m tendermint_tpu.ops.txtrace --urls host1:46657,host2:46657
    python -m tendermint_tpu.ops.txtrace --urls ... --hash 3FA9C1...
    python -m tendermint_tpu.ops.txtrace --urls ... --json

Per node it pulls the ``tx_trace`` RPC (libs/txtrace.py: completed ring
+ in-flight actives) and joins the records by tx HASH — the natural
cross-node causal id — into per-tx timelines: the stage instants are
absolute wall-clock seconds (the round-15 arrival-mark convention), so
one tx's lifecycle reads ACROSS the fleet: submitted on A (rpc_ingress
there), gossiped (p2p_broadcast on A, rpc_ingress source=peer on B),
reaped into B's proposal, committed everywhere. A tx parked mid-flight
(the netchaos partition scenario) shows with its last stamped stage and
no commit — which is the wedge-triage read.

Scrape-parallel like ops/fleet (one thread per node; a dead node
contributes an error entry, not a dead CLI). Importable pieces for
tests/benches: ``collect_txtraces`` / ``join_tx_timelines`` /
``render``.
"""

from __future__ import annotations

import argparse
import json
import sys

from tendermint_tpu.libs.txtrace import STAGES


def fetch_txtraces(url: str, last: int = 20, tx_hash: str = "",
                   timeout: float = 10.0) -> dict:
    from tendermint_tpu.rpc.client import HTTPClient

    client = HTTPClient(url, timeout=timeout)
    return client.tx_trace(hash=tx_hash, last=int(last))


def collect_txtraces(urls: list[str], last: int = 20,
                     tx_hash: str = "") -> dict:
    """{url: {"traces": [...], "active": [...]} | {"error": ...}} —
    scraped in parallel; partial fleets are when this tool matters."""
    from concurrent.futures import ThreadPoolExecutor

    if not urls:
        return {}

    def one(url: str) -> dict:
        try:
            return fetch_txtraces(url, last=last, tx_hash=tx_hash)
        except Exception as exc:  # noqa: BLE001 — one dead node != no view
            return {"error": f"{type(exc).__name__}: {exc}"}

    with ThreadPoolExecutor(max_workers=min(16, len(urls))) as pool:
        return dict(zip(urls, pool.map(one, urls)))


def join_tx_timelines(snapshot: dict) -> list[dict]:
    """Join per-node records into per-tx cross-node rows, newest
    activity first. Each row: the tx hash, its committed height (from
    whichever node knows it), per-node {stage: instant} maps, the
    submitting node (earliest rpc_ingress with source=rpc), and
    end-to-end latencies where measurable."""
    by_hash: dict[str, dict[str, dict]] = {}
    for url, entry in snapshot.items():
        if "error" in entry:
            continue
        for t in entry.get("traces", []) + entry.get("active", []):
            by_hash.setdefault(t["hash"], {})[url] = t

    rows = []
    for h, nodes in by_hash.items():
        ingresses = [
            (t["stages"].get("rpc_ingress"), url, t)
            for url, t in nodes.items()
            if t["stages"].get("rpc_ingress") is not None
        ]
        ingresses.sort(key=lambda x: x[0])
        submitted_on = next(
            (url for _at, url, t in ingresses if t.get("source") == "rpc"),
            ingresses[0][1] if ingresses else None,
        )
        height = max((t.get("height") or 0 for t in nodes.values()),
                     default=0)
        committed = any(
            t["stages"].get("block_commit") is not None
            for t in nodes.values()
        )
        proposed_on = next(
            (url for url, t in nodes.items()
             if t["stages"].get("proposal") is not None),
            None,
        )
        last_activity = max(
            (max(t["stages"].values()) for t in nodes.values()
             if t["stages"]),
            default=0.0,
        )
        commit_latency = min(
            (t["commit_latency_s"] for t in nodes.values()
             if t.get("commit_latency_s") is not None),
            default=None,
        )
        # the furthest stage ANY node stamped — a parked tx reads as
        # "parked at <last stage>" straight off this field
        last_stage = None
        for stage in STAGES:
            if any(t["stages"].get(stage) is not None
                   for t in nodes.values()):
                last_stage = stage
        rows.append({
            "hash": h,
            "height": height or None,
            "committed": committed,
            "submitted_on": submitted_on,
            "proposed_on": proposed_on,
            "last_stage": last_stage,
            "commit_latency_s": commit_latency,
            "nodes_reporting": len(nodes),
            "last_activity": last_activity,
            "per_node": {
                url: {
                    "source": t.get("source"),
                    "outcome": t.get("outcome"),
                    "stages": t["stages"],
                    "spans": t.get("spans", {}),
                }
                for url, t in nodes.items()
            },
        })
    rows.sort(key=lambda r: r["last_activity"], reverse=True)
    return rows


# -- rendering -----------------------------------------------------------------


def _ms(v) -> str:
    return "-" if v is None else f"{v * 1000:.1f}ms"


def render(rows: list[dict], out=sys.stdout, last: int = 10) -> None:
    if not rows:
        print("no traced txs reported (sampling knobs: "
              "TENDERMINT_TXTRACE_FIRST_K / _SAMPLE_N)", file=out)
        return
    for r in rows[: max(1, int(last))]:
        state = (
            f"committed @h={r['height']}" if r["committed"]
            else f"PARKED at {r['last_stage'] or 'nowhere'}"
        )
        lat = f" e2e {_ms(r['commit_latency_s'])}" if r["committed"] else ""
        print(f"tx {r['hash'][:16]}.. {state}{lat} "
              f"(submitted on {r['submitted_on'] or '?'}, "
              f"proposal on {r['proposed_on'] or '?'}, "
              f"{r['nodes_reporting']} node(s) reporting)", file=out)
        # per-stage instants relative to the earliest ingress
        base = min(
            (t["stages"].get("rpc_ingress") for t in r["per_node"].values()
             if t["stages"].get("rpc_ingress") is not None),
            default=None,
        )
        if base is None:
            continue
        nodes = sorted(r["per_node"])
        print(f"  {'stage':<16}" + "".join(f"{n:>22}" for n in nodes),
              file=out)
        for stage in STAGES:
            vals = []
            any_set = False
            for n in nodes:
                at = r["per_node"][n]["stages"].get(stage)
                if at is None:
                    vals.append(f"{'-':>22}")
                else:
                    any_set = True
                    vals.append(f"{f'+{(at - base) * 1000:.1f}ms':>22}")
            if any_set:
                print(f"  {stage:<16}" + "".join(vals), file=out)
        print(file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="cross-node tx-lifecycle timelines from tx_trace "
                    "RPC scrapes",
    )
    ap.add_argument("--urls", required=True,
                    help="comma-separated RPC addresses (host:port)")
    ap.add_argument("--hash", default="",
                    help="filter to one tx hash (hex)")
    ap.add_argument("--last", type=int, default=10,
                    help="how many recent txs to show (default 10)")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON instead of the rendered timelines")
    args = ap.parse_args(argv)
    urls = [u.strip() for u in args.urls.split(",") if u.strip()]

    snapshot = collect_txtraces(urls, last=max(args.last, 20),
                                tx_hash=args.hash)
    rows = join_tx_timelines(snapshot)
    try:
        if args.json:
            errors = {u: e["error"] for u, e in snapshot.items()
                      if "error" in e}
            print(json.dumps({"txs": rows, "errors": errors}, indent=2))
        else:
            for u, e in snapshot.items():
                if "error" in e:
                    print(f"{u}: UNREACHABLE ({e['error']})",
                          file=sys.stderr)
            render(rows, last=args.last)
    except BrokenPipeError:
        sys.stderr.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
