"""Doubling-free batched Ed25519 verify: per-validator comb tables + a
fixed-base MXU comb — the round-5 TPU kernel.

WHY. The f32/f32p ladders spend ~85% of their VPU work on the 254 point
doublings every signature pays (ops/ed25519_f32p.py header). Those
doublings are per-lane bilinear ops — a systolic matmul unit cannot share
weights across them, so the MXU idles while the VPU grinds. But the
consensus workload has structure the reference's per-sig loop
(types/validator_set.go:247-250) never exploits: THE SAME VALIDATOR KEYS
SIGN EVERY BLOCK. Precompute, once per key, a windowed multiple table of
the negated pubkey on device, and every later verification of that key
needs ZERO doublings:

    [s]B + [h](-A)  ==  sum_p T_B[p][s_p]  +  sum_p T_A[p][h_p]

with 4-bit windows: 64 positions per scalar, 16 entries each, so a verify
is 128 table lookups + 127 mixed (niels) point additions — ~3x fewer VPU
ops than the 127-step joint Straus ladder. The two halves engage the
hardware differently:

- [h](-A): per-lane gather from a device-resident POOL of per-validator
  tables (bf16 rows; 8-bit limbs are exact in bf16). HBM-bandwidth work.
- [s]B: one-hot(digit) x fixed-basis-table matmuls via dot_general with
  bf16 inputs and fp32 accumulation — the MXU path. Exact: one-hot is
  0/1, table limbs are <= 255 (both exact bf16), the MXU multiplies bf16
  exactly and accumulates fp32 over 16 terms of <= 255 each.

Amortization: building one validator's table costs ~13 verifies' worth of
device work (896 adds + 256 doubles + batch normalization), amortized
over every subsequent block that validator signs — hundreds to millions
of verifies in steady state. Unknown-key or tiny batches stay on the
existing kernels/CPU path (the gateway keeps its fallback semantics).

Verification math and accept/reject semantics are IDENTICAL to
ops/ed25519_f32.py (strict cofactorless RFC 8032: compare y(W) and
sign-x(W) against R), and all field arithmetic reuses the f32 radix-2^8
machinery, so its EXACTNESS ARGUMENT carries over; the one new formula
(niels mixed add) is bounds-checked in the docstring of _niels_add.

Reference hot loops this replaces: types/vote_set.go:175,
types/validator_set.go:247-250, blockchain/reactor.go:235.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto import ed25519 as ed_ref
from tendermint_tpu.ops import ed25519_f32 as base

logger = logging.getLogger("ops.ed25519_comb")

P = base.P
NL = base.NL
W_POS = 64  # 4-bit windows over 256 bits
W_ENT = 16  # entries per window (digit values 0..15)
COORD_ROWS = 3 * NL  # niels coords per entry: (y-x, y+x, 2dxy), 32 limbs each


# ---------------------------------------------------------------------------
# fixed-base table for B (host-computed once, python ints)
# ---------------------------------------------------------------------------

_b_table_cache: list = []
_b_table_lock = threading.Lock()


def _niels_rows_np(x: int, y: int) -> np.ndarray:
    """(96,) float32 canonical limbs of ((y-x) mod p, (y+x) mod p,
    (2d*x*y) mod p)."""
    t2 = (2 * ed_ref.D % P) * x % P * y % P
    out = np.empty(COORD_ROWS, dtype=np.float32)
    out[:NL] = base._int_to_limbs_const((y - x) % P)
    out[NL : 2 * NL] = base._int_to_limbs_const((y + x) % P)
    out[2 * NL :] = base._int_to_limbs_const(t2)
    return out


def b_table() -> np.ndarray:
    """(W_POS, W_ENT, 96) float32 niels table of v * 16^p * B. Entry 0 is
    the identity in niels form: (1, 1, 0)."""
    with _b_table_lock:
        if _b_table_cache:
            return _b_table_cache[0]
        tab = np.zeros((W_POS, W_ENT, COORD_ROWS), dtype=np.float32)
        ident = np.zeros(COORD_ROWS, dtype=np.float32)
        ident[0] = 1.0
        ident[NL] = 1.0
        gp = ed_ref.B  # extended (X, Y, Z=1, T)
        for p in range(W_POS):
            tab[p, 0] = ident
            acc = gp
            for v in range(1, W_ENT):
                ax, ay = base._affine(acc)
                tab[p, v] = _niels_rows_np(ax, ay)
                if v + 1 < W_ENT:
                    acc = ed_ref.point_add(acc, gp)
            for _ in range(4):  # gp <- 16 * gp
                gp = ed_ref.point_add(gp, gp)
        _b_table_cache.append(tab)
        return tab


# ---------------------------------------------------------------------------
# device kernels
# ---------------------------------------------------------------------------


def _digits4(limbs_u8: jax.Array) -> jax.Array:
    """(32,B) int32 byte limbs -> (64,B) int32 4-bit digits, little-endian
    position order (position p has weight 16^p)."""
    lo = limbs_u8 & 15
    hi = (limbs_u8 >> 4) & 15
    return jnp.stack([lo, hi], axis=1).reshape(2 * NL, limbs_u8.shape[-1])


def _niels_add(acc, my, py, t2):
    """Mixed addition acc + N where N is a niels-form affine point
    (my = y-x, py = y+x, t2 = 2d*x*y; implicit z = 1).

    BOUNDS (under the f32 EXACTNESS ARGUMENT's loose-limb invariants):
    my/py/t2 are canonical (limbs <= 255) — tighter than any operand the
    argument already covers, so a/b/c row sums are <= the point_add
    bounds; d = fadd(z1, z1) matches point_add's d; e..h and the closing
    four muls are literally point_add's closing pattern. Nothing exceeds
    the documented 2^23.5 ceiling."""
    x1, y1, z1, t1 = acc
    a = base.fmul(base.fsub(y1, x1), my)
    b = base.fmul(base.fadd(y1, x1), py)
    c = base.fmul(t1, t2)
    d = base.fadd(z1, z1)
    e = base.fsub(b, a)
    f = base.fsub(d, c)
    g = base.fadd(d, c)
    h = base.fadd(b, a)
    return (
        base.fmul(e, f),
        base.fmul(g, h),
        base.fmul(f, g),
        base.fmul(e, h),
    )


def _verify_comb_impl(pool, t_b, slots, r_y, r_sign, s8, h8):
    """pool: (C*W_POS*W_ENT, 96) bf16 per-validator niels tables (of -A);
    t_b: (W_POS, W_ENT, 96) f32 fixed-base table; slots: (B,) int32 pool
    slot per lane; r_y/r_sign/s8/h8 as in base._verify_impl. -> bool[B].

    Accumulates W = [s]B + [h](-A) as 128 niels lookups + 127 mixed adds
    (no doublings), then compares against R exactly like the ladder
    kernels."""
    batch = slots.shape[0]
    dh = _digits4(h8)  # (64,B) digits of h -> per-validator pool
    ds = _digits4(s8)  # (64,B) digits of s -> fixed-base table

    # [h](-A): gather 64 niels rows per lane from the pool
    pos = jnp.arange(W_POS, dtype=jnp.int32)[:, None]  # (64,1)
    flat = (slots[None, :] * W_POS + pos) * W_ENT + dh  # (64,B)
    rows_a = jnp.take(pool, flat.reshape(-1), axis=0)  # (64*B, 96) bf16
    rows_a = (
        rows_a.reshape(W_POS, batch, COORD_ROWS)
        .astype(jnp.float32)
        .transpose(0, 2, 1)
    )  # (64, 96, B)

    # [s]B: one-hot x basis-table batched matmul (MXU: bf16 inputs, fp32
    # accumulation; exact for 0/1 x <=255 integer operands)
    oh = (ds[:, None, :] == jnp.arange(W_ENT, dtype=jnp.int32)[None, :, None])
    rows_b = jax.lax.dot_general(
        t_b.astype(jnp.bfloat16),  # (64, 16, 96)
        oh.astype(jnp.bfloat16),  # (64, 16, B)
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # (64, 96, B)

    stream = jnp.concatenate([rows_a, rows_b], axis=0)  # (128, 96, B)

    zeros = stream[0, :NL] * 0.0
    one = zeros.at[0].set(1.0)
    ident = (zeros, one, one, zeros)

    def step(acc, row):
        return _niels_add(acc, row[:NL], row[NL : 2 * NL], row[2 * NL :]), None

    acc, _ = jax.lax.scan(step, ident, stream)

    px, py_, pz, _ = acc
    zinv = base.finv(pz)
    x_aff = base.fcanon(base.fmul(px, zinv))
    y_aff = base.fcanon(base.fmul(py_, zinv))
    sign = x_aff[0].astype(jnp.int32) & 1
    return jnp.all(y_aff == base.fcanon(r_y), axis=0) & (sign == r_sign)


_verify_jit = jax.jit(_verify_comb_impl)


# -- table building on device -------------------------------------------------


def _build_tables_impl(qx, qy):
    """qx/qy: (32, n) f32 canonical affine limbs of Q = -A per validator.
    Returns (n, W_POS*W_ENT, 96) float32 niels tables (canonical limbs,
    ready for a bf16 cast).

    Structure: scan over the 64 window positions carrying Q_p = 16^p * Q;
    each step emits the 15 extended-coordinate multiples v*Q_p (v=1..15,
    a chained point_add); then one Montgomery batch inversion over all
    960 entries x n lanes normalizes to affine, and a final pass forms
    canonical niels rows. ~13 signature-verifies of device work per
    validator, amortized over every later verify of that key."""
    n = qx.shape[-1]
    zeros = qx * 0.0
    one = zeros.at[0].set(1.0)
    d2 = jnp.broadcast_to(jnp.asarray(base._D2)[:, None], (NL, n))
    q0 = (qx, qy, one, base.fmul(qx, qy))

    def pos_step(q, _):
        entries = []
        acc = q
        for _v in range(1, W_ENT):
            entries.append(jnp.stack(acc, axis=0))  # (4, 32, n)
            acc = base.point_add(acc, q, d2)
        nxt = q
        for _ in range(4):
            nxt = base.point_double(nxt)
        return nxt, jnp.stack(entries, axis=0)  # (15, 4, 32, n)

    _, ext = jax.lax.scan(pos_step, q0, None, length=W_POS)
    # ext: (64, 15, 4, 32, n) extended entries
    ext = ext.reshape(W_POS * (W_ENT - 1), 4, NL, n)
    m = ext.shape[0]  # 960

    # Montgomery batch inversion of all entry Zs: forward prefix-product
    # scan, one shared finv, backward unwind — ~2x960 fmuls instead of 960
    # full inversions.
    zs = ext[:, 2]  # (960, 32, n)

    def fwd(carry, z):
        nxt = base.fmul(carry, z)
        return nxt, carry  # prefix BEFORE this element

    total, prefix = jax.lax.scan(fwd, one, zs)
    tinv = base.finv(total)

    def bwd(carry, inp):
        z, pref = inp
        inv_z = base.fmul(carry, pref)  # carry = inv(prefix_after)
        nxt = base.fmul(carry, z)
        return nxt, inv_z

    _, zinvs_rev = jax.lax.scan(bwd, tinv, (zs[::-1], prefix[::-1]))
    zinvs = zinvs_rev[::-1]  # (960, 32, n)

    def to_niels(inp):
        entry, zinv = inp
        x = base.fmul(entry[0], zinv)
        y = base.fmul(entry[1], zinv)
        t2 = base.fmul(base.fmul(x, y), d2)
        my = base.fcanon(base.fsub(y, x))
        py = base.fcanon(base.fadd(y, x))
        t2 = base.fcanon(t2)
        return jnp.stack([my, py, t2], axis=0)  # (3, 32, n)

    niels = jax.lax.map(to_niels, (ext, zinvs))  # (960, 3, 32, n)
    niels = niels.reshape(W_POS, W_ENT - 1, COORD_ROWS, n)
    ident = jnp.zeros((W_POS, 1, COORD_ROWS, n), dtype=jnp.float32)
    ident = ident.at[:, 0, 0].set(1.0).at[:, 0, NL].set(1.0)
    full = jnp.concatenate([ident, niels], axis=1)  # (64, 16, 96, n)
    return full.transpose(3, 0, 1, 2).reshape(n, W_POS * W_ENT, COORD_ROWS)


_build_jit = jax.jit(_build_tables_impl)


def _scatter_tables(pool, slots, tables):
    return pool.at[slots].set(tables)


_scatter_jit = jax.jit(_scatter_tables)


# ---------------------------------------------------------------------------
# the pool manager
# ---------------------------------------------------------------------------


class PoolExhausted(RuntimeError):
    """One batch references more distinct validator keys than the pool's
    maximum capacity; the caller should use a ladder kernel instead."""


def _neg_x_bytes(x_le: bytes) -> bytes:
    x = int.from_bytes(x_le, "little")
    return ((P - x) % P).to_bytes(32, "little")


class CombPool:
    """Device-resident LRU pool of per-validator comb tables.

    Slots are leased to pubkeys on first sight; the table build runs on
    device, batched across all new keys in the request. Capacity grows by
    doubling up to `cap` (env TENDERMINT_TPU_COMB_CAP, default 12288
    slots ~= 2.4 GB bf16 — sized for the 10k-validator benchmark on a
    16 GB v5e). Eviction is LRU; the pool array is rebuilt functionally
    (no donation: an in-flight verify may still reference the old
    buffer)."""

    def __init__(self, capacity: int | None = None, max_capacity: int | None = None):
        self.cap = int(
            max_capacity
            or os.environ.get("TENDERMINT_TPU_COMB_CAP", 12288)
        )
        c0 = int(capacity or min(self.cap, 256))
        self._c = c0
        self._pool = jnp.zeros(
            (c0 * W_POS * W_ENT, COORD_ROWS), dtype=jnp.bfloat16
        )
        self._lru: OrderedDict[bytes, int] = OrderedDict()
        self._free: list[int] = list(range(c0 - 1, 0, -1))  # slot 0 reserved
        self._lock = threading.Lock()
        self._tb = jnp.asarray(b_table())
        self.stats = {"builds": 0, "build_keys": 0, "evictions": 0, "grows": 0}

    @property
    def capacity(self) -> int:
        return self._c

    def _grow(self) -> None:
        new_c = min(self._c * 2, self.cap)
        if new_c == self._c:
            return
        pad = jnp.zeros(
            ((new_c - self._c) * W_POS * W_ENT, COORD_ROWS), dtype=jnp.bfloat16
        )
        self._pool = jnp.concatenate([self._pool, pad], axis=0)
        self._free.extend(range(new_c - 1, self._c - 1, -1))
        self._c = new_c
        self.stats["grows"] += 1

    def _take_slot(self, pinned: set[int]) -> int:
        if not self._free:
            self._grow()
        if self._free:
            return self._free.pop()
        # evict LRU (front of the OrderedDict) — but never a slot leased
        # to another lane of the batch currently being assembled: that
        # lane's slots[] entry would silently point at the new key's
        # table and reject a valid signature.
        for key, slot in self._lru.items():
            if slot not in pinned:
                del self._lru[key]
                self.stats["evictions"] += 1
                return slot
        raise PoolExhausted(
            f"batch needs more distinct validator keys than the comb "
            f"pool's max capacity ({self.cap} slots)"
        )

    def ensure(self, keys: list[bytes], xs: np.ndarray, ys: np.ndarray):
        """Lease slots for decompressed keys. keys[i] is the 32-byte
        compressed pubkey; xs/ys are (n, 32) u8 canonical affine limbs of
        A (NOT negated — negation happens here). Returns
        (slots int32 (n,), pool bf16 array snapshot). Caller must pass
        only keys whose decompression succeeded. Raises PoolExhausted when
        one batch holds more distinct keys than max capacity (the gateway
        backend falls back to the ladder kernel)."""
        with self._lock:
            missing: dict[bytes, int] = {}
            first_at: dict[bytes, int] = {}
            pinned: set[int] = set()
            slots = np.zeros(len(keys), dtype=np.int32)
            try:
                for i, k in enumerate(keys):
                    s = self._lru.get(k)
                    if s is not None:
                        self._lru.move_to_end(k)
                        slots[i] = s
                        pinned.add(s)
                        continue
                    s = missing.get(k)
                    if s is None:
                        s = self._take_slot(pinned)
                        missing[k] = s
                        first_at[k] = i
                        self._lru[k] = s
                        pinned.add(s)
                    slots[i] = s
            except PoolExhausted:
                # roll back this call's leases: the tables were never
                # built, and a leaked _lru entry would route the key's
                # NEXT batch onto a garbage slot table (valid signatures
                # rejected until restart) — round-5 review finding
                for k, s in missing.items():
                    if self._lru.get(k) == s:
                        del self._lru[k]
                    self._free.append(s)
                raise
            if missing:
                uniq = list(missing.keys())
                idx = [first_at[k] for k in uniq]
                qx = np.zeros((NL, len(uniq)), dtype=np.float32)
                qy = np.zeros((NL, len(uniq)), dtype=np.float32)
                for j, i in enumerate(idx):
                    nx = np.frombuffer(
                        _neg_x_bytes(xs[i].tobytes()), dtype=np.uint8
                    )
                    qx[:, j] = nx.astype(np.float32)
                    qy[:, j] = ys[i].astype(np.float32)
                tables = _build_jit(jnp.asarray(qx), jnp.asarray(qy))
                tslots = np.asarray(
                    [missing[k] for k in uniq], dtype=np.int32
                )
                # scatter whole-slot row blocks: view pool as (C, 1024, 96)
                pool3 = self._pool.reshape(self._c, W_POS * W_ENT, COORD_ROWS)
                pool3 = _scatter_jit(
                    pool3, jnp.asarray(tslots), tables.astype(jnp.bfloat16)
                )
                self._pool = pool3.reshape(
                    self._c * W_POS * W_ENT, COORD_ROWS
                )
                self.stats["builds"] += 1
                self.stats["build_keys"] += len(uniq)
            return slots, self._pool

    def table_b(self):
        return self._tb


_default_pool: list[CombPool] = []
_default_pool_lock = threading.Lock()


def default_pool() -> CombPool:
    with _default_pool_lock:
        if not _default_pool:
            _default_pool.append(CombPool())
        return _default_pool[0]


def set_default_pool(pool: CombPool) -> None:
    with _default_pool_lock:
        _default_pool.clear()
        _default_pool.append(pool)


def reset_default_pool() -> None:
    """Drop the process-wide pool (tests; also frees device memory)."""
    with _default_pool_lock:
        _default_pool.clear()
    with _seen_lock:
        _seen.clear()


# -- second-sight build policy ------------------------------------------------
#
# Building a key's comb table costs ~13 verifies of device work, paid off
# only if the key is seen again (validator keys sign every block; a
# mempool user key may never recur — reference mempool/mempool.go:166-205
# verifies each tx signature exactly once). Policy: build tables only for
# keys on their >= MIN_SIGHT-th batch appearance; lanes whose key has no
# table yet verify on the f32 ladder in the same call. Self-tuning, no
# caller hints: commits go all-comb from their second block, one-shot
# keys never trigger a build.

_seen: OrderedDict[bytes, int] = OrderedDict()
_seen_lock = threading.Lock()
_SEEN_CAP = 1 << 18


def _min_sight() -> int:
    return int(os.environ.get("TENDERMINT_TPU_COMB_MIN_SIGHT", "2"))


def _bump_seen(keys: set[bytes]) -> dict[bytes, int]:
    out = {}
    with _seen_lock:
        for k in keys:
            c = _seen.pop(k, 0) + 1
            _seen[k] = c
            out[k] = c
        while len(_seen) > _SEEN_CAP:
            _seen.popitem(last=False)
    return out


# ---------------------------------------------------------------------------
# gateway backend API
# ---------------------------------------------------------------------------


def _dispatch_comb(items, kidx, keys, pool_mgr):
    """Marshal + enqueue the comb kernel for items[kidx] (whose keys are
    all pool-eligible). Returns a resolver for bool[len(kidx)]."""
    sub = [items[i] for i in kidx]
    n = len(sub)
    bucket = base._next_pow2(n)
    ax, ay, ry, rs, s8, h8, valid = base.prepare_batch8(sub, bucket)
    slots = np.zeros(bucket, dtype=np.int32)
    vidx = [i for i in range(n) if valid[i]]
    if vidx:
        xs = ax.T[np.asarray(vidx)].astype(np.uint8)
        ys = ay.T[np.asarray(vidx)].astype(np.uint8)
        leased, pool_arr = pool_mgr.ensure(
            [keys[i] for i in vidx], xs, ys
        )
        slots[np.asarray(vidx)] = leased
    else:
        pool_arr = pool_mgr.ensure([], np.zeros((0, 32)), np.zeros((0, 32)))[1]
    ok_dev = _verify_jit(
        pool_arr,
        pool_mgr.table_b(),
        jnp.asarray(slots),
        jnp.asarray(ry),
        jnp.asarray(rs),
        jnp.asarray(s8),
        jnp.asarray(h8),
    )
    return lambda: np.asarray(ok_dev)[:n] & valid[:n]


def verify_batch_async(items: list[tuple[bytes, bytes, bytes]]):
    """Marshal + enqueue; returns a zero-arg resolver for bool[B] — the
    standard kernel contract (see base.verify_batch_async).

    Lane routing (see the second-sight policy note above): lanes whose
    key already has a pool table — or has now been seen MIN_SIGHT times —
    ride the comb kernel (building tables as needed); the rest, plus any
    malformed lanes, verify on the f32 ladder in the same call. Both
    dispatches are enqueued before either resolves, so device work
    overlaps."""
    n = len(items)
    if n == 0:
        return lambda: np.zeros(0, dtype=bool)
    pool_mgr = default_pool()
    keys = [
        bytes(p) if len(p) == 32 and len(s) == 64 else None
        for p, _m, s in items
    ]
    counts = _bump_seen({k for k in keys if k is not None})
    min_sight = _min_sight()
    with pool_mgr._lock:
        in_pool = {
            k for k in counts if k in pool_mgr._lru
        }
    comb_idx = [
        i
        for i, k in enumerate(keys)
        if k is not None and (k in in_pool or counts[k] >= min_sight)
    ]
    cset = set(comb_idx)
    ladder_idx = [i for i in range(n) if i not in cset]
    resolvers: list[tuple[list[int], object]] = []
    if comb_idx:
        try:
            r = _dispatch_comb(
                items, comb_idx, [keys[i] for i in comb_idx], pool_mgr
            )
            resolvers.append((comb_idx, r))
        except PoolExhausted:
            logger.warning(
                "comb pool exhausted (%d lanes); ladder fallback",
                len(comb_idx),
            )
            ladder_idx = sorted(ladder_idx + comb_idx)
    if ladder_idx:
        r = base.verify_batch_async([items[i] for i in ladder_idx])
        resolvers.append((ladder_idx, r))

    def resolve():
        out = np.zeros(n, dtype=bool)
        for idx, r in resolvers:
            out[np.asarray(idx)] = np.asarray(r())
        return out

    return resolve


def verify_batch(items: list[tuple[bytes, bytes, bytes]]) -> np.ndarray:
    """Drop-in gateway backend (same contract as base.verify_batch)."""
    return verify_batch_async(items)()
