"""Network fault tier (round 12): per-link TCP chaos for real testnets.

PR 3 built the DEVICE-plane fault harness (ops/faults.py: the UDS wire
between a node and its devd daemon). This module is the same idea one
layer up — the p2p NETWORK between nodes — now that the encrypted
transport is in-repo (crypto/x25519, crypto/chacha20poly1305) and
multi-node tests run over real TCP instead of loopback fabrics.

A `LinkProxy` fronts ONE directed p2p link: the dialing node is given
the proxy's address instead of the listener's, and every byte of the
connection (both directions of the TCP stream) relays through it. On
top of the byte relay sit the network fault controls:

- `partition()` / `heal()`: live connections are torn down
  (shutdown-then-close — the PR-3 lesson: close() alone never wakes a
  blocked recv) and new connects are refused until healed. The dialing
  switch's persistent-peer reconnect loop keeps retrying through the
  outage, so healing is observable as re-peering WITHOUT test
  intervention.
- `set_delay(c2s_s=, s2c_s=)`: ASYMMETRIC per-direction latency — each
  relayed chunk sleeps before forwarding, so a link can be slow one way
  and fast the other (the classic consensus-timeout aggravator).
- `set_wan(profile, seed=)` (round 18): seeded WAN shaping sampled from
  a named `WanProfile` distribution (`lan`, `continental`,
  `intercontinental`, `lossy-mobile`) — per-link base latency sampled
  once per direction, per-chunk jitter, a retransmit-STALL loss model
  (a TCP relay cannot drop stream bytes; loss is latency), bandwidth
  pacing, and a severe-loss connection-reset arm. Counted in the
  `netfaults_wan_*` scrape family.
- `set_reorder(n)`: swap the next n pairs of adjacent chunks. The
  SecretConnection's counter-nonce AEAD makes stream reordering
  DETECTABLE-BY-DESIGN: the receiver sees an authentication failure,
  poisons the connection, and the peer drops loudly (then reconnects).
  The scenario matrix asserts exactly that — reorder is tamper, not
  silent corruption.
- an optional `FaultPlan` (ops/faults taxonomy, reused verbatim) fires
  refuse/stall on connects and stall/drop/corrupt/truncate on relayed
  chunks, so the seeded deterministic schedules from the device tier
  drive network chaos too. `corrupt` here flips a byte INSIDE the
  encrypted stream — unlike the trusted local devd IPC, this wire is
  AEAD-protected, so payload corruption is in-contract and must surface
  as a loud peer error.

`NetFabric` owns all the directed links of an N-node testnet and maps
group-level operations (partition {0,1} | {2,3,4}, heal_all, per-link
delay) onto them. Peer churn — the listener-kill/restart arm — lives
with the node harness (tests/netchaos_common.py) because it owns the
listeners; the fabric contributes the link-level side (drop_all on the
churned node's links).

Counters: every link counts conns/refusals/bytes/injected faults into
flat `stats()` dicts, aggregated across registered fabrics into
scrape-only `netfaults_*` telemetry (same convention as faults_*), so a
chaos soak asserts on the scraped surface production has.
"""

from __future__ import annotations

import argparse
import json
import logging
import random
import signal
import socket
import threading
import time
from dataclasses import dataclass

from tendermint_tpu.ops.faults import FaultPlan, _kill_sock

logger = logging.getLogger("ops.netfaults")

_CHUNK = 65536

_COUNTER_KEYS = (
    "conns", "conns_refused", "bytes_c2s", "bytes_s2c",
    "partitions", "heals", "partition_drops",
    "delays_injected", "reorders_injected", "plan_faults",
    # WAN tier (round 18): per-chunk latency/jitter actually applied,
    # cumulative sleep injected, retransmit-stall hits from the loss
    # model, bytes paced through the bandwidth cap, and severe-loss
    # connection resets
    "wan_delays_applied", "wan_delay_seconds", "wan_loss_stalls",
    "wan_bytes_shaped", "wan_resets",
)


# -- WAN profiles (round 18) --------------------------------------------------
#
# A LinkProxy is a TCP byte relay, so byte LOSS cannot be modeled by
# dropping bytes (the AEAD layer above would read it as tamper, and real
# TCP never loses stream bytes anyway — loss shows up as retransmit
# latency). The loss model here is therefore a per-chunk retransmit
# STALL (an RTO-shaped delay spike) plus, for the severely lossy
# profiles, a small per-chunk probability of a full connection reset
# (the carrier-grade-NAT / cell-handoff failure mode; the dialing
# switch's persistent reconnect loop rides through it). Bandwidth caps
# pace each chunk by its serialization delay.


@dataclass(frozen=True)
class WanProfile:
    """A named distribution of link behavior. `delay_range_s` is sampled
    ONCE per link direction with a seeded RNG (links differ, runs with
    the same seed do not); jitter/loss/reset draw per chunk from the
    same seeded stream."""

    name: str
    delay_range_s: tuple[float, float]  # one-way base latency range
    jitter_s: float = 0.0               # uniform [0, jitter) per chunk
    loss: float = 0.0                   # P(chunk pays a retransmit stall)
    loss_stall_s: float = 0.0           # the stall (TCP RTO analogue)
    bandwidth_bps: float = 0.0          # 0 = uncapped
    reset_prob: float = 0.0             # P(connection reset per chunk)


WAN_PROFILES: dict[str, WanProfile] = {
    "lan": WanProfile("lan", (0.0002, 0.001), jitter_s=0.0005),
    "continental": WanProfile(
        "continental", (0.012, 0.035), jitter_s=0.004,
        loss=0.004, loss_stall_s=0.05, bandwidth_bps=8e6,
    ),
    "intercontinental": WanProfile(
        "intercontinental", (0.04, 0.09), jitter_s=0.012,
        loss=0.01, loss_stall_s=0.1, bandwidth_bps=4e6,
    ),
    "lossy-mobile": WanProfile(
        "lossy-mobile", (0.03, 0.08), jitter_s=0.03,
        loss=0.05, loss_stall_s=0.12, bandwidth_bps=2e6,
        reset_prob=0.0003,
    ),
}


def wan_profile(profile: "WanProfile | str") -> WanProfile:
    """Resolve a profile by name (the scenario-matrix spelling) or pass
    a custom WanProfile through."""
    if isinstance(profile, WanProfile):
        return profile
    try:
        return WAN_PROFILES[profile]
    except KeyError:
        raise KeyError(
            f"unknown WAN profile {profile!r}; "
            f"known: {sorted(WAN_PROFILES)}"
        ) from None


def geo_clusters(n: int, k: int) -> list[list[int]]:
    """Contiguous split of nodes 0..n-1 into k geo clusters — the
    "k clusters x m nodes" declaration scenarios use instead of hand-set
    delays (NetFabric.apply_geo maps intra/inter profiles onto it)."""
    if k <= 0:
        raise ValueError("need at least one cluster")
    base, extra = divmod(n, k)
    out, start = [], 0
    for c in range(k):
        size = base + (1 if c < extra else 0)
        out.append(list(range(start, start + size)))
        start += size
    return [c for c in out if c]


class LinkProxy:
    """One directed p2p link (dialer -> listener) as a TCP byte relay
    with injectable network faults. Thread-per-connection-direction; all
    control mutations are lock-guarded and take effect on the next chunk
    (delay/reorder) or immediately (partition)."""

    def __init__(self, upstream: tuple[str, int],
                 plan: FaultPlan | None = None, name: str = ""):
        self.upstream = upstream
        self.plan = plan
        self.name = name or f"link->{upstream[0]}:{upstream[1]}"
        self._mtx = threading.Lock()
        self._partitioned = False
        self._delay = {"c2s": 0.0, "s2c": 0.0}
        self._reorder_budget = 0
        # WAN shaping (round 18): (profile, per-direction sampled base
        # delay, seeded per-chunk RNG) or None. Armed by set_wan.
        self._wan: tuple[WanProfile, dict, random.Random] | None = None
        self._counters = {k: 0 for k in _COUNTER_KEYS}
        self._conns: list[socket.socket] = []
        self._stop = threading.Event()
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(64)
        srv.settimeout(0.3)
        self._srv = srv
        self.addr = srv.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"netfault-accept:{self.name}",
        )
        self._accept_thread.start()

    # -- addressing ---------------------------------------------------------

    @property
    def laddr(self) -> str:
        """host:port the DIALING node should be pointed at (seeds /
        persistent_peers entry)."""
        return f"{self.addr[0]}:{self.addr[1]}"

    # -- chaos controls -----------------------------------------------------

    def partition(self) -> None:
        """Sever the link: refuse new connects, reset live connections.
        Reset (not blackhole) keeps test wall-clock bounded; the slow-
        link failure mode is modeled by set_delay instead."""
        with self._mtx:
            already = self._partitioned
            self._partitioned = True
            if not already:
                self._counters["partitions"] += 1
        self._drop_all(count_as="partition_drops")

    def heal(self) -> None:
        with self._mtx:
            if self._partitioned:
                self._counters["heals"] += 1
            self._partitioned = False

    def partitioned(self) -> bool:
        with self._mtx:
            return self._partitioned

    def set_delay(self, c2s_s: float = 0.0, s2c_s: float = 0.0) -> None:
        """Asymmetric one-way latency, applied per relayed chunk."""
        with self._mtx:
            self._delay["c2s"] = max(0.0, float(c2s_s))
            self._delay["s2c"] = max(0.0, float(s2c_s))

    def set_wan(self, profile: "WanProfile | str | None",
                seed: int = 0) -> None:
        """Arm (or clear, profile=None) a WAN profile on this link. The
        per-direction base latency is sampled HERE, once, from a RNG
        seeded by (seed, link name, profile name) — deterministic across
        runs, different across links — so a fabric-wide apply_wan gives
        every link its own stable place in the distribution. Per-chunk
        jitter/loss/reset draws continue from the same stream."""
        if profile is None:
            with self._mtx:
                self._wan = None
            return
        p = wan_profile(profile)
        rng = random.Random(f"{seed}:{self.name}:{p.name}")
        base = {
            "c2s": rng.uniform(*p.delay_range_s),
            "s2c": rng.uniform(*p.delay_range_s),
        }
        with self._mtx:
            self._wan = (p, base, rng)

    def wan_profile_name(self) -> str | None:
        with self._mtx:
            return self._wan[0].name if self._wan is not None else None

    def set_reorder(self, swaps: int) -> None:
        """Swap the next `swaps` pairs of adjacent relayed chunks
        (either direction claims from the shared budget). The AEAD layer
        detects each swap as tampering — the assertion the scenario
        matrix makes."""
        with self._mtx:
            self._reorder_budget = max(0, int(swaps))

    def drop_all(self) -> None:
        """Reset live connections without partitioning (peer-churn
        support: the next dial succeeds)."""
        self._drop_all(count_as=None)

    def retarget(self, upstream: tuple[str, int]) -> None:
        """Point the link at a new upstream (rolling-restart support:
        a restarted node binds a fresh listener port; the fabric's
        inbound links re-aim so the dialers' persistent reconnect loops
        re-peer without test intervention). Live connections keep their
        old upstream until dropped."""
        with self._mtx:
            self.upstream = tuple(upstream)

    def stats(self) -> dict:
        with self._mtx:
            out = {f"netfaults_{k}": v for k, v in self._counters.items()}
            out["netfaults_partitioned"] = int(self._partitioned)
            out["netfaults_wan_profiled"] = int(self._wan is not None)
            return out

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        self._drop_all(count_as=None)
        if self._accept_thread.is_alive():
            self._accept_thread.join(timeout=5.0)

    # -- internals ----------------------------------------------------------

    def _note(self, key: str, v: int = 1) -> None:
        with self._mtx:
            self._counters[key] += v

    def _drop_all(self, count_as: str | None) -> None:
        with self._mtx:
            conns, self._conns = self._conns, []
            if count_as and conns:
                self._counters[count_as] += len(conns)
        for c in conns:
            _kill_sock(c)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._mtx:
                dark = self._partitioned
            f = None
            if not dark and self.plan is not None:
                f = self.plan.pick("connect", supported=("refuse", "stall"))
                if f is not None:
                    self._note("plan_faults")
            if dark or (f is not None and f.kind == "refuse"):
                self._note("conns_refused")
                _kill_sock(conn)
                continue
            if f is not None and f.kind == "stall":
                time.sleep(f.stall_s)
            try:
                up = socket.create_connection(self.upstream, timeout=5.0)
                # the connect timeout must NOT linger as an IO timeout: a
                # relay direction that idles 5 s (vote channel between
                # rounds) would raise and kill the whole link (the
                # FaultProxy learned the same lesson in PR 3)
                up.settimeout(None)
            except OSError:
                # upstream listener down (churn window): the dialer sees
                # exactly what a dead node produces
                self._note("conns_refused")
                _kill_sock(conn)
                continue
            self._note("conns")
            with self._mtx:
                self._conns += [conn, up]
            for src, dst, direction in ((conn, up, "c2s"), (up, conn, "s2c")):
                threading.Thread(
                    target=self._relay, args=(src, dst, direction),
                    daemon=True, name=f"netfault-{direction}:{self.name}",
                ).start()

    def _relay(self, src: socket.socket, dst: socket.socket,
               direction: str) -> None:
        held: bytes | None = None
        try:
            while not self._stop.is_set():
                try:
                    data = src.recv(_CHUNK)
                except socket.timeout:
                    # only armed while a chunk is held for reordering: an
                    # idle stream must not blackhole the held bytes (the
                    # peer may be WAITING on them — nothing else would
                    # ever arrive to trigger the swap)
                    if held is not None:
                        dst.sendall(held)
                        held = None
                    src.settimeout(None)
                    continue
                if not data:
                    return
                self._note(f"bytes_{direction}", len(data))
                if self.plan is not None:
                    f = self.plan.pick(
                        direction,
                        supported=("stall", "drop", "truncate", "corrupt"),
                    )
                    if f is not None:
                        self._note("plan_faults")
                        if f.kind == "stall":
                            time.sleep(f.stall_s)
                        elif f.kind == "drop":
                            return
                        elif f.kind == "truncate":
                            dst.sendall(data[: max(1, len(data) // 2)])
                            return
                        elif f.kind == "corrupt":
                            # inside the ENCRYPTED stream: the AEAD must
                            # flag it (in-contract, unlike devd IPC)
                            buf = bytearray(data)
                            buf[self.plan.corrupt_offset(0, len(buf))] ^= 0xFF
                            data = bytes(buf)
                with self._mtx:
                    delay = self._delay["c2s" if direction == "c2s" else "s2c"]
                    want_reorder = self._reorder_budget > 0 and held is None
                    if want_reorder:
                        self._reorder_budget -= 1
                    wan = self._wan
                    wan_sleep, wan_stalled, wan_reset = 0.0, False, False
                    if wan is not None:
                        # per-chunk draws under the link lock: both relay
                        # directions share the seeded RNG stream
                        p, base, rng = wan
                        wan_sleep = base[direction]
                        if p.jitter_s:
                            wan_sleep += rng.uniform(0.0, p.jitter_s)
                        if p.bandwidth_bps:
                            # bandwidth_bps is BITS per second (the
                            # profile table says Mbps): 8 bits/byte
                            wan_sleep += len(data) * 8 / p.bandwidth_bps
                        if p.loss and rng.random() < p.loss:
                            wan_sleep += p.loss_stall_s
                            wan_stalled = True
                        if p.reset_prob and rng.random() < p.reset_prob:
                            wan_reset = True
                if wan is not None:
                    self._note("wan_delays_applied")
                    self._note("wan_delay_seconds", wan_sleep)
                    if p.bandwidth_bps:
                        self._note("wan_bytes_shaped", len(data))
                    if wan_stalled:
                        self._note("wan_loss_stalls")
                    if wan_reset:
                        # severe-loss model: the connection dies (the
                        # finally clause resets both sides); the dialing
                        # switch's persistent reconnect loop recovers
                        self._note("wan_resets")
                        return
                    time.sleep(wan_sleep)
                if delay > 0:
                    self._note("delays_injected")
                    time.sleep(delay)
                if want_reorder:
                    held = data  # hold this chunk, release after the next
                    src.settimeout(0.25)  # idle flush bound (see above)
                    continue
                dst.sendall(data)
                if held is not None:
                    self._note("reorders_injected")
                    dst.sendall(held)
                    held = None
                    src.settimeout(None)
        except (ConnectionError, OSError):
            pass
        finally:
            if held is not None:
                try:
                    dst.sendall(held)
                except OSError:
                    pass
            for s in (src, dst):
                _kill_sock(s)


class NetFabric:
    """The directed links of one testnet: link (i, j) carries the
    connection node i DIALED to node j (the harness gives i the proxy's
    laddr as its seed for j). Group operations map onto per-link
    controls; everything heals."""

    def __init__(self, name: str = "netfabric"):
        self.name = name
        self._links: dict[tuple[int, int], LinkProxy] = {}
        self._mtx = threading.Lock()
        register_fabric(self)

    def add_link(self, i: int, j: int, upstream: tuple[int, int] | tuple,
                 plan: FaultPlan | None = None) -> LinkProxy:
        link = LinkProxy(tuple(upstream), plan=plan, name=f"{self.name}:{i}->{j}")
        with self._mtx:
            self._links[(i, j)] = link
        return link

    def link(self, i: int, j: int) -> LinkProxy | None:
        with self._mtx:
            return self._links.get((i, j))

    def links(self) -> dict:
        with self._mtx:
            return dict(self._links)

    def links_of(self, node: int) -> list[LinkProxy]:
        with self._mtx:
            return [
                l for (i, j), l in self._links.items() if node in (i, j)
            ]

    # -- group chaos --------------------------------------------------------

    def partition_groups(self, group_a) -> None:
        """Sever every link crossing the {group_a} | {rest} boundary."""
        ga = set(group_a)
        for (i, j), link in self.links().items():
            if (i in ga) != (j in ga):
                link.partition()

    def heal_all(self) -> None:
        for link in self.links().values():
            link.heal()

    # -- WAN tier (round 18) ------------------------------------------------

    def apply_wan(self, profile: "WanProfile | str | None",
                  seed: int = 0) -> None:
        """One WAN profile across every link (per-link latencies still
        differ: each samples its own base delay from the seeded
        distribution). None clears."""
        for link in self.links().values():
            link.set_wan(profile, seed=seed)

    def clear_wan(self) -> None:
        self.apply_wan(None)

    def apply_geo(self, clusters: list[list[int]],
                  intra: "WanProfile | str" = "lan",
                  inter: "WanProfile | str" = "intercontinental",
                  seed: int = 0) -> None:
        """Geo-cluster topology: low latency inside a cluster, high
        between clusters — "k clusters x m nodes" declared as data
        (geo_clusters(n, k) builds the cluster lists) instead of
        hand-set per-link delays. Links touching a node outside every
        cluster get the inter profile (conservative)."""
        member = {
            node: ci for ci, cl in enumerate(clusters) for node in cl
        }
        for (i, j), link in self.links().items():
            same = (
                i in member and j in member and member[i] == member[j]
            )
            link.set_wan(intra if same else inter, seed=seed)

    def set_delay(self, i: int, j: int, c2s_s: float = 0.0,
                  s2c_s: float = 0.0) -> None:
        link = self.link(i, j)
        if link is None:
            raise KeyError(f"no link {i}->{j}")
        link.set_delay(c2s_s=c2s_s, s2c_s=s2c_s)

    def stats(self) -> dict:
        """Aggregate flat counters over every link (the scrape surface)."""
        out = {f"netfaults_{k}": 0 for k in _COUNTER_KEYS}
        out["netfaults_partitioned"] = 0
        out["netfaults_wan_profiled"] = 0
        out["netfaults_links"] = 0
        for link in self.links().values():
            out["netfaults_links"] += 1
            for k, v in link.stats().items():
                out[k] = out.get(k, 0) + v
        return out

    def stop(self) -> None:
        for link in self.links().values():
            link.stop()
        unregister_fabric(self)


# -- telemetry (scrape-only, the ops/faults convention) -----------------------

_fabrics: list[NetFabric] = []
_reg_mtx = threading.Lock()


def register_fabric(fabric: NetFabric) -> NetFabric:
    with _reg_mtx:
        if fabric not in _fabrics:
            _fabrics.append(fabric)
    return fabric


def unregister_fabric(fabric: NetFabric) -> None:
    with _reg_mtx:
        if fabric in _fabrics:
            _fabrics.remove(fabric)


def telemetry_counters() -> dict:
    out = {f"netfaults_{k}": 0 for k in _COUNTER_KEYS}
    out["netfaults_partitioned"] = 0
    out["netfaults_wan_profiled"] = 0
    out["netfaults_links"] = 0
    with _reg_mtx:
        fabrics = list(_fabrics)
    for fabric in fabrics:
        for k, v in fabric.stats().items():
            out[k] = out.get(k, 0) + v
    return out


def _install_telemetry(reg) -> None:
    # scrape-only: the legacy metrics-RPC key set stays frozen. The
    # producer registers under its OWN prefix — producers are keyed by
    # prefix, so a second ""-prefixed registration would silently
    # REPLACE ops/faults' (exactly the collision that broke the chaos
    # suite's faults_supervisor_* assertions when this module first
    # shipped); the canonical netfaults_ names are rebuilt by stripping
    # the stats() prefix and letting the registry re-add it
    def produce() -> dict:
        return {
            k[len("netfaults_"):]: v
            for k, v in telemetry_counters().items()
        }

    reg.register_producer("netfaults", produce, legacy=False)


from tendermint_tpu.libs import telemetry as _telemetry  # noqa: E402

_telemetry.on_default_registry(_install_telemetry)


# -- standalone shim process --------------------------------------------------


def main(argv=None) -> int:
    """Run one LinkProxy as its own process (multi-process harnesses:
    point a node's seed entry at --listen-report's printed address).
    Counters print as ONE json line on SIGTERM/SIGINT."""
    ap = argparse.ArgumentParser(description=LinkProxy.__doc__)
    ap.add_argument("--upstream", required=True, help="host:port of the listener")
    ap.add_argument("--delay-c2s", type=float, default=0.0)
    ap.add_argument("--delay-s2c", type=float, default=0.0)
    ap.add_argument("--reorder", type=int, default=0,
                    help="swap the next N adjacent chunk pairs")
    ap.add_argument("--wan-profile", default="",
                    help=f"WAN shaping profile: one of {sorted(WAN_PROFILES)}")
    ap.add_argument("--wan-seed", type=int, default=0,
                    help="seed for the per-link WAN latency sample")
    args = ap.parse_args(argv)

    host, port = args.upstream.rsplit(":", 1)
    proxy = LinkProxy((host, int(port)))
    proxy.set_delay(c2s_s=args.delay_c2s, s2c_s=args.delay_s2c)
    if args.reorder:
        proxy.set_reorder(args.reorder)
    if args.wan_profile:
        proxy.set_wan(args.wan_profile, seed=args.wan_seed)
    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    logging.basicConfig(level=logging.INFO)
    # parseable: harnesses read the first line for the dial address
    print(proxy.laddr, flush=True)
    logger.info("link proxy %s -> %s", proxy.laddr, args.upstream)
    done.wait()
    stats = proxy.stats()
    proxy.stop()
    print(json.dumps(stats))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
