"""The batching gateway: where the host consensus plane meets the TPU
data plane.

The reference verifies signatures one at a time, inline, at three call
sites (types/vote_set.go:175, types/validator_set.go:247,
blockchain/reactor.go:235). Here those sites call a Verifier; the gateway
decides per batch whether the TPU kernel or the CPU loop runs, with
IDENTICAL accept/reject semantics (BASELINE.md north star: byte-identical
behavior, CPU fallback below a size threshold).

Policies:
- batches below `min_tpu_batch` run on CPU (kernel launch + host marshal
  overhead beats the win for small batches; single votes stay CPU);
- direct-kernel failures (compile error, device init) permanently fall
  back to CPU — deterministic in-process failures recur per batch;
- devd-transport failures feed the shared CircuitBreaker (round 8):
  open = CPU fallback per batch, half-open ping probes on jittered
  exponential backoff restore devd routing when the daemon returns —
  a transient daemon restart never latches the process on CPU;
- `mesh` sharding: on a multi-chip jax.sharding.Mesh the batch axis is
  sharded across devices — pure data parallelism over independent
  signatures, no collectives needed in the kernel itself.
"""

from __future__ import annotations

import logging
import os
import queue
import random
import threading
import time
from collections import OrderedDict

import numpy as np

from tendermint_tpu.crypto import ed25519 as ed_cpu
from tendermint_tpu.crypto.keys import verify_any
from tendermint_tpu.libs.envknob import env_number as _env_number

logger = logging.getLogger("ops.gateway")

Item = tuple[bytes, bytes, bytes]  # (pubkey, message, signature)


def _cpu_verify_batch(items: list[Item]) -> list[bool]:
    """CPU path: wide all-ed25519 batches ride the native C++ batch
    verifier (radix-2^51, one ctypes call — measured 1.4x the per-item
    python/OpenSSL loop; strict-RFC8032 semantics match
    crypto/ed25519.verify, parity-tested incl. high-s/bad-point edges in
    tests/test_ops_f32.py); everything else verifies per item."""
    if len(items) >= 16 and all(
        len(it[0]) == 32 and len(it[2]) == 64 for it in items
    ):
        try:
            from tendermint_tpu import native

            # ready(), not available(): the first wide batch on the live
            # vote path must never block behind a lazy C++ build
            if native.ready():
                return [bool(b) for b in native.ed25519_verify_batch(items)]
        except Exception:  # noqa: BLE001 — any native failure -> python
            logger.exception("native batch verify failed; per-item fallback")
    return [verify_any(pk, msg, sig) for pk, msg, sig in items]


# Every batch kernel exposes verify_batch(items) -> np.ndarray[bool] with
# identical accept/reject semantics (cross-checked lane-for-lane by
# tests/test_ops*.py). The default is the measured winner; the others stay
# selectable so the bake-off is reproducible and any backend regression
# has an immediate fallback. v5e, batch 8192, sustained device rate
# (pipelined, aggregate fetch):
#   f32p  119.7k sigs/s  pallas fp32 radix-2^8, VMEM-resident ladder
#   f32    92.2k sigs/s  fp32 radix-2^8 depthwise-conv field mults
#   int32  50.0k sigs/s  int32 radix-2^15 jnp limb vectors (VPU)
#   pallas 32.6k sigs/s  int32 radix-2^15 single-pallas_call ladder
# Round 5 adds "comb" (ops/ed25519_comb.py): doubling-free verify from
# per-validator device-resident comb tables + a fixed-base MXU comb —
# ~3x fewer VPU ops/lane than f32p once a key's table is built (keys
# repeat every block in consensus); first-sight lanes ride the f32
# ladder inside the same call. The device daemon bakes comb off against
# f32p at claim time and serves the measured winner.
KERNELS = {
    "comb": "tendermint_tpu.ops.ed25519_comb",
    "f32p": "tendermint_tpu.ops.ed25519_f32p",
    "f32": "tendermint_tpu.ops.ed25519_f32",
    "int32": "tendermint_tpu.ops.ed25519",
    "pallas": "tendermint_tpu.ops.ed25519_pallas",
    # not a kernel: socket IPC to the device daemon (devd.py), which runs
    # its claim-time bake-off winner (comb vs f32p on TPU; f32 on CPU) on
    # the device it holds. The automatic default whenever a daemon is
    # serving — see kernel_name().
    "devd": "tendermint_tpu.ops.devd_backend",
}


_platform_cache: dict = {}
_platform_lock = threading.Lock()


def resolve_platform() -> str | None:
    """BOUNDED platform resolution, cached per process. jax.devices()
    blocks FOREVER on a wedged accelerator tunnel, and even a bounded
    in-process probe thread left hanging poisons jax's backend-init lock
    (devd.subprocess_probe) — so no caller of the gateway may ever dial
    in-process before knowing the tunnel answers. Order:

    1. TENDERMINT_TPU_PLATFORM env override (tests pin "cpu");
    2. TENDERMINT_TPU_DISABLE=1 -> "cpu";
    3. a serving device daemon's platform (one socket ping);
    4. ONE throwaway-subprocess probe (~45s worst case), cached for the
       process lifetime. If it fails, this process's jax is pinned to
       the CPU backend so even the CPU-path kernels can't dial the dead
       tunnel, and None is returned."""
    if "v" in _platform_cache:
        return _platform_cache["v"]
    with _platform_lock:
        return _resolve_platform_locked()


def _resolve_platform_locked() -> str | None:
    if "v" in _platform_cache:  # a concurrent caller resolved while we waited
        return _platform_cache["v"]
    env = os.environ.get("TENDERMINT_TPU_PLATFORM", "")
    if env:
        _platform_cache["v"] = env
        return env
    if os.environ.get("TENDERMINT_TPU_DISABLE", "") == "1":
        _platform_cache["v"] = "cpu"
        return "cpu"
    from tendermint_tpu import devd

    rep = devd.available()
    if rep is not None:
        _platform_cache["v"] = rep.get("platform")
        return _platform_cache["v"]
    # A daemon mid-claim/warm holds (or is about to hold) the chip:
    # probing now would contend with its exclusive session — the
    # one-owner violation the devd discipline exists to prevent — and
    # latch this process onto the CPU path minutes before the daemon
    # starts serving. Wait it out (bounded; 0 disables). A daemon whose
    # own probes fail reports waiting-for-device — then the tunnel is
    # down for everyone and the bounded subprocess probe below settles
    # this process honestly.
    wait_s = float(os.environ.get("TENDERMINT_DEVD_RESOLVE_WAIT_S", "600"))
    if wait_s > 0 and os.path.exists(devd.sock_path()):
        import time

        deadline = time.monotonic() + wait_s
        try:
            client = devd.DevdClient(devd.sock_path())
            while time.monotonic() < deadline:
                ping = client.ping(timeout=3.0)
                if ping.get("held"):
                    devd.bust_avail_cache()
                    rep = devd.available()
                    break
                if ping.get("status") == "waiting-for-device":
                    break
                logger.info(
                    "device daemon %r; waiting for it to serve",
                    ping.get("status"),
                )
                time.sleep(5.0)
            client.close()
        except Exception:  # noqa: BLE001 — socket died; no daemon after all
            pass
        if rep is not None:
            _platform_cache["v"] = rep.get("platform")
            return _platform_cache["v"]
    p = devd.subprocess_probe(45.0)
    if p is None:
        pin_jax_cpu()
    _platform_cache["v"] = p
    return p


def pin_jax_cpu(strict: bool = False) -> None:
    """Force this process's jax onto the CPU backend. The environment's
    TPU-tunnel plugin re-forces jax_platforms at interpreter startup,
    overriding JAX_PLATFORMS=cpu — so any process that must never dial
    the (possibly wedged) tunnel calls this before its first jnp use.

    strict=True re-raises on failure: callers whose whole safety story
    is "this process can never touch the tunnel" (the CPU device
    daemon) must die visibly rather than proceed unpinned."""
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend may already be up
        if strict:
            raise
        logger.warning("could not pin jax to cpu")


def set_platform(platform: str | None) -> None:
    """Pin resolve_platform's answer for this process — for callers that
    already KNOW (the device daemon just probed; a test harness is CPU by
    construction) and must not pay or confuse a second resolution."""
    _platform_cache["v"] = platform


def on_tpu() -> bool:
    """Is the reachable accelerator real TPU hardware ("tpu", or "axon"
    for a tunneled chip)? The ONE platform check — the kernel default,
    the pallas interpret-mode switch, and the TPU-gated tests all call
    this, so a new platform string only needs adding here. Bounded: see
    resolve_platform."""
    return resolve_platform() in ("tpu", "axon")


def kernel_name() -> str:
    """Validated TENDERMINT_TPU_KERNEL. Raises on unknown names;
    Verifier.__init__ calls this so a typo'd env var fails at startup
    rather than silently latching the CPU fallback.

    Default is environment-aware, in priority order:
    1. a serving device daemon (devd.available) -> "devd": the daemon
       owns the chip, this process stays off the tunnel entirely (the
       wedge-proof path — see tendermint_tpu/devd.py);
    2. real TPU hardware -> "comb" (doubling-free comb kernel; its
       first-sight lanes internally ride the f32 ladder, so a cold
       process is never worse than the f32 baseline and steady-state
       consensus batches skip all 254 doublings per signature);
    3. otherwise "f32" — the pallas kernel only runs in slow interpret
       mode on CPU backends, while the conv-composed f32 kernel compiles
       natively everywhere.
    Resolving the platform needs an initialized jax backend, so the
    default branch is evaluated lazily here, not at import."""
    name = os.environ.get("TENDERMINT_TPU_KERNEL", "")
    if not name:
        from tendermint_tpu import devd

        if devd.available() is not None:
            return "devd"
        return "comb" if on_tpu() else "f32"
    if name not in KERNELS:
        raise ValueError(
            f"TENDERMINT_TPU_KERNEL={name!r}: expected one of {sorted(KERNELS)}"
        )
    return name


def kernel_module():
    """The verify kernel the gateway runs, per TENDERMINT_TPU_KERNEL."""
    import importlib

    return importlib.import_module(KERNELS[kernel_name()])


def shard_layout(arr) -> list[tuple[int, int]]:
    """(device_id, lanes) per addressable shard of a device array, sorted
    by device — measured proof that a dispatch actually landed sharded
    (dryrun_multichip asserts it covers every mesh device evenly)."""
    try:
        return sorted(
            (s.device.id, int(np.prod(s.data.shape)))
            for s in arr.addressable_shards
        )
    except Exception:  # noqa: BLE001 — layout capture must never fail a verify
        logger.exception("shard layout capture failed")
        return []


def _split_by_key_type(items: list[Item]):
    """(ed25519 items, their positions, other items, their positions).
    The kernel is ed25519-only; secp256k1 (33-byte pubkeys) and anything
    malformed verify on CPU (crypto/secp256k1.py explains why ECDSA
    stays off the device)."""
    ed_items, ed_pos, other_items, other_pos = [], [], [], []
    for i, it in enumerate(items):
        if len(it[0]) == 32 and len(it[2]) == 64:
            ed_items.append(it)
            ed_pos.append(i)
        else:
            other_items.append(it)
            other_pos.append(i)
    return ed_items, ed_pos, other_items, other_pos


class CircuitBreaker:
    """Shared closed → open → half-open degradation/recovery policy for
    the devd device plane (round 8).

    Before this existed, every consumer latched its own one-way flag on
    failure: `Verifier._demote_after_failure` pinned the process to the
    CPU fallback FOREVER after 3 transport errors, and the hash plane
    kept a separate single-shot skew latch — so a 2-second daemon
    restart demoted a live consensus node to CPU for its whole lifetime.
    The breaker replaces all of that with one recoverable state machine
    shared by both planes (Verifier, Hasher, ShardedVerifier's inherited
    paths, the mempool SigBatcher and consensus prime_cache_async, which
    all dispatch through them):

    - CLOSED: devd routes normally. `threshold` CONSECUTIVE failures
      (default 3, TENDERMINT_TPU_BREAKER_FAILURES) open it.
    - OPEN: callers route to the CPU fallback per batch — verdicts and
      digests stay correct, only the transport degrades. Probes are
      scheduled on exponential backoff with jitter (base
      TENDERMINT_TPU_BREAKER_BACKOFF_S, default 0.5 s; cap
      TENDERMINT_TPU_BREAKER_BACKOFF_CAP_S, default 30 s).
    - HALF-OPEN: when a probe is due, `allow()` runs it inline — the
      existing devd ping (cheap, ~1 ms against a live daemon, bounded
      ~1 s against a dead one; at most one caller probes per window,
      concurrent callers stay on the fallback). A healthy probe
      re-CLOSES the breaker and devd routing resumes; a failed one
      re-opens with doubled backoff. With no probe injected, the one
      `allow()` that finds a due window returns True as a TRIAL request
      and its record_success/record_failure settles the state.

    Observability: `stats()` returns flat numeric gauges (state,
    open/close transition counts, probe counts, consecutive failures,
    cumulative seconds on the fallback) that Verifier/Hasher `stats()`
    fold in — the metrics RPC exports them, so operators SEE
    degradation instead of inferring it from throughput."""

    CLOSED, HALF_OPEN, OPEN = 0, 1, 2

    def __init__(self, threshold: int | None = None,
                 base_backoff_s: float | None = None,
                 max_backoff_s: float | None = None,
                 probe=None, on_close=None, seed: int | None = None):
        self.threshold = max(1, int(
            threshold if threshold is not None
            else _env_number("TENDERMINT_TPU_BREAKER_FAILURES", 3)
        ))
        self.base_backoff_s = float(
            base_backoff_s if base_backoff_s is not None
            else _env_number("TENDERMINT_TPU_BREAKER_BACKOFF_S", 0.5)
        )
        self.max_backoff_s = float(
            max_backoff_s if max_backoff_s is not None
            else _env_number("TENDERMINT_TPU_BREAKER_BACKOFF_CAP_S", 30.0)
        )
        self._probe = probe
        self._on_close = on_close
        self._rng = random.Random(seed)
        self._mtx = threading.Lock()
        self._state = self.CLOSED
        self._fails = 0
        self._backoff = self.base_backoff_s
        self._opened_at = 0.0
        self._next_probe = 0.0
        self._probing = False
        self._opens = 0
        self._closes = 0
        self._probes = 0
        self._probe_failures = 0
        self._fallback_s = 0.0

    def _jittered(self, backoff: float) -> float:
        # full jitter on [0.5x, 1.5x]: many processes sharing one daemon
        # must not probe in lockstep after a restart
        return backoff * (0.5 + self._rng.random())

    def _open_locked(self, now: float, *, reopen: bool) -> None:
        if self._state != self.OPEN and not reopen:
            self._opens += 1
            self._opened_at = now
            self._backoff = self.base_backoff_s
        self._state = self.OPEN
        if reopen:
            self._backoff = min(self._backoff * 2.0, self.max_backoff_s)
        self._next_probe = now + self._jittered(self._backoff)

    def _close_locked(self, now: float) -> None:
        if self._state != self.CLOSED:
            self._closes += 1
            self._fallback_s += now - self._opened_at
        self._state = self.CLOSED
        self._fails = 0
        self._backoff = self.base_backoff_s

    def allow(self) -> bool:
        """May the caller route to devd right now? CLOSED: yes. OPEN
        with a probe due: run the probe (or admit one trial request) —
        success restores routing for everyone. Otherwise: no, take the
        fallback."""
        with self._mtx:
            if self._state == self.CLOSED:
                return True
            now = time.monotonic()
            if self._probing or now < self._next_probe:
                return False
            self._state = self.HALF_OPEN
            self._probes += 1
            if self._probe is None:
                # trial mode: this one request IS the probe; its
                # record_success/record_failure settles the state.
                # Advance the window NOW so concurrent/subsequent
                # callers stay on the fallback while the trial is in
                # flight (at most one trial per window — the same
                # contract the inline-probe branch keeps via _probing)
                self._next_probe = time.monotonic() + self._jittered(
                    self._backoff
                )
                return True
            self._probing = True
            probe = self._probe
        ok = False
        try:
            ok = bool(probe())
        except Exception:  # noqa: BLE001 — a raising probe is a failed probe
            logger.exception("breaker probe raised")
        closed = False
        with self._mtx:
            self._probing = False
            now = time.monotonic()
            if ok:
                self._close_locked(now)
                closed = True
            else:
                self._probe_failures += 1
                # reopen ONLY if this probe still owns the half-open
                # slot: a concurrent record_success may have closed the
                # breaker while the probe ran, and that fresh success
                # evidence outranks the stale probe verdict (reopening
                # a CLOSED breaker here would also leave _opened_at
                # pointing at the previous episode, double-counting
                # fallback_s on the next close)
                if self._state == self.HALF_OPEN:
                    self._open_locked(now, reopen=True)
        if closed:
            logger.warning("devd breaker re-closed: device routing restored")
            self._run_on_close()
        return ok

    def record_success(self) -> None:
        closed = False
        with self._mtx:
            self._fails = 0
            if self._state != self.CLOSED:
                self._close_locked(time.monotonic())
                closed = True
        if closed:
            logger.warning("devd breaker re-closed: device routing restored")
            self._run_on_close()

    def record_failure(self) -> bool:
        """Note one failure; True if the breaker is now open."""
        with self._mtx:
            now = time.monotonic()
            self._fails += 1
            if self._state == self.HALF_OPEN:
                # the trial request failed: straight back to OPEN with
                # doubled backoff
                self._probe_failures += 1
                self._open_locked(now, reopen=True)
                return True
            if self._state == self.CLOSED and self._fails >= self.threshold:
                self._open_locked(now, reopen=False)
                logger.warning(
                    "devd breaker OPEN after %d consecutive failures; "
                    "CPU fallback until a probe finds the daemon healthy",
                    self._fails,
                )
                return True
            return self._state == self.OPEN

    def _run_on_close(self) -> None:
        if self._on_close is None:
            return
        try:
            self._on_close()
        except Exception:  # noqa: BLE001 — a bad hook must not block recovery
            logger.exception("breaker on_close hook failed")

    @property
    def state(self) -> int:
        with self._mtx:
            return self._state

    def stats(self) -> dict:
        with self._mtx:
            now = time.monotonic()
            current = (now - self._opened_at) if self._state != self.CLOSED \
                else 0.0
            return {
                "breaker_state": self._state,  # 0 closed/1 half-open/2 open
                "breaker_opens": self._opens,
                "breaker_closes": self._closes,
                "breaker_probes": self._probes,
                "breaker_probe_failures": self._probe_failures,
                "breaker_consecutive_failures": self._fails,
                "breaker_fallback_s": round(self._fallback_s + current, 3),
            }


_devd_breakers: dict[str, CircuitBreaker] = {}
_breaker_mtx = threading.Lock()


def _devd_probe(path: str | None = None) -> bool:
    """The breaker's half-open health probe: ONE fresh ping (never the
    TTL cache — it may predate the daemon's death) proving a daemon is
    serving AND holds the device. `path` probes one sharded-plane
    endpoint; default is the primary socket."""
    from tendermint_tpu import devd

    devd.bust_avail_cache(path)
    return devd.available(timeout=1.0, path=path) is not None


def devd_breaker(endpoint: str | None = None) -> CircuitBreaker:
    """The breaker for one devd endpoint, from the keyed registry
    (round 21: the sharded device plane holds one breaker PER daemon
    socket, so a sick chip degrades capacity instead of the node).

    The no-arg form is the pre-sharding contract every existing consumer
    keeps using — Verifier, Hasher, node/health, node/flightrec,
    node/telemetry: it returns the PRIMARY endpoint's breaker (the first
    configured socket — with one daemon, the only one), so single-socket
    deployments still share ONE degradation state and recovery restores
    every plane at once."""
    if endpoint is None:
        from tendermint_tpu import devd

        endpoint = devd.sock_path()
    with _breaker_mtx:
        br = _devd_breakers.get(endpoint)
        if br is None:
            br = CircuitBreaker(
                probe=lambda: _devd_probe(endpoint),
                # a re-close means the daemon came BACK — possibly a
                # different build, so the per-daemon version-skew
                # latches must re-learn (devd_backend docstring)
                on_close=lambda: _breaker_on_close(endpoint),
            )
            _devd_breakers[endpoint] = br
        return br


def _breaker_on_close(endpoint: str) -> None:
    """Re-arm the version-skew latches for the endpoint whose breaker
    just re-closed: the single-socket client's module latches when it is
    the primary socket, and the sharded plane's per-endpoint latches
    either way."""
    from tendermint_tpu import devd
    from tendermint_tpu.ops import devd_backend, devd_shard

    devd_shard.reset_endpoint_latches(endpoint)
    if endpoint == devd.sock_path():
        devd_backend.reset_stream_latches()


def devd_breaker_states() -> dict[str, int]:
    """Snapshot of every REGISTERED breaker's state, keyed by endpoint
    socket path (never instantiates one — a scrape/watchdog must not
    spawn breakers for endpoints nothing has dispatched to)."""
    with _breaker_mtx:
        items = list(_devd_breakers.items())
    return {path: br.state for path, br in items}


def reset_devd_breaker() -> None:
    """Drop every registered breaker (tests; also re-reads the env
    knobs)."""
    with _breaker_mtx:
        _devd_breakers.clear()


# -- devd plane gating (round 21) --------------------------------------------
#
# Verifier/Hasher route per BATCH through these instead of the raw
# breaker: with one endpoint they ARE the one breaker (byte-for-byte the
# pre-sharding behavior); with N endpoints the plane admits work while
# ANY endpoint's breaker does, the dispatcher (ops/devd_shard) does the
# per-endpoint accounting slice by slice, and the CPU floor engages only
# when every breaker is open.


def devd_plane_allow() -> bool:
    """Admission gate for the devd route as a whole."""
    from tendermint_tpu.ops import devd_shard

    if devd_shard.enabled():
        return devd_shard.plane_allow()
    return devd_breaker().allow()


def devd_plane_failure() -> None:
    """A devd-route batch raised. Single-socket: count it on the one
    breaker. Sharded: the dispatcher already recorded each slice failure
    on the endpoint that failed it — a plane-level raise means no
    healthy endpoint remained, which those breakers already show, so
    recording it again (on the primary) would double-count."""
    from tendermint_tpu.ops import devd_shard

    if not devd_shard.enabled():
        devd_breaker().record_failure()


def devd_plane_success() -> None:
    """Mirror of devd_plane_failure for the success path."""
    from tendermint_tpu.ops import devd_shard

    if not devd_shard.enabled():
        devd_breaker().record_success()


class _PendingBatch:
    """An in-flight prime_cache_async dispatch. Each primed item maps to
    the shared handle; a background thread materializes the verdicts the
    moment the device answers — so the transport is ALWAYS drained (a
    devd stream whose resolver never ran would strand its connection and
    the daemon's sender), even when no verify_one ever pops an item
    (FIFO eviction, re-primed duplicates). result_for just waits.
    `on_done(dt_s)` fires once on successful resolution with the
    dispatch→verdicts wall time (the round-16 vote plane's batch
    histogram rides it)."""

    __slots__ = ("_done", "_event")

    def __init__(self, items: list[Item], resolve, on_done=None):
        self._done: dict[Item, bool] = {}
        self._event = threading.Event()
        t0 = time.monotonic()

        def materialize() -> None:
            try:
                self._done.update(
                    (it, bool(ok)) for it, ok in zip(items, resolve())
                )
                if on_done is not None:
                    on_done(time.monotonic() - t0)
            except Exception:  # noqa: BLE001 — round-8 latch sweep:
                # genuinely unconditional, NOT breaker business. The
                # resolver underneath already did the breaker accounting
                # (Verifier.verify_batch_async's resolve demotes through
                # _demote_after_failure); anything that still escapes
                # here only UNPRIMES the items — verify_one re-verifies
                # each on CPU, so a lost batch is latency, never a wrong
                # or dropped verdict (idempotent merge)
                logger.exception("async prime resolve failed")
            finally:
                self._event.set()

        threading.Thread(
            target=materialize, daemon=True, name="gateway-prime"
        ).start()

    def result_for(self, item: Item) -> bool | None:
        """The primed verdict, or None if the batch failed to resolve
        (caller re-verifies on CPU — never reject on transport loss)."""
        self._event.wait()
        return self._done.get(item)


class Verifier:
    """Batch signature verifier with TPU acceleration and CPU fallback."""

    def __init__(self, min_tpu_batch: int | None = None,
                 use_tpu: bool | None = None):
        if min_tpu_batch is None:
            # operator knob (round 8): small-validator-set deployments
            # (localnet, chaos harnesses) route narrow consensus batches
            # through devd only when told to
            min_tpu_batch = int(
                _env_number("TENDERMINT_TPU_MIN_BATCH", 32, cast=int)
            )
        kernel = None
        if use_tpu is None:
            if os.environ.get("TENDERMINT_TPU_DISABLE", "") == "1":
                use_tpu = False
            else:
                # default policy: the kernel path needs an accelerator (a
                # serving daemon or real hardware) or an explicit operator
                # kernel choice — on a CPU-only host the f32 kernel is
                # SLOWER than the native C++ batch verifier the CPU path
                # runs (measured: ~5k vs ~10k sigs/s), so "no accelerator"
                # must mean the native path, not a de-optimizing kernel
                kernel = kernel_name()
                use_tpu = (
                    kernel == "devd"
                    or bool(os.environ.get("TENDERMINT_TPU_KERNEL"))
                    or on_tpu()
                )
        if kernel is None and use_tpu:
            kernel = kernel_name()
        # kernel choice is resolved ONCE per verifier (a typo'd env var
        # fails at startup; a daemon appearing or dying mid-run cannot
        # flip the hot path under a live consensus node)
        self._kernel = kernel if use_tpu else None
        self.min_tpu_batch = min_tpu_batch
        self._tpu_ok = use_tpu
        self._mtx = threading.Lock()
        self._stats = {
            "tpu_batches": 0, "tpu_sigs": 0, "cpu_sigs": 0,
            # aggregate-commit verify lanes (docs/upgrade.md): device-
            # batched dual-scalar-muls vs the pure-python CPU floor
            "agg_batches": 0, "agg_lanes_device": 0, "agg_lanes_cpu": 0,
        }
        # verify-ahead results for the live vote path: consensus drains a
        # run of queued votes, batch-verifies here, then each add_vote's
        # verify_one pops its primed result (single-use)
        self._primed: dict[Item, bool] = {}
        self._primed_cap = 1 << 14

    def _kernel_module(self):
        """The batch kernel this verifier dispatches to. Overridable so
        ShardedVerifier can pin f32 for BOTH the sync and async paths."""
        import importlib

        return importlib.import_module(KERNELS[self._kernel])

    def _demote_after_failure(self) -> None:
        """A verify raised.

        devd route: feed the SHARED circuit breaker (round 8; replaces
        the permanent `_devd_fails >= 3 -> CPU forever` latch and the
        devd -> direct-kernel demotion). While the breaker is closed the
        caller's retry re-dispatches over devd (bounded: each failure
        counts toward the open threshold); once open, `_use_device`
        routes to the CPU fallback per batch and the breaker's ping
        probes restore devd routing when the daemon returns — a
        transient daemon restart costs seconds of fallback, not the
        process lifetime. The old dead-daemon -> in-process direct
        kernel switch is deliberately GONE: it was one-way (the daemon
        coming back found this process holding the chip — the one-owner
        violation devd exists to prevent) and its platform re-resolve
        could block the verify hot path behind a 45 s subprocess probe.
        A daemon retired FOR GOOD is an operator topology change: restart
        the node or set TENDERMINT_TPU_KERNEL explicitly.

        Direct-kernel failures still latch CPU permanently — a compile
        or device-init error in THIS process is deterministic, so
        retrying it per batch would fail identically (annotated per the
        round-8 latch sweep)."""
        if self._kernel == "devd":
            devd_plane_failure()
            return
        self._tpu_ok = False

    def _use_device(self, n: int) -> bool:
        """Route this batch to the kernel path? Size/health gates plus,
        on the devd route, the breaker plane (every breaker OPEN means
        CPU fallback for this batch — never a permanent demotion)."""
        if not (self._tpu_ok and n >= self.min_tpu_batch):
            return False
        return self._kernel != "devd" or devd_plane_allow()

    def _note_device_success(self) -> None:
        if self._kernel == "devd":
            devd_plane_success()

    # -- core API ----------------------------------------------------------

    def verify_batch(self, items: list[Item], _attempt: int = 0) -> list[bool]:
        n = len(items)
        if n == 0:
            return []
        ed_items, ed_pos, other_items, other_pos = _split_by_key_type(items)
        if other_items and ed_items:
            # mixed key types: kernel for the ed25519 lanes, CPU for the
            # rest, results re-interleaved in order
            out: list = [None] * n
            for p, ok in zip(ed_pos, self.verify_batch(ed_items)):
                out[p] = ok
            for p, ok in zip(other_pos, _cpu_verify_batch(other_items)):
                out[p] = ok
            with self._mtx:
                self._stats["cpu_sigs"] += len(other_items)
            return out
        if other_items:  # nothing for the kernel at all
            with self._mtx:
                self._stats["cpu_sigs"] += n
            return _cpu_verify_batch(items)
        if self._use_device(n) and _attempt <= self._max_retries():
            try:
                ops_ed = self._kernel_module()

                out = ops_ed.verify_batch(items)
                with self._mtx:
                    self._stats["tpu_batches"] += 1
                    self._stats["tpu_sigs"] += n
                self._note_device_success()
                return [bool(b) for b in out]
            except Exception:
                logger.exception("batch verify via %s failed", self._kernel)
                self._demote_after_failure()
                # at-least-once with idempotent merge: the WHOLE batch
                # re-verifies (devd retry while the breaker stays closed,
                # else the CPU fallback) — a chunk whose stream died
                # mid-flight is re-dispatched, never dropped. _attempt
                # bounds THIS batch's retries even when concurrent
                # successes on the other plane keep resetting the shared
                # breaker's consecutive-failure count (the recursion
                # must never be open-ended on the consensus hot path)
                return self.verify_batch(items, _attempt=_attempt + 1)
        with self._mtx:
            self._stats["cpu_sigs"] += n
        return _cpu_verify_batch(items)

    def _max_retries(self) -> int:
        """Per-BATCH retry bound for the devd route (direct kernels
        never retry: their failures latch). Matches the breaker
        threshold so a lone caller still drives the breaker open before
        giving up, while a batch can never recurse past it."""
        if self._kernel != "devd":
            return 0
        return devd_breaker().threshold

    def verify_batch_async(self, items: list[Item], _attempt: int = 0):
        """Pipelined form of verify_batch: marshals + enqueues the device
        kernel now, returns a zero-arg resolver that blocks for results.
        Host marshaling of the next batch can overlap device execution of
        this one (jax async dispatch). Falls back to an already-resolved
        CPU result below the batch threshold or after a TPU failure."""
        n = len(items)
        if n == 0:
            return lambda: []
        ed_items, ed_pos, other_items, other_pos = _split_by_key_type(items)
        if other_items:
            inner = self.verify_batch_async(ed_items) if ed_items else (lambda: [])
            with self._mtx:
                self._stats["cpu_sigs"] += len(other_items)

            def resolve_mixed():
                out: list = [None] * n
                for p, ok in zip(ed_pos, inner()):
                    out[p] = bool(ok)
                for p, ok in zip(other_pos, _cpu_verify_batch(other_items)):
                    out[p] = ok
                return out

            return resolve_mixed
        if self._use_device(n) and _attempt <= self._max_retries():
            try:
                ops_ed = self._kernel_module()
                if not hasattr(ops_ed, "verify_batch_async"):
                    # only the default kernel pipelines; the bake-off
                    # kernels verify synchronously under the same contract
                    res_now = self.verify_batch(items)
                    return lambda: res_now

                kernel_resolve = ops_ed.verify_batch_async(items)
                with self._mtx:
                    self._stats["tpu_batches"] += 1
                    self._stats["tpu_sigs"] += n

                def resolve():
                    # async dispatch surfaces device-side failures only at
                    # materialization: keep the sync path's fallback
                    # guarantee here too.
                    try:
                        res = [bool(b) for b in kernel_resolve()]
                        self._note_device_success()
                        return res
                    except Exception:
                        logger.exception(
                            "verify via %s failed at resolve", self._kernel
                        )
                        with self._mtx:
                            self._stats["tpu_batches"] -= 1
                            self._stats["tpu_sigs"] -= n
                        self._demote_after_failure()
                        return self.verify_batch(items)

                return resolve
            except Exception:
                logger.exception("batch verify via %s failed", self._kernel)
                self._demote_after_failure()
                return self.verify_batch_async(items, _attempt=_attempt + 1)
        with self._mtx:
            self._stats["cpu_sigs"] += n
        res = _cpu_verify_batch(items)
        return lambda: res

    def verify_aggregate(self, pubs: list[bytes], msgs: list[bytes],
                         rs: list[bytes], s_agg: bytes,
                         _attempt: int = 0) -> bool:
        """Half-aggregate verify (crypto/ed25519_agg equation) with the
        n+1 dual-scalar-mul lanes batched through the device plane —
        devd 'agg' op (sharded fleets slice the lanes with per-lane
        attribution), or the in-process int32 dsm ladder on a direct
        kernel. The pure-python reference (~4.5 ms/lane) is the CPU
        floor, taken below min_tpu_batch lanes, when every breaker is
        open, or on a pre-agg daemon (version skew — no breaker
        penalty). Semantics identical to ed25519_agg.verify_aggregate."""
        from tendermint_tpu.crypto import ed25519_agg

        terms = ed25519_agg.aggregate_terms(pubs, msgs, rs, s_agg)
        if terms is None:
            return False
        n = len(terms)
        if self._use_device(n) and _attempt <= self._max_retries():
            try:
                if self._kernel == "devd":
                    from tendermint_tpu.ops import devd_backend

                    try:
                        points = devd_backend.agg_batch(terms)
                    except devd_backend.AggUnsupported:
                        # healthy-but-old daemon: CPU floor, no breaker
                        # penalty, latched so the next commit skips the
                        # doomed attempt
                        points = None
                else:
                    from tendermint_tpu.ops import ed25519 as ops_ed

                    points = ops_ed.dsm_batch(terms)
                if points is not None:
                    with self._mtx:
                        self._stats["agg_batches"] += 1
                        self._stats["agg_lanes_device"] += n
                    self._note_device_success()
                    return ed25519_agg.finish_from_points(points)
            except Exception:
                logger.exception(
                    "aggregate verify via %s failed", self._kernel
                )
                self._demote_after_failure()
                return self.verify_aggregate(
                    pubs, msgs, rs, s_agg, _attempt=_attempt + 1
                )
        with self._mtx:
            self._stats["agg_lanes_cpu"] += n
        return ed25519_agg.verify_aggregate(pubs, msgs, rs, s_agg)

    def pop_primed(self, item: Item) -> bool | None:
        """Pop (single-use) the primed verdict for one item: True/False
        from a resolved batch, None if never primed, FIFO-evicted, or
        the batch failed to resolve — the caller re-verifies. The
        round-16 VoteBatcher reads its batched-vs-singleton accounting
        off this; verify_one is pop_primed + the CPU fallback."""
        with self._mtx:
            primed = self._primed.pop(item, None)
        if isinstance(primed, _PendingBatch):
            # wait OUTSIDE the mutex: this blocks on the device
            primed = primed.result_for(item)
        return primed

    def verify_one(self, pubkey: bytes, msg: bytes, sig: bytes) -> bool:
        """Single-signature path (vote-by-vote arrival). A result primed
        by prime_cache is consumed here without re-verifying; otherwise
        CPU — latency over throughput. Exists so VoteSet can take one
        pluggable callable."""
        primed = self.pop_primed((pubkey, msg, sig))
        if primed is not None:
            return primed
        with self._mtx:
            self._stats["cpu_sigs"] += 1
        return verify_any(pubkey, msg, sig)

    def prime_cache(self, items: list[Item]) -> None:
        """Batch-verify now (TPU when wide enough) and stash per-item
        results for imminent verify_one calls — how a burst of gossiped
        votes rides the kernel while VoteSet keeps its one-vote-at-a-time
        accept/reject semantics (SURVEY §7; ref types/vote_set.go:137-175
        verifies inline per vote). Unconsumed entries age out FIFO."""
        if not items:
            return
        oks = self.verify_batch(items)
        with self._mtx:
            for it, ok in zip(items, oks):
                self._primed[it] = bool(ok)
            while len(self._primed) > self._primed_cap:
                self._primed.pop(next(iter(self._primed)))

    def prime_cache_async(self, items: list[Item], on_done=None) -> None:
        """Pipelined prime_cache: dispatch the batch to the device NOW
        (verify_batch_async — streamed chunks on the devd backend) and
        park a pending handle per item; the first verify_one to pop one
        blocks for the batch verdicts. The caller's host work between
        dispatch and first pop (vote-set bookkeeping, the VoteBatcher's
        prepare-time screening in consensus/vote_batcher.py) overlaps
        marshal, IPC, and device compute instead of serializing behind
        them. `on_done(dt_s)` observes the dispatch→verdicts wall time
        on successful resolution."""
        if not items:
            return
        pending = _PendingBatch(items, self.verify_batch_async(items), on_done)
        with self._mtx:
            for it in items:
                self._primed[it] = pending
            while len(self._primed) > self._primed_cap:
                self._primed.pop(next(iter(self._primed)))

    def stats(self) -> dict:
        with self._mtx:
            out = dict(self._stats)
        if self._kernel == "devd":
            # serving-path observability: fold the streamed-transport
            # counters in so a node's stats() shows the data plane.
            # FLAT numeric keys — the metrics RPC (rpc/core/handlers.py)
            # exports stats() as scalar gauges
            try:
                from tendermint_tpu.ops import devd_backend

                for k, val in devd_backend.stream_stats().items():
                    out[k if k.startswith("stream") else f"stream_{k}"] = val
            except Exception:  # noqa: BLE001 — stats must never raise
                pass
            # degradation observability (round 8): breaker state +
            # transitions + time-in-fallback, and the faults_* counters
            # (zeros unless a chaos harness is registered) — operators
            # see a sick device plane, not just a throughput dip
            try:
                out.update(devd_breaker().stats())
                from tendermint_tpu.ops import faults

                out.update(faults.global_counters())
            except Exception:  # noqa: BLE001 — stats must never raise
                pass
        return out

    # -- adapters for the call sites --------------------------------------

    def commit_batch_verifier(self):
        """For ValidatorSet.verify_commit(batch_verifier=...)."""
        return self.verify_batch

    def vote_verifier(self):
        """For VoteSet.add_vote(verifier=...)."""
        return self.verify_one


class ShardedVerifier(Verifier):
    """Verifier whose kernel inputs are sharded over a device mesh along the
    batch axis. Each chip verifies its slice; results gather to host. This
    is how a 10k-validator commit rides a v5e pod slice: 10k lanes split
    over N chips on ICI.

    Two sharded backends: "f32p" (shard_map over the pallas ladder — the
    single-chip winner, now the TPU-mesh default; per-shard body is plain
    XLA on non-TPU meshes, same math — ed25519_f32p.make_sharded_verify)
    and "f32" (pjit over the conv formulation — the non-TPU default and
    the fallback). Bake-off backends don't shard; requesting one
    explicitly is an error rather than a silent misreport."""

    def __init__(self, mesh, min_tpu_batch: int | None = None):
        super().__init__(min_tpu_batch=min_tpu_batch, use_tpu=True)
        explicit = os.environ.get("TENDERMINT_TPU_KERNEL", "")
        if explicit and explicit not in ("f32", "f32p"):
            raise ValueError(
                f"ShardedVerifier shards the f32/f32p kernels; "
                f"TENDERMINT_TPU_KERNEL={explicit!r} — use the base "
                f"Verifier to run a bake-off backend or the device daemon"
            )
        # base init may have resolved devd; this class does its own
        # in-process sharded dispatch
        self._kernel = explicit or ("f32p" if on_tpu() else "f32")
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as PS

        from tendermint_tpu.ops import ed25519_f32 as ops_ed

        self.mesh = mesh
        self._n_dev = mesh.size
        # (device_id, lanes) per shard of the most recent sharded
        # dispatch — None until one runs (see shard_layout)
        self.last_shard_layout: list[tuple[int, int]] | None = None
        batch_last = NamedSharding(mesh, PS(None, "batch"))
        vec = NamedSharding(mesh, PS("batch"))
        self._verify = jax.jit(
            ops_ed._verify_impl,
            in_shardings=(batch_last, batch_last, batch_last, vec, batch_last, batch_last),
            out_shardings=vec,
        )

    def _kernel_module(self):
        # pin f32 for the inherited sync/async fallback paths — the
        # narrow-batch path must never swap onto the unsharded pallas
        # kernel (and self._kernel may be the sharded "f32p")
        import importlib

        return importlib.import_module(KERNELS["f32"])

    def verify_batch_async(self, items: list[Item], _attempt: int = 0):
        """Sharded pipelining: the pjit/shard_map dispatch is already
        asynchronous, so enqueue now and materialize in the resolver —
        same contract as the base class (which would otherwise fall back
        to the UNSHARDED kernel for async calls)."""
        n = len(items)
        if (
            n == 0
            or not self._tpu_ok
            or n < self.min_tpu_batch
            or any(len(it[0]) != 32 or len(it[2]) != 64 for it in items)
        ):
            return super().verify_batch_async(items, _attempt=_attempt)
        res = self.verify_batch(items)  # async dispatch inside; results
        # materialize before return today — acceptable: the sharded path
        # serves pod-scale batch posting, and jax's async dispatch still
        # overlaps device work with the caller's next marshal
        return lambda: res

    def verify_batch(self, items: list[Item], _attempt: int = 0) -> list[bool]:
        n = len(items)
        if n == 0:
            return []
        if any(len(it[0]) != 32 or len(it[2]) != 64 for it in items):
            # mixed key types: the base partitions and re-enters here with
            # the pure-ed25519 lanes; secp256k1 verifies on CPU
            return super().verify_batch(items, _attempt=_attempt)
        if not self._tpu_ok or n < self.min_tpu_batch:
            return super().verify_batch(items, _attempt=_attempt)
        try:
            if self._kernel == "f32p":
                from tendermint_tpu.ops import ed25519_f32p as ops_f32p

                ok_dev, valid, _n = ops_f32p.sharded_verify_arrays(
                    items, self.mesh, on_tpu()
                )
                self.last_shard_layout = shard_layout(ok_dev)
                oks = ops_f32p.materialize_verdicts(ok_dev, valid, n)
                with self._mtx:
                    self._stats["tpu_batches"] += 1
                    self._stats["tpu_sigs"] += n
                return [bool(b) for b in oks]

            import jax.numpy as jnp

            from tendermint_tpu.ops import ed25519_f32 as ops_ed

            # bucket so every device gets an equal, stable-shaped slice:
            # power-of-two rounded up to a multiple of the mesh size
            m = self._n_dev
            bucket = ops_ed._next_pow2(max(n, m))
            if bucket % m:
                bucket = ((bucket + m - 1) // m) * m
            ax, ay, ry, rs, s8, h8, valid = ops_ed.prepare_batch8(items, bucket)
            ok = self._verify(
                jnp.asarray(ax), jnp.asarray(ay), jnp.asarray(ry),
                jnp.asarray(rs), jnp.asarray(s8), jnp.asarray(h8),
            )
            self.last_shard_layout = shard_layout(ok)
            with self._mtx:
                self._stats["tpu_batches"] += 1
                self._stats["tpu_sigs"] += n
            return [bool(b) for b in (np.asarray(ok)[:n] & valid[:n])]
        except Exception:
            # round-8 latch sweep: these stay genuinely unconditional —
            # a sharded compile/dispatch failure in THIS process is
            # deterministic (same mesh, same program), so a breaker-style
            # retry would fail identically; the f32p -> f32 -> CPU ladder
            # is a one-way ratchet by design
            if self._kernel == "f32p":
                logger.exception("sharded f32p verify failed; trying f32")
                self._kernel = "f32"
                return self.verify_batch(items)
            logger.exception("sharded TPU verify failed; falling back to CPU")
            self._tpu_ok = False
            return super().verify_batch(items)


# -- merkle/hashing gateway --------------------------------------------------


def device_rtt_ms() -> float | None:
    """Measured device dispatch round trip (jitcache.probe_rtt_ms),
    cached per process under the platform lock (double-checked, like
    resolve_platform — two concurrent Hasher constructions must not
    race two probes at an exclusive device). This is the transport
    probe the Hasher policy keys on: a locally attached chip answers in
    <5 ms, the axon tunnel in 85-150 ms.

    Ownership reasoning (devd.py one-owner discipline): the probe runs
    ONLY when the bounded platform resolution says an accelerator
    answers AND no devd socket exists — serving or mid-claim, a
    daemon's socket means the chip is (about to be) someone else's.
    What remains is exactly the direct-kernel topology, where THIS
    process is the device's owner: the Verifier's kernels dial
    in-process on this path anyway, so an in-process probe adds no new
    ownership and reuses the already-initialized backend (near-instant
    when a kernel has run; one bounded dial otherwise). The dial is
    bounded by probe_rtt_ms's daemon-thread join — a wedged tunnel
    parks a thread instead of hanging the node, the same residual risk
    the direct-kernel path already accepts.

    A failed probe caches as None (CPU hashing) for the process
    lifetime; TENDERMINT_TPU_HASHES=1 is the operator override."""
    if "rtt" in _platform_cache:
        return _platform_cache["rtt"]
    # resolve the platform BEFORE taking the lock: resolve_platform
    # acquires _platform_lock itself (non-reentrant), so the pre-r7
    # ordering — on_tpu() under the lock — deadlocked any process whose
    # FIRST gateway call was a default Hasher construction (e.g.
    # benches/bench_partset.py standalone; masked elsewhere because a
    # Verifier or platform_label resolved the platform first)
    tpu = on_tpu()
    with _platform_lock:
        if "rtt" in _platform_cache:
            return _platform_cache["rtt"]
        rtt: float | None = None
        try:
            from tendermint_tpu import devd

            if tpu and not os.path.exists(devd.sock_path()):
                from tendermint_tpu.jitcache import probe_rtt_ms

                rtt = probe_rtt_ms(30.0)
                if rtt is not None:
                    logger.info("device rtt: %.1f ms", rtt)
        except Exception:  # noqa: BLE001 — probe failure means no offload
            logger.exception("device rtt probe failed")
            rtt = None
        _platform_cache["rtt"] = rtt
        return rtt


# Above this measured dispatch round-trip the hash offload can't win at
# production part-batch shapes: a 1 MB part set needs >200 MB/s to beat
# the host AVX-512 path, so even zero device compute loses once the
# round trip alone exceeds ~5 ms.
HASH_RTT_MS_MAX = 5.0


class _HashFuture:
    """Join handle for a submitted-early hash job (round 14). result()
    re-raises the worker-side exception; callers on the hot path catch
    and fall back to the inline compute."""

    __slots__ = ("_evt", "_value", "_exc")

    def __init__(self):
        self._evt = threading.Event()
        self._value = None
        self._exc: BaseException | None = None

    def _finish(self, value=None, exc: BaseException | None = None) -> None:
        self._value = value
        self._exc = exc
        self._evt.set()

    def result(self, timeout: float | None = None):
        if not self._evt.wait(timeout):
            raise TimeoutError("hash submission did not complete")
        if self._exc is not None:
            raise self._exc
        return self._value


class Hasher:
    """Batched hashing gateway for the PartSet/tx-tree hot paths.

    Policy (transport-keyed, round 5; round 7 adds the streamed devd
    route — supersedes the r4 "CPU-default FINAL" closure, which VERDICT
    r4 noted was drawn on tunnel-biased data): default is the measured
    transport.

    - Tunneled or absent chip (device_rtt_ms > HASH_RTT_MS_MAX or None):
      CPU. Measured on a v5e behind the axon tunnel
      (benches/bench_partset.py): offload 2.28 vs CPU 205 MB/s — the
      tunnel's 85-150 ms sync round trip alone caps a 1 MB part batch at
      ~8-11 MB/s, unwinnable regardless of kernel quality. Round 7
      replaces that single monolithic round trip with chunked hash
      frames over devd (hash_stream — ops/devd_backend.hash_batch):
      measured on the sim transport (BENCH_r07.json, device time held
      constant) the streamed path is ~2.2x the single-shot offload
      (34.9 -> 77.3 MB/s at 16 MB of 1 KB leaves), and the tree frame
      makes part-set proofs free — but a pipelined tunnel still can't
      close a 90x gap, so the tunneled default stays CPU until the
      live-chip streamed row (ROADMAP open item) says otherwise.
    - Locally attached chip (rtt <= HASH_RTT_MS_MAX): offload wide
      batches. With the round trip at local-PCIe/ICI scale the only
      structural argument left against the device is compression-chain
      serialism (a 64 KB part = 1024 sequential SHA/RIPEMD rounds, no
      MXU help, parallel only across parts) — a real handicap at 16-256
      part widths, but one to be MEASURED per deployment, not assumed:
      no local-chip environment has been available to close it (the
      driver box reaches the chip through the tunnel), so the local
      default stays ON to collect that number wherever one exists.

    Routing (resolved ONCE at construction, like Verifier's kernel):
    when offload is on and a device daemon is serving, every hash batch
    rides daemon IPC — streamed chunk frames at or above the
    ops/devd_backend width/bytes floor (mirroring
    TENDERMINT_DEVD_STREAM_MIN), single-shot below it — so this process
    never dials the chip the daemon owns (before r7, forcing
    TENDERMINT_TPU_HASHES=1 next to a serving daemon dialed in-process,
    violating the one-owner rule). With no daemon the in-process kernels
    run as before.

    The host path this competes with batches equal-length parts 16-wide
    into AVX-512 calls (native ripemd160_x16, ~1.2 GB/s; 4.9x the
    sequential loop) and builds trees with the flat level-order builder
    (merkle.simple.FlatTree, ~2.9x the recursive proofs build at the
    1 MB / 64 KB shape) — CPU here is an optimized floor, not a punt.
    Overrides: TENDERMINT_TPU_HASHES=1 forces offload (any transport),
    =0 forces CPU; TENDERMINT_TPU_DISABLE=1 forces CPU."""

    def __init__(self, min_tpu_batch: int | None = None,
                 use_tpu: bool | None = None):
        if min_tpu_batch is None:
            min_tpu_batch = int(
                _env_number("TENDERMINT_TPU_HASH_MIN_BATCH", 16, cast=int)
            )
        if use_tpu is None:
            env = os.environ.get("TENDERMINT_TPU_HASHES", "")
            if os.environ.get("TENDERMINT_TPU_DISABLE", "") == "1" or env == "0":
                use_tpu = False
            elif env == "1":
                use_tpu = True
            else:
                rtt = device_rtt_ms()
                use_tpu = rtt is not None and rtt <= HASH_RTT_MS_MAX
        self.min_tpu_batch = min_tpu_batch
        self._tpu_ok = use_tpu
        self._route = None
        if use_tpu:
            from tendermint_tpu import devd

            self._route = "devd" if devd.available() is not None else "local"
        self._mtx = threading.Lock()
        self._stats = {
            "tpu_part_batches": 0, "tpu_leaves": 0,
            "tpu_tx_roots": 0, "cpu_leaves": 0,
            # batch-shape observability (same spirit as the verify
            # stream counters): bytes through the batched hash path and
            # the last/EWMA per-batch latency, so a misbehaving hash
            # transport is measurable in production, not just in benches
            "batch_bytes": 0, "batch_ms_last": 0.0, "batch_ms_avg": 0.0,
            # tx-root cache (mempool -> proposal path): reproposals and
            # gossip re-validation of an unchanged tx set never rehash
            "tx_root_cache_hits": 0,
            # round 14: submitted-early futures (pipelined proposal
            # build) — jobs queued to the submit worker, and how many
            # txs_hash() calls JOINED an in-flight early submission
            # instead of recomputing
            "submitted_jobs": 0, "tx_root_prehash_joins": 0,
            # streamed hash transport gauges, ALWAYS present (zeros off
            # the devd route) so the metrics RPC exports a stable gauge
            # set — flat numerics, same contract as Verifier's stream_*
            "stream_batches": 0, "stream_chunks_out": 0,
            "stream_lanes": 0, "stream_bytes_out": 0,
            "stream_trees": 0, "stream_reconnects": 0,
            "stream_single_batches": 0, "stream_single_lanes": 0,
        }
        # mempool->proposal tx-root cache: keyed by the tx tuple (one
        # C-level siphash pass over the raw txs — the leaf-hash tuple
        # would cost the very RIPEMD pass the cache exists to skip).
        # Cap is small on purpose: keys pin their tx bytes, and the
        # repropose/re-validate window is a handful of recent sets
        self._tx_roots: OrderedDict[tuple, bytes] = OrderedDict()
        self._tx_roots_cap = 16
        # round 14 (pipelined execution): submitted-early hash futures.
        # One daemon worker serializes submissions (the streamed devd
        # client is pooled but ordering keeps the batch-shape gauges
        # meaningful); in-flight tx roots dedupe so the consensus
        # thread's later txs_hash() JOINS the early submission instead
        # of re-hashing beside it.
        self._submit_q: "queue.Queue | None" = None
        self._submit_thread: threading.Thread | None = None
        self._inflight_tx_roots: dict[tuple, _HashFuture] = {}
        # round 11: full distribution behind batch_ms_last/_avg (one
        # observe per offload batch; scrape-only via GET /metrics)
        from tendermint_tpu.libs import telemetry

        self._batch_hist = telemetry.default_registry().histogram(
            "gateway_hash_batch_seconds",
            "hash-offload batch wall time (devd IPC or in-process kernel)",
        )

    def stats(self) -> dict:
        with self._mtx:
            out = dict(self._stats)
        if self._route == "devd":
            # live client-side hash-transport counters overlay the zeros
            # (flat numeric keys: the metrics RPC exports scalar gauges)
            try:
                from tendermint_tpu.ops import devd_backend

                for k, val in devd_backend.hash_stream_stats().items():
                    out[k if k.startswith("stream") else f"stream_{k}"] = val
            except Exception:  # noqa: BLE001 — stats must never raise
                pass
            # the SAME shared breaker the verify plane rides (round 8)
            try:
                out.update(devd_breaker().stats())
                from tendermint_tpu.ops import faults

                out.update(faults.global_counters())
            except Exception:  # noqa: BLE001 — stats must never raise
                pass
        return out

    def _use_offload(self, n: int) -> bool:
        """Route this batch to the offload path? On the devd route the
        breaker plane gates per batch (every breaker open = host hashing
        for THIS batch, devd routing restored by the next healthy
        probe — never the old permanent `_tpu_ok = False` latch)."""
        if not (self._tpu_ok and n >= self.min_tpu_batch):
            return False
        return self._route != "devd" or devd_plane_allow()

    def _demote_after_failure(self) -> None:
        """A hash offload raised. devd route -> the breaker plane
        (transient transport failure, recoverable). In-process kernel
        route -> permanent CPU latch, annotated per the round-8 sweep:
        a jax compile/dispatch failure in this process is deterministic
        and would recur per batch."""
        if self._route == "devd":
            devd_plane_failure()
            return
        self._tpu_ok = False

    def _note_offload_success(self) -> None:
        if self._route == "devd":
            devd_plane_success()

    def _note_batch(self, n_bytes: int, dt_s: float) -> None:
        self._batch_hist.observe(dt_s)
        ms = dt_s * 1000.0
        with self._mtx:
            s = self._stats
            s["batch_bytes"] += n_bytes
            s["batch_ms_last"] = round(ms, 3)
            s["batch_ms_avg"] = round(
                0.8 * s["batch_ms_avg"] + 0.2 * ms, 3
            ) if s["batch_ms_avg"] else round(ms, 3)

    def _offload_leaf_hashes(self, chunks: list[bytes], mode: str) -> list[bytes]:
        """One offload batch on the resolved route (devd IPC stream or
        in-process kernel). Raises on failure; callers demote to CPU."""
        if self._route == "devd":
            from tendermint_tpu.ops import devd_backend

            return devd_backend.hash_batch(chunks, mode)
        from tendermint_tpu.ops import merkle as ops_merkle

        if mode == "part":
            return ops_merkle.part_leaf_hashes(chunks)
        return ops_merkle.leaf_hashes(chunks)

    def part_leaf_hashes(self, chunks: list[bytes]) -> list[bytes]:
        """Part.Hash batch — for PartSet.from_data(hasher=...)."""
        if self._use_offload(len(chunks)):
            try:
                t0 = time.perf_counter()
                out = self._offload_leaf_hashes(chunks, "part")
                self._note_batch(
                    sum(len(c) for c in chunks), time.perf_counter() - t0
                )
                with self._mtx:
                    self._stats["tpu_part_batches"] += 1
                    self._stats["tpu_leaves"] += len(chunks)
                self._note_offload_success()
                return out
            except Exception:
                logger.exception("TPU part hashing failed; falling back to CPU")
                self._demote_after_failure()
        with self._mtx:
            self._stats["cpu_leaves"] += len(chunks)
        from tendermint_tpu import native

        # ready(), not available(): this sits on the consensus hot path,
        # and available() may synchronously run a ~minutes-long native
        # build on a fresh checkout (same rule as the verify fallback)
        if len(chunks) >= 2 and native.ready():
            # 16 equal-length parts per SIMD call (native ripemd160_x16):
            # ~5x the per-part OpenSSL loop at production shapes
            return native.ripemd160_batch(chunks)
        from tendermint_tpu.crypto.hashing import ripemd160

        return [ripemd160(c) for c in chunks]

    def part_set_tree(self, chunks: list[bytes]):
        """(leaf hashes, merkle.simple.FlatTree) for a part set when the
        offload path serves it, None when the caller should build on
        host (PartSet.from_data falls to the flat host builder). On the
        devd route ONE streamed pass returns leaf digests AND every
        internal tree node (the hash_stream tree frame), so proofs cost
        this process zero hashing; the in-process route reads the same
        node buffer off the tree kernel (ops/merkle)."""
        if not self._use_offload(len(chunks)):
            return None
        from tendermint_tpu.merkle.simple import FlatTree

        try:
            t0 = time.perf_counter()
            if self._route == "devd":
                from tendermint_tpu.ops import devd_backend

                digests, nodes = devd_backend.hash_tree(chunks, "part")
                digests = [bytes(d) for d in digests]
                tree = FlatTree.from_nodes(
                    len(chunks), digests + [bytes(x) for x in nodes]
                )
            else:
                from tendermint_tpu.ops import merkle as ops_merkle

                digests = ops_merkle.part_leaf_hashes(chunks)
                tree = FlatTree.from_nodes(
                    len(chunks),
                    ops_merkle.tree_nodes_from_leaf_digests(digests),
                )
            self._note_batch(
                sum(len(c) for c in chunks), time.perf_counter() - t0
            )
            with self._mtx:
                self._stats["tpu_part_batches"] += 1
                self._stats["tpu_leaves"] += len(chunks)
            self._note_offload_success()
            return digests, tree
        except Exception:
            logger.exception("TPU part-set tree failed; falling back to CPU")
            self._demote_after_failure()
            return None

    # -- submitted-early futures (round 14, pipelined proposal build) -----

    def _submit(self, fn) -> _HashFuture:
        """Queue `fn` on the single daemon submit worker; returns the
        join handle. The worker is lazy: processes that never submit
        (most tests, the verify-only planes) pay nothing."""
        fut = _HashFuture()
        with self._mtx:
            if self._submit_q is None:
                self._submit_q = queue.Queue()
                self._submit_thread = threading.Thread(
                    target=self._submit_loop, daemon=True,
                    name="gw.hashSubmit",
                )
                self._submit_thread.start()
            self._stats["submitted_jobs"] += 1
            q = self._submit_q
        q.put((fut, fn))
        return fut

    def _submit_loop(self) -> None:
        while True:
            fut, fn = self._submit_q.get()
            try:
                fut._finish(value=fn())
            except BaseException as exc:  # noqa: BLE001 — joined by caller
                fut._finish(exc=exc)

    def submit_tx_root(self, txs: list[bytes]) -> _HashFuture:
        """Start hashing the tx root NOW (streamed devd plane / AVX /
        CPU ladder) and return a future; a later tx_merkle_root() on the
        same tx set joins the in-flight job instead of recomputing.
        consensus/state.create_proposal_block submits right after the
        mempool reap so the root hashes while the commit/evidence/header
        assemble."""
        key = tuple(txs)
        done = _HashFuture()
        with self._mtx:
            cached = self._tx_roots.get(key)
            if cached is not None:
                self._tx_roots.move_to_end(key)
                done._finish(value=cached)
                return done
            fut = self._inflight_tx_roots.get(key)
            if fut is not None:
                return fut
            fut = _HashFuture()
            self._inflight_tx_roots[key] = fut

        def work():
            try:
                root = self._tx_merkle_root_uncached(txs)
            except BaseException as exc:  # noqa: BLE001 — joined by caller
                with self._mtx:
                    self._inflight_tx_roots.pop(key, None)
                fut._finish(exc=exc)
                return
            with self._mtx:
                # resolve BEFORE clearing in-flight: a joiner either sees
                # the in-flight future (and gets this root) or the LRU
                self._tx_roots[key] = root
                while len(self._tx_roots) > self._tx_roots_cap:
                    self._tx_roots.popitem(last=False)
            fut._finish(value=root)
            with self._mtx:
                self._inflight_tx_roots.pop(key, None)

        self._submit(work)
        return fut

    def submit_part_set_tree(self, chunks: list[bytes]) -> _HashFuture:
        """part_set_tree as a future: the devd/AVX round trip overlaps
        the caller's Part-object construction (types/part_set.py joins
        before building proofs). Resolves to (digests, FlatTree) or None
        exactly like part_set_tree."""
        return self._submit(lambda: self.part_set_tree(chunks))

    def tx_merkle_root(self, txs: list[bytes]) -> bytes:
        """Txs.Hash — the tx-tree root (types/tx.go:33-46), batched when
        wide enough. Injected into types/tx via set_batch_tx_root at node
        assembly so every block build/validate rides it. Roots are
        memoized per tx set (small LRU): the mempool -> proposal path
        recomputes the same root on repropose, block re-validation, and
        gossip receipt — those now cost one dict lookup, no rehash. A
        root submitted early (submit_tx_root) is JOINED, not recomputed."""
        key = tuple(txs)
        with self._mtx:
            cached = self._tx_roots.get(key)
            if cached is not None:
                self._tx_roots.move_to_end(key)
                self._stats["tx_root_cache_hits"] += 1
                return cached
            fut = self._inflight_tx_roots.get(key)
        if fut is not None:
            try:
                root = fut.result(timeout=120)
                with self._mtx:
                    self._stats["tx_root_prehash_joins"] += 1
                return root
            except Exception:
                logger.exception(
                    "early tx-root submission failed; recomputing inline"
                )
        root = self._tx_merkle_root_uncached(txs)
        with self._mtx:
            self._tx_roots[key] = root
            while len(self._tx_roots) > self._tx_roots_cap:
                self._tx_roots.popitem(last=False)
        return root

    def _tx_merkle_root_uncached(self, txs: list[bytes]) -> bytes:
        if self._use_offload(len(txs)):
            try:
                t0 = time.perf_counter()
                if self._route == "devd":
                    from tendermint_tpu.ops import devd_backend

                    # tree=True: the daemon's tree kernel returns every
                    # internal node; the root is the last one — zero
                    # host hashing on the whole path
                    digests, nodes = devd_backend.hash_tree(txs, "leaf")
                    out = bytes(nodes[-1]) if nodes else bytes(digests[0])
                else:
                    from tendermint_tpu.ops import merkle as ops_merkle

                    out = ops_merkle.merkle_root_from_leaf_digests(
                        ops_merkle.leaf_hashes(txs)
                    )
                self._note_batch(
                    sum(len(t) for t in txs), time.perf_counter() - t0
                )
                with self._mtx:
                    self._stats["tpu_tx_roots"] += 1
                    self._stats["tpu_leaves"] += len(txs)
                self._note_offload_success()
                return out
            except Exception:
                logger.exception("TPU tx hashing failed; falling back to CPU")
                self._demote_after_failure()
        from tendermint_tpu.merkle.simple import simple_hash_from_byteslices

        with self._mtx:
            self._stats["cpu_leaves"] += len(txs)
        return simple_hash_from_byteslices(txs)


# -- module-level default instances ------------------------------------------

_default_verifier: Verifier | None = None
_default_hasher: Hasher | None = None
_default_mtx = threading.Lock()


def default_verifier() -> Verifier:
    global _default_verifier
    with _default_mtx:
        if _default_verifier is None:
            _default_verifier = Verifier()
        return _default_verifier


def default_hasher() -> Hasher:
    global _default_hasher
    with _default_mtx:
        if _default_hasher is None:
            _default_hasher = Hasher()
        return _default_hasher
