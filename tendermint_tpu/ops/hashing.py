"""Batched RIPEMD-160 and SHA-256 for TPU (pure jnp, uint32 lanes).

Layout: a batch of messages is packed host-side (numpy) into a dense
uint32 word tensor [batch, max_blocks, 16] plus a per-message block count.
The compression function runs as a lax.scan over the block axis, vmapped
implicitly by operating on the whole batch per step; messages shorter than
max_blocks freeze their state via jnp.where masking, so ragged batches of
similar sizes share one kernel launch. All ops are 32-bit integer adds,
rotates, and bitwise logic — VPU work that XLA fuses into a handful of
loops; there is no MXU component to hashing.

Parity: digests are bit-identical to hashlib/crypto.hashing (tests
cross-check against RIPEMD-160 KATs and random inputs).
"""

from __future__ import annotations

import struct
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Host-side packing
# ---------------------------------------------------------------------------


def pack_messages(msgs: list[bytes], little_endian: bool, max_blocks: int | None = None):
    """MD-pad each message and pack to (uint32[B, max_blocks, 16],
    int32[B] block counts). LE for RIPEMD-160, BE for SHA-256."""
    n = len(msgs)
    padded = []
    nblocks = np.empty(n, dtype=np.int32)
    for i, m in enumerate(msgs):
        bitlen = len(m) * 8
        pad_len = (55 - len(m)) % 64
        if little_endian:
            p = m + b"\x80" + b"\x00" * pad_len + struct.pack("<Q", bitlen)
        else:
            p = m + b"\x80" + b"\x00" * pad_len + struct.pack(">Q", bitlen)
        padded.append(p)
        nblocks[i] = len(p) // 64
    mb = max_blocks if max_blocks is not None else int(nblocks.max(initial=1))
    words = np.zeros((n, mb, 16), dtype=np.uint32)
    fmt = "<16I" if little_endian else ">16I"
    for i, p in enumerate(padded):
        for b in range(nblocks[i]):
            words[i, b] = struct.unpack(fmt, p[b * 64 : (b + 1) * 64])
    return words, nblocks


# ---------------------------------------------------------------------------
# RIPEMD-160 (constants match crypto/hashing.py; see that module for KATs)
# ---------------------------------------------------------------------------

from tendermint_tpu.crypto.hashing import _K1, _K2, _R1, _R2, _S1, _S2

_INIT_RIPEMD = np.array(
    [0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0], dtype=np.uint32
)

# The 80 rounds are rolled into a lax.scan rather than unrolled python
# loops: the unrolled 320-op dependency chain made XLA:CPU's LLVM
# pipeline take minutes per shape, and scan keeps the graph O(1) in
# round count. _SCAN_UNROLL re-unrolls chunks inside the compiled loop
# so the TPU VPU still sees fused multi-round chains.
_SCAN_UNROLL = 8

# Flattened per-step round tables (step j = round j//16, index j%16).
_R1F = np.concatenate([np.asarray(r, np.int32) for r in _R1])
_R2F = np.concatenate([np.asarray(r, np.int32) for r in _R2])
_S1F = np.concatenate([np.asarray(s, np.uint32) for s in _S1])
_S2F = np.concatenate([np.asarray(s, np.uint32) for s in _S2])
_K1F = np.repeat(np.asarray(_K1, np.uint32), 16)
_K2F = np.repeat(np.asarray(_K2, np.uint32), 16)
_RNDF = np.repeat(np.arange(5, dtype=np.int32), 16)


def _rol(x, n):
    return (x << n) | (x >> (32 - n))


def _f_sel(j, x, y, z):
    """RIPEMD round function selected by traced round index j (0..4):
    all five are cheap VPU bitwise ops, so compute-and-select beats a
    branch inside the scan body."""
    f0 = x ^ y ^ z
    f1 = (x & y) | (~x & z)
    f2 = (x | ~y) ^ z
    f3 = (x & z) | (y & ~z)
    f4 = x ^ (y | ~z)
    return jnp.where(
        j == 0, f0, jnp.where(j == 1, f1, jnp.where(j == 2, f2, jnp.where(j == 3, f3, f4)))
    )


def _ripemd160_block(state, words):
    """One compression step. state: (B,5) uint32; words: (B,16) uint32."""
    h = [state[:, i] for i in range(5)]
    # message-word selection is a static gather outside the loop
    w1 = jnp.swapaxes(jnp.take(words, jnp.asarray(_R1F), axis=1), 0, 1)  # (80,B)
    w2 = jnp.swapaxes(jnp.take(words, jnp.asarray(_R2F), axis=1), 0, 1)
    xs = (
        w1, w2,
        jnp.asarray(_S1F), jnp.asarray(_S2F),
        jnp.asarray(_K1F), jnp.asarray(_K2F),
        jnp.asarray(_RNDF),
    )

    def step(carry, inp):
        a1, b1, c1, d1, e1, a2, b2, c2, d2, e2 = carry
        x1, x2, s1, s2, k1, k2, rnd = inp
        t = _rol(a1 + _f_sel(rnd, b1, c1, d1) + x1 + k1, s1) + e1
        a1, e1, d1, c1, b1 = e1, d1, _rol(c1, jnp.uint32(10)), b1, t
        t = _rol(a2 + _f_sel(4 - rnd, b2, c2, d2) + x2 + k2, s2) + e2
        a2, e2, d2, c2, b2 = e2, d2, _rol(c2, jnp.uint32(10)), b2, t
        return (a1, b1, c1, d1, e1, a2, b2, c2, d2, e2), None

    init = (*h, *h)
    (a1, b1, c1, d1, e1, a2, b2, c2, d2, e2), _ = jax.lax.scan(
        step, init, xs, unroll=_SCAN_UNROLL
    )
    h0, h1, h2, h3, h4 = h
    return jnp.stack(
        [h1 + c1 + d2, h2 + d1 + e2, h3 + e1 + a2, h4 + a1 + b2, h0 + b1 + c2],
        axis=1,
    )


@partial(jax.jit, static_argnames=())
def ripemd160_words(words: jax.Array, nblocks: jax.Array) -> jax.Array:
    """words: uint32[B, NB, 16]; nblocks: int32[B] -> digests uint32[B, 5]
    (little-endian words)."""
    B = words.shape[0]
    init = jnp.broadcast_to(jnp.asarray(_INIT_RIPEMD), (B, 5))

    def step(state, inp):
        blk_idx, blk_words = inp
        new_state = _ripemd160_block(state, blk_words)
        active = (blk_idx < nblocks)[:, None]
        return jnp.where(active, new_state, state), None

    idxs = jnp.arange(words.shape[1], dtype=jnp.int32)
    final, _ = jax.lax.scan(step, init, (idxs, jnp.swapaxes(words, 0, 1)))
    return final


def digests_to_bytes_le(digests: np.ndarray) -> list[bytes]:
    d = np.asarray(digests, dtype="<u4")
    return [d[i].tobytes() for i in range(d.shape[0])]


def ripemd160_batch(msgs: list[bytes]) -> list[bytes]:
    """Convenience host API: batch-hash arbitrary messages."""
    if not msgs:
        return []
    words, nblocks = pack_messages(msgs, little_endian=True)
    out = ripemd160_words(jnp.asarray(words), jnp.asarray(nblocks))
    return digests_to_bytes_le(np.asarray(out))


# ---------------------------------------------------------------------------
# SHA-256
# ---------------------------------------------------------------------------

_SHA_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_INIT_SHA = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)


def _ror(x, n):
    return (x >> n) | (x << (32 - n))


def _sha256_block(state, words):
    """state: (B,8); words: (B,16) big-endian-packed.

    Message schedule and rounds both run as lax.scan (see the RIPEMD
    note above on why rolled loops: unrolled bodies stall XLA:CPU's
    LLVM passes for minutes; _SCAN_UNROLL restores in-loop fusion)."""

    def sched_step(win, _):
        # win: (B,16) sliding window of the last 16 schedule words
        w15, w2, w16, w7 = win[:, 1], win[:, 14], win[:, 0], win[:, 9]
        s0 = _ror(w15, 7) ^ _ror(w15, 18) ^ (w15 >> 3)
        s1 = _ror(w2, 17) ^ _ror(w2, 19) ^ (w2 >> 10)
        new = w16 + s0 + w7 + s1
        return jnp.concatenate([win[:, 1:], new[:, None]], axis=1), new

    _, tail = jax.lax.scan(sched_step, words, None, length=48, unroll=_SCAN_UNROLL)
    all_w = jnp.concatenate([jnp.swapaxes(words, 0, 1), tail], axis=0)  # (64,B)

    def round_step(st, inp):
        w_t, k_t = inp
        a, b, c, d, e, f, g, h = st
        s1 = _ror(e, 6) ^ _ror(e, 11) ^ _ror(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k_t + w_t
        s0 = _ror(a, 2) ^ _ror(a, 13) ^ _ror(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        return (t1 + s0 + maj, a, b, c, d + t1, e, f, g), None

    init = tuple(state[:, i] for i in range(8))
    final, _ = jax.lax.scan(
        round_step, init, (all_w, jnp.asarray(_SHA_K)), unroll=_SCAN_UNROLL
    )
    return state + jnp.stack(final, axis=1)


@jax.jit
def sha256_words(words: jax.Array, nblocks: jax.Array) -> jax.Array:
    """words: uint32[B, NB, 16] (big-endian packing); -> uint32[B, 8]."""
    B = words.shape[0]
    init = jnp.broadcast_to(jnp.asarray(_INIT_SHA), (B, 8))

    def step(state, inp):
        blk_idx, blk_words = inp
        new_state = _sha256_block(state, blk_words)
        active = (blk_idx < nblocks)[:, None]
        return jnp.where(active, new_state, state), None

    idxs = jnp.arange(words.shape[1], dtype=jnp.int32)
    final, _ = jax.lax.scan(step, init, (idxs, jnp.swapaxes(words, 0, 1)))
    return final


def digests_to_bytes_be(digests: np.ndarray) -> list[bytes]:
    d = np.asarray(digests, dtype=np.uint32).astype(">u4")
    return [d[i].tobytes() for i in range(d.shape[0])]


def sha256_batch(msgs: list[bytes]) -> list[bytes]:
    if not msgs:
        return []
    words, nblocks = pack_messages(msgs, little_endian=False)
    out = sha256_words(jnp.asarray(words), jnp.asarray(nblocks))
    return digests_to_bytes_be(np.asarray(out))
