"""TPU data plane: batched crypto kernels in JAX.

This is the layer the reference doesn't have (it is 100% Go; SURVEY.md §2):
the embarrassingly-parallel crypto loops of the consensus hot path —

- per-vote Ed25519 verification (types/vote_set.go:175)
- VerifyCommit's sequential verify loop (types/validator_set.go:247-250)
- fast-sync per-block commit verification (blockchain/reactor.go:235)
- PartSet/tx-tree Merkle hashing (types/part_set.go:95, types/tx.go:33)

— re-expressed as wide batches over TPU lanes:

- `hashing`: RIPEMD-160 / SHA-256 compression functions in pure uint32
  jnp ops, vectorized over messages, lax.scan over blocks.
- `merkle`:  level-by-level tree hashing with host-computed structure.
- `ed25519`: batched signature verification on limb-based GF(2^255-19)
  arithmetic (radix 2^15, int32 lanes; no 64-bit ops needed).
- `gateway`: the batching gateway the consensus layer talks to — flush
  policies, CPU fallback below a batch-size threshold, byte-identical
  semantics with the crypto package, and shard_map sharding over a
  jax.sharding.Mesh for multi-chip scale.

Everything is jittable with static shapes (bucketed padding), bfloat16-free
(integer ops on the VPU), and designed for XLA fusion rather than
hand-scheduling.
"""
