"""Gateway kernel backend that routes batches to the device daemon.

Selected as `devd` in ops/gateway.KERNELS (and as the automatic default
when a daemon is serving — gateway.kernel_name). With this backend a
node, bench, or test process NEVER initializes a jax backend or dials
the accelerator tunnel: the daemon (tendermint_tpu/devd.py) owns the
device; this module is pure socket IPC. That is the wedge-proofing: the
only process with device state is one that is never killed mid-op.

Transport policy (round 6): batches at or above TENDERMINT_DEVD_STREAM_MIN
lanes (default 256) ride the STREAMED protocol — fixed-width binary chunk
frames submitted while the daemon verifies earlier chunks, verdicts
streaming back per chunk (devd.DevdClient.verify_stream_async; protocol
in tendermint_tpu/devd.py / docs/streaming-devd.md). Below the threshold
the single-shot pickle op wins: one small frame beats stream setup. A
daemon that rejects verify_stream (version skew) latches the single-shot
path for the process lifetime.

Same contract as the kernel modules (ops/ed25519_f32.py): verify_batch
returns an array-like of bools; verify_batch_async returns a zero-arg
resolver. Failures raise — the gateway's existing CPU-fallback handling
(ops/gateway.Verifier.verify_batch) treats a dead daemon exactly like a
dead device.

Sharded plane (round 21): when TENDERMINT_DEVD_SOCKS names two or more
endpoints, every entry point delegates to ops/devd_shard — the same
contracts, dispatched across the fleet with work-stealing and
per-endpoint breakers. With one endpoint the single-client path below
runs unchanged.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from tendermint_tpu import devd

_client: devd.DevdClient | None = None
_mtx = threading.Lock()
# False once the serving daemon rejected verify_stream — don't pay a
# doomed stream attempt per batch against a pre-streaming daemon
_stream_ok = True


def _get_client() -> devd.DevdClient:
    global _client
    with _mtx:
        if _client is None:
            _client = devd.DevdClient()
        return _client


def _stream_min() -> int:
    try:
        return int(os.environ.get("TENDERMINT_DEVD_STREAM_MIN", "256"))
    except ValueError:  # a typo'd env var must not latch the CPU path
        return 256


def _use_stream(n: int) -> bool:
    return _stream_ok and n >= _stream_min()


def _shard():
    """The sharded dispatcher, when >= 2 endpoints are configured."""
    from tendermint_tpu.ops import devd_shard

    return devd_shard if devd_shard.enabled() else None


def verify_batch(items) -> np.ndarray:
    items = list(items)
    shard = _shard()
    if shard is not None:
        return np.asarray(shard.verify_batch(items), dtype=bool)
    c = _get_client()
    if _use_stream(len(items)):
        try:
            return np.asarray(c.verify_stream(items), dtype=bool)
        except devd.DevdError as exc:
            if "too old" not in str(exc):
                raise
            _latch_single_shot()
    return np.asarray(c.verify_batch(items), dtype=bool)


def verify_batch_async(items):
    items = list(items)
    shard = _shard()
    if shard is not None:
        resolve_shard = shard.verify_batch_async(items)
        return lambda: np.asarray(resolve_shard(), dtype=bool)
    c = _get_client()
    if _use_stream(len(items)):
        resolve = c.verify_stream_async(items)

        def resolve_stream() -> np.ndarray:
            try:
                return np.asarray(resolve(), dtype=bool)
            except devd.DevdError as exc:
                if "too old" not in str(exc):
                    raise
                _latch_single_shot()
                return np.asarray(c.verify_batch(items), dtype=bool)

        return resolve_stream
    resolve = c.verify_batch_async(items)
    return lambda: np.asarray(resolve(), dtype=bool)


def _latch_single_shot() -> None:
    global _stream_ok
    _stream_ok = False


def reset_stream_latches() -> None:
    """Re-arm the version-skew latches (verify, hash, AND agg planes).
    Called by the shared circuit breaker's on_close hook (ops/gateway):
    the latches are per-DAEMON facts, and a breaker re-close means the
    daemon came back — possibly upgraded — so the latched-off fast paths
    must get another chance instead of staying latched off by the build
    that died."""
    global _stream_ok, _hash_stream_ok, _agg_ok
    _stream_ok = True
    _hash_stream_ok = True
    _agg_ok = True


# -- aggregate plane ----------------------------------------------------------
#
# The aggregate-commit verify's dual-scalar-mul lanes (docs/upgrade.md):
# one "agg" op per commit, lanes batched daemon-side through
# ops/ed25519.dsm_batch. Sharded fleets split the lanes across endpoints
# with the same offset-merge per-lane attribution the verify plane has.


class AggUnsupported(Exception):
    """The serving daemon predates the agg op (version skew). The
    gateway treats this as 'route unavailable' — straight to the CPU
    floor, NO breaker penalty (the daemon is healthy, just old)."""


_agg_ok = True


def _latch_agg_off() -> None:
    global _agg_ok
    _agg_ok = False


def agg_batch(terms) -> list[tuple[int, int]]:
    """Per-lane [a]P + [b]Q over the daemon-owned device; terms as in
    ops/ed25519.dsm_batch. Raises AggUnsupported on a pre-agg daemon
    (latched for the daemon's lifetime; re-armed by breaker re-close)."""
    if not _agg_ok:
        raise AggUnsupported("daemon predates the agg op (latched)")
    terms = [tuple(t) for t in terms]
    shard = _shard()
    try:
        if shard is not None:
            return shard.agg_batch(terms)
        return _get_client().agg_batch(terms)
    except devd.DevdError as exc:
        if "unknown op" not in str(exc):
            raise
        _latch_agg_off()
        raise AggUnsupported(str(exc)) from exc


def stream_stats() -> dict:
    """Client-side streamed-transport counters; Verifier.stats() exposes
    them so the serving path is observable from the node process too.
    Sharded: summed across every endpoint's client."""
    shard = _shard()
    if shard is not None:
        return shard.stream_stats()
    return _get_client().stream_stats()


# -- hash plane ---------------------------------------------------------------
#
# Same transport policy as verify, plus a BYTES floor: part-set batches
# are few-but-fat (16 x 64 KB for a 1 MB block — far under the 256-lane
# stream min that fits signature lanes), and it is exactly those megabyte
# frames whose marshal the stream exists to overlap with device hashing.

_HASH_STREAM_MIN_BYTES = 1 << 18  # 256 KB

# the hash plane's OWN version-skew latch: a round-6 daemon serves
# verify_stream fine while rejecting hash_stream — latching the shared
# verify flag would silently reintroduce the serving-path gap PR 1 closed
_hash_stream_ok = True


def _hash_stream_min_bytes() -> int:
    try:
        return int(os.environ.get(
            "TENDERMINT_DEVD_HASH_STREAM_MIN_BYTES",
            str(_HASH_STREAM_MIN_BYTES),
        ))
    except ValueError:
        return _HASH_STREAM_MIN_BYTES


def _use_hash_stream(n: int, total_bytes: int) -> bool:
    return _hash_stream_ok and (
        n >= _stream_min() or total_bytes >= _hash_stream_min_bytes()
    )


def _latch_hash_single_shot() -> None:
    global _hash_stream_ok
    _hash_stream_ok = False


def _hash_chunk(mode: str) -> int | None:
    """Stream chunk width in ITEMS: TENDERMINT_DEVD_HASH_CHUNK pins it;
    otherwise part mode frames narrow (parts are 64 KB each — 8 parts =
    a 512 KB frame, enough to overlap decode with device compute without
    starving the pipeline), leaf mode rides the daemon-advertised verify
    width (tx leaves are sig-lane sized)."""
    try:
        env = int(os.environ.get("TENDERMINT_DEVD_HASH_CHUNK", "0") or 0)
    except ValueError:
        env = 0
    if env > 0:
        return env
    return 8 if mode == "part" else None


def hash_batch(items, mode: str = "part") -> list[bytes]:
    """Batched daemon-side hashing (gateway.Hasher's devd route):
    streamed chunk frames when the batch is wide or fat enough, the
    single-shot pickle op otherwise. Digests byte-identical to
    crypto.hashing.ripemd160 / merkle.simple.leaf_hash."""
    items = [bytes(b) for b in items]
    shard = _shard()
    if shard is not None:
        return shard.hash_batch(items, mode)
    c = _get_client()
    if _use_hash_stream(len(items), sum(len(b) for b in items)):
        try:
            return c.hash_stream(items, mode=mode, chunk=_hash_chunk(mode))
        except devd.DevdError as exc:
            if "too old" not in str(exc):
                raise
            _latch_hash_single_shot()
    return c.hash_batch(items, mode=mode)


def hash_tree(items, mode: str = "part") -> tuple[list, list]:
    """(leaf digests, postorder internal tree nodes) — the proof-free
    part-set path: one streamed pass hashes every leaf AND the whole
    Merkle tree daemon-side (merkle.simple.FlatTree.from_nodes
    rehydrates host proofs with zero host hashing). Sharded: leaves
    hash across the fleet, the internal nodes build host-side from the
    gathered digests (devd_shard.hash_tree — byte-identical buffer)."""
    items = [bytes(b) for b in items]
    shard = _shard()
    if shard is not None:
        return shard.hash_tree(items, mode)
    c = _get_client()
    if _use_hash_stream(len(items), sum(len(b) for b in items)):
        try:
            return c.hash_stream(
                items, mode=mode, tree=True, chunk=_hash_chunk(mode)
            )
        except devd.DevdError as exc:
            if "too old" not in str(exc):
                raise
            _latch_hash_single_shot()
    return c.hash_batch(items, mode=mode, tree=True)


def hash_stream_stats() -> dict:
    """Client-side hash-transport counters; gateway.Hasher.stats() folds
    them in as flat stream_* gauges for the metrics RPC. Sharded:
    summed across every endpoint's client."""
    shard = _shard()
    if shard is not None:
        return shard.hash_stream_stats()
    return _get_client().hash_stream_stats()
