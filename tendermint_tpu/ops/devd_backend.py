"""Gateway kernel backend that routes batches to the device daemon.

Selected as `devd` in ops/gateway.KERNELS (and as the automatic default
when a daemon is serving — gateway.kernel_name). With this backend a
node, bench, or test process NEVER initializes a jax backend or dials
the accelerator tunnel: the daemon (tendermint_tpu/devd.py) owns the
device; this module is pure socket IPC. That is the wedge-proofing: the
only process with device state is one that is never killed mid-op.

Same contract as the kernel modules (ops/ed25519_f32.py): verify_batch
returns an array-like of bools; verify_batch_async returns a zero-arg
resolver. Failures raise — the gateway's existing CPU-fallback handling
(ops/gateway.Verifier.verify_batch) treats a dead daemon exactly like a
dead device.
"""

from __future__ import annotations

import threading

import numpy as np

from tendermint_tpu import devd

_client: devd.DevdClient | None = None
_mtx = threading.Lock()


def _get_client() -> devd.DevdClient:
    global _client
    with _mtx:
        if _client is None:
            _client = devd.DevdClient()
        return _client


def verify_batch(items) -> np.ndarray:
    return np.asarray(_get_client().verify_batch(items), dtype=bool)


def verify_batch_async(items):
    resolve = _get_client().verify_batch_async(items)
    return lambda: np.asarray(resolve(), dtype=bool)
