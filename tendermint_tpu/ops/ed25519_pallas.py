"""Pallas TPU kernel for batched Ed25519 verification.

STATUS: bake-off alternative, selectable with TENDERMINT_TPU_KERNEL=pallas.
Lost the production bake-off to ops/ed25519_f32.py (32.6k vs 94.4k sigs/s
at batch 8192 on a v5e — see ops/gateway.py KERNELS): the f32 kernel's
conv-lowered field multiplies ride the MXU while this ladder is VPU-bound
int32 work, and VMEM residency alone doesn't close that gap. Kept as the
VMEM-resident reference point for future pallas work and as a second
device implementation the tests cross-check.

The XLA-composed variant (ops/ed25519.py) bottoms out at ~350ms/batch on a
v5e because the limb accumulator updates materialize through HBM between
HLO ops. This kernel runs the ENTIRE double-scalar ladder inside one
pallas_call: field elements live as (1, TB)-row register/VMEM values for a
lane tile of TB signatures, the 253-iteration Straus loop is a fori_loop,
and nothing touches HBM between bit steps.

Same math as ops/ed25519.py (radix-2^15/17-limb int32, hi/lo split,
complete Edwards formulas, compress-and-compare against R); the host
marshaling (prepare_batch) is shared. Tests cross-check lane-for-lane
against the CPU verifier.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tendermint_tpu.ops import ed25519 as base

NLIMB = base.NLIMB
M15 = base.M15

# Field elements inside the kernel are Python lists of 17 (1, TB) int32
# arrays — fully unrolled limb arithmetic on full-width vector rows.


def _carry_rows(x: list):
    out = []
    c = None
    for k in range(NLIMB):
        v = x[k] if c is None else x[k] + c
        out.append(v & M15)
        c = v >> 15
    v0 = out[0] + 19 * c
    out[0] = v0 & M15
    out[1] = out[1] + (v0 >> 15)
    return out


def _fmul_rows(a: list, b: list) -> list:
    acc = [None] * 34
    for i in range(NLIMB):
        ai = a[i]
        for j in range(NLIMB):
            p = ai * b[j]
            lo = p & M15
            hi = p >> 15
            k = i + j
            acc[k] = lo if acc[k] is None else acc[k] + lo
            acc[k + 1] = hi if acc[k + 1] is None else acc[k + 1] + hi
    res = [acc[k] for k in range(NLIMB)]
    for k in range(NLIMB, 34):
        res[k - NLIMB] = res[k - NLIMB] + 19 * acc[k]
    return _carry_rows(res)


def _fsq_rows(a: list) -> list:
    acc = [None] * 34
    for i in range(NLIMB):
        p = a[i] * a[i]
        lo, hi = p & M15, p >> 15
        k = 2 * i
        acc[k] = lo if acc[k] is None else acc[k] + lo
        acc[k + 1] = hi if acc[k + 1] is None else acc[k + 1] + hi
        for j in range(i + 1, NLIMB):
            p2 = 2 * (a[i] * a[j])
            lo, hi = p2 & M15, p2 >> 15
            k = i + j
            acc[k] = lo if acc[k] is None else acc[k] + lo
            acc[k + 1] = hi if acc[k + 1] is None else acc[k + 1] + hi
    res = [acc[k] for k in range(NLIMB)]
    for k in range(NLIMB, 34):
        res[k - NLIMB] = res[k - NLIMB] + 19 * acc[k]
    return _carry_rows(res)


_PX2_L = [int(v) for v in base._PX2]
_P_L = [int(v) for v in base._P_LIMBS]
_D2_L = [int(v) for v in base._D2]
_BX_L = [int(v) for v in base._BX]
_BY_L = [int(v) for v in base._BY]
_BT_L = [int(v) for v in base._BT]


def _fadd_rows(a, b):
    return _carry_rows([a[k] + b[k] for k in range(NLIMB)])


def _fsub_rows(a, b):
    return _carry_rows([a[k] + _PX2_L[k] - b[k] for k in range(NLIMB)])


def _point_add_rows(p1, p2, d2_rows):
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = _fmul_rows(_fsub_rows(y1, x1), _fsub_rows(y2, x2))
    b = _fmul_rows(_fadd_rows(y1, x1), _fadd_rows(y2, x2))
    c = _fmul_rows(_fmul_rows(t1, t2), d2_rows)
    zz = _fmul_rows(z1, z2)
    d = _fadd_rows(zz, zz)
    e = _fsub_rows(b, a)
    f = _fsub_rows(d, c)
    g = _fadd_rows(d, c)
    h = _fadd_rows(b, a)
    return (
        _fmul_rows(e, f),
        _fmul_rows(g, h),
        _fmul_rows(f, g),
        _fmul_rows(e, h),
    )


def _point_double_rows(p1):
    x1, y1, z1, _ = p1
    a = _fsq_rows(x1)
    b = _fsq_rows(y1)
    zz = _fsq_rows(z1)
    c = _fadd_rows(zz, zz)
    h = _fadd_rows(a, b)
    e = _fsub_rows(h, _fsq_rows(_fadd_rows(x1, y1)))
    g = _fsub_rows(a, b)
    f = _fadd_rows(c, g)
    return (
        _fmul_rows(e, f),
        _fmul_rows(g, h),
        _fmul_rows(f, g),
        _fmul_rows(e, h),
    )


def _fcanon_rows(x):
    x = _carry_rows(x)
    for _ in range(2):
        borrow = None
        out = []
        for k in range(NLIMB):
            v = x[k] - _P_L[k] - (borrow if borrow is not None else 0)
            out.append(v & M15)
            borrow = (v >> 15) & 1
        ge = borrow == 0
        x = [jnp.where(ge, out[k], x[k]) for k in range(NLIMB)]
    return x


def _finv_rows(z):
    def rep_sq(x, n):
        # rolled loop to bound code size; x stacked to (17, TB) for carry
        def body(_, v):
            return jnp.stack(_fsq_rows([v[k] for k in range(NLIMB)]))

        if n <= 4:
            for _ in range(n):
                x = _fsq_rows(x)
            return x
        stacked = jax.lax.fori_loop(0, n, body, jnp.stack(x))
        return [stacked[k] for k in range(NLIMB)]

    z2 = _fsq_rows(z)
    z9 = _fmul_rows(rep_sq(z2, 2), z)
    z11 = _fmul_rows(z9, z2)
    z_5_0 = _fmul_rows(_fsq_rows(z11), z9)
    z_10_0 = _fmul_rows(rep_sq(z_5_0, 5), z_5_0)
    z_20_0 = _fmul_rows(rep_sq(z_10_0, 10), z_10_0)
    z_40_0 = _fmul_rows(rep_sq(z_20_0, 20), z_20_0)
    z_50_0 = _fmul_rows(rep_sq(z_40_0, 10), z_10_0)
    z_100_0 = _fmul_rows(rep_sq(z_50_0, 50), z_50_0)
    z_200_0 = _fmul_rows(rep_sq(z_100_0, 100), z_100_0)
    z_250_0 = _fmul_rows(rep_sq(z_200_0, 50), z_50_0)
    return _fmul_rows(rep_sq(z_250_0, 5), z11)


def _verify_kernel(ax_ref, ay_ref, ry_ref, rsign_ref, sbits_ref, hbits_ref, out_ref):
    # lane tile is (S, 128): one full (8,128) vreg per limb row when S=8
    S, LANES = ax_ref.shape[1], ax_ref.shape[2]

    def rows(ref):
        return [ref[k] for k in range(NLIMB)]

    def const_rows(vals):
        return [jnp.full((S, LANES), v, dtype=jnp.int32) for v in vals]

    zero = jnp.zeros((S, LANES), dtype=jnp.int32)
    one_v = jnp.ones((S, LANES), dtype=jnp.int32)
    zeros = [zero] * NLIMB
    one = [one_v] + [zero] * (NLIMB - 1)

    ax = rows(ax_ref)
    ay = rows(ay_ref)
    d2_rows = const_rows(_D2_L)

    nax = _fsub_rows(zeros, ax)
    neg_a = (nax, ay, one, _fmul_rows(nax, ay))
    b_pt = (const_rows(_BX_L), const_rows(_BY_L), one, const_rows(_BT_L))
    b_neg_a = _point_add_rows(b_pt, neg_a, d2_rows)
    ident = (zeros, one, one, zeros)

    def pack(pt):
        return jnp.stack([jnp.stack(coord) for coord in pt])  # (4,17,TB)

    tab_ident = pack(ident)
    tab_b = pack(b_pt)
    tab_na = pack(neg_a)
    tab_bna = pack(b_neg_a)

    def unpack(arr):
        return tuple([arr[c][k] for k in range(NLIMB)] for c in range(4))

    def step(i, acc_arr):
        acc = unpack(acc_arr)
        acc = _point_double_rows(acc)
        # bits stored MSB-first row 0 = bit 252
        sb = sbits_ref[i]
        hb = hbits_ref[i]
        sel = sb + 2 * hb
        addend_arr = jnp.where(
            (sel == 0)[None, None], tab_ident,
            jnp.where(
                (sel == 1)[None, None], tab_b,
                jnp.where((sel == 2)[None, None], tab_na, tab_bna),
            ),
        )
        res = _point_add_rows(acc, unpack(addend_arr), d2_rows)
        return pack(res)

    acc_arr = jax.lax.fori_loop(0, 253, step, pack(ident))
    px, py, pz, _ = unpack(acc_arr)
    zinv = _finv_rows(pz)
    x_aff = _fcanon_rows(_fmul_rows(px, zinv))
    y_aff = _fcanon_rows(_fmul_rows(py, zinv))
    ry = _fcanon_rows(rows(ry_ref))
    eq = jnp.ones((S, LANES), dtype=jnp.bool_)
    for k in range(NLIMB):
        eq = eq & (y_aff[k] == ry[k])
    eq = eq & ((x_aff[0] & 1) == rsign_ref[0])
    out_ref[0] = eq.astype(jnp.int32)


def _make_verify(s_tile: int, interpret: bool):
    """Inputs shaped (rows, S, 128) with the batch laid out as (S, 128)
    lane tiles; the grid walks S in s_tile chunks."""

    def call(ax, ay, ry, rsign, sbits, hbits):
        s_total = ax.shape[1]
        spec17 = pl.BlockSpec((NLIMB, s_tile, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM)
        spec253 = pl.BlockSpec((253, s_tile, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM)
        spec1 = pl.BlockSpec((1, s_tile, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM)
        return pl.pallas_call(
            _verify_kernel,
            grid=(s_total // s_tile,),
            in_specs=[spec17, spec17, spec17, spec1, spec253, spec253],
            out_specs=spec1,
            out_shape=jax.ShapeDtypeStruct((1, s_total, 128), jnp.int32),
            interpret=interpret,
        )(ax, ay, ry, rsign, sbits, hbits)

    return jax.jit(call)


_verify_calls: dict = {}


def _get_verify(tb: int, interpret: bool):
    key = (tb, interpret)
    if key not in _verify_calls:
        _verify_calls[key] = _make_verify(tb, interpret)
    return _verify_calls[key]


def _on_tpu() -> bool:
    from tendermint_tpu.ops.gateway import on_tpu

    return on_tpu()


S_TILE = 8  # (8, 128) = one full int32 vreg per limb row


def verify_batch(items: list[tuple[bytes, bytes, bytes]]) -> np.ndarray:
    """Drop-in replacement for ops.ed25519.verify_batch using the Pallas
    kernel (interpret mode off-TPU so tests run on CPU)."""
    n = len(items)
    if n == 0:
        return np.zeros(0, dtype=bool)
    interpret = not _on_tpu()
    tile_lanes = S_TILE * 128
    bucket = ((n + tile_lanes - 1) // tile_lanes) * tile_lanes
    s_total = bucket // 128
    ax, ay, ry, rs, s_bits, h_bits, valid = base.prepare_batch(items, bucket)
    # kernel expects bits MSB-first rows; reshape batch to (S, 128) tiles
    s_rev = np.ascontiguousarray(s_bits[::-1]).reshape(253, s_total, 128)
    h_rev = np.ascontiguousarray(h_bits[::-1]).reshape(253, s_total, 128)
    fn = _get_verify(S_TILE, interpret)
    ok = fn(
        jnp.asarray(ax.reshape(NLIMB, s_total, 128)),
        jnp.asarray(ay.reshape(NLIMB, s_total, 128)),
        jnp.asarray(ry.reshape(NLIMB, s_total, 128)),
        jnp.asarray(rs.reshape(1, s_total, 128)),
        jnp.asarray(s_rev), jnp.asarray(h_rev),
    )
    return (np.asarray(ok).reshape(-1)[:n] != 0) & valid[:n]
