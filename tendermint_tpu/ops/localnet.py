"""Hundreds-of-nodes localnet tier (round 20, docs/localnet.md).

The netchaos harness (tests/netchaos_common.py) runs N full nodes
IN-PROCESS — perfect for white-box assertions, but every node shares
one interpreter, one GIL, one crash domain. This module is the same
scenario vocabulary one tier up: N real node PROCESSES (the existing
CLI node, `python -m tendermint_tpu.cli node`) on loopback, each with
its own home/keys/DBs/WAL, peered through `ops/netfaults` LinkProxy
relays so the WHOLE chaos vocabulary — partitions, seeded WAN profiles,
geo-cluster topologies, rolling restarts — applies unchanged to a
process fleet. Everything is read back through the public scrape
surface (`ops/fleet`: GET /metrics + /health + consensus_trace), never
by reaching into harness objects: what a scenario asserts here is what
an operator of a real deployment could assert.

One seeded `LocalnetSpec` generates the entire net: N homes under one
root (privval keys derived from `(chain_id, index)`, one shared
genesis, per-home config.toml written through the real TOML round-trip
so the CLI node loads EXACTLY what a production home would carry).
Ports are explicit (`base_port + 2i` p2p, `+2i+1` RPC) — the fabric's
links can be strung before any process exists.

Topology is part of the spec, because a single box cannot carry a
50-node FULL mesh (1225 proxied links ≈ 5k fds and 2.5k relay
threads): `full` (node i dials every j < i — the netchaos shape,
default up to 16 nodes), `ring` (i dials (i+1..i+k) mod n — bounded
degree, diameter n/2k; the default beyond 16), `star` (everyone dials
node 0 — the seeds-node shape). Every directed dial edge gets its own
LinkProxy, so group chaos maps exactly as in the in-process tier.

Scheduling reality check: the nodes are Python processes sharing this
box's cores. The consensus timeout schedule baked into each config.toml
scales with fleet size (a 50-process net on few cores needs wider
propose windows than a 4-process one) and with the WAN profile (the
netchaos lesson: a 100 ms propose window can never cover a 40-90 ms
per-chunk link). Baked in — not mutated live — because these are real
processes: there is no shared config object to poke.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from dataclasses import dataclass, field

from tendermint_tpu.ops import fleet
from tendermint_tpu.ops.netfaults import NetFabric, geo_clusters, wan_profile

logger = logging.getLogger("ops.localnet")

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# dial-degree ceiling where full mesh hands over to the ring (links grow
# O(n^2) vs O(n*k); at 16 the mesh is 120 links — still one box's worth)
FULL_MESH_MAX = 16
DEFAULT_RING_K = 4


@dataclass
class LocalnetSpec:
    """Everything that defines one localnet, seeded: two runs from the
    same spec generate identical keys, genesis (bar the timestamp),
    configs, and link fabric."""

    n: int = 4
    root: str = ""
    chain_id: str = "localnet"
    seed: int = 0
    # full | ring | star | "" (auto: full up to FULL_MESH_MAX, then ring)
    topology: str = ""
    ring_k: int = DEFAULT_RING_K
    base_port: int = 47100
    proxy_app: str = "kvstore"
    db_backend: str = "memdb"
    tx_index: str = "kv"
    gossip_dedup: bool = True
    # netfaults WAN profile name baked into the timeout schedule and
    # applied to every link at start ("" = clean loopback)
    wan: str = ""
    # >0: geo-cluster net (k clusters, lan inside / `wan` — or
    # intercontinental — between)
    geo: int = 0
    log_level: str = "error"
    # commit pacing: real timeout_commit (not the test preset's skipped
    # one) so the fleet's skew/byte-per-height readouts are meaningful
    timeout_commit: float = 0.1
    # commit-format schedule baked into the shared genesis (round 22):
    # heights >= upgrade_height carry upgrade_format last-commits,
    # heights below stay on commit_format forever (docs/upgrade.md).
    # upgrade_height=0 = no flip scheduled.
    commit_format: str = "full"
    upgrade_height: int = 0
    upgrade_format: str = "aggregate"
    # peer discovery: run the PEX reactor + address book on every node
    # (the pex_churn scenario's subject)
    pex: bool = False
    # block tx cap baked into every config.toml (0 = the config default;
    # the overload scenario shrinks it so a bulk backlog spans blocks)
    max_block_txs: int = 0
    extra_args: list = field(default_factory=list)
    # extra environment for every node process — how scenarios arm the
    # TENDERMINT_RPC_* / TENDERMINT_MEMPOOL_LANE_* overload knobs
    # (rpc/admission.py, mempool lanes) without touching config.toml
    extra_env: dict = field(default_factory=dict)

    def resolved_topology(self) -> str:
        if self.topology:
            return self.topology
        return "full" if self.n <= FULL_MESH_MAX else "ring"

    def p2p_port(self, i: int) -> int:
        return self.base_port + 2 * i

    def rpc_port(self, i: int) -> int:
        return self.base_port + 2 * i + 1

    def home(self, i: int) -> str:
        return os.path.join(self.root, f"node{i}")

    def dial_edges(self) -> list[tuple[int, int]]:
        """The directed dial edges (i dials j) of the topology. One
        direction per pair everywhere — inbound/outbound dedup never
        races, exactly the netchaos invariant."""
        topo = self.resolved_topology()
        n = self.n
        if topo == "full":
            return [(i, j) for i in range(n) for j in range(i)]
        if topo == "star":
            return [(i, 0) for i in range(1, n)]
        if topo == "ring":
            k = max(1, min(self.ring_k, n - 1))
            edges = set()
            for i in range(n):
                for d in range(1, k + 1):
                    j = (i + d) % n
                    if (j, i) not in edges and i != j:
                        edges.add((i, j))
            return sorted(edges)
        raise ValueError(
            f"unknown topology {topo!r}; known: full, ring, star"
        )

    def consensus_timeouts(self) -> dict:
        """The schedule baked into every config.toml: sized for N
        Python processes sharing this box's cores, floored for the WAN
        profile when one is armed (the netchaos _WAN_TIMEOUT_FLOOR
        lesson, applied at generation time because processes can't be
        poked live)."""
        cores = os.cpu_count() or 1
        # how oversubscribed the box is: 50 processes on 1 core need
        # ~their whole schedule stretched; 4 on 8 cores need nothing
        crowd = max(1.0, self.n / max(cores, 1) / 4.0)
        t = {
            "timeout_propose": 0.5 * crowd,
            "timeout_propose_delta": 0.25,
            "timeout_prevote": 0.1 * crowd,
            "timeout_prevote_delta": 0.1,
            "timeout_precommit": 0.1 * crowd,
            "timeout_precommit_delta": 0.1,
            "timeout_commit": self.timeout_commit,
        }
        heavy = self.wan and wan_profile(self.wan).name != "lan"
        if heavy or self.geo > 0:
            floors = {
                "timeout_propose": 1.0, "timeout_propose_delta": 0.25,
                "timeout_prevote": 0.4, "timeout_prevote_delta": 0.2,
                "timeout_precommit": 0.4, "timeout_precommit_delta": 0.2,
            }
            for k, floor in floors.items():
                t[k] = max(t[k], floor)
        return t


class LocalNode:
    """One node process of the fleet. RPC/metrics via loopback HTTP —
    the same surface ops/fleet scrapes."""

    def __init__(self, spec: LocalnetSpec, index: int):
        self.spec = spec
        self.index = index
        self.home = spec.home(index)
        self.p2p_port = spec.p2p_port(index)
        self.rpc_port = spec.rpc_port(index)
        self.proc: subprocess.Popen | None = None

    @property
    def rpc_url(self) -> str:
        return f"127.0.0.1:{self.rpc_port}"

    def start(self, seeds: str = "") -> None:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.setdefault("TENDERMINT_TPU_DISABLE", "1")
        # never probe a live devd daemon from a fleet member: 50 nodes
        # hammering one accelerator socket is not this tier's scenario
        env.setdefault("TENDERMINT_DEVD_SOCK", "/nonexistent/devd.sock")
        # tight reconnect cadence (the netchaos value): a healed
        # partition must re-peer in ~a second, and a rolling restart's
        # peers must survive the whole outage window
        env.setdefault("TENDERMINT_P2P_RECONNECT_INTERVAL_S", "0.5")
        env.setdefault("TENDERMINT_P2P_RECONNECT_ATTEMPTS", "600")
        if self.spec.pex:
            # whole discovery->dial->evict cycles inside a scenario
            # window (production default is 30 s between ensure rounds)
            env.setdefault("TENDERMINT_PEX_ENSURE_PERIOD_S", "2")
        env.update({k: str(v) for k, v in self.spec.extra_env.items()})
        env["PYTHONPATH"] = REPO
        cmd = [
            sys.executable, "-m", "tendermint_tpu.cli",
            "--home", self.home, "node",
            "--p2p.laddr", f"tcp://127.0.0.1:{self.p2p_port}",
            "--rpc.laddr", f"tcp://127.0.0.1:{self.rpc_port}",
            "--p2p.addr_book_strict", "false",
            "--log_level", self.spec.log_level,
        ]
        if seeds:
            cmd += ["--seeds", seeds]
        cmd += list(self.spec.extra_args)
        self.proc = subprocess.Popen(
            cmd, cwd=REPO, env=env,
            stdout=open(os.path.join(self.home, "node.log"), "ab"),
            stderr=subprocess.STDOUT,
        )

    def rpc(self, method: str, params: dict | None = None,
            timeout: float = 10.0):
        body = json.dumps({
            "jsonrpc": "2.0", "id": "localnet", "method": method,
            "params": params or {},
        }).encode()
        req = urllib.request.Request(
            f"http://{self.rpc_url}/", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
        if out.get("error"):
            raise RuntimeError(f"node{self.index} {method}: {out['error']}")
        return out["result"]

    def height(self) -> int:
        try:
            return int(self.rpc("status", timeout=5)["latest_block_height"])
        except Exception:  # noqa: BLE001 — down/starting counts as -1
            return -1

    def metrics_height(self) -> int:
        """Height via GET /metrics — the admission-exempt ops surface
        (rpc/admission "ops" kind), so it reads true even while this
        node's RPC ingress is rate-limiting or shedding reads."""
        try:
            m = self.metrics()
            return int(fleet.metric_value(m, "consensus_height",
                                          default=-1) or -1)
        except Exception:  # noqa: BLE001
            return -1

    def flight_events(self, kind: str | None = None) -> list[dict]:
        """The flight-recorder ring via GET /debug/flight (ops-exempt)."""
        with urllib.request.urlopen(
            f"http://{self.rpc_url}/debug/flight", timeout=10
        ) as resp:
            events = json.loads(resp.read()).get("events", [])
        if kind is not None:
            events = [e for e in events if e.get("kind") == kind]
        return events

    def metrics(self) -> dict:
        return fleet.fetch_metrics(self.rpc_url)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self, sig=signal.SIGTERM) -> None:
        if self.proc is None:
            return
        try:
            self.proc.send_signal(sig)
            self.proc.wait(timeout=15)
        except Exception:  # noqa: BLE001 — a wedged shutdown escalates:
            # dropping the handle would orphan a process on bound ports
            try:
                self.proc.kill()
                self.proc.wait(timeout=15)
            except Exception:  # noqa: BLE001
                pass
        self.proc = None


class ReplicaProc:
    """One verified read-replica process (round 24, `cli replica`,
    docs/serving.md § Read replicas) following an upstream node's RPC.
    The replica_flood scenario scales these out in front of node 0 and
    points the read flood at them instead of the validator."""

    def __init__(self, home: str, upstream: str, rpc_port: int,
                 extra_env: dict | None = None):
        self.home = home
        self.upstream = upstream
        self.rpc_port = rpc_port
        self.extra_env = dict(extra_env or {})
        self.proc: subprocess.Popen | None = None

    @property
    def rpc_url(self) -> str:
        return f"127.0.0.1:{self.rpc_port}"

    def start(self) -> None:
        os.makedirs(self.home, exist_ok=True)
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.setdefault("TENDERMINT_TPU_DISABLE", "1")
        env.setdefault("TENDERMINT_DEVD_SOCK", "/nonexistent/devd.sock")
        env.update({k: str(v) for k, v in self.extra_env.items()})
        env["PYTHONPATH"] = REPO
        cmd = [
            sys.executable, "-m", "tendermint_tpu.cli",
            "--home", self.home, "replica",
            "--upstream", self.upstream,
            "--rpc.laddr", f"tcp://127.0.0.1:{self.rpc_port}",
            "--log_level", "error",
        ]
        self.proc = subprocess.Popen(
            cmd, cwd=REPO, env=env,
            stdout=open(os.path.join(self.home, "replica.log"), "ab"),
            stderr=subprocess.STDOUT,
        )

    def rpc(self, method: str, params: dict | None = None,
            timeout: float = 10.0):
        body = json.dumps({
            "jsonrpc": "2.0", "id": "localnet", "method": method,
            "params": params or {},
        }).encode()
        req = urllib.request.Request(
            f"http://{self.rpc_url}/", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
        if out.get("error"):
            raise RuntimeError(f"replica:{self.rpc_port} {method}: "
                               f"{out['error']}")
        return out["result"]

    def metrics(self) -> dict:
        return fleet.fetch_metrics(self.rpc_url)

    def lag(self) -> int:
        """replica_lag_heights off /status; -1 while down/warming.
        Raises if the process EXITED: a dead replica must never read
        as merely-warming — a zombie from a prior run squatting the
        port would answer /status in its place and the caller's wait
        loop would bind the flood to stale state."""
        if self.proc is not None and self.proc.poll() is not None:
            tail = b""
            try:
                with open(os.path.join(self.home, "replica.log"), "rb") as f:
                    tail = f.read()[-400:]
            except OSError:
                pass
            raise RuntimeError(
                f"replica :{self.rpc_port} exited "
                f"rc={self.proc.returncode}: ...{tail.decode(errors='replace')}"
            )
        try:
            st = self.rpc("status", timeout=5)
            if not st.get("replica", {}).get("connected"):
                return -1
            if int(st.get("latest_block_height") or 0) < 2:
                return -1
            return int(st["replica_lag_heights"])
        except Exception:  # noqa: BLE001 — down/starting counts as -1
            return -1

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self, sig=signal.SIGTERM) -> None:
        if self.proc is None:
            return
        try:
            self.proc.send_signal(sig)
            self.proc.wait(timeout=15)
        except Exception:  # noqa: BLE001 — escalate a wedged shutdown
            try:
                self.proc.kill()
                self.proc.wait(timeout=15)
            except Exception:  # noqa: BLE001
                pass
        self.proc = None


class Localnet:
    """The process fleet: generate -> start -> drive/chaos -> read."""

    def __init__(self, spec: LocalnetSpec):
        if not spec.root:
            raise ValueError("LocalnetSpec.root is required")
        self.spec = spec
        self.fabric = NetFabric(
            name=f"localnet-{os.path.basename(spec.root)}"
        )
        self.nodes = [LocalNode(spec, i) for i in range(spec.n)]
        self._edges = spec.dial_edges()

    # -- generation ---------------------------------------------------------

    def generate(self) -> "Localnet":
        """N homes + shared genesis + per-home config.toml, all from
        the spec. Keys are seeded from (chain_id, seed, index) so two
        runs of the same spec produce the same validator set."""
        from tendermint_tpu.config import load_config
        from tendermint_tpu.config.toml import config_to_toml, ensure_root
        from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
        from tendermint_tpu.types import (
            GenesisDoc,
            GenesisValidator,
            PrivValidatorFS,
        )

        spec = self.spec
        os.makedirs(spec.root, exist_ok=True)
        pvs = []
        for i in range(spec.n):
            ensure_root(spec.home(i))
            pv = PrivValidatorFS(
                gen_priv_key_ed25519(
                    f"{spec.chain_id}-{spec.seed}-val-{i}".encode()
                ),
                None,
            )
            pvs.append(pv)
        genesis = GenesisDoc(
            genesis_time_ns=time.time_ns(),
            chain_id=spec.chain_id,
            validators=[
                GenesisValidator(pv.get_pub_key(), 10, f"node{i}")
                for i, pv in enumerate(pvs)
            ],
            commit_format=spec.commit_format,
            upgrade_height=spec.upgrade_height,
            upgrade_format=spec.upgrade_format if spec.upgrade_height else "",
        )
        genesis.validate_and_complete()
        timeouts = spec.consensus_timeouts()
        for i, pv in enumerate(pvs):
            home = spec.home(i)
            cfg = load_config(home)
            cfg.base.chain_id = spec.chain_id
            cfg.base.moniker = f"node{i}"
            cfg.base.proxy_app = spec.proxy_app
            cfg.base.db_backend = spec.db_backend
            cfg.base.tx_index = spec.tx_index
            cfg.consensus.gossip_dedup = spec.gossip_dedup
            cfg.p2p.pex_reactor = spec.pex
            for k, v in timeouts.items():
                setattr(cfg.consensus, k, v)
            cfg.consensus.skip_timeout_commit = False
            if spec.max_block_txs:
                cfg.consensus.max_block_size_txs = spec.max_block_txs
            with open(os.path.join(home, "config.toml"), "w") as f:
                f.write(config_to_toml(cfg))
            pv.file_path = cfg.base.priv_validator_file()
            pv.save()
            genesis.save_as(cfg.base.genesis_file())
        return self

    def seed_addr_book(self, idx: int, addrs: list[str]) -> int:
        """Pre-seed node idx's on-disk address book (before start: the
        node loads it at boot). Entries are "ip:port" strings; each is
        written as a new-bucket address sourced from itself — exactly
        what a PEX flood of hearsay addresses leaves behind. Returns
        entries written."""
        import hashlib as _hashlib

        from tendermint_tpu.config import load_config

        cfg = load_config(self.spec.home(idx))
        path = cfg.p2p.addr_book()
        entries = [
            {"addr": a, "src": a, "attempts": 0, "bucket_type": "new"}
            for a in addrs
        ]
        # deterministic per-node bucket salt: two runs of one spec place
        # the same addresses in the same buckets
        key = _hashlib.sha256(
            f"{self.spec.chain_id}-{self.spec.seed}-book-{idx}".encode()
        ).hexdigest()[:48]
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"key": key, "addrs": entries}, f)
        return len(entries)

    # -- lifecycle ----------------------------------------------------------

    def _seeds_for(self, i: int) -> str:
        """Node i's seed list: one LinkProxy laddr per outgoing dial
        edge (created on first use, reused across restarts so armed
        chaos — WAN shaping, delays — rides through)."""
        seeds = []
        for (a, b) in self._edges:
            if a != i:
                continue
            link = self.fabric.link(a, b)
            if link is None:
                link = self.fabric.add_link(
                    a, b, ("127.0.0.1", self.spec.p2p_port(b))
                )
            seeds.append(link.laddr)
        return ",".join(seeds)

    def start(self) -> "Localnet":
        for node in self.nodes:
            node.start(seeds=self._seeds_for(node.index))
        if self.spec.geo > 0:
            self.apply_geo(self.spec.geo)
        elif self.spec.wan:
            self.apply_wan(self.spec.wan)
        return self

    def restart_node(self, idx: int, sig=signal.SIGKILL) -> None:
        """Kill node idx (SIGKILL by default — the crash arm; pass
        SIGTERM for a graceful roll) and boot it again on the SAME
        ports and home. Its links drop live connections so peers see a
        dead node immediately; their persistent reconnect loops re-peer
        through the same proxies once it's back."""
        node = self.nodes[idx]
        node.kill(sig)
        for link in self.fabric.links_of(idx):
            link.drop_all()
        node.start(seeds=self._seeds_for(idx))

    def stop(self, keep_root: bool = False) -> None:
        for node in self.nodes:
            node.kill(signal.SIGTERM)
        self.fabric.stop()
        if not keep_root:
            shutil.rmtree(self.spec.root, ignore_errors=True)

    # -- chaos verbs (the netchaos vocabulary, process tier) ----------------

    def partition(self, group_a) -> None:
        self.fabric.partition_groups(set(group_a))

    def heal(self) -> None:
        self.fabric.heal_all()

    def apply_wan(self, profile, seed: int | None = None) -> None:
        self.fabric.apply_wan(
            profile, seed=self.spec.seed if seed is None else seed
        )

    def apply_geo(self, k: int, intra="lan", inter=None,
                  seed: int | None = None) -> list[list[int]]:
        clusters = geo_clusters(self.spec.n, k)
        self.fabric.apply_geo(
            clusters, intra=intra,
            inter=inter or (self.spec.wan or "intercontinental"),
            seed=self.spec.seed if seed is None else seed,
        )
        return clusters

    def clear_wan(self) -> None:
        self.fabric.clear_wan()

    # -- readout (the public scrape surface only) ---------------------------

    def fleet_urls(self, nodes: list[int] | None = None) -> list[str]:
        idxs = nodes if nodes is not None else range(len(self.nodes))
        return [self.nodes[i].rpc_url for i in idxs]

    def heights(self) -> list[int]:
        return [n.height() for n in self.nodes]

    def wait_height(self, h: int, timeout: float = 180.0,
                    nodes: list[int] | None = None) -> bool:
        idxs = list(nodes if nodes is not None else range(len(self.nodes)))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(self.nodes[i].height() >= h for i in idxs):
                return True
            time.sleep(0.5)
        return all(self.nodes[i].height() >= h for i in idxs)

    def timeline(self, last: int = 10, nodes: list[int] | None = None):
        """ops/fleet cross-node height rows (propagation lag, quorum
        formation, commit skew) off live scrapes."""
        snapshot = fleet.collect(self.fleet_urls(nodes), last=last)
        return fleet.build_timeline(
            {u: e.get("traces", []) for u, e in snapshot.items()}, last=last
        )

    def scrape_totals(self, names: list[str],
                      nodes: list[int] | None = None) -> dict:
        """Sum each metric across the fleet (label series summed per
        node by fleet.metric_value). A dead node contributes nothing."""
        out = {name: 0.0 for name in names}
        for url in self.fleet_urls(nodes):
            try:
                m = fleet.fetch_metrics(url)
            except Exception:  # noqa: BLE001 — partial fleets still read
                continue
            for name in names:
                out[name] += fleet.metric_value(m, name, default=0) or 0
        return out

    def duplicate_vote_ratio(self, nodes: list[int] | None = None) -> float:
        """The redundancy number this round engineers down: fleet-wide
        duplicate votes per accepted vote (PR-17/20 counters; the
        2N*N-redundancy literature's measurable)."""
        t = self.scrape_totals(
            ["consensus_vote_duplicates", "consensus_vote_accepted"], nodes
        )
        accepted = t["consensus_vote_accepted"]
        return (t["consensus_vote_duplicates"] / accepted) if accepted else 0.0

    def gossip_bytes(self, nodes: list[int] | None = None) -> float:
        """Fleet-total p2p bytes written (all channels)."""
        return self.scrape_totals(
            ["p2p_peer_send_bytes_total"], nodes
        )["p2p_peer_send_bytes_total"]

    # -- convergence --------------------------------------------------------

    def fingerprint(self, idx: int, height: int) -> tuple:
        """(block hash, part-set root, app hash) at `height` via RPC —
        the byte-identity surface, read as an operator would."""
        res = self.nodes[idx].rpc("block", {"height": height})
        meta, block = res["block_meta"], res["block"]
        return (
            meta["block_id"]["hash"],
            meta["block_id"]["parts"]["hash"],
            block["header"]["app_hash"],
        )

    def last_commit_is_aggregate(self, idx: int, height: int) -> bool:
        """Wire-format probe: does the block at `height` carry an
        aggregate last-commit? Read off the public RPC block JSON (the
        "s_agg" key is the aggregate's signature scalar — full commits
        have "precommits" instead), the same way an operator would
        confirm the cutover actually happened on the wire."""
        res = self.nodes[idx].rpc("block", {"height": height})
        lc = (res["block"] or {}).get("last_commit") or {}
        return "s_agg" in lc

    def assert_converged(self, upto: int, from_height: int = 1,
                         nodes: list[int] | None = None) -> int:
        """Per-height byte identity across `nodes` for every height in
        [from_height, upto]. Returns heights compared."""
        idxs = list(nodes if nodes is not None else range(len(self.nodes)))
        compared = 0
        for h in range(from_height, upto + 1):
            prints = {i: self.fingerprint(i, h) for i in idxs}
            distinct = set(prints.values())
            assert len(distinct) == 1, (
                f"fleet diverges at height {h}: {prints}"
            )
            compared += 1
        return compared


# -- overload scenario helpers ------------------------------------------------

# knobs the overload scenario arms on every node (spec.extra_env wins):
# a per-IP rate limit the flood address must trip, a tiny WS send queue
# with fast eviction, a bulk lane small enough to fill inside the
# scenario window, and a request deadline so no handler wait outlives
# the flood
OVERLOAD_ENV_DEFAULTS = {
    "TENDERMINT_RPC_RATE_LIMIT": "40",
    "TENDERMINT_RPC_RATE_BURST": "80",
    "TENDERMINT_RPC_WS_QUEUE": "8",
    "TENDERMINT_RPC_WS_MAX_OVERFLOWS": "2",
    "TENDERMINT_RPC_WS_SNDBUF": "8192",
    "TENDERMINT_RPC_DEADLINE_S": "10",
    "TENDERMINT_MEMPOOL_LANE_BULK_MAX_TXS": "150",
}
# distinct loopback source addresses: the per-IP token buckets throttle
# each flood plane separately, and neither touches the 127.0.0.1
# control-plane bucket the scenario driver uses
OVERLOAD_WRITE_IP = "127.0.0.2"
OVERLOAD_READ_IP = "127.0.0.3"


def _flood_loop(port: int, method: str, make_params, stop, statuses: dict,
                source_ip: str) -> None:
    """One flood client pinned to `source_ip` via source_address.
    Typed sheds (429/503) are the scenario working, not failures:
    each HTTP status is tallied and the loop keeps pressing."""
    import http.client

    conn = None
    i = 0
    while not stop.is_set():
        i += 1
        try:
            if conn is None:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=5,
                    source_address=(source_ip, 0))
            body = json.dumps({
                "jsonrpc": "2.0", "id": i, "method": method,
                "params": make_params(i),
            }).encode()
            conn.request("POST", "/", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            statuses[resp.status] = statuses.get(resp.status, 0) + 1
        except Exception:  # noqa: BLE001 — refused/dropped connections
            # under load are expected; reconnect and keep the pressure on
            statuses["err"] = statuses.get("err", 0) + 1
            try:
                if conn is not None:
                    conn.close()
            except Exception:  # noqa: BLE001
                pass
            conn = None


def _slow_ws_subscribe(port: int):
    """A deliberately-slow NewBlock subscriber: tiny receive buffer,
    subscribes, then never reads a byte again. The server's bounded
    send queue must absorb, drop-oldest, and finally evict it — without
    the event bus ever blocking on this socket."""
    import base64
    import socket

    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    s.settimeout(10.0)
    s.connect(("127.0.0.1", port))
    key = base64.b64encode(os.urandom(16)).decode()
    s.sendall((
        f"GET /websocket HTTP/1.1\r\nHost: 127.0.0.1:{port}\r\n"
        "Upgrade: websocket\r\nConnection: Upgrade\r\n"
        f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
    ).encode())
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = s.recv(4096)
        if not chunk:
            raise ConnectionError("ws handshake failed")
        buf += chunk
    if b"101" not in buf.split(b"\r\n", 1)[0]:
        raise ConnectionError(f"ws handshake rejected: {buf[:120]!r}")
    payload = json.dumps({
        "jsonrpc": "2.0", "id": 1, "method": "subscribe",
        "params": {"event": "NewBlock"},
    }).encode()
    mask = os.urandom(4)
    frame = bytearray([0x81, 0x80 | len(payload)]) + mask + bytes(
        c ^ mask[i % 4] for i, c in enumerate(payload))
    s.sendall(bytes(frame))
    return s


# -- the scenario matrix ------------------------------------------------------


def run_scenario(spec: LocalnetSpec, scenario: str = "converge",
                 heights: int = 5, keep_root: bool = False) -> dict:
    """One named netchaos-style scenario against a process fleet.

    converge        — boot, reach `heights`, assert per-height byte
                      identity across ALL nodes (under the spec's WAN /
                      geo shaping, if any)
    partition_heal  — converge, sever a 1/3 minority, prove the 2/3
                      majority keeps committing while the minority is
                      frozen, heal, prove the minority catches up and
                      the whole fleet is byte-identical
    rolling_restart — converge, SIGKILL-and-restart a third of the
                      fleet one node at a time, prove each rejoins and
                      the fleet converges byte-identically
    upgrade         — rolling-upgrade a live net across the genesis
                      commit-format flip (docs/upgrade.md): converge
                      below upgrade_height H, SIGKILL a laggard BEFORE
                      the flip, prove the rest cross H without missing
                      a height, roll one survivor through the boundary,
                      restart the laggard and prove it catches up
                      THROUGH both formats; per-height byte identity on
                      both sides of H; upgrade_* scrape asserts (the
                      flip is visible on the public surface)
    pex_churn       — star + PEX: pre-seed every spoke's address book
                      with ~500 hearsay addresses dominated by one
                      subnet, run real discovery dials, prove the book
                      contains the domination (max_group bounded by
                      bucket hashing), evicts under pressure, and the
                      real net stays peered and committing
    overload        — the round-23 overload-control proof: measure the
                      unloaded cadence, then flood node 0 with bulk
                      writes + hot reads from throttled source IPs and
                      two deliberately-slow WS subscribers, while
                      asserting consensus cadence stays within 1.5x the
                      baseline, sheds are scrape-visible
                      (rpc_shed_total / mempool_lane_full_total /
                      ws_evictions_total), a priority probe tx commits
                      AHEAD of a bulk marker submitted before it, the
                      ladder transition landed in the flight ring, and
                      per-height byte identity holds across the fleet
    replica_flood   — the round-24 read-replica proof: boot verified
                      replica processes behind node 0, point a hot
                      verified-read flood + WS subscribers at THEM, and
                      assert the validator's commit cadence stays flat,
                      replica-served blocks are byte-identical to the
                      validator's, the replica_* scrape rows move, and
                      a TENDERMINT_REPLICA_TAMPER replica is rejected
                      by 100% of verifying clients

    Returns a flat JSON-able result row (heights/s, duplicate-vote
    ratio, fleet bytes — the bench's raw material)."""
    if scenario == "upgrade" and spec.upgrade_height == 0:
        # default flip far enough in that the net demonstrably runs the
        # old format first, near enough that the scenario stays short
        spec.upgrade_height = max(4, heights)
    if scenario == "pex_churn":
        spec.topology = spec.topology or "star"
        spec.pex = True
    if scenario == "overload":
        # small blocks so the bulk backlog spans several heights (the
        # priority-ordering proof needs the marker to wait its turn)
        spec.max_block_txs = spec.max_block_txs or 10
        for k, v in OVERLOAD_ENV_DEFAULTS.items():
            spec.extra_env.setdefault(k, v)
    net = Localnet(spec)
    try:
        net.generate()
        injected = 0
        if scenario == "pex_churn":
            # one deterministic hearsay set, the same on every node (so
            # hub-side gossip re-adds known keys and bucket pressure is
            # real): ~420 addresses inside ONE dominating subnet, ~80
            # spread across distinct groups. 127.x.y.z is all loopback
            # on Linux — dials fail instantly (refused), which is what
            # drives is_bad/eviction inside the scenario window.
            dominated = [f"127.66.6.{i}:26656" for i in range(1, 251)]
            dominated += [f"127.66.7.{i}:26656" for i in range(1, 171)]
            spread = [f"127.{70 + i}.1.1:26656" for i in range(80)]
            for i in range(spec.n):
                injected = net.seed_addr_book(i, dominated + spread)
        t0 = time.monotonic()
        net.start()
        if not net.wait_height(1, timeout=180.0):
            raise AssertionError(
                f"fleet never reached height 1: {net.heights()}"
            )
        result: dict = {
            "scenario": scenario,
            "n": spec.n,
            "topology": spec.resolved_topology(),
            "wan": spec.wan or None,
            "geo": spec.geo or None,
            "gossip_dedup": spec.gossip_dedup,
        }
        if scenario == "converge":
            ok = net.wait_height(heights, timeout=60.0 * heights)
            assert ok, f"no convergence at {heights}: {net.heights()}"
            elapsed = time.monotonic() - t0
            result["heights"] = heights
            result["heights_per_s"] = heights / elapsed
            result["converged_heights"] = net.assert_converged(heights)
        elif scenario == "partition_heal":
            assert spec.n >= 4, "partition_heal needs n >= 4"
            ok = net.wait_height(heights, timeout=60.0 * heights)
            assert ok, f"no convergence at {heights}: {net.heights()}"
            minority = list(range(spec.n // 3))
            majority = [i for i in range(spec.n) if i not in minority]
            net.partition(minority)
            h0 = max(net.heights())
            ok = net.wait_height(h0 + 3, timeout=120.0, nodes=majority)
            assert ok, (
                f"majority stalled during partition: {net.heights()}"
            )
            frozen = [net.nodes[i].height() for i in minority]
            net.heal()
            target = max(net.heights()) + 2
            ok = net.wait_height(target, timeout=180.0)
            assert ok, f"minority never healed: {net.heights()}"
            result["heights"] = target
            result["minority_frozen_at"] = frozen
            result["converged_heights"] = net.assert_converged(target)
        elif scenario == "rolling_restart":
            ok = net.wait_height(heights, timeout=60.0 * heights)
            assert ok, f"no convergence at {heights}: {net.heights()}"
            victims = list(range(max(1, spec.n // 3)))
            for idx in victims:
                net.restart_node(idx)
                back = net.wait_height(
                    max(net.heights()) + 1, timeout=180.0, nodes=[idx]
                )
                assert back, f"node{idx} never rejoined: {net.heights()}"
            target = max(net.heights())
            ok = net.wait_height(target, timeout=120.0)
            assert ok, f"fleet lost a node after the roll: {net.heights()}"
            result["heights"] = target
            result["restarted"] = victims
            result["converged_heights"] = net.assert_converged(target)
        elif scenario == "upgrade":
            assert spec.n >= 4, "upgrade needs n >= 4 (laggard down at flip)"
            H = spec.upgrade_height
            assert H >= 2, "upgrade scenario needs a scheduled flip"
            # converge on the OLD format first
            ok = net.wait_height(max(2, H - 2), timeout=60.0 * H)
            assert ok, f"no pre-flip convergence: {net.heights()}"
            # the laggard goes down BEFORE the flip and sleeps through it
            laggard = spec.n - 1
            killed_at = net.nodes[laggard].height()
            assert killed_at < H, (
                f"laggard already past the flip ({killed_at} >= {H}); "
                "raise upgrade_height"
            )
            net.nodes[laggard].kill(signal.SIGKILL)
            survivors = [i for i in range(spec.n) if i != laggard]
            # the live net crosses H without missing a height: +2/3 of
            # the validator set keeps committing straight through the
            # format boundary (this wait stalling IS a missed height)
            ok = net.wait_height(H + 2, timeout=120.0 + 30.0 * H,
                                 nodes=survivors)
            assert ok, f"net stalled at the flip: {net.heights()}"
            # roll one survivor across the boundary (the rolling-upgrade
            # arm proper: its WAL replay spans both formats)
            net.restart_node(survivors[0])
            ok = net.wait_height(
                max(net.nodes[i].height() for i in survivors[1:]) + 1,
                timeout=180.0, nodes=[survivors[0]],
            )
            assert ok, f"rolled node never rejoined: {net.heights()}"
            # the laggard wakes up post-flip and catches up THROUGH both
            # formats (full blocks below H, aggregate from H on)
            net.restart_node(laggard)
            target = max(
                h for h in net.heights() if h >= 0
            ) + 2
            ok = net.wait_height(target, timeout=240.0)
            assert ok, f"laggard never caught up: {net.heights()}"
            # byte identity on BOTH sides of the boundary, every node
            result["converged_heights"] = net.assert_converged(target)
            # wire-format proof off the public RPC: the block AT the
            # flip carries an aggregate last-commit, the one below it a
            # full one — on the laggard, which fetched both via catchup
            if H >= 3:
                assert not net.last_commit_is_aggregate(laggard, H - 1), (
                    f"height {H - 1} (below flip) carries an aggregate"
                )
            assert net.last_commit_is_aggregate(laggard, H), (
                f"height {H} (at flip) does not carry an aggregate"
            )
            totals = net.scrape_totals([
                "upgrade_agg_commits_proposed", "upgrade_active",
                "upgrade_agg_commit_rejects",
                "p2p_adversary_schedule_refused",
            ])
            assert totals["upgrade_agg_commits_proposed"] >= 1, (
                f"no proposer ever built an aggregate: {totals}"
            )
            assert totals["upgrade_active"] == spec.n, (
                f"some node does not report the flip active: {totals}"
            )
            assert totals["p2p_adversary_schedule_refused"] == 0, (
                f"schedule refusals inside a homogeneous net: {totals}"
            )
            result["upgrade_height"] = H
            result["heights"] = target
            result["laggard"] = laggard
            result["laggard_killed_at"] = killed_at
            result["agg_commits_proposed"] = int(
                totals["upgrade_agg_commits_proposed"]
            )
            result["agg_commit_rejects"] = int(
                totals["upgrade_agg_commit_rejects"]
            )
        elif scenario == "pex_churn":
            # the real net must form and commit THROUGH the churn
            ok = net.wait_height(2, timeout=120.0)
            assert ok, f"star net never formed: {net.heights()}"
            # loading the dominated book already evicted down to the
            # group's bucket capacity; the RUNTIME proof is that real
            # discovery (failed dials + gossip re-offers) keeps the
            # churn going — evictions must GROW past the boot baseline
            base = net.scrape_totals(["p2p_addrbook_evictions"])[
                "p2p_addrbook_evictions"]
            deadline = time.monotonic() + 90.0
            evictions = base
            while time.monotonic() < deadline:
                evictions = net.scrape_totals(["p2p_addrbook_evictions"])[
                    "p2p_addrbook_evictions"]
                if evictions > base:
                    break
                time.sleep(2.0)
            assert evictions > base, (
                f"no address-book eviction under live churn "
                f"(boot baseline {base})"
            )
            # domination containment: bucket hashing caps any one group
            # at NEW_BUCKETS_PER_ADDRESS * BUCKET_SIZE bucket slots, so
            # no book is owned by the flooding subnet
            sizes, max_groups = [], []
            for node in net.nodes:
                m = node.metrics()
                sizes.append(fleet.metric_value(m, "p2p_addrbook_size",
                                                default=0) or 0)
                mg = fleet.metric_value(m, "p2p_addrbook_max_group",
                                        default=0) or 0
                max_groups.append(mg)
                assert mg <= 256, (
                    f"node{node.index} book dominated: max_group={mg}"
                )
            # and the net is still alive: commits advanced during churn
            h0 = max(net.heights())
            ok = net.wait_height(h0 + 2, timeout=120.0)
            assert ok, f"net stalled under address churn: {net.heights()}"
            result["heights"] = h0 + 2
            result["addrs_injected"] = injected
            result["book_sizes"] = [int(s) for s in sizes]
            result["book_max_groups"] = [int(g) for g in max_groups]
            result["book_evictions"] = int(evictions)
        elif scenario == "overload":
            assert spec.n >= 2, "overload needs n >= 2 (byte identity)"
            target_node = net.nodes[0]
            # unloaded baseline cadence, measured AFTER boot settles so
            # genesis/dial time doesn't pollute the denominator
            ok = net.wait_height(2, timeout=120.0)
            assert ok, f"net never settled: {net.heights()}"
            b0 = target_node.metrics_height()
            t_b = time.monotonic()
            ok = net.wait_height(b0 + heights, timeout=60.0 * heights)
            assert ok, f"no unloaded convergence: {net.heights()}"
            baseline_hps = heights / (time.monotonic() - t_b)
            port = target_node.rpc_port
            stop = threading.Event()
            write_stats = [{} for _ in range(4)]
            read_stats = [{} for _ in range(4)]
            floods = [
                threading.Thread(
                    target=_flood_loop, daemon=True,
                    args=(port, "broadcast_tx_async",
                          lambda i, w=w: {
                              "tx": f"bulk:f{w}-{i}=x".encode().hex()},
                          stop, write_stats[w], OVERLOAD_WRITE_IP),
                ) for w in range(4)
            ] + [
                threading.Thread(
                    target=_flood_loop, daemon=True,
                    args=(port, "status", lambda i: {}, stop, st,
                          OVERLOAD_READ_IP),
                ) for st in read_stats
            ]
            slow_socks: list = []
            try:
                for th in floods:
                    th.start()
                # phase 1 — build a multi-block bulk backlog, read off
                # the scrape surface (ops-exempt even under the flood)
                want = 5 * spec.max_block_txs
                deadline = time.monotonic() + 90.0
                depth = 0
                while time.monotonic() < deadline:
                    depth = fleet.metric_value(
                        target_node.metrics(), "mempool_lane_bulk_size",
                        default=0) or 0
                    if depth >= want:
                        break
                    time.sleep(0.25)
                assert depth >= want, (
                    f"bulk backlog never built: {depth} < {want}")
                # ordering probe: bulk marker FIRST (behind the
                # backlog), priority probe SECOND — the probe must
                # still commit at a strictly lower height. Retries
                # because the driver shares node-side pressure sheds.
                marker_hash = ""
                deadline = time.monotonic() + 60.0
                while not marker_hash and time.monotonic() < deadline:
                    try:
                        marker_hash = target_node.rpc(
                            "broadcast_tx_async",
                            {"tx": b"bulk:marker=1".hex()})["hash"]
                    except Exception:  # noqa: BLE001 — lane-full/shed
                        time.sleep(0.2)
                assert marker_hash, "bulk marker never admitted"
                probe_hash = ""
                deadline = time.monotonic() + 60.0
                while not probe_hash and time.monotonic() < deadline:
                    try:
                        probe_hash = target_node.rpc(
                            "broadcast_tx_async",
                            {"tx": b"pri:probe=1".hex()})["hash"]
                    except Exception:  # noqa: BLE001
                        time.sleep(0.2)
                assert probe_hash, "priority probe never admitted"
                # phase 2 — add the slow subscribers and measure the
                # loaded cadence over a window long enough for their
                # queues to fill, overflow, and evict
                slow_socks = [_slow_ws_subscribe(port) for _ in range(2)]
                flood_heights = max(heights, 8)
                h0 = target_node.metrics_height()
                t_f = time.monotonic()
                deadline = t_f + 120.0 * flood_heights
                while time.monotonic() < deadline:
                    if target_node.metrics_height() >= h0 + flood_heights:
                        break
                    time.sleep(0.25)
                h1 = target_node.metrics_height()
                assert h1 >= h0 + flood_heights, (
                    f"consensus stalled under flood: {h0} -> {h1}")
                flood_hps = flood_heights / (time.monotonic() - t_f)
                # the slow subscribers must get EVICTED, not merely
                # lag. Their sockets stay OPEN here — closing them
                # would read as dead clients (plain teardown), never
                # as evictions. Empty blocks keep firing NewBlock
                # after the floods stop, so keep scraping (ops-exempt)
                # until the overflow ladder ejects at least one.
                stop.set()
                for th in floods:
                    th.join(timeout=10)
                deadline = time.monotonic() + 120.0
                evictions = 0
                while time.monotonic() < deadline:
                    evictions = net.scrape_totals(["ws_evictions_total"])[
                        "ws_evictions_total"]
                    if evictions >= 1:
                        break
                    time.sleep(1.0)
                assert evictions >= 1, (
                    "no slow-subscriber eviction recorded")
            finally:
                stop.set()
                for th in floods:
                    th.join(timeout=10)
                for s in slow_socks:
                    try:
                        s.close()
                    except OSError:
                        pass
            # the tentpole promise: the ladder shed reads and bulk
            # writes BEFORE it let consensus slow past 1.5x baseline
            assert flood_hps >= baseline_hps / 1.5, (
                f"cadence degraded past 1.5x: {flood_hps:.2f} hps under "
                f"flood vs {baseline_hps:.2f} unloaded")

            def _tx_height(tx_hash: str, what: str) -> int:
                # post-flood the node keeps committing (draining the
                # backlog), so retry until the tx lands; also rides out
                # any residual shed-reads window at the driver's edge
                deadline = time.monotonic() + 180.0
                while time.monotonic() < deadline:
                    try:
                        return int(target_node.rpc(
                            "tx", {"hash": tx_hash})["height"])
                    except Exception:  # noqa: BLE001 — not yet committed
                        time.sleep(0.5)
                raise AssertionError(f"{what} never committed")

            probe_h = _tx_height(probe_hash, "priority probe")
            marker_h = _tx_height(marker_hash, "bulk marker")
            assert probe_h < marker_h, (
                f"priority probe (h{probe_h}) did not beat the bulk "
                f"marker (h{marker_h})")
            # every shed is scrape-visible
            totals = net.scrape_totals([
                "rpc_shed_total", "mempool_lane_full_total",
                "ws_dropped_events_total", "mempool_shed_writes_rejects",
            ])
            assert totals["rpc_shed_total"] > 0, totals
            assert totals["mempool_lane_full_total"] > 0, totals
            assert totals["ws_dropped_events_total"] > 0, totals
            # the ladder transition landed in the flight ring
            overload_events = target_node.flight_events("overload")
            assert overload_events, "no overload event in the flight ring"
            # per-height byte identity through the flood window —
            # lanes reorder WITHIN a block's reap, never across nodes
            target = min(h for h in net.heights() if h >= 0)
            result["converged_heights"] = net.assert_converged(target)
            result["heights"] = target
            result["baseline_heights_per_s"] = round(baseline_hps, 3)
            result["flood_heights_per_s"] = round(flood_hps, 3)
            result["cadence_ratio"] = round(flood_hps / baseline_hps, 3)
            result["probe_height"] = probe_h
            result["marker_height"] = marker_h
            result["rpc_sheds"] = int(totals["rpc_shed_total"])
            result["lane_full_rejects"] = int(
                totals["mempool_lane_full_total"])
            result["shed_writes_rejects"] = int(
                totals["mempool_shed_writes_rejects"])
            result["ws_evictions"] = int(evictions)
            result["ws_dropped_events"] = int(
                totals["ws_dropped_events_total"])
            result["overload_transitions"] = len(overload_events)
            agg: dict = {}
            for st in write_stats + read_stats:
                for k, v in st.items():
                    agg[str(k)] = agg.get(str(k), 0) + v
            result["flood_statuses"] = agg
        elif scenario == "replica_flood":
            # round-24 read-replica proof: verified replicas absorb a
            # hot read flood while the validator's commit cadence stays
            # flat, replica-served blocks are byte-identical to the
            # validator's, and a tampering replica is rejected by every
            # verifying client
            assert spec.n >= 2, "replica_flood needs n >= 2 (byte identity)"
            from tendermint_tpu.rpc.client import HTTPClient, WSClient
            from tendermint_tpu.rpc.light import (
                LightClient,
                LightClientError,
            )

            target_node = net.nodes[0]
            ok = net.wait_height(2, timeout=120.0)
            assert ok, f"net never settled: {net.heights()}"
            # commit content for the replicas to serve before measuring
            keys = [f"rk{i}".encode() for i in range(8)]
            for i, k in enumerate(keys):
                deadline = time.monotonic() + 60.0
                sent = False
                while not sent and time.monotonic() < deadline:
                    try:
                        target_node.rpc("broadcast_tx_async",
                                        {"tx": (k + b"=rv%d" % i).hex()})
                        sent = True
                    except Exception:  # noqa: BLE001 — mempool backoff
                        time.sleep(0.2)
                assert sent, f"seed key {k!r} never admitted"
            # unloaded baseline cadence
            b0 = target_node.metrics_height()
            t_b = time.monotonic()
            ok = net.wait_height(b0 + heights, timeout=60.0 * heights)
            assert ok, f"no unloaded convergence: {net.heights()}"
            baseline_hps = heights / (time.monotonic() - t_b)
            # two honest replicas + one tampering one behind node 0
            rep_base = spec.base_port + 2 * spec.n + 10
            replicas = [
                ReplicaProc(os.path.join(spec.root, f"replica{i}"),
                            target_node.rpc_url, rep_base + i)
                for i in range(2)
            ]
            tamper_rep = ReplicaProc(
                os.path.join(spec.root, "replica-tamper"),
                target_node.rpc_url, rep_base + 2,
                extra_env={"TENDERMINT_REPLICA_TAMPER": "value"},
            )
            procs = replicas + [tamper_rep]
            stop = threading.Event()
            read_stats: list[dict] = [{} for _ in range(4)]
            floods: list[threading.Thread] = []
            subs: list = []
            try:
                for r in procs:
                    r.start()
                for r in procs:
                    deadline = time.monotonic() + 120.0
                    while r.lag() != 0 and time.monotonic() < deadline:
                        time.sleep(0.25)
                    assert r.lag() == 0, (
                        f"replica :{r.rpc_port} never caught up")

                # the read flood lands on the REPLICAS only: verified
                # hot-key reads plus relayed-event subscribers — the
                # validator serves none of it
                def read_params(i, keys=keys):
                    return {"data": keys[i % len(keys)].hex(), "path": "",
                            "height": 0, "prove": True}

                for j, st in enumerate(read_stats):
                    floods.append(threading.Thread(
                        target=_flood_loop, daemon=True,
                        args=(replicas[j % len(replicas)].rpc_port,
                              "abci_query", read_params, stop, st,
                              f"127.0.1.{j + 1}"),
                    ))
                for r in replicas:
                    ws = WSClient(r.rpc_url)
                    ws.subscribe("NewBlock")
                    subs.append(ws)
                for th in floods:
                    th.start()
                # loaded cadence, measured on the validator
                flood_heights = max(heights, 6)
                h0 = target_node.metrics_height()
                t_f = time.monotonic()
                deadline = t_f + 120.0 * flood_heights
                while time.monotonic() < deadline:
                    if target_node.metrics_height() >= h0 + flood_heights:
                        break
                    time.sleep(0.25)
                h1 = target_node.metrics_height()
                assert h1 >= h0 + flood_heights, (
                    f"consensus stalled under replica flood: {h0} -> {h1}")
                flood_hps = flood_heights / (time.monotonic() - t_f)
                # every downstream subscriber rode the relayed stream
                relayed = 0
                for ws in subs:
                    try:
                        ev = ws.next_event(timeout=30.0)
                        hdr = ((ev.get("data") or {}).get("block")
                               or {}).get("header") or {}
                        if hdr.get("height"):
                            relayed += 1
                    except Exception:  # noqa: BLE001 — counted below
                        pass
                assert relayed == len(subs), (
                    f"only {relayed}/{len(subs)} subscribers saw events")
                stop.set()
                for th in floods:
                    th.join(timeout=10)
                # replica scrape surface: reads served off the verified
                # cache, zero proof failures on the honest replicas
                served = hits = 0.0
                for r in replicas:
                    m = r.metrics()
                    assert (fleet.metric_value(
                        m, "replica_height", default=0) or 0) >= 1, (
                        f"replica :{r.rpc_port} reports no height")
                    assert (fleet.metric_value(
                        m, "replica_proof_verify_failures",
                        default=0) or 0) == 0, (
                        f"proof failures on honest replica :{r.rpc_port}")
                    served += fleet.metric_value(
                        m, "replica_served_reads_total", default=0) or 0
                    hits += fleet.metric_value(
                        m, "replica_cache_hits", default=0) or 0
                assert served > 0, "replicas served no reads"
                assert hits > 0, "no proof-cache hits under a hot-key flood"
                # cadence: the validator must not feel the read flood
                assert flood_hps >= baseline_hps / 1.5, (
                    f"cadence degraded past 1.5x behind replicas: "
                    f"{flood_hps:.2f} hps vs {baseline_hps:.2f} unloaded")
                # byte identity: a replica-served block IS the
                # validator's block, byte for byte
                h = max(1, target_node.metrics_height() - 2)
                want = json.dumps(
                    target_node.rpc("block", {"height": h}), sort_keys=True)
                for r in replicas:
                    got = json.dumps(
                        r.rpc("block", {"height": h}), sort_keys=True)
                    assert got == want, (
                        f"replica :{r.rpc_port} serves different bytes "
                        f"at height {h}")
                # tamper probe: a verifying client rejects EVERY read
                # from the lying replica — corruption is detected, not
                # propagated
                lc = LightClient.from_genesis(
                    HTTPClient(tamper_rep.rpc_url))
                probe_keys = keys[:4]
                rejected = 0
                for k in probe_keys:
                    try:
                        lc.verified_query(k)
                    except LightClientError:
                        rejected += 1
                assert rejected == len(probe_keys), (
                    f"tampered replica only rejected "
                    f"{rejected}/{len(probe_keys)} reads")
            finally:
                stop.set()
                for th in floods:
                    th.join(timeout=10)
                for ws in subs:
                    try:
                        ws.close()
                    except Exception:  # noqa: BLE001
                        pass
                for r in procs:
                    r.kill()
            target = min(h for h in net.heights() if h >= 0)
            result["converged_heights"] = net.assert_converged(target)
            result["heights"] = target
            result["replicas"] = len(replicas)
            result["baseline_heights_per_s"] = round(baseline_hps, 3)
            result["flood_heights_per_s"] = round(flood_hps, 3)
            result["cadence_ratio"] = round(flood_hps / baseline_hps, 3)
            result["replica_reads_served"] = int(served)
            result["replica_cache_hits"] = int(hits)
            result["tamper_rejected"] = rejected
            result["tamper_probes"] = len(probe_keys)
            agg: dict = {}
            for st in read_stats:
                for k, v in st.items():
                    agg[str(k)] = agg.get(str(k), 0) + v
            result["flood_statuses"] = agg
        else:
            raise ValueError(
                f"unknown scenario {scenario!r}; known: converge, "
                "partition_heal, rolling_restart, upgrade, pex_churn, "
                "overload, replica_flood"
            )
        result["duplicate_vote_ratio"] = net.duplicate_vote_ratio()
        result["gossip_bytes"] = net.gossip_bytes()
        result["final_heights"] = net.heights()
        return result
    finally:
        net.stop(keep_root=keep_root)


# -- CLI ----------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="N-process localnet: generate homes, boot real CLI "
                    "nodes through netfaults link proxies, run a chaos "
                    "scenario, read convergence off the scrape surface",
    )
    ap.add_argument("--n", type=int, default=10)
    ap.add_argument("--root", default="",
                    help="net root dir (default: a temp dir, removed "
                         "unless --keep)")
    ap.add_argument("--scenario", default="converge",
                    choices=["converge", "partition_heal", "rolling_restart",
                             "upgrade", "pex_churn", "overload",
                             "replica_flood"])
    ap.add_argument("--heights", type=int, default=5)
    ap.add_argument("--topology", default="",
                    choices=["", "full", "ring", "star"])
    ap.add_argument("--ring-k", type=int, default=DEFAULT_RING_K)
    ap.add_argument("--wan", default="",
                    help="netfaults WAN profile (lan, continental, "
                         "intercontinental, lossy-mobile)")
    ap.add_argument("--geo", type=int, default=0,
                    help="geo-cluster count (0 = off)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--base-port", type=int, default=47100)
    ap.add_argument("--upgrade-height", type=int, default=0,
                    help="schedule the commit-format flip at this height "
                         "(upgrade scenario defaults to max(4, --heights))")
    ap.add_argument("--no-dedup", action="store_true",
                    help="boot with gossip_dedup=false (the pre-round-20 "
                         "gossip baseline)")
    ap.add_argument("--keep", action="store_true",
                    help="keep homes + logs after the run")
    args = ap.parse_args(argv)

    import tempfile

    logging.basicConfig(level=logging.INFO)
    root = args.root or tempfile.mkdtemp(prefix="localnet-")
    spec = LocalnetSpec(
        n=args.n, root=root, seed=args.seed, topology=args.topology,
        ring_k=args.ring_k, base_port=args.base_port, wan=args.wan,
        geo=args.geo, gossip_dedup=not args.no_dedup,
        upgrade_height=args.upgrade_height,
    )
    result = run_scenario(
        spec, scenario=args.scenario, heights=args.heights,
        keep_root=args.keep,
    )
    if args.keep:
        result["root"] = root
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
