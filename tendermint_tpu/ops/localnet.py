"""Hundreds-of-nodes localnet tier (round 20, docs/localnet.md).

The netchaos harness (tests/netchaos_common.py) runs N full nodes
IN-PROCESS — perfect for white-box assertions, but every node shares
one interpreter, one GIL, one crash domain. This module is the same
scenario vocabulary one tier up: N real node PROCESSES (the existing
CLI node, `python -m tendermint_tpu.cli node`) on loopback, each with
its own home/keys/DBs/WAL, peered through `ops/netfaults` LinkProxy
relays so the WHOLE chaos vocabulary — partitions, seeded WAN profiles,
geo-cluster topologies, rolling restarts — applies unchanged to a
process fleet. Everything is read back through the public scrape
surface (`ops/fleet`: GET /metrics + /health + consensus_trace), never
by reaching into harness objects: what a scenario asserts here is what
an operator of a real deployment could assert.

One seeded `LocalnetSpec` generates the entire net: N homes under one
root (privval keys derived from `(chain_id, index)`, one shared
genesis, per-home config.toml written through the real TOML round-trip
so the CLI node loads EXACTLY what a production home would carry).
Ports are explicit (`base_port + 2i` p2p, `+2i+1` RPC) — the fabric's
links can be strung before any process exists.

Topology is part of the spec, because a single box cannot carry a
50-node FULL mesh (1225 proxied links ≈ 5k fds and 2.5k relay
threads): `full` (node i dials every j < i — the netchaos shape,
default up to 16 nodes), `ring` (i dials (i+1..i+k) mod n — bounded
degree, diameter n/2k; the default beyond 16), `star` (everyone dials
node 0 — the seeds-node shape). Every directed dial edge gets its own
LinkProxy, so group chaos maps exactly as in the in-process tier.

Scheduling reality check: the nodes are Python processes sharing this
box's cores. The consensus timeout schedule baked into each config.toml
scales with fleet size (a 50-process net on few cores needs wider
propose windows than a 4-process one) and with the WAN profile (the
netchaos lesson: a 100 ms propose window can never cover a 40-90 ms
per-chunk link). Baked in — not mutated live — because these are real
processes: there is no shared config object to poke.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.request
from dataclasses import dataclass, field

from tendermint_tpu.ops import fleet
from tendermint_tpu.ops.netfaults import NetFabric, geo_clusters, wan_profile

logger = logging.getLogger("ops.localnet")

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# dial-degree ceiling where full mesh hands over to the ring (links grow
# O(n^2) vs O(n*k); at 16 the mesh is 120 links — still one box's worth)
FULL_MESH_MAX = 16
DEFAULT_RING_K = 4


@dataclass
class LocalnetSpec:
    """Everything that defines one localnet, seeded: two runs from the
    same spec generate identical keys, genesis (bar the timestamp),
    configs, and link fabric."""

    n: int = 4
    root: str = ""
    chain_id: str = "localnet"
    seed: int = 0
    # full | ring | star | "" (auto: full up to FULL_MESH_MAX, then ring)
    topology: str = ""
    ring_k: int = DEFAULT_RING_K
    base_port: int = 47100
    proxy_app: str = "kvstore"
    db_backend: str = "memdb"
    tx_index: str = "kv"
    gossip_dedup: bool = True
    # netfaults WAN profile name baked into the timeout schedule and
    # applied to every link at start ("" = clean loopback)
    wan: str = ""
    # >0: geo-cluster net (k clusters, lan inside / `wan` — or
    # intercontinental — between)
    geo: int = 0
    log_level: str = "error"
    # commit pacing: real timeout_commit (not the test preset's skipped
    # one) so the fleet's skew/byte-per-height readouts are meaningful
    timeout_commit: float = 0.1
    extra_args: list = field(default_factory=list)

    def resolved_topology(self) -> str:
        if self.topology:
            return self.topology
        return "full" if self.n <= FULL_MESH_MAX else "ring"

    def p2p_port(self, i: int) -> int:
        return self.base_port + 2 * i

    def rpc_port(self, i: int) -> int:
        return self.base_port + 2 * i + 1

    def home(self, i: int) -> str:
        return os.path.join(self.root, f"node{i}")

    def dial_edges(self) -> list[tuple[int, int]]:
        """The directed dial edges (i dials j) of the topology. One
        direction per pair everywhere — inbound/outbound dedup never
        races, exactly the netchaos invariant."""
        topo = self.resolved_topology()
        n = self.n
        if topo == "full":
            return [(i, j) for i in range(n) for j in range(i)]
        if topo == "star":
            return [(i, 0) for i in range(1, n)]
        if topo == "ring":
            k = max(1, min(self.ring_k, n - 1))
            edges = set()
            for i in range(n):
                for d in range(1, k + 1):
                    j = (i + d) % n
                    if (j, i) not in edges and i != j:
                        edges.add((i, j))
            return sorted(edges)
        raise ValueError(
            f"unknown topology {topo!r}; known: full, ring, star"
        )

    def consensus_timeouts(self) -> dict:
        """The schedule baked into every config.toml: sized for N
        Python processes sharing this box's cores, floored for the WAN
        profile when one is armed (the netchaos _WAN_TIMEOUT_FLOOR
        lesson, applied at generation time because processes can't be
        poked live)."""
        cores = os.cpu_count() or 1
        # how oversubscribed the box is: 50 processes on 1 core need
        # ~their whole schedule stretched; 4 on 8 cores need nothing
        crowd = max(1.0, self.n / max(cores, 1) / 4.0)
        t = {
            "timeout_propose": 0.5 * crowd,
            "timeout_propose_delta": 0.25,
            "timeout_prevote": 0.1 * crowd,
            "timeout_prevote_delta": 0.1,
            "timeout_precommit": 0.1 * crowd,
            "timeout_precommit_delta": 0.1,
            "timeout_commit": self.timeout_commit,
        }
        heavy = self.wan and wan_profile(self.wan).name != "lan"
        if heavy or self.geo > 0:
            floors = {
                "timeout_propose": 1.0, "timeout_propose_delta": 0.25,
                "timeout_prevote": 0.4, "timeout_prevote_delta": 0.2,
                "timeout_precommit": 0.4, "timeout_precommit_delta": 0.2,
            }
            for k, floor in floors.items():
                t[k] = max(t[k], floor)
        return t


class LocalNode:
    """One node process of the fleet. RPC/metrics via loopback HTTP —
    the same surface ops/fleet scrapes."""

    def __init__(self, spec: LocalnetSpec, index: int):
        self.spec = spec
        self.index = index
        self.home = spec.home(index)
        self.p2p_port = spec.p2p_port(index)
        self.rpc_port = spec.rpc_port(index)
        self.proc: subprocess.Popen | None = None

    @property
    def rpc_url(self) -> str:
        return f"127.0.0.1:{self.rpc_port}"

    def start(self, seeds: str = "") -> None:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.setdefault("TENDERMINT_TPU_DISABLE", "1")
        # never probe a live devd daemon from a fleet member: 50 nodes
        # hammering one accelerator socket is not this tier's scenario
        env.setdefault("TENDERMINT_DEVD_SOCK", "/nonexistent/devd.sock")
        # tight reconnect cadence (the netchaos value): a healed
        # partition must re-peer in ~a second, and a rolling restart's
        # peers must survive the whole outage window
        env.setdefault("TENDERMINT_P2P_RECONNECT_INTERVAL_S", "0.5")
        env.setdefault("TENDERMINT_P2P_RECONNECT_ATTEMPTS", "600")
        env["PYTHONPATH"] = REPO
        cmd = [
            sys.executable, "-m", "tendermint_tpu.cli",
            "--home", self.home, "node",
            "--p2p.laddr", f"tcp://127.0.0.1:{self.p2p_port}",
            "--rpc.laddr", f"tcp://127.0.0.1:{self.rpc_port}",
            "--p2p.addr_book_strict", "false",
            "--log_level", self.spec.log_level,
        ]
        if seeds:
            cmd += ["--seeds", seeds]
        cmd += list(self.spec.extra_args)
        self.proc = subprocess.Popen(
            cmd, cwd=REPO, env=env,
            stdout=open(os.path.join(self.home, "node.log"), "ab"),
            stderr=subprocess.STDOUT,
        )

    def rpc(self, method: str, params: dict | None = None,
            timeout: float = 10.0):
        body = json.dumps({
            "jsonrpc": "2.0", "id": "localnet", "method": method,
            "params": params or {},
        }).encode()
        req = urllib.request.Request(
            f"http://{self.rpc_url}/", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            out = json.loads(resp.read())
        if out.get("error"):
            raise RuntimeError(f"node{self.index} {method}: {out['error']}")
        return out["result"]

    def height(self) -> int:
        try:
            return int(self.rpc("status", timeout=5)["latest_block_height"])
        except Exception:  # noqa: BLE001 — down/starting counts as -1
            return -1

    def metrics(self) -> dict:
        return fleet.fetch_metrics(self.rpc_url)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self, sig=signal.SIGTERM) -> None:
        if self.proc is None:
            return
        try:
            self.proc.send_signal(sig)
            self.proc.wait(timeout=15)
        except Exception:  # noqa: BLE001 — a wedged shutdown escalates:
            # dropping the handle would orphan a process on bound ports
            try:
                self.proc.kill()
                self.proc.wait(timeout=15)
            except Exception:  # noqa: BLE001
                pass
        self.proc = None


class Localnet:
    """The process fleet: generate -> start -> drive/chaos -> read."""

    def __init__(self, spec: LocalnetSpec):
        if not spec.root:
            raise ValueError("LocalnetSpec.root is required")
        self.spec = spec
        self.fabric = NetFabric(
            name=f"localnet-{os.path.basename(spec.root)}"
        )
        self.nodes = [LocalNode(spec, i) for i in range(spec.n)]
        self._edges = spec.dial_edges()

    # -- generation ---------------------------------------------------------

    def generate(self) -> "Localnet":
        """N homes + shared genesis + per-home config.toml, all from
        the spec. Keys are seeded from (chain_id, seed, index) so two
        runs of the same spec produce the same validator set."""
        from tendermint_tpu.config import load_config
        from tendermint_tpu.config.toml import config_to_toml, ensure_root
        from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
        from tendermint_tpu.types import (
            GenesisDoc,
            GenesisValidator,
            PrivValidatorFS,
        )

        spec = self.spec
        os.makedirs(spec.root, exist_ok=True)
        pvs = []
        for i in range(spec.n):
            ensure_root(spec.home(i))
            pv = PrivValidatorFS(
                gen_priv_key_ed25519(
                    f"{spec.chain_id}-{spec.seed}-val-{i}".encode()
                ),
                None,
            )
            pvs.append(pv)
        genesis = GenesisDoc(
            genesis_time_ns=time.time_ns(),
            chain_id=spec.chain_id,
            validators=[
                GenesisValidator(pv.get_pub_key(), 10, f"node{i}")
                for i, pv in enumerate(pvs)
            ],
        )
        timeouts = spec.consensus_timeouts()
        for i, pv in enumerate(pvs):
            home = spec.home(i)
            cfg = load_config(home)
            cfg.base.chain_id = spec.chain_id
            cfg.base.moniker = f"node{i}"
            cfg.base.proxy_app = spec.proxy_app
            cfg.base.db_backend = spec.db_backend
            cfg.base.tx_index = spec.tx_index
            cfg.consensus.gossip_dedup = spec.gossip_dedup
            for k, v in timeouts.items():
                setattr(cfg.consensus, k, v)
            cfg.consensus.skip_timeout_commit = False
            with open(os.path.join(home, "config.toml"), "w") as f:
                f.write(config_to_toml(cfg))
            pv.file_path = cfg.base.priv_validator_file()
            pv.save()
            genesis.save_as(cfg.base.genesis_file())
        return self

    # -- lifecycle ----------------------------------------------------------

    def _seeds_for(self, i: int) -> str:
        """Node i's seed list: one LinkProxy laddr per outgoing dial
        edge (created on first use, reused across restarts so armed
        chaos — WAN shaping, delays — rides through)."""
        seeds = []
        for (a, b) in self._edges:
            if a != i:
                continue
            link = self.fabric.link(a, b)
            if link is None:
                link = self.fabric.add_link(
                    a, b, ("127.0.0.1", self.spec.p2p_port(b))
                )
            seeds.append(link.laddr)
        return ",".join(seeds)

    def start(self) -> "Localnet":
        for node in self.nodes:
            node.start(seeds=self._seeds_for(node.index))
        if self.spec.geo > 0:
            self.apply_geo(self.spec.geo)
        elif self.spec.wan:
            self.apply_wan(self.spec.wan)
        return self

    def restart_node(self, idx: int, sig=signal.SIGKILL) -> None:
        """Kill node idx (SIGKILL by default — the crash arm; pass
        SIGTERM for a graceful roll) and boot it again on the SAME
        ports and home. Its links drop live connections so peers see a
        dead node immediately; their persistent reconnect loops re-peer
        through the same proxies once it's back."""
        node = self.nodes[idx]
        node.kill(sig)
        for link in self.fabric.links_of(idx):
            link.drop_all()
        node.start(seeds=self._seeds_for(idx))

    def stop(self, keep_root: bool = False) -> None:
        for node in self.nodes:
            node.kill(signal.SIGTERM)
        self.fabric.stop()
        if not keep_root:
            shutil.rmtree(self.spec.root, ignore_errors=True)

    # -- chaos verbs (the netchaos vocabulary, process tier) ----------------

    def partition(self, group_a) -> None:
        self.fabric.partition_groups(set(group_a))

    def heal(self) -> None:
        self.fabric.heal_all()

    def apply_wan(self, profile, seed: int | None = None) -> None:
        self.fabric.apply_wan(
            profile, seed=self.spec.seed if seed is None else seed
        )

    def apply_geo(self, k: int, intra="lan", inter=None,
                  seed: int | None = None) -> list[list[int]]:
        clusters = geo_clusters(self.spec.n, k)
        self.fabric.apply_geo(
            clusters, intra=intra,
            inter=inter or (self.spec.wan or "intercontinental"),
            seed=self.spec.seed if seed is None else seed,
        )
        return clusters

    def clear_wan(self) -> None:
        self.fabric.clear_wan()

    # -- readout (the public scrape surface only) ---------------------------

    def fleet_urls(self, nodes: list[int] | None = None) -> list[str]:
        idxs = nodes if nodes is not None else range(len(self.nodes))
        return [self.nodes[i].rpc_url for i in idxs]

    def heights(self) -> list[int]:
        return [n.height() for n in self.nodes]

    def wait_height(self, h: int, timeout: float = 180.0,
                    nodes: list[int] | None = None) -> bool:
        idxs = list(nodes if nodes is not None else range(len(self.nodes)))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(self.nodes[i].height() >= h for i in idxs):
                return True
            time.sleep(0.5)
        return all(self.nodes[i].height() >= h for i in idxs)

    def timeline(self, last: int = 10, nodes: list[int] | None = None):
        """ops/fleet cross-node height rows (propagation lag, quorum
        formation, commit skew) off live scrapes."""
        snapshot = fleet.collect(self.fleet_urls(nodes), last=last)
        return fleet.build_timeline(
            {u: e.get("traces", []) for u, e in snapshot.items()}, last=last
        )

    def scrape_totals(self, names: list[str],
                      nodes: list[int] | None = None) -> dict:
        """Sum each metric across the fleet (label series summed per
        node by fleet.metric_value). A dead node contributes nothing."""
        out = {name: 0.0 for name in names}
        for url in self.fleet_urls(nodes):
            try:
                m = fleet.fetch_metrics(url)
            except Exception:  # noqa: BLE001 — partial fleets still read
                continue
            for name in names:
                out[name] += fleet.metric_value(m, name, default=0) or 0
        return out

    def duplicate_vote_ratio(self, nodes: list[int] | None = None) -> float:
        """The redundancy number this round engineers down: fleet-wide
        duplicate votes per accepted vote (PR-17/20 counters; the
        2N*N-redundancy literature's measurable)."""
        t = self.scrape_totals(
            ["consensus_vote_duplicates", "consensus_vote_accepted"], nodes
        )
        accepted = t["consensus_vote_accepted"]
        return (t["consensus_vote_duplicates"] / accepted) if accepted else 0.0

    def gossip_bytes(self, nodes: list[int] | None = None) -> float:
        """Fleet-total p2p bytes written (all channels)."""
        return self.scrape_totals(
            ["p2p_peer_send_bytes_total"], nodes
        )["p2p_peer_send_bytes_total"]

    # -- convergence --------------------------------------------------------

    def fingerprint(self, idx: int, height: int) -> tuple:
        """(block hash, part-set root, app hash) at `height` via RPC —
        the byte-identity surface, read as an operator would."""
        res = self.nodes[idx].rpc("block", {"height": height})
        meta, block = res["block_meta"], res["block"]
        return (
            meta["block_id"]["hash"],
            meta["block_id"]["parts"]["hash"],
            block["header"]["app_hash"],
        )

    def assert_converged(self, upto: int, from_height: int = 1,
                         nodes: list[int] | None = None) -> int:
        """Per-height byte identity across `nodes` for every height in
        [from_height, upto]. Returns heights compared."""
        idxs = list(nodes if nodes is not None else range(len(self.nodes)))
        compared = 0
        for h in range(from_height, upto + 1):
            prints = {i: self.fingerprint(i, h) for i in idxs}
            distinct = set(prints.values())
            assert len(distinct) == 1, (
                f"fleet diverges at height {h}: {prints}"
            )
            compared += 1
        return compared


# -- the scenario matrix ------------------------------------------------------


def run_scenario(spec: LocalnetSpec, scenario: str = "converge",
                 heights: int = 5, keep_root: bool = False) -> dict:
    """One named netchaos-style scenario against a process fleet.

    converge        — boot, reach `heights`, assert per-height byte
                      identity across ALL nodes (under the spec's WAN /
                      geo shaping, if any)
    partition_heal  — converge, sever a 1/3 minority, prove the 2/3
                      majority keeps committing while the minority is
                      frozen, heal, prove the minority catches up and
                      the whole fleet is byte-identical
    rolling_restart — converge, SIGKILL-and-restart a third of the
                      fleet one node at a time, prove each rejoins and
                      the fleet converges byte-identically

    Returns a flat JSON-able result row (heights/s, duplicate-vote
    ratio, fleet bytes — the bench's raw material)."""
    net = Localnet(spec)
    try:
        net.generate()
        t0 = time.monotonic()
        net.start()
        if not net.wait_height(1, timeout=180.0):
            raise AssertionError(
                f"fleet never reached height 1: {net.heights()}"
            )
        result: dict = {
            "scenario": scenario,
            "n": spec.n,
            "topology": spec.resolved_topology(),
            "wan": spec.wan or None,
            "geo": spec.geo or None,
            "gossip_dedup": spec.gossip_dedup,
        }
        if scenario == "converge":
            ok = net.wait_height(heights, timeout=60.0 * heights)
            assert ok, f"no convergence at {heights}: {net.heights()}"
            elapsed = time.monotonic() - t0
            result["heights"] = heights
            result["heights_per_s"] = heights / elapsed
            result["converged_heights"] = net.assert_converged(heights)
        elif scenario == "partition_heal":
            assert spec.n >= 4, "partition_heal needs n >= 4"
            ok = net.wait_height(heights, timeout=60.0 * heights)
            assert ok, f"no convergence at {heights}: {net.heights()}"
            minority = list(range(spec.n // 3))
            majority = [i for i in range(spec.n) if i not in minority]
            net.partition(minority)
            h0 = max(net.heights())
            ok = net.wait_height(h0 + 3, timeout=120.0, nodes=majority)
            assert ok, (
                f"majority stalled during partition: {net.heights()}"
            )
            frozen = [net.nodes[i].height() for i in minority]
            net.heal()
            target = max(net.heights()) + 2
            ok = net.wait_height(target, timeout=180.0)
            assert ok, f"minority never healed: {net.heights()}"
            result["heights"] = target
            result["minority_frozen_at"] = frozen
            result["converged_heights"] = net.assert_converged(target)
        elif scenario == "rolling_restart":
            ok = net.wait_height(heights, timeout=60.0 * heights)
            assert ok, f"no convergence at {heights}: {net.heights()}"
            victims = list(range(max(1, spec.n // 3)))
            for idx in victims:
                net.restart_node(idx)
                back = net.wait_height(
                    max(net.heights()) + 1, timeout=180.0, nodes=[idx]
                )
                assert back, f"node{idx} never rejoined: {net.heights()}"
            target = max(net.heights())
            ok = net.wait_height(target, timeout=120.0)
            assert ok, f"fleet lost a node after the roll: {net.heights()}"
            result["heights"] = target
            result["restarted"] = victims
            result["converged_heights"] = net.assert_converged(target)
        else:
            raise ValueError(
                f"unknown scenario {scenario!r}; known: converge, "
                "partition_heal, rolling_restart"
            )
        result["duplicate_vote_ratio"] = net.duplicate_vote_ratio()
        result["gossip_bytes"] = net.gossip_bytes()
        result["final_heights"] = net.heights()
        return result
    finally:
        net.stop(keep_root=keep_root)


# -- CLI ----------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="N-process localnet: generate homes, boot real CLI "
                    "nodes through netfaults link proxies, run a chaos "
                    "scenario, read convergence off the scrape surface",
    )
    ap.add_argument("--n", type=int, default=10)
    ap.add_argument("--root", default="",
                    help="net root dir (default: a temp dir, removed "
                         "unless --keep)")
    ap.add_argument("--scenario", default="converge",
                    choices=["converge", "partition_heal", "rolling_restart"])
    ap.add_argument("--heights", type=int, default=5)
    ap.add_argument("--topology", default="",
                    choices=["", "full", "ring", "star"])
    ap.add_argument("--ring-k", type=int, default=DEFAULT_RING_K)
    ap.add_argument("--wan", default="",
                    help="netfaults WAN profile (lan, continental, "
                         "intercontinental, lossy-mobile)")
    ap.add_argument("--geo", type=int, default=0,
                    help="geo-cluster count (0 = off)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--base-port", type=int, default=47100)
    ap.add_argument("--no-dedup", action="store_true",
                    help="boot with gossip_dedup=false (the pre-round-20 "
                         "gossip baseline)")
    ap.add_argument("--keep", action="store_true",
                    help="keep homes + logs after the run")
    args = ap.parse_args(argv)

    import tempfile

    logging.basicConfig(level=logging.INFO)
    root = args.root or tempfile.mkdtemp(prefix="localnet-")
    spec = LocalnetSpec(
        n=args.n, root=root, seed=args.seed, topology=args.topology,
        ring_k=args.ring_k, base_port=args.base_port, wan=args.wan,
        geo=args.geo, gossip_dedup=not args.no_dedup,
    )
    result = run_scenario(
        spec, scenario=args.scenario, heights=args.heights,
        keep_root=args.keep,
    )
    if args.keep:
        result["root"] = root
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
