"""Injectable fault harness for the devd device plane (round 8).

The consensus critical path now runs through a socket to a separate
daemon process (PR 1 verify plane, PR 2 hash plane) — which means the
failure modes that matter are TRANSPORT failure modes: a daemon killed
mid-stream, a truncated or corrupted chunk frame, a read that stalls
until the io budget, a refused connect, a version-skewed daemon. Before
this module the only way to exercise any of them was hand-killing
daemons. A `FaultPlan` is a DETERMINISTIC, seeded schedule of such
faults that tests and benches inject WITHOUT monkeypatching client or
daemon internals, deployed either of two ways:

- **in-process** (`install_client_faults`): wraps every new DevdClient
  connection via the sanctioned `devd.set_socket_wrapper` hook — the
  production client code path runs unmodified, faults fire at the
  socket boundary (sendall/recv). Cheap, runs anywhere, covers the
  client-side triage (reconnect-once, breaker demotion, CPU fallback).
- **out-of-process** (`FaultProxy`): a UDS shim process/thread in front
  of a REAL daemon. The client speaks the real wire protocol to the
  proxy; every length-prefixed frame relays byte-for-byte unless the
  plan injects — so `verify_stream`/`hash_stream` framing, the daemon's
  malformed-frame error path, and the daemon-side abort handling are
  exercised on real bytes. `python -m tendermint_tpu.ops.faults` runs
  it as its own process for multi-process harnesses (localnet).

Every injected fault increments a `faults_*` counter; registered plans
surface those counters alongside the existing `stream_*` gauges in
`Verifier.stats()` / `Hasher.stats()` (flat numerics — the metrics RPC
exports them as scalar gauges), so a chaos run's observability is the
SAME observability an operator has in production.

`DaemonSupervisor` drives the kill/restart arm of a chaos schedule. It
is chip-free BY CONSTRUCTION: it refuses to supervise anything but an
ACCEPT_CPU (sim or CPU-kernel) daemon — SIGKILLing a real device owner
mid-op is exactly the tunnel-wedging accident devd.py exists to prevent
(round-3 postmortem), and no test harness may ever automate it.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import pickle
import random
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time

logger = logging.getLogger("ops.faults")

# The fault taxonomy (docs/streaming-devd.md "Failure model"):
#   refuse    connect refused (daemon down / socket gone)
#   corrupt   byte flip inside a relayed frame payload (framing intact)
#   truncate  frame cut mid-payload, connection closed (framing broken)
#   stall     read/write stalled for stall_s before proceeding
#   drop      connection closed without warning mid-exchange
#   skew      a *_stream header answered like a pre-streaming daemon
#             (pickle {"ok": False}) — the version-skew path
#   kill      daemon killed/restarted (DaemonSupervisor / blackout)
FAULT_KINDS = ("refuse", "corrupt", "truncate", "stall", "drop", "skew", "kill")

# plan event streams a Fault can key on: "connect" (new client conn),
# "c2s" (client->daemon frame), "s2c" (daemon->client frame)
FAULT_EVENTS = ("connect", "c2s", "s2c")


class Fault:
    """One rule in a FaultPlan: fire `kind` on the `first`-th event of
    stream `on` (1-based), then every `every` events after, at most
    `limit` times total. Deterministic by construction — the schedule is
    a pure function of the event sequence."""

    __slots__ = ("kind", "on", "first", "every", "limit", "stall_s", "fired")

    def __init__(self, kind: str, on: str, first: int = 1, every: int = 0,
                 limit: int = 1, stall_s: float = 0.5):
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}: {FAULT_KINDS}")
        if on not in FAULT_EVENTS:
            raise ValueError(f"unknown fault event {on!r}: {FAULT_EVENTS}")
        self.kind = kind
        self.on = on
        self.first = max(1, int(first))
        self.every = max(0, int(every))
        self.limit = max(1, int(limit))
        self.stall_s = float(stall_s)
        self.fired = 0

    def due(self, n: int) -> bool:
        if self.fired >= self.limit:
            return False
        if n == self.first:
            return True
        return bool(self.every) and n > self.first and (
            (n - self.first) % self.every == 0
        )

    def __repr__(self) -> str:  # schedule debugging in test failures
        return (
            f"Fault({self.kind} on {self.on} first={self.first} "
            f"every={self.every} limit={self.limit} fired={self.fired})"
        )


class FaultPlan:
    """A seeded, deterministic schedule of device-plane faults plus the
    counters proving what actually fired. The seed drives only the
    *content* randomness (which byte a corrupt flips); *when* faults
    fire is a pure function of the event sequence, so a replayed run
    injects the identical schedule."""

    def __init__(self, faults=(), seed: int = 0):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self.faults = list(faults)
        self.counters = {f"faults_{k}": 0 for k in FAULT_KINDS}
        self._events = {e: 0 for e in FAULT_EVENTS}
        self._mtx = threading.Lock()

    def add(self, kind: str, on: str, **kw) -> "FaultPlan":
        self.faults.append(Fault(kind, on, **kw))
        return self

    def pick(self, event: str, supported=None):
        """Advance the `event` stream one step; the Fault due at this
        step (counters noted), or None. `supported` (an iterable of
        kinds, None = all) names what the CALLING injection point can
        actually inject here — a due fault it cannot inject is skipped
        WITHOUT being consumed or counted (and warned about once), so
        the faults_* counters only ever report injections that really
        happened and a mis-targeted rule is loud, not silently eaten."""
        with self._mtx:
            self._events[event] += 1
            n = self._events[event]
            for f in self.faults:
                if f.on != event or not f.due(n):
                    continue
                if supported is not None and f.kind not in supported:
                    logger.warning(
                        "fault %r due but not injectable at this point "
                        "(supports %s); skipped, not counted", f,
                        tuple(supported),
                    )
                    continue
                f.fired += 1
                self.counters[f"faults_{f.kind}"] += 1
                return f
        return None

    def wants(self, kind: str, event: str) -> bool:
        """Does any not-yet-exhausted rule target (kind, event)? Lets
        injection points skip per-frame work (e.g. header sniffing for
        skew) when no rule could ever need it."""
        with self._mtx:
            return any(
                f.kind == kind and f.on == event and f.fired < f.limit
                for f in self.faults
            )

    def note(self, kind: str) -> None:
        """Count a fault injected OUTSIDE the event streams (a daemon
        kill by the supervisor, a proxy blackout)."""
        with self._mtx:
            self.counters[f"faults_{kind}"] += 1

    def corrupt_offset(self, lo: int, hi: int) -> int:
        """Seeded byte position for a corrupt fault (content randomness
        is the ONLY thing the rng decides)."""
        with self._mtx:
            return self._rng.randrange(lo, max(lo + 1, hi))

    def stats(self) -> dict:
        with self._mtx:
            out = dict(self.counters)
            out["faults_total"] = sum(self.counters.values())
            return out


# -- registry: stats visibility alongside the stream_* gauges -----------------

_registry: list[FaultPlan] = []
_reg_mtx = threading.Lock()


def register(plan: FaultPlan) -> FaultPlan:
    with _reg_mtx:
        if plan not in _registry:
            _registry.append(plan)
    return plan


def unregister(plan: FaultPlan) -> None:
    with _reg_mtx:
        if plan in _registry:
            _registry.remove(plan)


def global_counters() -> dict:
    """Aggregated faults_* counters over every registered plan — a
    STABLE key set (all zeros with no harness installed), folded into
    Verifier/Hasher stats() so chaos observability is production
    observability."""
    out = {f"faults_{k}": 0 for k in FAULT_KINDS}
    with _reg_mtx:
        plans = list(_registry)
    for plan in plans:
        for k, v in plan.stats().items():
            if k in out:
                out[k] += v
    return out


# -- telemetry plane (round 11) -----------------------------------------------
#
# The fault counters and the supervisor's kill/restart totals register
# into the process-wide telemetry registry, so a chaos soak asserts on
# SCRAPED metrics (GET /metrics, or registry flatten) instead of
# reaching into harness objects — the same surface production has.

_sup_totals = {"kills": 0, "restarts": 0}
_sup_mtx = threading.Lock()


def _note_supervisor(kind: str) -> None:
    with _sup_mtx:
        _sup_totals[kind] += 1


def telemetry_counters() -> dict:
    """faults_* across every registered plan + supervisor churn totals
    (flat numerics; registered as a scrape-only producer below)."""
    out = global_counters()
    with _sup_mtx:
        out["faults_supervisor_kills"] = _sup_totals["kills"]
        out["faults_supervisor_restarts"] = _sup_totals["restarts"]
    return out


def _install_telemetry(reg) -> None:
    # prefix "": the keys already carry the canonical faults_ prefix.
    # legacy=False: scrape-only — the metrics RPC's flat key set must
    # stay byte-compatible (faults_* already ride gateway_verify_* /
    # gateway_hash_* there on the devd route)
    reg.register_producer("", telemetry_counters, legacy=False)


from tendermint_tpu.libs import telemetry as _telemetry  # noqa: E402

_telemetry.on_default_registry(_install_telemetry)


# -- in-process deployment: DevdClient socket wrapper -------------------------


class FaultSocket:
    """Socket proxy injecting plan faults at the client's socket
    boundary. The client sends every frame with ONE sendall (header
    pickle and chunk frames alike), so c2s faults key cleanly on sendall
    calls; s2c faults key on recv calls (the client reads the 4-byte
    length and the payload in separate _recv_exact passes — a corrupt
    may therefore land in either, both of which must surface as a
    client-visible error, never a hang). Everything else delegates to
    the wrapped socket."""

    def __init__(self, sock: socket.socket, plan: FaultPlan):
        self._sock = sock
        self._plan = plan
        # s2c frame tracking: the client reads each frame as a 4-byte
        # length prefix then the payload (possibly in several recv
        # calls). Faults key on FRAMES — fired once, at the first
        # payload read — so the event stream is deterministic (recv
        # call chunking varies run to run) and a corrupt can only ever
        # land in the frame's leading structural bytes, never on a
        # continuation read deep in payload (which would be the silent
        # rot the taxonomy declares out of contract)
        self._len_rem = 4
        self._len_acc = b""
        self._frame_rem = 0
        self._frame_new = False

    # -- fault points -------------------------------------------------------

    def sendall(self, data) -> None:
        f = self._plan.pick(
            "c2s", supported=("stall", "drop", "truncate", "corrupt")
        )
        if f is not None:
            if f.kind == "stall":
                time.sleep(f.stall_s)
            elif f.kind == "drop":
                # shutdown-then-close (_kill_sock): a resolver thread may
                # be blocked in recv on this same fd, and close() alone
                # would leave it wedged for the full stream budget
                _kill_sock(self._sock)
                raise ConnectionError("fault: connection dropped before send")
            elif f.kind == "truncate":
                cut = max(1, len(data) // 2)
                try:
                    self._sock.sendall(bytes(data[:cut]))
                finally:
                    _kill_sock(self._sock)
                raise ConnectionError("fault: frame truncated mid-send")
            elif f.kind == "corrupt":
                buf = bytearray(data)
                # STRUCTURAL corruption: flip a byte in the frame's
                # leading structure (lane counts / status / lens planes)
                # — the region the existing frame validation rejects
                # loudly. Never the 4-byte outer length prefix (a
                # corrupted LENGTH leaves the daemon blocked reading
                # bytes that never come — its reads are unbudgeted by
                # design, trusted local IPC), and not arbitrary payload
                # bytes either: on a checksummed local socket a flipped
                # sig/msg byte models memory corruption, not transport
                # failure, and is undetectable BY DESIGN (docs
                # "Failure model") — injecting it would assert a
                # contract the protocol does not make
                if len(buf) > 5:
                    off = self._plan.corrupt_offset(4, min(len(buf), 12))
                    buf[off] ^= 0xFF
                data = bytes(buf)
        return self._sock.sendall(data)

    def recv(self, n: int) -> bytes:
        if self._len_rem > 0:
            # length-prefix bytes: pass through untouched — a flipped
            # length desynchronizes the framing into a silent
            # both-sides wedge, modeling nothing the protocol can
            # detect (docs "Failure model")
            data = self._sock.recv(min(n, self._len_rem))
            self._len_rem -= len(data)
            self._len_acc += data
            if self._len_rem == 0:
                (self._frame_rem,) = struct.unpack(">I", self._len_acc)
                self._len_acc = b""
                self._frame_new = True
                if self._frame_rem == 0:  # empty frame: next is a new one
                    self._len_rem = 4
            return data
        f = None
        if self._frame_new:  # first payload read of this frame
            self._frame_new = False
            f = self._plan.pick("s2c", supported=("stall", "drop", "corrupt"))
        if f is not None:
            if f.kind == "stall":
                time.sleep(f.stall_s)
            elif f.kind == "drop":
                _kill_sock(self._sock)
                raise ConnectionError("fault: connection dropped mid-read")
        data = bytearray(self._sock.recv(min(n, self._frame_rem)))
        self._frame_rem -= len(data)
        if self._frame_rem == 0:
            self._len_rem = 4
        if f is not None and f.kind == "corrupt" and data:
            # structural head of the frame (status/index/counts)
            data[self._plan.corrupt_offset(0, min(len(data), 9))] ^= 0xFF
        return bytes(data)

    # -- plain delegation ---------------------------------------------------

    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def shutdown(self, how) -> None:
        self._sock.shutdown(how)

    def close(self) -> None:
        self._sock.close()

    def fileno(self) -> int:
        return self._sock.fileno()


def install_client_faults(plan: FaultPlan) -> FaultPlan:
    """Route every NEW DevdClient connection in this process through the
    plan (devd.set_socket_wrapper — the sanctioned injection point; no
    client internals are monkeypatched). Connect-stream faults fire at
    wrap time: `refuse` closes the fresh socket and raises
    ConnectionRefusedError exactly as a dead daemon would. Pair with
    `uninstall_client_faults()` in test teardown."""
    from tendermint_tpu import devd

    def wrap(sock: socket.socket):
        f = plan.pick("connect", supported=("refuse", "stall"))
        if f is not None and f.kind == "refuse":
            sock.close()
            raise ConnectionRefusedError("fault: connect refused")
        if f is not None and f.kind == "stall":
            time.sleep(f.stall_s)
        return FaultSocket(sock, plan)

    devd.set_socket_wrapper(wrap)
    return register(plan)


def uninstall_client_faults(plan: FaultPlan | None = None) -> None:
    from tendermint_tpu import devd

    devd.set_socket_wrapper(None)
    if plan is not None:
        unregister(plan)


# -- out-of-process deployment: wire shim in front of a real daemon -----------


# the proxy reads frames with the REAL client/daemon read loop — if its
# semantics ever change (error taxonomy, interrupt handling), the
# byte-for-byte relay guarantee must change with them, not drift
from tendermint_tpu.devd import _recv_exact  # noqa: E402


def _kill_sock(s: socket.socket) -> None:
    """shutdown THEN close. close() alone from another thread does NOT
    wake a recv blocked on the same fd (the in-flight syscall pins the
    file description, so no FIN ever goes out and BOTH sides hang —
    exactly the wedge the first chaos soak caught in the relay
    teardown); shutdown() tears the connection down immediately."""
    try:
        s.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        s.close()
    except Exception:  # noqa: BLE001 — teardown best effort
        pass


def _is_stream_header(payload: bytes) -> bool:
    """Is this c2s frame a verify_stream/hash_stream header? (Binary
    chunk frames virtually never unpickle; a failed loads is a clean
    'no'.)"""
    try:
        obj = pickle.loads(payload)
    except Exception:  # noqa: BLE001 — binary chunk frame, not a header
        return False
    return isinstance(obj, dict) and str(obj.get("op", "")).endswith("_stream")


class FaultProxy:
    """Frame-aware UDS shim between DevdClients and a real daemon: both
    planes' wire framing crosses byte-for-byte (length prefix + payload
    relayed as read), and the plan injects at frame granularity — so a
    `corrupt` lands inside a real chunk/digest frame, a `truncate` cuts
    a real frame mid-payload, and `skew` answers a *_stream header with
    the pickle error a pre-streaming daemon would send (the client's
    version-skew latch path). `blackout()` emulates daemon death without
    touching the daemon: live connections drop and new connects refuse
    for the window. Runs as threads in-process, or standalone via
    `python -m tendermint_tpu.ops.faults`."""

    def __init__(self, listen_path: str, upstream_path: str,
                 plan: FaultPlan | None = None):
        self.listen_path = listen_path
        self.upstream_path = upstream_path
        self.plan = plan if plan is not None else FaultPlan()
        self._srv: socket.socket | None = None
        self._stop = threading.Event()
        self._conns: list[socket.socket] = []
        self._mtx = threading.Lock()
        self._blackout_until = 0.0
        self._accept_thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FaultProxy":
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        if os.path.exists(self.listen_path):
            os.unlink(self.listen_path)
        srv.bind(self.listen_path)
        os.chmod(self.listen_path, 0o600)
        srv.listen(64)
        srv.settimeout(0.5)
        self._srv = srv
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="fault-proxy-accept"
        )
        self._accept_thread.start()
        register(self.plan)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        if self._srv is not None:
            self._srv.close()
        try:
            os.unlink(self.listen_path)
        except OSError:
            pass
        self._drop_all()
        unregister(self.plan)

    def blackout(self, seconds: float) -> None:
        """Daemon-death emulation for `kill` schedules that must not
        actually SIGKILL (e.g. a shared daemon): refuse new connects and
        drop live ones for the window."""
        with self._mtx:
            self._blackout_until = time.monotonic() + seconds
        self.plan.note("kill")
        self._drop_all()

    def _drop_all(self) -> None:
        with self._mtx:
            conns, self._conns = self._conns, []
        for c in conns:
            _kill_sock(c)

    # -- relay --------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            now = time.monotonic()
            with self._mtx:
                dark = now < self._blackout_until
            f = None if dark else self.plan.pick(
                "connect", supported=("refuse", "stall")
            )
            if dark or (f is not None and f.kind == "refuse"):
                conn.close()
                continue
            if f is not None and f.kind == "stall":
                time.sleep(f.stall_s)
            try:
                up = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                up.settimeout(5.0)
                up.connect(self.upstream_path)
                up.settimeout(None)
            except OSError:
                # upstream daemon down: the client sees exactly what a
                # dead daemon produces — an immediately closed conn
                conn.close()
                continue
            with self._mtx:
                self._conns += [conn, up]
            threading.Thread(
                target=self._relay, args=(conn, up, "c2s"),
                daemon=True, name="fault-proxy-c2s",
            ).start()
            threading.Thread(
                target=self._relay, args=(up, conn, "s2c"),
                daemon=True, name="fault-proxy-s2c",
            ).start()

    def _relay(self, src: socket.socket, dst: socket.socket,
               direction: str) -> None:
        try:
            while not self._stop.is_set():
                hdr = _recv_exact(src, 4)
                (n,) = struct.unpack(">I", hdr)
                payload = _recv_exact(src, n)
                supported = ["stall", "drop", "truncate", "corrupt"]
                # skew only injects on a frame that actually IS a stream
                # header — advertise it as supported only then, so a due
                # skew rule is never consumed (or counted) by a frame it
                # cannot apply to
                if direction == "c2s" and self.plan.wants("skew", "c2s") \
                        and _is_stream_header(payload):
                    supported.append("skew")
                f = self.plan.pick(direction, supported=supported)
                if f is not None:
                    if f.kind == "stall":
                        time.sleep(f.stall_s)
                    elif f.kind == "drop":
                        return
                    elif f.kind == "truncate":
                        dst.sendall(hdr + payload[: max(1, n // 2)])
                        return
                    elif f.kind == "corrupt" and n > 1:
                        # structural region only (status/index/counts/
                        # lens planes) — see FaultSocket.sendall: flips
                        # the validation layer detects, not silent
                        # payload rot the trusted-IPC contract excludes
                        buf = bytearray(payload)
                        buf[self.plan.corrupt_offset(0, min(n, 9))] ^= 0xFF
                        payload = bytes(buf)
                    elif f.kind == "skew" and direction == "c2s" \
                            and _is_stream_header(payload):
                        # answer like a pre-streaming daemon and swallow
                        # the header: the client must latch single-shot,
                        # not hang
                        rep = pickle.dumps(
                            {"ok": False, "error": "unknown op (skewed)"}
                        )
                        src.sendall(struct.pack(">I", len(rep)) + rep)
                        continue
                dst.sendall(hdr + payload)
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            for s in (src, dst):
                _kill_sock(s)


# -- daemon churn: the kill/restart arm of a chaos schedule -------------------


class DaemonSupervisor:
    """Spawn, SIGKILL, and restart a devd daemon on a schedule. Chip-free
    by construction: refuses any environment that is not ACCEPT_CPU —
    automating the SIGKILL of a real device owner is the round-3 tunnel
    wedge, and no harness gets to do it. Kills note `faults_kill` on the
    plan, so the chaos tests can assert the schedule actually fired."""

    def __init__(self, sock_path: str, extra_env: dict | None = None,
                 plan: FaultPlan | None = None):
        env = dict(extra_env or {})
        env.setdefault("TENDERMINT_DEVD_ACCEPT_CPU", "1")
        if env.get("TENDERMINT_DEVD_ACCEPT_CPU") != "1":
            raise ValueError(
                "DaemonSupervisor only supervises ACCEPT_CPU daemons: "
                "SIGKILLing a real device owner mid-op wedges the tunnel "
                "(tendermint_tpu/devd.py round-3 postmortem)"
            )
        self.sock_path = sock_path
        self.extra_env = env
        self.plan = plan
        self.proc: subprocess.Popen | None = None
        # daemon stderr goes to a FILE, not a pipe: nothing drains a
        # pipe while the daemon serves, so a chatty daemon (INFO
        # logging + jax warnings) would fill the 64 KB pipe buffer and
        # block inside its own logging call mid-soak — a fake liveness
        # failure. The file doubles as the death report.
        self.log_path = os.path.join(
            tempfile.gettempdir(),
            f"devd-supervised-{os.getpid()}-{id(self):x}.log",
        )
        self._churn_stop = threading.Event()
        self._churn_thread: threading.Thread | None = None
        self.kills = 0
        self.restarts = 0

    def start(self, wait_held_s: float = 30.0) -> None:
        if self.plan is not None:
            # kills noted on the plan must be scrape-visible (round 11):
            # register it so global_counters()/the telemetry producer
            # aggregate it like the injection harnesses' plans
            register(self.plan)
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "TENDERMINT_DEVD_SOCK": self.sock_path,
            "TENDERMINT_DEVD_EXIT_ON_TERM": "1",
            **self.extra_env,
        }
        # a sharded-plane harness exports the fleet's endpoint list; the
        # daemon itself must bind exactly ITS socket, never consult the
        # fleet topology
        env.pop("TENDERMINT_DEVD_SOCKS", None)
        with open(self.log_path, "ab") as log:
            self.proc = subprocess.Popen(
                [sys.executable, "-m", "tendermint_tpu.devd"],
                env=env, cwd=repo,
                stdout=subprocess.DEVNULL, stderr=log,
            )
        if wait_held_s > 0:
            self.wait_held(wait_held_s)

    def _log_tail(self, nbytes: int = 2000) -> bytes:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - nbytes))
                return f.read()
        except OSError:
            return b""

    def wait_held(self, deadline_s: float) -> dict:
        from tendermint_tpu import devd

        client = devd.DevdClient(self.sock_path)
        deadline = time.time() + deadline_s
        try:
            while time.time() < deadline:
                if self.proc is not None and self.proc.poll() is not None:
                    raise RuntimeError(
                        f"supervised daemon died: {self._log_tail()!r}"
                    )
                try:
                    rep = client.ping(timeout=2.0)
                    if rep.get("held"):
                        return rep
                except Exception:  # noqa: BLE001 — not serving yet
                    pass
                time.sleep(0.1)
            raise TimeoutError(
                f"daemon on {self.sock_path} never reached serving state"
            )
        finally:
            client.close()

    def kill(self) -> None:
        """SIGKILL — the fault being modeled is an unclean death, so no
        graceful shutdown op (and devd ignores SIGTERM by design)."""
        if self.proc is None:
            return
        try:
            self.proc.kill()
            self.proc.wait(timeout=15)
        except Exception:  # noqa: BLE001 — reaped elsewhere / already gone
            pass
        self.proc = None
        self.kills += 1
        _note_supervisor("kills")
        if self.plan is not None:
            self.plan.note("kill")

    def restart(self, wait_held_s: float = 30.0) -> None:
        self.kill()
        # an unclean kill leaves the bound socket file behind; devd's own
        # startup probe handles the stale socket, so just restart
        self.start(wait_held_s=wait_held_s)
        self.restarts += 1
        _note_supervisor("restarts")

    def churn(self, down_s: float = 0.5, up_s: float = 2.0,
              cycles: int = 0) -> None:
        """Background kill/restart loop: daemon down for down_s, up for
        up_s, `cycles` times (0 = until stop_churn). Always exits with
        the daemon RUNNING so recovery is observable."""

        def run() -> None:
            n = 0
            while not self._churn_stop.is_set():
                if cycles and n >= cycles:
                    break
                self.kill()
                if self._churn_stop.wait(down_s):
                    break
                try:
                    self.start(wait_held_s=30.0)
                except Exception:  # noqa: BLE001 — restart raced stop()
                    logger.exception("chaos restart failed")
                    break
                self.restarts += 1
                _note_supervisor("restarts")
                n += 1
                if self._churn_stop.wait(up_s):
                    break
            if self.proc is None and not self._churn_stop.is_set():
                try:
                    self.start(wait_held_s=30.0)
                except Exception:  # noqa: BLE001 — leave down; stop() reaps
                    logger.exception("final chaos restart failed")

        self._churn_stop.clear()
        self._churn_thread = threading.Thread(
            target=run, daemon=True, name="chaos-churn"
        )
        self._churn_thread.start()

    def stop_churn(self, ensure_up: bool = True) -> None:
        self._churn_stop.set()
        if self._churn_thread is not None:
            self._churn_thread.join(timeout=60.0)
            self._churn_thread = None
        if ensure_up and self.proc is None:
            self.start(wait_held_s=30.0)

    def stop(self) -> None:
        self._churn_stop.set()
        if self._churn_thread is not None:
            self._churn_thread.join(timeout=60.0)
        if self.proc is not None:
            try:
                self.proc.kill()
                self.proc.wait(timeout=15)
            except Exception:  # noqa: BLE001 — already gone
                pass
            self.proc = None
        if self.plan is not None:
            unregister(self.plan)


class DaemonFleet:
    """N supervised sim daemons on distinct sockets — the sharded device
    plane's chaos/bench substrate (round 21). Same ACCEPT_CPU-only rule
    as DaemonSupervisor (which it composes); `sock_paths` joins directly
    into TENDERMINT_DEVD_SOCKS."""

    def __init__(self, n: int, sock_dir: str | None = None,
                 extra_env: dict | None = None):
        base = sock_dir or tempfile.gettempdir()
        self.supervisors = [
            DaemonSupervisor(
                os.path.join(
                    base, f"devd-fleet-{os.getpid()}-{id(self):x}-{i}.sock"
                ),
                extra_env=dict(extra_env or {}),
            )
            for i in range(n)
        ]

    @property
    def sock_paths(self) -> list[str]:
        return [s.sock_path for s in self.supervisors]

    @property
    def socks_env(self) -> str:
        """The TENDERMINT_DEVD_SOCKS value for this fleet."""
        return ",".join(self.sock_paths)

    def start(self, wait_held_s: float = 30.0) -> "DaemonFleet":
        started = []
        try:
            for s in self.supervisors:
                s.start(wait_held_s=wait_held_s)
                started.append(s)
        except BaseException:
            for s in started:
                s.stop()
            raise
        return self

    def kill(self, i: int) -> None:
        self.supervisors[i].kill()

    def restart(self, i: int, wait_held_s: float = 30.0) -> None:
        self.supervisors[i].restart(wait_held_s=wait_held_s)

    def stop(self) -> None:
        for s in self.supervisors:
            s.stop()


# -- standalone shim process --------------------------------------------------


def main(argv=None) -> int:
    """Run a FaultProxy as its own process (multi-process harnesses —
    localnet nodes point TENDERMINT_DEVD_SOCK at --listen). The schedule
    is built from the repeat-rate flags; counters print as ONE json line
    on SIGTERM/SIGINT."""
    ap = argparse.ArgumentParser(description=FaultProxy.__doc__)
    ap.add_argument("--listen", required=True, help="UDS path to serve")
    ap.add_argument("--upstream", required=True, help="real daemon socket")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--corrupt-every", type=int, default=0,
                    help="corrupt every Nth daemon->client frame")
    ap.add_argument("--truncate-every", type=int, default=0,
                    help="truncate every Nth client->daemon frame")
    ap.add_argument("--stall-every", type=int, default=0,
                    help="stall every Nth daemon->client frame")
    ap.add_argument("--stall-s", type=float, default=0.5)
    args = ap.parse_args(argv)

    plan = FaultPlan(seed=args.seed)
    big = 1 << 30  # rate rules: fire forever at the given cadence
    if args.corrupt_every:
        plan.add("corrupt", "s2c", first=args.corrupt_every,
                 every=args.corrupt_every, limit=big)
    if args.truncate_every:
        plan.add("truncate", "c2s", first=args.truncate_every,
                 every=args.truncate_every, limit=big)
    if args.stall_every:
        plan.add("stall", "s2c", first=args.stall_every,
                 every=args.stall_every, limit=big, stall_s=args.stall_s)

    proxy = FaultProxy(args.listen, args.upstream, plan).start()
    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    logging.basicConfig(level=logging.INFO)
    logger.info("fault proxy %s -> %s", args.listen, args.upstream)
    done.wait()
    proxy.stop()
    print(json.dumps(plan.stats()))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
