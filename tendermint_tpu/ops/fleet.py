"""Fleet observability aggregator (round 15): cross-node height
timelines from nothing but each node's public scrape surface.

    python -m tendermint_tpu.ops.fleet --urls host1:46657,host2:46657 --last 5
    python -m tendermint_tpu.ops.fleet --urls ... --json

Per node it pulls GET /metrics (Prometheus text 0.0.4), GET /health
(node/health.py contract), and the ``consensus_trace`` RPC — then joins
the traces' gossip arrival marks (consensus/trace.py ARRIVALS, absolute
wall-clock instants) across nodes into a per-height timeline:

- **propagation lag**: spread of ``first_block_part`` instants — how long
  after the proposer held the first part the slowest peer did;
- **quorum-formation time**: per node, ``precommit_quorum`` (and
  ``prevote_quorum``) minus the height's start — the committee-scale
  bottleneck the vote-dissemination literature engineers against;
- **commit skew**: spread of the finalize instants — how staggered the
  fleet commits the same height.

This is the measurement substrate the multi-node pipeline/latency bench
needs (ROADMAP: "4-process Localnet latency bench"), and what the
netchaos partition scenario asserts on: a partition is a quorum-time
spike + a degraded /health + frozen per-peer gossip counters, all read
from scrapes — never by reaching into harness objects.

Importable pieces (used by tests/test_fleet.py and benches/bench_fleet.py):
``fetch_metrics`` / ``fetch_health`` / ``fetch_traces`` / ``collect`` /
``build_timeline`` / ``metric_value`` / ``render``.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import urllib.error
import urllib.request

# one sample line: name{labels} value  (labels optional)
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>\+Inf|-Inf|NaN|[0-9.eE+-]+)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> dict:
    """Prometheus text 0.0.4 -> {sample_name: [(labels_dict, value)]}.
    Sample names keep their _bucket/_sum/_count suffixes — this is a
    scrape reader, not a data model."""
    out: dict[str, list] = {}

    def unescape(v: str) -> str:
        return (v.replace(r"\n", "\n").replace(r"\"", '"')
                .replace("\\\\", "\\"))

    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        labels = {
            k: unescape(v)
            for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        raw = m.group("value")
        value = float("inf") if raw == "+Inf" else (
            float("-inf") if raw == "-Inf" else float(raw)
        )
        out.setdefault(m.group("name"), []).append((labels, value))
    return out


def metric_value(metrics: dict, name: str, labels: dict | None = None,
                 default: float | None = None) -> float | None:
    """First sample of `name` whose labels contain `labels`; with no
    labels given and several series, the SUM (the per-peer counters'
    natural fleet read)."""
    samples = metrics.get(name)
    if not samples:
        return default
    if labels:
        for lbls, v in samples:
            if all(lbls.get(k) == str(want) for k, want in labels.items()):
                return v
        return default
    if len(samples) == 1:
        return samples[0][1]
    return sum(v for _l, v in samples)


# -- scrape --------------------------------------------------------------------


def _base(url: str) -> str:
    return url if url.startswith("http") else f"http://{url}"


def fetch_metrics(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(f"{_base(url)}/metrics",
                                timeout=timeout) as r:
        return parse_prometheus(r.read().decode())


def fetch_health(url: str, timeout: float = 10.0) -> dict:
    """GET /health — parsed whatever the HTTP status (503 = failing is
    still a well-formed body, and exactly what a probe wants to read)."""
    req = urllib.request.Request(f"{_base(url)}/health")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except urllib.error.HTTPError as exc:
        body = exc.read().decode()
        try:
            return json.loads(body)
        except ValueError:
            raise exc


def fetch_traces(url: str, last: int = 10, timeout: float = 10.0) -> list:
    from tendermint_tpu.rpc.client import HTTPClient

    client = HTTPClient(url, timeout=timeout)
    return client.consensus_trace(last=int(last))["traces"]


def _collect_one(url: str, last: int) -> dict:
    entry: dict = {}
    try:
        entry["metrics"] = fetch_metrics(url)
        entry["health"] = fetch_health(url)
        entry["traces"] = fetch_traces(url, last=last)
    except Exception as exc:  # noqa: BLE001 — one dead node != no view
        entry["error"] = f"{type(exc).__name__}: {exc}"
    return entry


def collect(urls: list[str], last: int = 10) -> dict:
    """Scrape every node IN PARALLEL (one thread per node); a dead node
    contributes an {"error": ...} entry instead of killing the fleet
    view — and costs one timeout, not a serial stall of the whole
    render (partial fleets are exactly when an operator reaches for
    this tool)."""
    from concurrent.futures import ThreadPoolExecutor

    if not urls:
        return {}
    with ThreadPoolExecutor(max_workers=min(16, len(urls))) as pool:
        entries = pool.map(lambda u: _collect_one(u, last), urls)
        return dict(zip(urls, entries))


# -- timeline reconstruction ---------------------------------------------------


def _spread(instants: list[float]) -> float | None:
    return (max(instants) - min(instants)) if len(instants) >= 2 else None


def build_timeline(per_node_traces: dict, last: int = 10) -> list[dict]:
    """Join per-node traces into per-height cross-node rows, newest
    first. `per_node_traces`: {node_key: [trace dicts]} (the
    consensus_trace JSON shape). Rows carry None where a mark is absent
    (a catchup height has no prevote quorum; a single reporter has no
    skew) — the renderer prints "-", JSON keeps null."""
    by_height: dict[int, dict[str, dict]] = {}
    for node, traces in per_node_traces.items():
        for t in traces or []:
            by_height.setdefault(t["height"], {})[node] = t

    rows = []
    for height in sorted(by_height, reverse=True)[: max(1, int(last))]:
        nodes = by_height[height]
        first_parts, commits, quorum_s, prevote_q_s = [], [], [], []
        per_node = {}
        for node, t in nodes.items():
            arr = t.get("arrivals", {})
            start = t.get("started_at")
            fp, cm = arr.get("first_block_part"), arr.get("commit")
            if fp is not None:
                first_parts.append(fp)
            if cm is not None:
                commits.append(cm)
            pq, vq = arr.get("precommit_quorum"), arr.get("prevote_quorum")
            q = (pq - start) if (pq is not None and start is not None) \
                else None
            v = (vq - start) if (vq is not None and start is not None) \
                else None
            if q is not None:
                quorum_s.append(q)
            if v is not None:
                prevote_q_s.append(v)
            per_node[node] = {
                "wall_s": t.get("wall_s"),
                "rounds": t.get("rounds"),
                "first_part_at": fp,
                "commit_at": cm,
                "prevote_quorum_s": v,
                "precommit_quorum_s": q,
            }
        rows.append({
            "height": height,
            "nodes_reporting": len(nodes),
            "propagation_lag_s": _spread(first_parts),
            "prevote_quorum_s_max": max(prevote_q_s) if prevote_q_s else None,
            "precommit_quorum_s_max": max(quorum_s) if quorum_s else None,
            "precommit_quorum_s_min": min(quorum_s) if quorum_s else None,
            "commit_skew_s": _spread(commits),
            "per_node": per_node,
        })
    return rows


def fleet_summary(snapshot: dict) -> dict:
    """One status row per node off the scrape: height, peers, health,
    gossip send totals — the 'is the fleet alive' glance."""
    out = {}
    for url, entry in snapshot.items():
        if "error" in entry:
            out[url] = {"error": entry["error"]}
            continue
        m = entry["metrics"]
        health = entry.get("health", {})
        peers = (metric_value(m, "p2p_peers_outbound", default=0) or 0) + (
            metric_value(m, "p2p_peers_inbound", default=0) or 0
        )
        out[url] = {
            "height": metric_value(m, "consensus_height"),
            "peers": peers,
            "health": health.get("status", "?"),
            "vote_gossip_sends": metric_value(
                m, "p2p_peer_vote_gossip_sends_total", default=0
            ),
            "vote_gossip_send_failures": metric_value(
                m, "p2p_peer_vote_gossip_send_failures_total", default=0
            ),
        }
    return out


# -- rendering -----------------------------------------------------------------


def _ms(v) -> str:
    return "-" if v is None else f"{v * 1000:.1f}ms"


def render(snapshot: dict, rows: list[dict], out=sys.stdout) -> None:
    print("fleet:", file=out)
    for url, s in fleet_summary(snapshot).items():
        if "error" in s:
            print(f"  {url:<28} UNREACHABLE ({s['error']})", file=out)
            continue
        print(
            f"  {url:<28} height {int(s['height'] or 0):<7} "
            f"peers {int(s['peers']):<3} health {s['health']:<9} "
            f"gossip sends {int(s['vote_gossip_sends'] or 0)} "
            f"(+{int(s['vote_gossip_send_failures'] or 0)} failed)",
            file=out,
        )
    print(file=out)
    if not rows:
        print("no cross-node heights reconstructed yet", file=out)
        return
    print(
        f"{'height':>8}  {'nodes':>5}  {'prop-lag':>9}  "
        f"{'prevote-q':>10}  {'precommit-q':>11}  {'commit-skew':>11}",
        file=out,
    )
    for r in rows:
        print(
            f"{r['height']:>8}  {r['nodes_reporting']:>5}  "
            f"{_ms(r['propagation_lag_s']):>9}  "
            f"{_ms(r['prevote_quorum_s_max']):>10}  "
            f"{_ms(r['precommit_quorum_s_max']):>11}  "
            f"{_ms(r['commit_skew_s']):>11}",
            file=out,
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="cross-node height timelines + fleet health from "
                    "GET /metrics + consensus_trace + GET /health scrapes",
    )
    ap.add_argument("--urls", required=True,
                    help="comma-separated RPC addresses (host:port)")
    ap.add_argument("--last", type=int, default=10,
                    help="how many recent heights (default 10)")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON instead of the rendered tables")
    args = ap.parse_args(argv)
    urls = [u.strip() for u in args.urls.split(",") if u.strip()]

    snapshot = collect(urls, last=args.last)
    rows = build_timeline(
        {u: e.get("traces", []) for u, e in snapshot.items()},
        last=args.last,
    )
    try:
        if args.json:
            print(json.dumps({
                "fleet": fleet_summary(snapshot),
                "health": {u: e.get("health") for u, e in snapshot.items()},
                "timeline": rows,
            }, indent=2))
        else:
            render(snapshot, rows)
    except BrokenPipeError:
        sys.stderr.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
