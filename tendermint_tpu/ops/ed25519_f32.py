"""Batched Ed25519 verification in fp32 radix-2^8 limbs — the production
TPU kernel.

Replaces the reference's sequential per-signature verify loops
(types/vote_set.go:175, types/validator_set.go:247-250) with a wide SIMD
batch, like ops/ed25519.py — but the field arithmetic runs in float32,
where the TPU VPU fuses multiply+accumulate into FMAs. Measured on a
v5e chip this kernel's fmul is ~2x the int32 radix-2^15 variant's
(22.8us vs 43.9us per (B=8192) field multiply), because the schoolbook
row sums become FMA chains instead of separate int multiply + mask +
shift + add sequences.

EXACTNESS ARGUMENT (all fp32 values are integers; fp32 is exact for
integers < 2^24; every intermediate below stays under 2^23.5):

- Field elements are 32 limbs of radix 2^8, layout (32, B) float32,
  limb-major (batch minor = TPU lane dimension).
- "Loose" limbs after a 3-pass carry satisfy: limb0 <= 749, limbs 1..31
  <= 268 (pass 3 carries are <= 13, and limb0 absorbs 38*carry_top).
- fadd output: inputs <= 825 per limb -> sum <= 1650 -> 1-pass carry
  gives limb0 <= 255+38*6=483, others <= 262.
- fsub(a, b) = carry1(a + PAD - b) where PAD has all limbs in
  [1024, 1279] and value == 0 mod p (see _make_pad), so every limb stays
  non-negative; carry input <= 749+1279 = 2028 -> 1-pass output
  limb0 <= 255+38*7 = 521, others <= 262.
- fmul row sums: with operand limbs bounded as above, anti-diagonal k has
  at most one (0,0) term <= 749^2 = 562k, two limb0 cross terms
  <= 2*749*825 = 1.24M, and 30 generic terms <= 30*825^2 = 20M... the
  825 bound only ever applies to ONE operand (fadd outputs feed fmul
  opposite a table/carry-tight operand in every formula below); the
  worst real pairing is 825-vs-825 in point_double's fsq(fadd(x,y)):
  row sum <= 32*825^2 = 21.8M < 2^24.4 — TOO CLOSE, so point formulas
  pre-carry: fsq/fmul begin with a 1-pass carry when fed by fadd
  (handled by fadd itself carrying to <= 483/262: row sums
  <= 483^2 + 2*483*268 + 30*268^2 = 2.7M < 2^21.4). Products
  <= 749*268 < 2^17.7 each: exact.
- fold (rows k >= 32, weight 2^(8k) = 38*2^(8(k-32)) mod p): each row
  <= 2^21.6 is split hi/lo at 2^8 so the folded addends are <= 38*255
  and 38*2^13.6 = 2^18.9; post-fold rows <= 2^21.7.
- fmul's closing 3-pass carry: pass1 top carry <= 2^13.7 so
  limb0 <= 255 + 38*2^13.7 = 2^19; pass2 limb1 <= 255 + 2^11 = 2303,
  limb0 <= 255 + 38*66 = 2763; pass3 carries <= 13 -> the loose bound
  above. All carry intermediates < 2^21.7: exact.

Verification math is identical to ops/ed25519.py (strict cofactorless
RFC 8032: compress([s]B + [h](-A)) == R), and the host marshaling is
byte-level (radix-2^8 IS the byte string), which makes prepare cheaper
than the radix-2^15 bit repacking.

Tests cross-check lane-for-lane against crypto/ed25519.py (RFC 8032
vectors, random, malformed).
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto import ed25519 as ed_ref

P = ed_ref.P
L = ed_ref.L
NL = 32  # limbs
R = 256.0  # radix
RINV = 1.0 / 256.0


# ---------------------------------------------------------------------------
# constants
# ---------------------------------------------------------------------------


def _int_to_limbs_const(v: int) -> np.ndarray:
    return np.frombuffer(v.to_bytes(32, "little"), dtype=np.uint8).astype(np.float32)


def _make_pad() -> np.ndarray:
    """All-limb pad >= 1024, value == 0 mod p, digits <= 1279: lets fsub
    stay non-negative per limb for any loose operand (limbs <= 749)."""
    base = 1024 * sum(1 << (8 * k) for k in range(NL))
    c = (-base) % P
    digits = np.frombuffer(c.to_bytes(32, "little"), dtype=np.uint8).astype(np.float32)
    pad = digits + 1024.0
    assert (sum(int(pad[k]) << (8 * k) for k in range(NL))) % P == 0
    return pad


_PAD = _make_pad()
_D2 = _int_to_limbs_const((2 * ed_ref.D) % P)
_P_LIMBS = _int_to_limbs_const(P)
_BX = _int_to_limbs_const(ed_ref.B[0])
_BY = _int_to_limbs_const(ed_ref.B[1])


def _affine(pt) -> tuple[int, int]:
    zinv = pow(pt[2], P - 2, P)
    return (pt[0] * zinv % P, pt[1] * zinv % P)


_B2_AFF = _affine(ed_ref.point_add(ed_ref.B, ed_ref.B))
_B3_AFF = _affine(ed_ref.point_add(ed_ref.point_add(ed_ref.B, ed_ref.B), ed_ref.B))
_B2X, _B2Y = _int_to_limbs_const(_B2_AFF[0]), _int_to_limbs_const(_B2_AFF[1])
_B3X, _B3Y = _int_to_limbs_const(_B3_AFF[0]), _int_to_limbs_const(_B3_AFF[1])


# ---------------------------------------------------------------------------
# field arithmetic on (32, B) float32
# ---------------------------------------------------------------------------


def _roll38(hi: jax.Array) -> jax.Array:
    """Carries shift up one limb; the top carry wraps to limb 0 with
    weight 38 (2^256 = 2*19 mod p)."""
    return jnp.concatenate([38.0 * hi[NL - 1 :], hi[: NL - 1]], axis=0)


def _carry1(x: jax.Array) -> jax.Array:
    hi = jnp.floor(x * RINV)
    return x - hi * R + _roll38(hi)


def _carry3(x: jax.Array) -> jax.Array:
    return _carry1(_carry1(_carry1(x)))


def fadd(a: jax.Array, b: jax.Array) -> jax.Array:
    return _carry1(a + b)


def fsub(a: jax.Array, b: jax.Array) -> jax.Array:
    return _carry1(a + jnp.asarray(_PAD)[:, None] - b)


def fmul(a: jax.Array, b: jax.Array) -> jax.Array:
    """Schoolbook limb multiply as a depthwise 1-D convolution: the
    anti-diagonal row sums c_k = sum_i a_i*b_{k-i} ARE a length-32 full
    correlation per lane, which XLA lowers onto the MXU (batch = conv
    channels, limbs = spatial). Measured 13us vs 44us for the int32
    rank-1-update formulation at B=8192 — and ~15 HLO ops instead of ~90,
    so the full ladder graph compiles quickly.

    Precision=HIGHEST makes the MXU passes exact for the integer ranges
    here (products < 2^21, row sums < 2^23.5; verified against python
    ints with limbs pinned at the loose-bound maxima)."""
    batch = a.shape[-1]
    lhs = a.T[None]  # (1, B, 32)  N=1, C=batch, W=limbs
    rhs = b.T[:, None, ::-1]  # (B, 1, 32) depthwise filters (reversed)
    out = jax.lax.conv_general_dilated(
        lhs,
        rhs,
        window_strides=(1,),
        padding=[(NL - 1, NL - 1)],
        feature_group_count=batch,
        dimension_numbers=("NCW", "OIW", "NCW"),
        precision=jax.lax.Precision.HIGHEST,
    )
    rows = out[0].T  # (63, B): rows[k] = sum_{i+j=k} a_i * b_j
    # fold rows k>=32 (weight 2^(8k) = 38*2^(8(k-32)) mod p) with a hi/lo
    # split so every addend stays well under 2^24
    t = rows[NL:]
    t_hi = jnp.floor(t * RINV)
    t_lo = t - t_hi * R
    res = rows[:NL]
    res = res.at[: NL - 1].add(38.0 * t_lo)
    res = res.at[1:NL].add(38.0 * t_hi)
    return _carry3(res)


def fsq(a: jax.Array) -> jax.Array:
    return fmul(a, a)


def _rep_sq(x: jax.Array, n: int) -> jax.Array:
    if n <= 8:
        for _ in range(n):
            x = fsq(x)
        return x
    return jax.lax.fori_loop(0, n, lambda _, v: fsq(v), x)


def finv(z: jax.Array) -> jax.Array:
    z2 = fsq(z)
    z9 = fmul(_rep_sq(z2, 2), z)
    z11 = fmul(z9, z2)
    z_5_0 = fmul(fsq(z11), z9)
    z_10_0 = fmul(_rep_sq(z_5_0, 5), z_5_0)
    z_20_0 = fmul(_rep_sq(z_10_0, 10), z_10_0)
    z_40_0 = fmul(_rep_sq(z_20_0, 20), z_20_0)
    z_50_0 = fmul(_rep_sq(z_40_0, 10), z_10_0)
    z_100_0 = fmul(_rep_sq(z_50_0, 50), z_50_0)
    z_200_0 = fmul(_rep_sq(z_100_0, 100), z_100_0)
    z_250_0 = fmul(_rep_sq(z_200_0, 50), z_50_0)
    return fmul(_rep_sq(z_250_0, 5), z11)


def _seq_carry(x: jax.Array) -> jax.Array:
    """One sequential full carry pass limb 0 -> 31; the carry out of the
    top limb wraps to limb 0 with weight 38. Unlike the parallel _carry1
    (which leaves each limb's incoming carry un-propagated), this
    guarantees limbs 1..31 end in [0, 256); limb 0 may exceed 255 only by
    the wrapped 38*carry_top."""
    carry = jnp.zeros(x.shape[-1], dtype=jnp.float32)
    out = []
    for k in range(NL):
        v = x[k] + carry
        carry = jnp.floor(v * RINV)
        out.append(v - carry * R)
    res = jnp.stack(out, axis=0)
    return res.at[0].add(38.0 * carry)


def fcanon(x: jax.Array) -> jax.Array:
    """Fully reduce to canonical digits in [0, 256) representing a value
    in [0, p).

    Three sequential carry passes provably canonicalize any loose input
    (limbs <= 825): pass 1 carries are <= 3 so limb0 <= 255 + 38*3 = 369
    with all other digits < 256; pass 2's top carry is then <= 1 so
    limb0 <= 293; if pass 3 still wraps, the pre-wrap value was
    < 2^256 + 76, so the post-wrap value is < 76 + 38 — canonical either
    way. (A parallel-only carry chain is NOT enough: carries landing on
    limb 0 can leave it at up to 293 for values < p, and the digit-wise
    equality check in _verify_impl would then falsely reject a valid
    signature — found by round-2 review, regression-tested in
    tests/test_ops_f32.py.) Then <= 2 conditional subtractions of p
    bring the value below p (2^256 < 3p)."""
    x = _seq_carry(_seq_carry(_seq_carry(x)))
    for _ in range(2):
        borrow = None
        out = []
        for k in range(NL):
            v = x[k] - float(_P_LIMBS[k]) - (borrow if borrow is not None else 0.0)
            neg = (v < 0).astype(jnp.float32)
            out.append(v + neg * R)
            borrow = neg
        sub = jnp.stack(out, axis=0)
        ge = borrow == 0
        x = jnp.where(ge[None, :], sub, x)
    return x


# ---------------------------------------------------------------------------
# point arithmetic (extended coordinates), complete formulas
# ---------------------------------------------------------------------------


def point_add(p1, p2, d2):
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = fmul(fsub(y1, x1), fsub(y2, x2))
    b = fmul(fadd(y1, x1), fadd(y2, x2))
    c = fmul(fmul(t1, t2), d2)
    zz = fmul(z1, z2)
    d = fadd(zz, zz)
    e = fsub(b, a)
    f = fsub(d, c)
    g = fadd(d, c)
    h = fadd(b, a)
    return (fmul(e, f), fmul(g, h), fmul(f, g), fmul(e, h))


def point_double(p1):
    x1, y1, z1, _ = p1
    a = fsq(x1)
    b = fsq(y1)
    zz = fsq(z1)
    c = fadd(zz, zz)
    h = fadd(a, b)
    e = fsub(h, fsq(fadd(x1, y1)))
    g = fsub(a, b)
    f = fadd(c, g)
    return (fmul(e, f), fmul(g, h), fmul(f, g), fmul(e, h))


# ---------------------------------------------------------------------------
# the verify kernel
# ---------------------------------------------------------------------------


def _digits2(limbs_u8: jax.Array) -> jax.Array:
    """(32,B) int32 byte limbs -> (127,B) int32 2-bit digits MSB-first.
    Scalars < L < 2^253, so digits above 126 are zero."""
    shifts = jnp.arange(0, 8, 2, dtype=jnp.int32)  # bit pairs within a byte
    d = (limbs_u8[:, None, :] >> shifts[None, :, None]) & 3  # (32,4,B)
    d = d.reshape(NL * 4, limbs_u8.shape[-1])[:127]  # little-endian digits
    return d[::-1]


def _verify_impl(ax, ay, r_y, r_sign, s8, h8):
    """ax/ay: affine pubkey limbs (32,B) f32; r_y: R's y limbs (canonical);
    r_sign: (B,) int32 x-parity of R; s8/h8: (32,B) int32 byte limbs of the
    scalars. Returns bool[B].

    Interleaved Straus, 2-bit joint windows: 127 x (2 doublings + 1
    16-entry table add)."""
    batch = ax.shape[-1]
    # derive from the input (not jnp.zeros): the scan carry must be
    # batch-varying from step 0 under shard_map's manual axes (see the
    # same construction in ed25519_f32p._ladder); value-identical
    zeros = ax * 0.0
    one = zeros.at[0].set(1.0)
    d2 = jnp.broadcast_to(jnp.asarray(_D2)[:, None], (NL, batch))

    def const_pt(xc, yc):
        x = jnp.broadcast_to(jnp.asarray(xc)[:, None], (NL, batch))
        y = jnp.broadcast_to(jnp.asarray(yc)[:, None], (NL, batch))
        return (x, y, one, fmul(x, y))

    nax = fsub(zeros, ax)
    neg_a = (nax, ay, one, fmul(nax, ay))
    na2 = point_double(neg_a)
    na3 = point_add(na2, neg_a, d2)
    ident = (zeros, one, one, zeros)
    b_row = [ident, const_pt(_BX, _BY), const_pt(_B2X, _B2Y), const_pt(_B3X, _B3Y)]
    a_row = [ident, neg_a, na2, na3]
    table = []
    for j in range(4):
        for i in range(4):
            if i == 0:
                table.append(a_row[j])
            elif j == 0:
                table.append(b_row[i])
            else:
                table.append(point_add(b_row[i], a_row[j], d2))
    tcoords = [jnp.stack([t[c] for t in table], axis=0) for c in range(4)]  # (16,32,B)

    xs = jnp.stack([_digits2(s8), _digits2(h8)], axis=1)  # (127,2,B)
    idx16 = jnp.arange(16, dtype=jnp.int32)

    def step(acc, dig):
        acc = point_double(point_double(acc))
        sel = dig[0] + 4 * dig[1]  # (B,)
        onehot = (sel[None, :] == idx16[:, None]).astype(jnp.float32)  # (16,B)
        addend = tuple(jnp.sum(onehot[:, None, :] * tc, axis=0) for tc in tcoords)
        return point_add(acc, addend, d2), None

    acc, _ = jax.lax.scan(step, ident, xs)

    px, py, pz, _ = acc
    zinv = finv(pz)
    x_aff = fcanon(fmul(px, zinv))
    y_aff = fcanon(fmul(py, zinv))
    sign = x_aff[0].astype(jnp.int32) & 1
    return jnp.all(y_aff == fcanon(r_y), axis=0) & (sign == r_sign)


_verify_jit = jax.jit(_verify_impl)


# ---------------------------------------------------------------------------
# host marshaling: byte-level (radix-2^8 IS the little-endian byte string)
#
# This is the sustained-throughput bottleneck the kernel exposes: at
# batch 8192 the device runs ~91 ms while a per-item python loop
# (sha512 + decompress each) took ~146 ms, capping the delivered rate at
# half the kernel's. The marshal below is vectorized numpy for the
# canonical checks, one native C call per batch for the SHA512(R||A||M)
# mod L digests (tm_ed25519_hram_batch), and native batch decompression
# of only the UNIQUE pubkeys (validator keys repeat every commit) with a
# host-side cache. Pure-python fallbacks cover a missing native library.
# ---------------------------------------------------------------------------

_pubkey_cache: dict[bytes, tuple[bytes, bytes] | None] = {}

_L_ARR = np.frombuffer(L.to_bytes(32, "little"), dtype=np.uint8)
_P_ARR = np.frombuffer(P.to_bytes(32, "little"), dtype=np.uint8)
_Z32 = b"\x00" * 32
_Z64 = b"\x00" * 64


def _lt_bytes_le(a: np.ndarray, c: np.ndarray) -> np.ndarray:
    """value(a[i]) < value(c) for little-endian byte rows a (n,32) vs a
    constant c (32,) — vectorized big-endian lexicographic compare."""
    diff = a != c[None, :]
    first = diff[:, ::-1].argmax(axis=1)  # offset of most-significant diff
    idx = 31 - first
    less = a[np.arange(len(a)), idx] < c[idx]
    return diff.any(axis=1) & less


def _decompress_rows(pub_parts: list[bytes]):
    """n compressed keys -> ((n,32) x, (n,32) y, ok mask), deduplicating
    repeated keys (a commit is few validators, many messages) through the
    host cache, with native batch decompress for the misses."""
    from tendermint_tpu import native

    uniq_index: dict[bytes, int] = {}
    inv = np.empty(len(pub_parts), dtype=np.intp)
    uniq: list[bytes] = []
    for i, key in enumerate(pub_parts):
        j = uniq_index.get(key)
        if j is None:
            j = len(uniq)
            uniq_index[key] = j
            uniq.append(key)
        inv[i] = j
    u = len(uniq)
    ux = np.zeros((u, 32), dtype=np.uint8)
    uy = np.zeros((u, 32), dtype=np.uint8)
    uok = np.zeros(u, dtype=bool)
    misses = []
    for j, key in enumerate(uniq):
        hit = _pubkey_cache.get(key, False)
        if hit is False:
            misses.append(j)
        elif hit is not None:
            ux[j] = np.frombuffer(hit[0], dtype=np.uint8)
            uy[j] = np.frombuffer(hit[1], dtype=np.uint8)
            uok[j] = True
    if misses:
        if native.available():
            flat = np.frombuffer(
                b"".join(uniq[j] for j in misses), dtype=np.uint8
            )
            xy, ok = native.ed25519_decompress_batch(
                np.ascontiguousarray(flat), len(misses)
            )
            midx = np.asarray(misses)
            ux[midx] = xy[:, :32]
            uy[midx] = xy[:, 32:]
            uok[midx] = ok
            for k, j in enumerate(misses):
                if len(_pubkey_cache) < 1_000_000:
                    _pubkey_cache[uniq[j]] = (
                        (xy[k, :32].tobytes(), xy[k, 32:].tobytes())
                        if ok[k]
                        else None
                    )
        else:
            for j in misses:
                key = uniq[j]
                pt = ed_ref.point_decompress(key)
                res = None if pt is None else (
                    pt[0].to_bytes(32, "little"),
                    pt[1].to_bytes(32, "little"),
                )
                if len(_pubkey_cache) < 1_000_000:
                    _pubkey_cache[key] = res
                if res is not None:
                    ux[j] = np.frombuffer(res[0], dtype=np.uint8)
                    uy[j] = np.frombuffer(res[1], dtype=np.uint8)
                    uok[j] = True
    return ux[inv], uy[inv], uok[inv]


def _hram_rows(
    sigs: np.ndarray, pubs: np.ndarray, msgs: list[bytes], valid: np.ndarray
) -> np.ndarray:
    """(n,32) u8 LE rows of SHA512(R || A || M) mod L."""
    from tendermint_tpu import native

    n = len(msgs)
    if native.available():
        offsets = np.zeros(n + 1, dtype=np.uint64)
        total = 0
        for i, m in enumerate(msgs):
            total += len(m)
            offsets[i + 1] = total
        data = (
            np.frombuffer(b"".join(msgs), dtype=np.uint8)
            if total
            else np.zeros(1, np.uint8)
        )
        return native.ed25519_hram_batch(
            np.ascontiguousarray(sigs).reshape(-1),
            np.ascontiguousarray(pubs).reshape(-1),
            np.ascontiguousarray(data),
            offsets,
            n,
        )
    h8 = np.zeros((n, 32), dtype=np.uint8)
    for i in range(n):
        if not valid[i]:
            continue
        h = (
            int.from_bytes(
                hashlib.sha512(
                    sigs[i, :32].tobytes() + pubs[i].tobytes() + msgs[i]
                ).digest(),
                "little",
            )
            % L
        )
        h8[i] = np.frombuffer(h.to_bytes(32, "little"), dtype=np.uint8)
    return h8


def prepare_batch8(items: list[tuple[bytes, bytes, bytes]], bucket: int):
    """Marshal (pubkey, msg, sig) triples into kernel inputs.

    Returns (ax f32(32,B), ay f32(32,B), ry f32(32,B), r_sign int32(B,),
    s8 int32(32,B), h8 int32(32,B), valid bool(B,)). Invalid rows (bad
    point/non-canonical s or R/bad lengths) get benign placeholders and
    valid=False. The only per-item python is the shape check + bytes
    collection; checks/digests/decompression are vectorized or native."""
    n = len(items)
    pub_parts: list[bytes] = []
    sig_parts: list[bytes] = []
    msgs: list[bytes] = []
    shape_ok = np.ones(n, dtype=bool)
    for i, (pub, msg, sig) in enumerate(items):
        if len(sig) != 64 or len(pub) != 32:
            shape_ok[i] = False
            pub_parts.append(_Z32)
            sig_parts.append(_Z64)
            msgs.append(b"")
        else:
            pub_parts.append(bytes(pub))
            sig_parts.append(bytes(sig))
            msgs.append(bytes(msg))

    pubs = (
        np.frombuffer(b"".join(pub_parts), dtype=np.uint8).reshape(n, 32)
        if n
        else np.zeros((0, 32), dtype=np.uint8)
    )
    sigs = (
        np.frombuffer(b"".join(sig_parts), dtype=np.uint8).reshape(n, 64)
        if n
        else np.zeros((0, 64), dtype=np.uint8)
    )
    s_rows = sigs[:, 32:]
    r_rows = sigs[:, :32].copy()
    top = r_rows[:, 31].copy()
    r_rows[:, 31] &= 0x7F
    rs_rows = (top >> 7).astype(np.int32)

    s_ok = _lt_bytes_le(s_rows, _L_ARR)  # s < L
    r_ok = _lt_bytes_le(r_rows, _P_ARR)  # canonical R.y < p
    ax_rows, ay_rows, a_ok = _decompress_rows(pub_parts)
    valid_n = shape_ok & s_ok & r_ok & a_ok
    h_rows = _hram_rows(sigs, pubs, msgs, valid_n)

    # benign placeholders on invalid rows (and bucket padding): the kernel
    # runs every lane, so inputs must stay byte-valued; results are masked.
    inval = ~valid_n
    ax = np.zeros((bucket, 32), dtype=np.uint8)
    ay = np.zeros((bucket, 32), dtype=np.uint8)
    ay[:, 0] = 1
    ry = np.zeros((bucket, 32), dtype=np.uint8)
    ry[:, 0] = 1
    rs = np.zeros(bucket, dtype=np.int32)
    s8 = np.zeros((bucket, 32), dtype=np.uint8)
    h8 = np.zeros((bucket, 32), dtype=np.uint8)
    valid = np.zeros(bucket, dtype=bool)
    if n:
        ax[:n] = np.where(inval[:, None], 0, ax_rows)
        ay[:n] = np.where(inval[:, None], ay[:n], ay_rows)
        ry[:n] = np.where(inval[:, None], ry[:n], r_rows)
        rs[:n] = np.where(inval, 0, rs_rows)
        s8[:n] = np.where(inval[:, None], 0, s_rows)
        h8[:n] = np.where(inval[:, None], 0, h_rows)
        valid[:n] = valid_n

    return (
        np.ascontiguousarray(ax.T.astype(np.float32)),
        np.ascontiguousarray(ay.T.astype(np.float32)),
        np.ascontiguousarray(ry.T.astype(np.float32)),
        rs,
        np.ascontiguousarray(s8.T.astype(np.int32)),
        np.ascontiguousarray(h8.T.astype(np.int32)),
        valid,
    )


def _next_pow2(n: int) -> int:
    b = 8
    while b < n:
        b <<= 1
    return b


def verify_batch_async(items: list[tuple[bytes, bytes, bytes]]):
    """Marshal + enqueue the device kernel now; return a zero-arg resolver
    that materializes bool[B]. The single definition of the marshal/
    dispatch/mask sequence — verify_batch is this plus an immediate
    resolve, so the sync and async paths cannot drift."""
    n = len(items)
    if n == 0:
        return lambda: np.zeros(0, dtype=bool)
    bucket = _next_pow2(n)
    ax, ay, ry, rs, s8, h8, valid = prepare_batch8(items, bucket)
    ok_dev = _verify_jit(
        jnp.asarray(ax),
        jnp.asarray(ay),
        jnp.asarray(ry),
        jnp.asarray(rs),
        jnp.asarray(s8),
        jnp.asarray(h8),
    )
    return lambda: np.asarray(ok_dev)[:n] & valid[:n]


def verify_batch(items: list[tuple[bytes, bytes, bytes]]) -> np.ndarray:
    """Batched strict-RFC8032 verify -> bool[B]; semantics identical to
    crypto.ed25519.verify per item. Padded to power-of-two buckets so jit
    recompilation is bounded."""
    return verify_batch_async(items)()
