"""Batched Ed25519 signature verification for TPU (pure jnp, int32 lanes).

STATUS: tested math-reference implementation and selectable backend.
The production default is ops/ed25519_f32.py (94.4k sigs/s vs this
kernel's 50.0k at batch 8192 on a v5e — see ops/gateway.py KERNELS);
select this one with TENDERMINT_TPU_KERNEL=int32. It stays in-tree as
the independently-derived oracle the rigorous RFC 8032 / malformed-input
tests cross-check (tests/test_ops.py), and its limb codecs
(int_to_limbs_np, scalar_bits_np) are shared by the pallas kernel.

This kernel replaces the reference's sequential per-vote/per-commit Ed25519
verify loops (types/vote_set.go:175, types/validator_set.go:247-250) with a
wide SIMD batch: every lane verifies one signature, all lanes share the
instruction stream.

Design notes (TPU-first, not a port of any CPU bignum library):

- Field GF(2^255-19) in radix 2^15 with 17 limbs (15*17 = 255, so the
  modular fold is limb-aligned: limb k >= 17 folds into limb k-17 times 19).
- LIMB-MAJOR layout: a batch of field elements is int32[17, B] — the batch
  axis is the TPU's 128-wide lane dimension, the limb axis is the
  instruction stream. Every limb operation is a full-width vector op; with
  the batch axis minor there are no strided column accesses and no wasted
  lanes. (The batch-minor variant of this kernel measured ~25x slower.)
- 15-bit limbs keep every partial product under 2^30; products are split
  hi/lo at bit 15 BEFORE accumulation so row sums stay under 2^21 — the
  whole multiply needs no 64-bit type (TPU has no native wide int).
  Anti-diagonal accumulation uses shift-and-add via jnp.pad, not scatter.
- Verification checks the strict (cofactorless) RFC 8032 equation
  [s]B == R + [h]A, rearranged as P := [s]B + [h](-A), then point-compresses
  P and compares against the signature's R half. One field inversion
  (addition chain), no on-TPU decompression of R; pubkey decompression is
  cached per validator on host (validator sets are stable across blocks).
- Double-scalar multiplication is interleaved Straus over 253 bit
  positions under lax.scan: per bit one complete-Edwards doubling and one
  select-add from {identity, B, -A, B-A}. Complete formulas (RFC 8032
  section 5.1.4) mean no data-dependent branches.
- The outer SHA-512 hash h = H(R || A || M) mod L stays on HOST: hashing is
  C-speed and cheap; the TPU gets only fixed-shape scalar bit arrays.

Batch semantics match crypto/ed25519.verify exactly (tests cross-check
RFC 8032 vectors, random sign/verify, and malformed-input rejection).
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto import ed25519 as ed_ref

P = ed_ref.P
L = ed_ref.L
M15 = 0x7FFF
NLIMB = 17

# ---------------------------------------------------------------------------
# host <-> limb conversion (host arrays are (B, 17); device layout (17, B))
# ---------------------------------------------------------------------------


def int_to_limbs_np(vals: list[int]) -> np.ndarray:
    """list of ints < 2^256 -> int32[17, B] radix-2^15 limb-major limbs."""
    b = np.zeros((len(vals), 32), dtype=np.uint8)
    for i, v in enumerate(vals):
        b[i] = np.frombuffer(v.to_bytes(32, "little"), dtype=np.uint8)
    bits = np.unpackbits(b, axis=1, bitorder="little")  # (B, 256)
    limbs = bits[:, :255].reshape(len(vals), NLIMB, 15)
    weights = (1 << np.arange(15)).astype(np.int32)
    return np.ascontiguousarray((limbs * weights).sum(axis=2).astype(np.int32).T)


def limbs_to_int(limbs: np.ndarray) -> int:
    """int32[17] -> int."""
    return sum(int(limbs[k]) << (15 * k) for k in range(NLIMB))


def scalar_bits_np(vals: list[int], nbits: int = 253) -> np.ndarray:
    """ints -> int32[nbits, B] little-endian bit-major bits."""
    b = np.zeros((len(vals), 32), dtype=np.uint8)
    for i, v in enumerate(vals):
        b[i] = np.frombuffer(v.to_bytes(32, "little"), dtype=np.uint8)
    bits = np.unpackbits(b, axis=1, bitorder="little")
    return np.ascontiguousarray(bits[:, :nbits].astype(np.int32).T)


def _const_limbs(v: int) -> np.ndarray:
    return int_to_limbs_np([v])[:, 0]  # (17,)


_D2 = _const_limbs((2 * ed_ref.D) % P)
_P_LIMBS = np.array([32749] + [32767] * 16, dtype=np.int32)
_PX2 = (2 * _P_LIMBS).astype(np.int32)
_BX = _const_limbs(ed_ref.B[0])
_BY = _const_limbs(ed_ref.B[1])
_BT = _const_limbs((ed_ref.B[0] * ed_ref.B[1]) % P)
_SQRT_M1 = _const_limbs(ed_ref.I_SQRT)
_D_LIMBS = _const_limbs(ed_ref.D)


def _affine(pt) -> tuple[int, int]:
    zinv = pow(pt[2], P - 2, P)
    return (pt[0] * zinv % P, pt[1] * zinv % P)


# 2B and 3B affine constants for the 2-bit windowed ladder
_B2_AFF = _affine(ed_ref.point_add(ed_ref.B, ed_ref.B))
_B3_AFF = _affine(
    ed_ref.point_add(ed_ref.point_add(ed_ref.B, ed_ref.B), ed_ref.B)
)
_B2X, _B2Y = _const_limbs(_B2_AFF[0]), _const_limbs(_B2_AFF[1])
_B3X, _B3Y = _const_limbs(_B3_AFF[0]), _const_limbs(_B3_AFF[1])

# ---------------------------------------------------------------------------
# field arithmetic on (17, B) int32 arrays
# ---------------------------------------------------------------------------


def _roll19(hi: jax.Array) -> jax.Array:
    """Shift carries up one limb; the top limb's carry wraps to limb 0
    with weight 19 (2^255 = 19 mod p)."""
    return jnp.concatenate([19 * hi[NLIMB - 1 :], hi[: NLIMB - 1]], axis=0)


def _carry(x: jax.Array) -> jax.Array:
    """Reduce limbs to the LOOSE range [0, ~2^15]; inputs non-negative
    < 2^26 per limb. TWO fully-parallel passes instead of a 17-step
    sequential chain — the chain was the kernel's critical path (every
    fmul ends in a carry; the ladder runs ~4000 of them).

    Bounds: pass 1 carries < 2^11 (19x top-fold < 19*2^11), so y < 2^15 +
    19*2^11 < 2^17; pass 2 carries <= 3, leaving limbs <= 2^15 - 1 + 57.
    The multiply tolerates that loose bound: products stay < 2^31 and the
    17-row accumulator sums < 2^21 per window, refolding < 2^26 — inside
    this function's own input bound, so the loose form is closed under
    fmul/fadd/fsub."""
    y = (x & M15) + _roll19(x >> 15)
    return (y & M15) + _roll19(y >> 15)


def fadd(a: jax.Array, b: jax.Array) -> jax.Array:
    return _carry(a + b)


def fsub(a: jax.Array, b: jax.Array) -> jax.Array:
    return _carry(a + jnp.asarray(_PX2)[:, None] - b)


def fmul(a: jax.Array, b: jax.Array) -> jax.Array:
    """Schoolbook multiply, hi/lo split, shift-and-add accumulation.
    a, b: (17, B) -> (17, B). All int32, batch-width vector ops only."""
    # 17 rank-1 row updates: row i of the schoolbook grid is a[i] * b —
    # ONE (17,B) multiply — whose hi/lo halves land at limb windows
    # [i, i+17) and [i+1, i+18) of a 35-limb accumulator via static slice
    # adds. ~90 medium-sized HLO ops per multiply: small enough for XLA to
    # compile quickly, dataflow-only so it fuses with VMEM-resident
    # intermediates (the fully-unrolled 900-op variant compiled for >10min;
    # the batch-minor variant wasted 7/8 of the VPU lanes).
    batch = a.shape[-1]
    acc = jnp.zeros((34, batch), dtype=jnp.int32)
    for i in range(NLIMB):
        p = a[i][None, :] * b  # (17, B) < 2^30
        acc = acc.at[i : i + NLIMB].add(p & M15)
        acc = acc.at[i + 1 : i + 1 + NLIMB].add(p >> 15)
    # fold: limb k>=17 has weight 2^(15k) = 19 * 2^(15(k-17)); the hi
    # window of row 16 tops out at limb 33, so one fold suffices
    res = acc[:NLIMB] + 19 * acc[NLIMB:34]
    return _carry(res)


def fsq(a: jax.Array) -> jax.Array:
    return fmul(a, a)


def _rep_sq(x: jax.Array, n: int) -> jax.Array:
    """n repeated squarings; rolled into fori_loop past a small count to
    keep the HLO graph (and compile time) bounded."""
    if n <= 8:
        for _ in range(n):
            x = fsq(x)
        return x
    return jax.lax.fori_loop(0, n, lambda _, v: fsq(v), x)


def finv(z: jax.Array) -> jax.Array:
    """z^(p-2) via the standard 254-squaring addition chain."""
    z2 = fsq(z)
    z9 = fmul(_rep_sq(z2, 2), z)
    z11 = fmul(z9, z2)
    z_5_0 = fmul(fsq(z11), z9)  # 2^5 - 1
    z_10_0 = fmul(_rep_sq(z_5_0, 5), z_5_0)
    z_20_0 = fmul(_rep_sq(z_10_0, 10), z_10_0)
    z_40_0 = fmul(_rep_sq(z_20_0, 20), z_20_0)
    z_50_0 = fmul(_rep_sq(z_40_0, 10), z_10_0)
    z_100_0 = fmul(_rep_sq(z_50_0, 50), z_50_0)
    z_200_0 = fmul(_rep_sq(z_100_0, 100), z_100_0)
    z_250_0 = fmul(_rep_sq(z_200_0, 50), z_50_0)
    return fmul(_rep_sq(z_250_0, 5), z11)  # 2^255 - 21


def fcanon(x: jax.Array) -> jax.Array:
    """Fully reduce to the canonical representative in [0, p)."""
    x = _carry(x)
    for _ in range(2):
        borrow = None
        out = []
        for k in range(NLIMB):
            v = x[k] - int(_P_LIMBS[k]) - (borrow if borrow is not None else 0)
            out.append(v & M15)
            borrow = (v >> 15) & 1
        sub = jnp.stack(out, axis=0)
        ge = borrow == 0
        x = jnp.where(ge[None, :], sub, x)
    return x


def feq(a: jax.Array, b: jax.Array) -> jax.Array:
    """Canonical equality -> bool[B]."""
    return jnp.all(fcanon(a) == fcanon(b), axis=0)


# ---------------------------------------------------------------------------
# point arithmetic (extended coordinates X, Y, Z, T), complete formulas
# ---------------------------------------------------------------------------


def point_add(p1, p2):
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = fmul(fsub(y1, x1), fsub(y2, x2))
    b = fmul(fadd(y1, x1), fadd(y2, x2))
    c = fmul(fmul(t1, t2), jnp.asarray(_D2)[:, None])
    zz = fmul(z1, z2)
    d = fadd(zz, zz)
    e = fsub(b, a)
    f = fsub(d, c)
    g = fadd(d, c)
    h = fadd(b, a)
    return (fmul(e, f), fmul(g, h), fmul(f, g), fmul(e, h))


def point_double(p1):
    x1, y1, z1, _ = p1
    a = fsq(x1)
    b = fsq(y1)
    zz = fsq(z1)
    c = fadd(zz, zz)
    h = fadd(a, b)
    e = fsub(h, fsq(fadd(x1, y1)))
    g = fsub(a, b)
    f = fadd(c, g)
    return (fmul(e, f), fmul(g, h), fmul(f, g), fmul(e, h))


def _identity(batch: int):
    zeros = jnp.zeros((NLIMB, batch), dtype=jnp.int32)
    one = zeros.at[0].set(1)
    return (zeros, one, one, zeros)


def _select4(sel: jax.Array, options):
    """sel: int32[B] in 0..3; options: 4 points of (17,B) coords."""
    out = []
    for coord in range(4):
        stacked = jnp.stack([opt[coord] for opt in options], axis=0)  # (4,17,B)
        picked = jnp.take_along_axis(stacked, sel[None, None, :], axis=0)
        out.append(picked[0])
    return tuple(out)


# ---------------------------------------------------------------------------
# the verify kernel
# ---------------------------------------------------------------------------


def _digits2_from_limbs(limbs: jax.Array) -> jax.Array:
    """(17,B) 15-bit limbs -> (127,B) 2-bit digits, MSB-first. Scalars are
    < L < 2^253, so bits 253/254 are zero. Unpacking on-device keeps the
    host->device transfer at 17 words/scalar instead of 253 bit-ints —
    transfer volume was the sustained-throughput bottleneck."""
    shifts = jnp.arange(15, dtype=jnp.int32)
    bits = (limbs[:, None, :] >> shifts[None, :, None]) & 1  # (17,15,B)
    bits = bits.reshape(NLIMB * 15, limbs.shape[-1])[:254]  # little-endian
    d = bits[0::2] + 2 * bits[1::2]  # (127,B)
    return d[::-1]


def _verify_impl(ax, ay, r_y, r_sign, s_limbs, h_limbs):
    """ax/ay: affine pubkey limbs (17,B); r_y: R's y limbs (canonical,
    host-validated < p); r_sign: (B,) x-parity of R; s_limbs/h_limbs:
    (17,B) 15-bit limb encodings of the scalars. Returns bool[B].

    Interleaved Straus with 2-bit joint windows: 127 iterations of
    (2 doublings + 1 table add) instead of 253 x (1 doubling + 1 add) —
    same 253 doublings, half the point additions. The 16-entry table
    [i]B + [j](-A), i,j in 0..3, costs ~11 one-time point ops (B-side
    multiples are host constants)."""
    batch = ax.shape[-1]
    zeros = jnp.zeros((NLIMB, batch), dtype=jnp.int32)
    one = zeros.at[0].set(1)

    def const_pt(xc, yc):
        x = jnp.broadcast_to(jnp.asarray(xc)[:, None], (NLIMB, batch))
        y = jnp.broadcast_to(jnp.asarray(yc)[:, None], (NLIMB, batch))
        return (x, y, one, fmul(x, y))

    # -A = (p - x, y) and its small multiples
    nax = fsub(zeros, ax)
    neg_a = (nax, ay, one, fmul(nax, ay))
    na2 = point_double(neg_a)
    na3 = point_add(na2, neg_a)
    ident = _identity(batch)
    b_row = [ident, const_pt(_BX, _BY), const_pt(_B2X, _B2Y), const_pt(_B3X, _B3Y)]
    a_row = [ident, neg_a, na2, na3]
    table = []
    for j in range(4):  # h digit (multiples of -A)
        for i in range(4):  # s digit (multiples of B)
            if i == 0:
                table.append(a_row[j])
            elif j == 0:
                table.append(b_row[i])
            else:
                table.append(point_add(b_row[i], a_row[j]))
    tcoords = [
        jnp.stack([t[c] for t in table], axis=0) for c in range(4)
    ]  # 4 x (16,17,B)

    xs = jnp.stack(
        [_digits2_from_limbs(s_limbs), _digits2_from_limbs(h_limbs)], axis=1
    )  # (127,2,B)
    idx16 = jnp.arange(16, dtype=jnp.int32)

    def step(acc, dig):
        acc = point_double(point_double(acc))
        sel = dig[0] + 4 * dig[1]  # (B,)
        onehot = (sel[None, :] == idx16[:, None]).astype(jnp.int32)  # (16,B)
        addend = tuple(
            jnp.sum(onehot[:, None, :] * tc, axis=0) for tc in tcoords
        )
        return point_add(acc, addend), None

    acc, _ = jax.lax.scan(step, ident, xs)

    # compress P and compare with R
    px, py, pz, _ = acc
    zinv = finv(pz)
    x_aff = fcanon(fmul(px, zinv))
    y_aff = fcanon(fmul(py, zinv))
    sign = x_aff[0] & 1
    return jnp.all(y_aff == fcanon(r_y), axis=0) & (sign == r_sign)


_verify_jit = jax.jit(_verify_impl)


# ---------------------------------------------------------------------------
# pubkey decompression kernel (for cache misses / arbitrary key batches)
# ---------------------------------------------------------------------------


def _pow_2_252_m3(z: jax.Array) -> jax.Array:
    z2 = fsq(z)
    z9 = fmul(_rep_sq(z2, 2), z)
    z11 = fmul(z9, z2)
    z_5_0 = fmul(fsq(z11), z9)
    z_10_0 = fmul(_rep_sq(z_5_0, 5), z_5_0)
    z_20_0 = fmul(_rep_sq(z_10_0, 10), z_10_0)
    z_40_0 = fmul(_rep_sq(z_20_0, 20), z_20_0)
    z_50_0 = fmul(_rep_sq(z_40_0, 10), z_10_0)
    z_100_0 = fmul(_rep_sq(z_50_0, 50), z_50_0)
    z_200_0 = fmul(_rep_sq(z_100_0, 100), z_100_0)
    z_250_0 = fmul(_rep_sq(z_200_0, 50), z_50_0)
    return fmul(_rep_sq(z_250_0, 2), z)  # 2^252 - 3


def _decompress_impl(y_limbs, x_sign):
    """RFC 8032 5.1.3 point decompression, batched.
    Returns (x_limbs (17,B), valid bool[B])."""
    batch = y_limbs.shape[-1]
    zeros = jnp.zeros((NLIMB, batch), dtype=jnp.int32)
    one = zeros.at[0].set(1)
    # constants must be batch-width: fmul sizes its accumulator from its
    # FIRST argument's batch axis
    d_l = jnp.broadcast_to(jnp.asarray(_D_LIMBS)[:, None], (NLIMB, batch))
    sqrt_m1 = jnp.broadcast_to(jnp.asarray(_SQRT_M1)[:, None], (NLIMB, batch))
    y2 = fsq(y_limbs)
    u = fsub(y2, one)
    v = fadd(fmul(d_l, y2), one)
    v3 = fmul(fsq(v), v)
    v7 = fmul(fsq(v3), v)
    x = fmul(fmul(u, v3), _pow_2_252_m3(fmul(u, v7)))
    vx2 = fmul(v, fsq(x))
    ok_direct = feq(vx2, u)
    neg_u = fsub(zeros, u)
    ok_flip = feq(vx2, neg_u)
    x = jnp.where(ok_flip[None, :], fmul(x, sqrt_m1), x)
    x = fcanon(x)
    valid = ok_direct | ok_flip
    x_is_zero = jnp.all(x == 0, axis=0)
    want_flip = x_sign != (x[0] & 1)
    valid = valid & ~(x_is_zero & (x_sign == 1))
    x = jnp.where(want_flip[None, :], fsub(zeros, x), x)
    return fcanon(x), valid


_decompress_jit = jax.jit(_decompress_impl)


def decompress_batch(compressed: list[bytes]) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """32-byte encodings -> (x_limbs int32[17,B], y_limbs int32[17,B],
    valid bool[B]). Rejects non-canonical y >= p on host."""
    n = len(compressed)
    bucket = _next_pow2(max(n, 1))  # pad: one compiled program per bucket
    ys, signs, valid_host = [], [], []
    for c in compressed:
        yi = int.from_bytes(c, "little")
        signs.append((yi >> 255) & 1)
        yi &= (1 << 255) - 1
        if yi >= P:
            valid_host.append(False)
            ys.append(0)
        else:
            valid_host.append(True)
            ys.append(yi)
    ys += [1] * (bucket - n)
    signs += [0] * (bucket - n)
    valid_host += [False] * (bucket - n)
    y_limbs = int_to_limbs_np(ys)
    x_limbs, valid_dev = _decompress_jit(
        jnp.asarray(y_limbs), jnp.asarray(np.array(signs, dtype=np.int32))
    )
    valid = np.asarray(valid_dev) & np.array(valid_host)
    return np.asarray(x_limbs)[:, :n], y_limbs[:, :n], valid[:n]


# ---------------------------------------------------------------------------
# host orchestration
# ---------------------------------------------------------------------------

_pubkey_cache: dict[bytes, tuple[int, int] | None] = {}


def _decompress_pubkey_cached(pub: bytes) -> tuple[int, int] | None:
    """Affine (x, y) ints for a compressed pubkey; None if invalid.
    Cached: validator pubkeys repeat for every vote/commit."""
    hit = _pubkey_cache.get(pub, False)
    if hit is not False:
        return hit
    pt = ed_ref.point_decompress(pub)
    res = None if pt is None else (pt[0], pt[1])
    if len(_pubkey_cache) < 1_000_000:
        _pubkey_cache[pub] = res
    return res


def _next_pow2(n: int) -> int:
    b = 8
    while b < n:
        b <<= 1
    return b


def _prepare_ints(items: list[tuple[bytes, bytes, bytes]], bucket: int):
    """Shared host validation/marshaling: returns python-int columns
    (ax, ay, ry, r_sign, s, h, valid)."""
    ax_i, ay_i, ry_i = [0] * bucket, [1] * bucket, [1] * bucket
    rs = np.zeros(bucket, dtype=np.int32)
    s_i, h_i = [0] * bucket, [0] * bucket
    valid = np.zeros(bucket, dtype=bool)

    for i, (pub, msg, sig) in enumerate(items):
        if len(sig) != 64 or len(pub) != 32:
            continue
        aff = _decompress_pubkey_cached(bytes(pub))
        if aff is None:
            continue
        r_bytes, s_bytes = sig[:32], sig[32:]
        s = int.from_bytes(s_bytes, "little")
        if s >= L:
            continue
        ry = int.from_bytes(r_bytes, "little")
        r_sign = (ry >> 255) & 1
        ry &= (1 << 255) - 1
        if ry >= P:
            continue
        h = (
            int.from_bytes(
                hashlib.sha512(bytes(r_bytes) + bytes(pub) + bytes(msg)).digest(),
                "little",
            )
            % L
        )
        ax_i[i], ay_i[i], ry_i[i] = aff[0], aff[1], ry
        rs[i] = r_sign
        s_i[i], h_i[i] = s, h
        valid[i] = True
    return ax_i, ay_i, ry_i, rs, s_i, h_i, valid


def prepare_batch(items: list[tuple[bytes, bytes, bytes]], bucket: int):
    """Bit-array form (used by the pallas variant): returns
    (ax, ay, ry, r_sign, s_bits(253,B), h_bits(253,B), valid)."""
    ax_i, ay_i, ry_i, rs, s_i, h_i, valid = _prepare_ints(items, bucket)
    return (
        int_to_limbs_np(ax_i),
        int_to_limbs_np(ay_i),
        int_to_limbs_np(ry_i),
        rs,
        scalar_bits_np(s_i),
        scalar_bits_np(h_i),
        valid,
    )


def prepare_batch_limbs(items: list[tuple[bytes, bytes, bytes]], bucket: int):
    """Limb form (the jnp verify kernel): scalars travel as (17,B) 15-bit
    limbs; the kernel unpacks digits on-device."""
    ax_i, ay_i, ry_i, rs, s_i, h_i, valid = _prepare_ints(items, bucket)
    return (
        int_to_limbs_np(ax_i),
        int_to_limbs_np(ay_i),
        int_to_limbs_np(ry_i),
        rs,
        int_to_limbs_np(s_i),
        int_to_limbs_np(h_i),
        valid,
    )


# ---------------------------------------------------------------------------
# batched dual scalar multiplication: per-lane [a]P + [b]Q for VARIABLE
# points (the aggregate-commit verify's per-lane term [z_i]R_i +
# [z_i*h_i]A_i — see crypto/ed25519_agg.py and docs/upgrade.md). Same
# 2-bit interleaved Straus scan as _verify_impl, but the whole 16-entry
# table is built from per-lane points instead of host constants.
# ---------------------------------------------------------------------------


def _dsm_impl(px, py, qx, qy, a_limbs, b_limbs):
    """px/py, qx/qy: affine point limbs (17,B); a_limbs/b_limbs: (17,B)
    15-bit limb scalars (< L). Returns canonical affine (x (17,B),
    y (17,B)) of [a]P + [b]Q per lane."""
    batch = px.shape[-1]
    zeros = jnp.zeros((NLIMB, batch), dtype=jnp.int32)
    one = zeros.at[0].set(1)

    p1 = (px, py, one, fmul(px, py))
    q1 = (qx, qy, one, fmul(qx, qy))
    p2, q2 = point_double(p1), point_double(q1)
    p3, q3 = point_add(p2, p1), point_add(q2, q1)
    ident = _identity(batch)
    p_row = [ident, p1, p2, p3]
    q_row = [ident, q1, q2, q3]
    table = []
    for j in range(4):  # b digit (multiples of Q)
        for i in range(4):  # a digit (multiples of P)
            if i == 0:
                table.append(q_row[j])
            elif j == 0:
                table.append(p_row[i])
            else:
                table.append(point_add(p_row[i], q_row[j]))
    tcoords = [jnp.stack([t[c] for t in table], axis=0) for c in range(4)]

    xs = jnp.stack(
        [_digits2_from_limbs(a_limbs), _digits2_from_limbs(b_limbs)], axis=1
    )  # (127,2,B)
    idx16 = jnp.arange(16, dtype=jnp.int32)

    def step(acc, dig):
        acc = point_double(point_double(acc))
        sel = dig[0] + 4 * dig[1]
        onehot = (sel[None, :] == idx16[:, None]).astype(jnp.int32)
        addend = tuple(
            jnp.sum(onehot[:, None, :] * tc, axis=0) for tc in tcoords
        )
        return point_add(acc, addend), None

    acc, _ = jax.lax.scan(step, ident, xs)
    ax_, ay_, az_, _ = acc
    zinv = finv(az_)
    return fcanon(fmul(ax_, zinv)), fcanon(fmul(ay_, zinv))


_dsm_jit = jax.jit(_dsm_impl)

# identity lane padding for dsm_batch: [0]P + [0]Q from the neutral point
_DSM_PAD = (0, (0, 1), 0, (0, 1))


def dsm_batch(
    terms: list[tuple[int, tuple[int, int], int, tuple[int, int]]],
) -> list[tuple[int, int]]:
    """terms: (a, (px, py), b, (qx, qy)) per lane, scalars already
    reduced mod L, points affine on-curve (caller-validated — the
    aggregate path decompresses via crypto/ed25519.point_decompress).
    Returns per-lane affine [a]P + [b]Q as python ints. Padded to the
    next power of two like verify_batch (one compiled program per
    bucket)."""
    n = len(terms)
    if n == 0:
        return []
    bucket = _next_pow2(n)
    padded = list(terms) + [_DSM_PAD] * (bucket - n)
    a_i = [t[0] for t in padded]
    b_i = [t[2] for t in padded]
    px_i = [t[1][0] for t in padded]
    py_i = [t[1][1] for t in padded]
    qx_i = [t[3][0] for t in padded]
    qy_i = [t[3][1] for t in padded]
    x_l, y_l = _dsm_jit(
        jnp.asarray(int_to_limbs_np(px_i)),
        jnp.asarray(int_to_limbs_np(py_i)),
        jnp.asarray(int_to_limbs_np(qx_i)),
        jnp.asarray(int_to_limbs_np(qy_i)),
        jnp.asarray(int_to_limbs_np(a_i)),
        jnp.asarray(int_to_limbs_np(b_i)),
    )
    x_np, y_np = np.asarray(x_l), np.asarray(y_l)
    return [
        (limbs_to_int(x_np[:, i]), limbs_to_int(y_np[:, i])) for i in range(n)
    ]


def verify_batch(items: list[tuple[bytes, bytes, bytes]]) -> np.ndarray:
    """Batched strict-RFC8032 verify of (pubkey32, message, signature64)
    triples -> bool[B]. Semantics identical to crypto.ed25519.verify per
    item. Batch is padded to the next power of two so jit re-compilation is
    bounded (one program per bucket)."""
    n = len(items)
    if n == 0:
        return np.zeros(0, dtype=bool)
    bucket = _next_pow2(n)
    ax, ay, ry, rs, s_l, h_l, valid = prepare_batch_limbs(items, bucket)
    ok = _verify_jit(
        jnp.asarray(ax),
        jnp.asarray(ay),
        jnp.asarray(ry),
        jnp.asarray(rs),
        jnp.asarray(s_l),
        jnp.asarray(h_l),
    )
    return np.asarray(ok)[:n] & valid[:n]
