"""Pallas TPU kernel: fp32 radix-2^8 Ed25519 verify, VMEM-resident ladder.

STATUS: bake-off candidate, selectable with TENDERMINT_TPU_KERNEL=f32p.

Same field representation, bounds, and verification math as the XLA-composed
production kernel (ops/ed25519_f32.py — read its EXACTNESS ARGUMENT first;
every bound there applies unchanged here), but the entire 127-step joint
Straus ladder runs inside ONE pallas_call so intermediate limb rows never
round-trip through HBM between HLO ops. Two pallas-only wins over the
conv formulation:

- fsq uses the symmetric schoolbook (a_i*a_j counted once, doubled):
  ~528 FMAs instead of 1024. The row sums are mathematically identical to
  fmul(a, a)'s, so the f32 exactness bounds are unchanged.
- the 16-entry window-table select is an in-register masked FMA
  accumulation, not a gather through memory.

Field elements are Python lists of 32 (S, 128) float32 rows (limb-major,
fully unrolled limb arithmetic, batch in the lane dimensions) — the same
row discipline as the int32 pallas kernel (ops/ed25519_pallas.py), in the
arithmetic that won the round-2 bake-off.

Host marshaling is shared with ed25519_f32 (prepare_batch8); the 2-bit
digit expansion runs on-device outside the kernel (f32._digits2) so the
H2D payload stays byte-sized.

Reference hot loops this replaces: types/vote_set.go:175,
types/validator_set.go:247-250, blockchain/reactor.go:235.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tendermint_tpu.ops import ed25519_f32 as base

NL = base.NL  # 32 limbs of radix 2^8
R = base.R
RINV = base.RINV

_PAD_L = [float(v) for v in base._PAD]
_P_L = [float(v) for v in base._P_LIMBS]
_D2_L = [float(v) for v in base._D2]
_BX_L = [float(v) for v in base._BX]
_BY_L = [float(v) for v in base._BY]
_B2X_L = [float(v) for v in base._B2X]
_B2Y_L = [float(v) for v in base._B2Y]
_B3X_L = [float(v) for v in base._B3X]
_B3Y_L = [float(v) for v in base._B3Y]


# -- field arithmetic on lists of 32 (S, 128) f32 rows -----------------------


def _carry1_rows(x: list) -> list:
    """Parallel 1-pass carry, identical to base._carry1: hi = floor(x/256)
    moves up one limb; the top carry wraps to limb 0 with weight 38."""
    hi = [jnp.floor(x[k] * RINV) for k in range(NL)]
    out = [x[k] - hi[k] * R for k in range(NL)]
    out[0] = out[0] + 38.0 * hi[NL - 1]
    for k in range(1, NL):
        out[k] = out[k] + hi[k - 1]
    return out


def _carry3_rows(x: list) -> list:
    return _carry1_rows(_carry1_rows(_carry1_rows(x)))


def _fadd_rows(a: list, b: list) -> list:
    return _carry1_rows([a[k] + b[k] for k in range(NL)])


def _fsub_rows(a: list, b: list) -> list:
    return _carry1_rows([a[k] + _PAD_L[k] - b[k] for k in range(NL)])


def _fold_rows(acc: list) -> list:
    """acc: 63 anti-diagonal row sums; fold rows k>=32 with the hi/lo
    split from base.fmul (weight 2^(8k) = 38*2^(8(k-32)) mod p)."""
    res = list(acc[:NL])
    for k in range(NL, 2 * NL - 1):
        t = acc[k]
        t_hi = jnp.floor(t * RINV)
        t_lo = t - t_hi * R
        res[k - NL] = res[k - NL] + 38.0 * t_lo
        res[k - NL + 1] = res[k - NL + 1] + 38.0 * t_hi
    return _carry3_rows(res)


def _fmul_rows(a: list, b: list) -> list:
    acc = [None] * (2 * NL - 1)
    for i in range(NL):
        ai = a[i]
        for j in range(NL):
            p = ai * b[j]
            k = i + j
            acc[k] = p if acc[k] is None else acc[k] + p
    return _fold_rows(acc)


def _fsq_rows(a: list) -> list:
    """Symmetric schoolbook: same row sums as _fmul_rows(a, a) — the f32
    bounds hold verbatim — with ~half the FMAs."""
    acc = [None] * (2 * NL - 1)
    for i in range(NL):
        p = a[i] * a[i]
        k = 2 * i
        acc[k] = p if acc[k] is None else acc[k] + p
        for j in range(i + 1, NL):
            p2 = 2.0 * a[i] * a[j]
            k = i + j
            acc[k] = p2 if acc[k] is None else acc[k] + p2
    return _fold_rows(acc)


def _point_add_rows(p1, p2, d2):
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = _fmul_rows(_fsub_rows(y1, x1), _fsub_rows(y2, x2))
    b = _fmul_rows(_fadd_rows(y1, x1), _fadd_rows(y2, x2))
    c = _fmul_rows(_fmul_rows(t1, t2), d2)
    zz = _fmul_rows(z1, z2)
    d = _fadd_rows(zz, zz)
    e = _fsub_rows(b, a)
    f = _fsub_rows(d, c)
    g = _fadd_rows(d, c)
    h = _fadd_rows(b, a)
    return (
        _fmul_rows(e, f),
        _fmul_rows(g, h),
        _fmul_rows(f, g),
        _fmul_rows(e, h),
    )


def _point_double_rows(p1):
    x1, y1, z1, _ = p1
    a = _fsq_rows(x1)
    b = _fsq_rows(y1)
    zz = _fsq_rows(z1)
    c = _fadd_rows(zz, zz)
    h = _fadd_rows(a, b)
    e = _fsub_rows(h, _fsq_rows(_fadd_rows(x1, y1)))
    g = _fsub_rows(a, b)
    f = _fadd_rows(c, g)
    return (
        _fmul_rows(e, f),
        _fmul_rows(g, h),
        _fmul_rows(f, g),
        _fmul_rows(e, h),
    )


def _seq_carry_rows(x: list) -> list:
    carry = None
    out = []
    for k in range(NL):
        v = x[k] if carry is None else x[k] + carry
        carry = jnp.floor(v * RINV)
        out.append(v - carry * R)
    out[0] = out[0] + 38.0 * carry
    return out


def _fcanon_rows(x: list) -> list:
    """Port of base.fcanon (3 sequential passes + <=2 conditional
    p-subtractions); see its docstring for why parallel carries alone are
    not enough."""
    x = _seq_carry_rows(_seq_carry_rows(_seq_carry_rows(x)))
    for _ in range(2):
        borrow = None
        out = []
        for k in range(NL):
            v = x[k] - _P_L[k] - (borrow if borrow is not None else 0.0)
            neg = (v < 0).astype(jnp.float32)
            out.append(v + neg * R)
            borrow = neg
        ge = borrow == 0
        x = [jnp.where(ge, out[k], x[k]) for k in range(NL)]
    return x


def _finv_rows(z: list) -> list:
    def rep_sq(x, n):
        if n <= 4:
            for _ in range(n):
                x = _fsq_rows(x)
            return x

        def body(_, v):
            return jnp.stack(_fsq_rows([v[k] for k in range(NL)]))

        stacked = jax.lax.fori_loop(0, n, body, jnp.stack(x))
        return [stacked[k] for k in range(NL)]

    z2 = _fsq_rows(z)
    z9 = _fmul_rows(rep_sq(z2, 2), z)
    z11 = _fmul_rows(z9, z2)
    z_5_0 = _fmul_rows(_fsq_rows(z11), z9)
    z_10_0 = _fmul_rows(rep_sq(z_5_0, 5), z_5_0)
    z_20_0 = _fmul_rows(rep_sq(z_10_0, 10), z_10_0)
    z_40_0 = _fmul_rows(rep_sq(z_20_0, 20), z_20_0)
    z_50_0 = _fmul_rows(rep_sq(z_40_0, 10), z_10_0)
    z_100_0 = _fmul_rows(rep_sq(z_50_0, 50), z_50_0)
    z_200_0 = _fmul_rows(rep_sq(z_100_0, 100), z_100_0)
    z_250_0 = _fmul_rows(rep_sq(z_200_0, 50), z_50_0)
    return _fmul_rows(rep_sq(z_250_0, 5), z11)


# -- the kernel ---------------------------------------------------------------


def _ladder(ax_ref, ay_ref, ry_ref, rsign_ref, dig_s_ref, dig_h_ref):
    """The full f32p verify ladder — table build, 127-step joint Straus
    walk with masked-FMA select, inversion, canonicalization, R-point
    comparison. Written against ref-OR-array inputs: `x[k]` (static limb
    index) and `x[i]` (traced step index) mean the same thing for a
    pallas VMEM ref and a jnp array, so ONE body serves both the Mosaic
    kernel (_verify_kernel) and the plain-XLA per-shard path the sharded
    verifier runs on non-TPU meshes (make_sharded_verify). Returns the
    (S, LANES) int32 accept mask."""
    S, LANES = ax_ref.shape[1], ax_ref.shape[2]

    def rows(ref):
        return [ref[k] for k in range(NL)]

    def const_rows(vals):
        return [jnp.full((S, LANES), v, dtype=jnp.float32) for v in vals]

    # derive zero/one from the input rows (not jnp.zeros): under
    # shard_map the fori_loop carry must be batch-varying from step 0,
    # and a fresh constant is replicated — the scan would reject the
    # carry with a varying-manual-axes mismatch. Inside the pallas
    # kernel this is the same value either way.
    zero = ax_ref[0] * 0.0
    one_v = zero + 1.0
    zeros = [zero] * NL
    one = [one_v] + [zero] * (NL - 1)
    d2 = const_rows(_D2_L)

    ax = rows(ax_ref)
    ay = rows(ay_ref)

    def const_pt(xl, yl):
        x, y = const_rows(xl), const_rows(yl)
        return (x, y, one, _fmul_rows(x, y))

    nax = _fsub_rows(zeros, ax)
    neg_a = (nax, ay, one, _fmul_rows(nax, ay))
    na2 = _point_double_rows(neg_a)
    na3 = _point_add_rows(na2, neg_a, d2)
    ident = (zeros, one, one, zeros)
    b_row = [ident, const_pt(_BX_L, _BY_L), const_pt(_B2X_L, _B2Y_L), const_pt(_B3X_L, _B3Y_L)]
    a_row = [ident, neg_a, na2, na3]
    table = []
    for j in range(4):
        for i in range(4):
            if i == 0:
                table.append(a_row[j])
            elif j == 0:
                table.append(b_row[i])
            else:
                table.append(_point_add_rows(b_row[i], a_row[j], d2))
    def step(i, acc):
        acc = _point_double_rows(_point_double_rows(acc))
        sel = dig_s_ref[i] + 4 * dig_h_ref[i]  # (S, LANES) int32
        # masked-FMA 16-way select, accumulated row-by-row so the loop
        # carry stays a pytree of rows (no stack/unstack copies per step)
        masks = [(sel == e).astype(jnp.float32) for e in range(16)]
        addend = tuple(
            [
                sum(masks[e] * table[e][c][k] for e in range(16))
                for k in range(NL)
            ]
            for c in range(4)
        )
        res = _point_add_rows(acc, addend, d2)
        return tuple(tuple(res[c]) for c in range(4))

    acc0 = tuple(tuple(ident[c]) for c in range(4))
    acc = jax.lax.fori_loop(0, 127, step, acc0)

    px, py, pz, _ = acc
    zinv = _finv_rows(pz)
    x_aff = _fcanon_rows(_fmul_rows(px, zinv))
    y_aff = _fcanon_rows(_fmul_rows(py, zinv))
    ry = _fcanon_rows(rows(ry_ref))
    eq = jnp.ones((S, LANES), dtype=jnp.bool_)
    for k in range(NL):
        eq = eq & (y_aff[k] == ry[k])
    sign = jnp.mod(x_aff[0], 2.0).astype(jnp.int32)
    eq = eq & (sign == rsign_ref[0])
    return eq.astype(jnp.int32)


def _verify_kernel(ax_ref, ay_ref, ry_ref, rsign_ref, dig_s_ref, dig_h_ref, out_ref):
    out_ref[0] = _ladder(ax_ref, ay_ref, ry_ref, rsign_ref, dig_s_ref, dig_h_ref)


S_TILE = 8  # (8, 128) f32 rows; tile = 1024 lanes (Mosaic requires the
# second-to-last block dim divisible by 8). Window table 16*4*32 rows
# = 8.4MB VMEM; total working set fits in v5e's 16MB with the inputs.


def _make_verify(s_tile: int, interpret: bool):
    def call(ax, ay, ry, rsign, dig_s, dig_h):
        s_total = ax.shape[1]
        spec32 = pl.BlockSpec(
            (NL, s_tile, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM
        )
        spec127 = pl.BlockSpec(
            (127, s_tile, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM
        )
        spec1 = pl.BlockSpec(
            (1, s_tile, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM
        )
        return pl.pallas_call(
            _verify_kernel,
            grid=(s_total // s_tile,),
            in_specs=[spec32, spec32, spec32, spec1, spec127, spec127],
            out_specs=spec1,
            out_shape=jax.ShapeDtypeStruct((1, s_total, 128), jnp.int32),
            interpret=interpret,
        )(ax, ay, ry, rsign, dig_s, dig_h)

    return jax.jit(call)


_verify_calls: dict = {}


def _get_verify(tile: int, interpret: bool):
    key = (tile, interpret)
    if key not in _verify_calls:
        _verify_calls[key] = _make_verify(tile, interpret)
    return _verify_calls[key]


def _on_tpu() -> bool:
    from tendermint_tpu.ops.gateway import on_tpu

    return on_tpu()


@jax.jit
def _expand_digits(s8, h8):
    """(32, B) int32 byte limbs -> (127, S, 128) 2-bit digits MSB-first,
    computed on device so the H2D payload stays byte-shaped."""
    ds = base._digits2(s8).reshape(127, -1, 128)
    dh = base._digits2(h8).reshape(127, -1, 128)
    return ds, dh


def marshal_device_args(items: list[tuple[bytes, bytes, bytes]]):
    """Host marshal + H2D: kernel-call args for a batch. Returns
    (args, valid, n) where args feeds _get_verify(S_TILE, ...) directly.
    The SINGLE definition of the dispatch layout — verify_batch_async and
    the out-of-suite soak (scripts/check_f32.py) both use it, so a layout
    change cannot silently leave the soak measuring a stale path."""
    n = len(items)
    tile_lanes = S_TILE * 128
    # power-of-two tile counts so distinct Mosaic compiles stay bounded at
    # log2(maxN) shapes (the 127-step unrolled ladder takes ~2min to
    # compile; a fresh compile per 1024-lane band would stall consensus)
    n_tiles = 1
    while n_tiles * tile_lanes < n:
        n_tiles <<= 1
    bucket = n_tiles * tile_lanes
    ax, ay, ry, rs, s8, h8, valid = base.prepare_batch8(items, bucket)
    s_total = bucket // 128
    dig_s, dig_h = _expand_digits(jnp.asarray(s8), jnp.asarray(h8))
    args = (
        jnp.asarray(ax.reshape(NL, s_total, 128)),
        jnp.asarray(ay.reshape(NL, s_total, 128)),
        jnp.asarray(ry.reshape(NL, s_total, 128)),
        jnp.asarray(rs.reshape(1, s_total, 128)),
        dig_s,
        dig_h,
    )
    return args, valid, n


def verify_batch_async(items: list[tuple[bytes, bytes, bytes]]):
    """Marshal + enqueue now; return a zero-arg resolver for bool[B] —
    same pipelining contract as base.verify_batch_async."""
    if len(items) == 0:
        return lambda: np.zeros(0, dtype=bool)
    args, valid, n = marshal_device_args(items)
    fn = _get_verify(S_TILE, not _on_tpu())
    ok = fn(*args)
    return lambda: materialize_verdicts(ok, valid, n)


def verify_batch(items: list[tuple[bytes, bytes, bytes]]) -> np.ndarray:
    """Drop-in gateway backend (same contract as base.verify_batch)."""
    return verify_batch_async(items)()


# -- multi-chip: the ladder sharded over a device mesh ------------------------

_sharded_calls: dict = {}


def lane_quantum(n_dev: int, on_tpu: bool) -> int:
    """Smallest lane count divisible into equal per-device shards: each
    device takes whole (S, 128) rows, and on TPU Mosaic additionally
    needs S_TILE rows per grid step."""
    return n_dev * 128 * (S_TILE if on_tpu else 1)


def make_sharded_verify(mesh, on_tpu: bool):
    """jit(shard_map(per-shard verify)) over `mesh`'s "batch" axis — the
    f32p kernel's multi-chip path.

    Pure data parallelism: all inputs are (rows, S, 128) with the S
    dimension sharded, each chip verifies its slice, no collectives
    (independent signature lanes — SURVEY §2.3). The per-shard body:

    - TPU mesh: byte-digit expansion (base._digits2, plain XLA) feeding
      the SAME Mosaic pallas_call the single-chip path runs — the
      VMEM-resident ladder, grid over the shard's tiles.
    - non-TPU mesh: the conv-lowered fp32 ladder (base._verify_impl) on
      the shard's flattened lanes. The unrolled pallas body cannot stand
      in here: it is Mosaic-shaped (~3*10^5 scalar HLO ops), and XLA CPU
      was measured at >40min compiling it (interpret mode: >9min for ONE
      128-lane tile). Same field representation, same radix-2^8 ladder
      algorithm, same accept/reject semantics (lane-for-lane parity is
      pinned by tests); the pallas BODY's own parity stays covered by the
      hardware-gated single-chip test (tests/test_ops_f32.py).

    So a CPU-mesh run (tests, dryrun_multichip) executes the f32p path's
    real sharding structure — specs, bucketing, marshal, digit layout —
    end to end, and a TPU mesh runs the real kernel per chip."""
    n_dev = mesh.size
    # Mesh is hashable by value — an id() key could hand a NEW mesh at a
    # recycled address the stale compiled call of a dead one
    key = (mesh, on_tpu)
    if key in _sharded_calls:
        return _sharded_calls[key]
    import jax
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as PS

    spec = PS(None, "batch", None)

    def per_shard(ax, ay, ry, rs, s8, h8):
        s_local = ax.shape[1]
        if on_tpu:
            ds = base._digits2(s8.reshape(32, -1)).reshape(127, s_local, 128)
            dh = base._digits2(h8.reshape(32, -1)).reshape(127, s_local, 128)
            spec32 = pl.BlockSpec(
                (NL, S_TILE, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM
            )
            spec127 = pl.BlockSpec(
                (127, S_TILE, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM
            )
            spec1 = pl.BlockSpec(
                (1, S_TILE, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM
            )
            return pl.pallas_call(
                _verify_kernel,
                grid=(s_local // S_TILE,),
                in_specs=[spec32, spec32, spec32, spec1, spec127, spec127],
                out_specs=spec1,
                out_shape=jax.ShapeDtypeStruct((1, s_local, 128), jnp.int32),
            )(ax, ay, ry, rs, ds, dh)
        ok = base._verify_impl(
            ax.reshape(NL, -1), ay.reshape(NL, -1), ry.reshape(NL, -1),
            rs.reshape(-1), s8.reshape(32, -1), h8.reshape(32, -1),
        )
        return ok.astype(jnp.int32).reshape(1, s_local, 128)

    fn = jax.jit(
        shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(spec, spec, spec, spec, spec, spec),
            out_specs=spec,
        )
    )
    _sharded_calls[key] = fn
    return fn


def sharded_verify_arrays(items, mesh, on_tpu: bool):
    """Marshal + dispatch a batch through make_sharded_verify, returning
    (ok_device_array, valid_mask, n) with the result STILL on device and
    sharded over the mesh — callers can inspect `.addressable_shards` to
    assert the per-device layout (dryrun_multichip does) before
    materializing. Buckets to the smallest power of two >= n that divides
    into equal per-device shards (compile count stays bounded at
    log2(maxN) shapes per mesh)."""
    n = len(items)
    if n == 0:
        return None, np.zeros(0, dtype=bool), 0
    q = lane_quantum(mesh.size, on_tpu)
    bucket = q
    while bucket < n:
        bucket <<= 1
    ax, ay, ry, rs, s8, h8, valid = base.prepare_batch8(items, bucket)
    s_total = bucket // 128
    fn = make_sharded_verify(mesh, on_tpu)
    ok = fn(
        jnp.asarray(ax.reshape(NL, s_total, 128)),
        jnp.asarray(ay.reshape(NL, s_total, 128)),
        jnp.asarray(ry.reshape(NL, s_total, 128)),
        jnp.asarray(rs.reshape(1, s_total, 128)),
        jnp.asarray(s8.reshape(32, s_total, 128)),
        jnp.asarray(h8.reshape(32, s_total, 128)),
    )
    return ok, valid, n


def materialize_verdicts(ok, valid, n: int) -> np.ndarray:
    """Fetch a device verdict array and mask to per-item booleans — the
    ONE masking tail every batched-verify exit shares (gateway sharded
    paths included), so accept/reject coercion can never drift between
    call sites."""
    if n == 0:
        return np.zeros(0, dtype=bool)
    return (np.asarray(ok).reshape(-1)[:n] != 0) & valid[:n]


def sharded_verify_batch(items, mesh, on_tpu: bool) -> np.ndarray:
    """Materialized form of sharded_verify_arrays (the gateway's entry)."""
    ok, valid, n = sharded_verify_arrays(items, mesh, on_tpu)
    return materialize_verdicts(ok, valid, n)
