"""Vectorized simple-Merkle-tree hashing for TPU.

Replaces the reference's sequential tree loops (types/part_set.go:95-122
NewPartSetFromData, types/tx.go:33-46 Txs.Hash) with level-parallel batched
RIPEMD-160:

1. Host computes the tree SHAPE only — the left-heavy (n+1)//2 split,
   taken from THE shape oracle merkle.simple._flat_shape (one
   implementation serves this kernel and the host FlatTree builder, so
   their postorder slot contract cannot drift) — as a dense schedule of
   (left, right, out) node-slot triples grouped into dependency rounds
   (height levels). The schedule depends only on n and is lru-cached per
   exact leaf count (leaves cannot be padded: the tree over the first n
   leaves of a padded set is a different tree). Part-set sizes repeat
   heavily so the cache hits; _run_tree jit-specializes on
   (slots, n_rounds) which collide often.
2. TPU holds a node-slot buffer of 20-byte digests as uint32[slots, 5] and,
   per round, gathers children, assembles the 44-byte inner-node preimage
   (length-prefixed left || length-prefixed right — matching
   merkle.simple.inner_hash exactly) entirely with integer shifts, and runs
   one batched compression.

The returned node buffer also yields every internal node, so SimpleProof
aunts come for free without extra hashing (used by PartSet.from_data).
Slot order (leaves 0..n-1, then internal nodes in postorder, root last)
matches merkle.simple.FlatTree exactly — tree_nodes_from_leaf_digests
feeds FlatTree.from_nodes, which is how the devd hash_stream tree frame
turns one device pass into host root + every proof with zero host
hashing.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.ops.hashing import (
    _INIT_RIPEMD,
    _ripemd160_block,
    digests_to_bytes_le,
    pack_messages,
    ripemd160_words,
)

# ---------------------------------------------------------------------------
# Host: tree schedule
# ---------------------------------------------------------------------------


@lru_cache(maxsize=64)
def _dense_schedule(n_bucket: int):
    """Dense schedule arrays for one exact leaf count (n_bucket >= 2),
    derived from THE shape oracle (merkle.simple._flat_shape) — one
    implementation of the postorder slot order + by-height level
    grouping serves both the host FlatTree builder and this kernel, so
    the byte-parity contract between them cannot drift.
    left/right/out: int32[n_rounds, max_width]; entries beyond a round's
    width are no-ops (combine slot 0,0 -> scratch).
    Returns (left, right, out, scratch_slot, buffer_rows, real_slots,
    n_rounds); real_slots = 2n-1 (root last), buffer_rows adds the
    scratch sink row."""
    from tendermint_tpu.merkle.simple import _flat_shape

    _, _, levels = _flat_shape(n_bucket)
    n_rounds = len(levels)
    max_width = max(len(level) for level in levels)
    real_slots = 2 * n_bucket - 1
    scratch = real_slots  # one extra slot absorbs no-op writes
    left = np.zeros((n_rounds, max_width), dtype=np.int32)
    right = np.zeros((n_rounds, max_width), dtype=np.int32)
    out = np.full((n_rounds, max_width), scratch, dtype=np.int32)
    for r, level in enumerate(levels):
        for k, (o, ls, rs) in enumerate(level):
            left[r, k] = ls
            right[r, k] = rs
            out[r, k] = o
    return left, right, out, scratch, real_slots + 1, real_slots, n_rounds


# ---------------------------------------------------------------------------
# TPU: inner-node preimage assembly + per-round hashing
# ---------------------------------------------------------------------------

# 44-byte preimage: 0x01 0x14 | left(20) | 0x01 0x14 | right(20), then MD
# padding: 0x80 at byte 44, zeros, bit length 352 in LE at bytes 56..63.


def _bytes_from_words(w: jax.Array) -> jax.Array:
    """uint32[B,5] -> uint32[B,20] byte values (LE)."""
    shifts = jnp.arange(4, dtype=jnp.uint32) * 8
    b = (w[:, :, None] >> shifts[None, None, :]) & jnp.uint32(0xFF)
    return b.reshape(w.shape[0], 20)


def _inner_preimage_words(left: jax.Array, right: jax.Array) -> jax.Array:
    """left/right digests uint32[B,5] -> one padded block uint32[B,16]."""
    B = left.shape[0]
    lb = _bytes_from_words(left)
    rb = _bytes_from_words(right)
    buf = jnp.zeros((B, 64), dtype=jnp.uint32)
    pre = jnp.uint32(0x01), jnp.uint32(0x14)
    buf = buf.at[:, 0].set(pre[0]).at[:, 1].set(pre[1])
    buf = jax.lax.dynamic_update_slice(buf, lb, (0, 2))
    buf = buf.at[:, 22].set(pre[0]).at[:, 23].set(pre[1])
    buf = jax.lax.dynamic_update_slice(buf, rb, (0, 24))
    buf = buf.at[:, 44].set(jnp.uint32(0x80))
    buf = buf.at[:, 56].set(jnp.uint32(0x60)).at[:, 57].set(jnp.uint32(0x01))
    shifts = jnp.arange(4, dtype=jnp.uint32) * 8
    words = (buf.reshape(B, 16, 4) << shifts[None, None, :]).sum(
        axis=2, dtype=jnp.uint32
    )
    return words


def _inner_hash_batch(left: jax.Array, right: jax.Array) -> jax.Array:
    """Batched inner_hash on digests uint32[B,5] -> uint32[B,5]."""
    words = _inner_preimage_words(left, right)
    init = jnp.broadcast_to(jnp.asarray(_INIT_RIPEMD), (left.shape[0], 5))
    return _ripemd160_block(init, words)


@partial(jax.jit, static_argnames=("n_rounds",))
def _run_tree(nodes: jax.Array, left: jax.Array, right: jax.Array, out: jax.Array,
              n_rounds: int) -> jax.Array:
    """nodes: uint32[slots,5] with leaves filled; returns all slots filled."""

    def round_body(r, nodes):
        l = nodes[left[r]]
        rt = nodes[right[r]]
        h = _inner_hash_batch(l, rt)
        return nodes.at[out[r]].set(h)

    return jax.lax.fori_loop(0, n_rounds, round_body, nodes)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def tree_nodes_from_leaf_digests(digests: list[bytes]) -> list[bytes]:
    """All 2n-1 tree node hashes from 20-byte leaf digests — leaves
    0..n-1, internal nodes in postorder, root last (the FlatTree slot
    order). TPU does every compression; the host only reshapes the node
    buffer. This is the payload of the devd hash_stream tree frame."""
    n = len(digests)
    if n <= 1:
        return list(digests)
    left, right, out, scratch, rows, real_slots, n_rounds = _dense_schedule(n)
    nodes_np = np.zeros((rows, 5), dtype=np.uint32)
    for i, d in enumerate(digests):
        nodes_np[i] = np.frombuffer(d, dtype="<u4")
    nodes = _run_tree(
        jnp.asarray(nodes_np), jnp.asarray(left), jnp.asarray(right),
        jnp.asarray(out), n_rounds,
    )
    # drop the scratch row: 2n-1 real nodes + 1 no-op sink
    return digests_to_bytes_le(np.asarray(nodes))[:real_slots]


def tree_hash_from_leaf_digests(digests: list[bytes]) -> tuple[bytes, list[list[bytes]]]:
    """Root + per-leaf aunt lists (bottom-up order) from 20-byte leaf
    digests. TPU does all hashing; host assembles proofs as FlatTree
    views over the node buffer. Mirrors
    merkle.simple.simple_proofs_from_hashes output."""
    from tendermint_tpu.merkle.simple import FlatTree

    n = len(digests)
    if n == 0:
        return b"", []
    if n == 1:
        return digests[0], [[]]
    tree = FlatTree.from_nodes(n, tree_nodes_from_leaf_digests(digests))
    return tree.root(), [tree.aunts_for(i) for i in range(n)]


def merkle_root_from_leaf_digests(digests: list[bytes]) -> bytes:
    if not digests:
        return b""
    # root = last node in the buffer; skips materializing any aunts
    return tree_nodes_from_leaf_digests(digests)[-1]


def part_leaf_hashes(chunks: list[bytes]) -> list[bytes]:
    """Batched Part.Hash: raw ripemd160 over each chunk (the per-64KB-part
    hashing hot path, types/part_set.go:32-41)."""
    if not chunks:
        return []
    words, nblocks = pack_messages(chunks, little_endian=True)
    out = ripemd160_words(jnp.asarray(words), jnp.asarray(nblocks))
    return digests_to_bytes_le(np.asarray(out))


def leaf_hashes(items: list[bytes]) -> list[bytes]:
    """Batched merkle.simple.leaf_hash: ripemd160 of length-prefixed items
    (tx leaves, commit vote leaves)."""
    from tendermint_tpu.codec.binary import encode_bytes

    if not items:
        return []
    msgs = [encode_bytes(it) for it in items]
    words, nblocks = pack_messages(msgs, little_endian=True)
    out = ripemd160_words(jnp.asarray(words), jnp.asarray(nblocks))
    return digests_to_bytes_le(np.asarray(out))
