"""Vectorized simple-Merkle-tree hashing for TPU.

Replaces the reference's sequential tree loops (types/part_set.go:95-122
NewPartSetFromData, types/tx.go:33-46 Txs.Hash) with level-parallel batched
RIPEMD-160:

1. Host computes the tree SHAPE only — the recursive (n+1)//2 split of
   merkle/simple.py — as a dense schedule of (left, right, out) node-slot
   triples grouped into dependency rounds (depth levels). The schedule
   depends only on n and is lru-cached per exact leaf count (leaves cannot
   be padded: the tree over the first n leaves of a padded set is a
   different tree). Part-set sizes repeat heavily so the cache hits;
   _run_tree jit-specializes on (slots, n_rounds) which collide often.
2. TPU holds a node-slot buffer of 20-byte digests as uint32[slots, 5] and,
   per round, gathers children, assembles the 44-byte inner-node preimage
   (length-prefixed left || length-prefixed right — matching
   merkle.simple.inner_hash exactly) entirely with integer shifts, and runs
   one batched compression.

The returned node buffer also yields every internal node, so SimpleProof
aunts come for free without extra hashing (used by PartSet.from_data).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.ops.hashing import (
    _INIT_RIPEMD,
    _ripemd160_block,
    digests_to_bytes_le,
    pack_messages,
    ripemd160_words,
)

# ---------------------------------------------------------------------------
# Host: tree schedule
# ---------------------------------------------------------------------------


class _TreeSchedule:
    __slots__ = ("n", "slots", "rounds", "root_slot", "combines")

    def __init__(self, n: int):
        """Build the combine schedule for n leaves (slots 0..n-1 = leaves).
        combines: list of (left, right, out); rounds: list of index ranges
        into combines, grouped by dependency depth."""
        self.n = n
        next_slot = n
        combines: list[tuple[int, int, int]] = []
        depths: list[int] = []

        def build(lo: int, hi: int) -> tuple[int, int]:
            """Return (slot, depth) of subtree over leaves [lo, hi)."""
            nonlocal next_slot
            count = hi - lo
            if count == 1:
                return lo, 0
            mid = lo + (count + 1) // 2
            ls, ld = build(lo, mid)
            rs, rd = build(mid, hi)
            out = next_slot
            next_slot += 1
            combines.append((ls, rs, out))
            depths.append(max(ld, rd) + 1)
            return out, max(ld, rd) + 1

        if n == 0:
            self.slots = 0
            self.rounds = []
            self.root_slot = -1
            self.combines = []
            return
        root, _ = build(0, n)
        self.slots = next_slot
        self.root_slot = root
        # group by depth
        order = sorted(range(len(combines)), key=lambda i: depths[i])
        self.combines = [combines[i] for i in order]
        self.rounds = []
        i = 0
        while i < len(order):
            d = depths[order[i]]
            j = i
            while j < len(order) and depths[order[j]] == d:
                j += 1
            self.rounds.append((i, j))
            i = j


@lru_cache(maxsize=64)
def _dense_schedule(n_bucket: int):
    """Dense schedule arrays for one exact leaf count:
    left/right/out: int32[max_rounds, max_width]; counts: int32[max_rounds].
    Entries beyond a round's count are no-ops (combine slot 0,0 -> scratch).
    Returns (left, right, out, scratch_slot, total_slots, py_schedule)."""
    sched = _TreeSchedule(n_bucket)
    max_width = max((j - i for i, j in sched.rounds), default=0)
    n_rounds = len(sched.rounds)
    scratch = sched.slots  # one extra slot absorbs no-op writes
    left = np.zeros((n_rounds, max_width), dtype=np.int32)
    right = np.zeros((n_rounds, max_width), dtype=np.int32)
    out = np.full((n_rounds, max_width), scratch, dtype=np.int32)
    for r, (i, j) in enumerate(sched.rounds):
        for k, (ls, rs, os_) in enumerate(sched.combines[i:j]):
            left[r, k] = ls
            right[r, k] = rs
            out[r, k] = os_
    return left, right, out, scratch, sched.slots + 1, sched


# ---------------------------------------------------------------------------
# TPU: inner-node preimage assembly + per-round hashing
# ---------------------------------------------------------------------------

# 44-byte preimage: 0x01 0x14 | left(20) | 0x01 0x14 | right(20), then MD
# padding: 0x80 at byte 44, zeros, bit length 352 in LE at bytes 56..63.


def _bytes_from_words(w: jax.Array) -> jax.Array:
    """uint32[B,5] -> uint32[B,20] byte values (LE)."""
    shifts = jnp.arange(4, dtype=jnp.uint32) * 8
    b = (w[:, :, None] >> shifts[None, None, :]) & jnp.uint32(0xFF)
    return b.reshape(w.shape[0], 20)


def _inner_preimage_words(left: jax.Array, right: jax.Array) -> jax.Array:
    """left/right digests uint32[B,5] -> one padded block uint32[B,16]."""
    B = left.shape[0]
    lb = _bytes_from_words(left)
    rb = _bytes_from_words(right)
    buf = jnp.zeros((B, 64), dtype=jnp.uint32)
    pre = jnp.uint32(0x01), jnp.uint32(0x14)
    buf = buf.at[:, 0].set(pre[0]).at[:, 1].set(pre[1])
    buf = jax.lax.dynamic_update_slice(buf, lb, (0, 2))
    buf = buf.at[:, 22].set(pre[0]).at[:, 23].set(pre[1])
    buf = jax.lax.dynamic_update_slice(buf, rb, (0, 24))
    buf = buf.at[:, 44].set(jnp.uint32(0x80))
    buf = buf.at[:, 56].set(jnp.uint32(0x60)).at[:, 57].set(jnp.uint32(0x01))
    shifts = jnp.arange(4, dtype=jnp.uint32) * 8
    words = (buf.reshape(B, 16, 4) << shifts[None, None, :]).sum(
        axis=2, dtype=jnp.uint32
    )
    return words


def _inner_hash_batch(left: jax.Array, right: jax.Array) -> jax.Array:
    """Batched inner_hash on digests uint32[B,5] -> uint32[B,5]."""
    words = _inner_preimage_words(left, right)
    init = jnp.broadcast_to(jnp.asarray(_INIT_RIPEMD), (left.shape[0], 5))
    return _ripemd160_block(init, words)


@partial(jax.jit, static_argnames=("n_rounds",))
def _run_tree(nodes: jax.Array, left: jax.Array, right: jax.Array, out: jax.Array,
              n_rounds: int) -> jax.Array:
    """nodes: uint32[slots,5] with leaves filled; returns all slots filled."""

    def round_body(r, nodes):
        l = nodes[left[r]]
        rt = nodes[right[r]]
        h = _inner_hash_batch(l, rt)
        return nodes.at[out[r]].set(h)

    return jax.lax.fori_loop(0, n_rounds, round_body, nodes)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def tree_hash_from_leaf_digests(digests: list[bytes]) -> tuple[bytes, list[list[bytes]]]:
    """Root + per-leaf aunt lists (bottom-up order) from 20-byte leaf
    digests. TPU does all hashing; host assembles proofs from the node
    buffer. Mirrors merkle.simple.simple_proofs_from_hashes output."""
    n = len(digests)
    if n == 0:
        return b"", []
    if n == 1:
        return digests[0], [[]]
    left, right, out, scratch, slots, sched = _dense_schedule(n)
    nodes_np = np.zeros((slots, 5), dtype=np.uint32)
    for i, d in enumerate(digests):
        nodes_np[i] = np.frombuffer(d, dtype="<u4")
    nodes = _run_tree(
        jnp.asarray(nodes_np), jnp.asarray(left), jnp.asarray(right),
        jnp.asarray(out), len(sched.rounds),
    )
    nodes_host = np.asarray(nodes)
    all_hashes = digests_to_bytes_le(nodes_host)
    root = all_hashes[sched.root_slot]

    # host-side proof assembly: walk the recursion again (shape-only)
    aunts: list[list[bytes]] = [[] for _ in range(n)]
    combine_map = {(ls, rs): o for ls, rs, o in sched.combines}

    def walk(lo: int, hi: int) -> int:
        count = hi - lo
        if count == 1:
            return lo
        mid = lo + (count + 1) // 2
        ls = walk(lo, mid)
        rs = walk(mid, hi)
        for i in range(lo, mid):
            aunts[i].append(all_hashes[rs])
        for i in range(mid, hi):
            aunts[i].append(all_hashes[ls])
        return combine_map[(ls, rs)]

    walk(0, n)
    return root, aunts


def merkle_root_from_leaf_digests(digests: list[bytes]) -> bytes:
    root, _ = tree_hash_from_leaf_digests(digests)
    return root


def part_leaf_hashes(chunks: list[bytes]) -> list[bytes]:
    """Batched Part.Hash: raw ripemd160 over each chunk (the per-64KB-part
    hashing hot path, types/part_set.go:32-41)."""
    if not chunks:
        return []
    words, nblocks = pack_messages(chunks, little_endian=True)
    out = ripemd160_words(jnp.asarray(words), jnp.asarray(nblocks))
    return digests_to_bytes_le(np.asarray(out))


def leaf_hashes(items: list[bytes]) -> list[bytes]:
    """Batched merkle.simple.leaf_hash: ripemd160 of length-prefixed items
    (tx leaves, commit vote leaves)."""
    from tendermint_tpu.codec.binary import encode_bytes

    if not items:
        return []
    msgs = [encode_bytes(it) for it in items]
    words, nblocks = pack_messages(msgs, little_endian=True)
    out = ripemd160_words(jnp.asarray(words), jnp.asarray(nblocks))
    return digests_to_bytes_le(np.asarray(out))
