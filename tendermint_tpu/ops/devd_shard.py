"""Sharded devd dispatch: N daemon endpoints behind one gateway (round 21).

One gateway -> one daemon -> one socket capped the device plane at a
single chip. This module is the dispatcher that lifts that ceiling:
``TENDERMINT_DEVD_SOCKS`` (comma-separated socket paths; the ``[device]``
config section feeds it at node assembly) names a FLEET of devd daemons,
and every verify/hash batch wide enough to shard splits into contiguous
slices scheduled across the healthy endpoints. PAPERS.md's FPGA ECDSA
verification engine (arXiv 2112.02229) is the architectural reference:
a pool of fixed-function verify engines behind one dispatch queue —
devd endpoints are that pool.

Scheduling: each dispatch plans ~2 slices per healthy endpoint (never
below the TENDERMINT_TPU_MIN_BATCH floor per slice) and gives every
slice a round-robin "home" endpoint. One worker per endpoint drains its
own slices first, then STEALS from the shared tail — so a slow chip
finishes its first slice while idle endpoints absorb the residue, and
the batch completes at the speed of the fleet, not the slowest member.

Failure semantics: each endpoint has its own ``CircuitBreaker`` in
ops/gateway's keyed registry. A failed slice records on THAT endpoint's
breaker, re-queues, and a healthy endpoint re-dispatches it — per-lane
verdict attribution survives because results merge back at the slice's
original offsets. The dispatch raises (-> the gateway's existing CPU
fallback) only when no endpoint can make progress; the plane as a whole
falls to the native/AVX floor only once every breaker is open
(gateway.devd_plane_allow).

With fewer than two endpoints ``enabled()`` is False and none of this
engages: ops/devd_backend keeps its single-client path byte-for-byte.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from tendermint_tpu import devd

logger = logging.getLogger(__name__)


class DevdShardError(devd.DevdError):
    """A sharded dispatch could not complete on ANY endpoint. The
    gateway's existing devd failure handling (bounded retry, then the
    CPU floor) treats it exactly like a dead single daemon."""


def endpoint_paths() -> list[str]:
    """The configured endpoint sockets: TENDERMINT_DEVD_SOCKS entries
    (stripped, de-duplicated, order preserved), falling back to the
    primary single socket (devd.sock_path())."""
    paths: list[str] = []
    for p in os.environ.get("TENDERMINT_DEVD_SOCKS", "").split(","):
        p = p.strip()
        if p and p not in paths:
            paths.append(p)
    if not paths:
        return [devd.sock_path()]
    return paths


def enabled() -> bool:
    """The sharded dispatcher engages only at >= 2 endpoints: with one,
    ops/devd_backend's single-client path runs unchanged."""
    return len(endpoint_paths()) >= 2


# -- endpoint objects ---------------------------------------------------------


class _Endpoint:
    """One daemon socket: its client, its version-skew latches, and its
    dispatch counters. The breaker deliberately does NOT live here — it
    sits in gateway's keyed registry so node/health, node/flightrec, and
    the telemetry scrape observe the same object the dispatcher feeds."""

    def __init__(self, path: str):
        self.path = path
        self.client = devd.DevdClient(path)
        # per-DAEMON version-skew latches (mirrors ops/devd_backend's
        # module latches): a pre-streaming daemon on one socket must not
        # latch the streamed path off for its healthy siblings
        self.stream_ok = True
        self.hash_stream_ok = True
        self.mtx = threading.Lock()
        self.outstanding = 0
        self.dispatched_slices = 0
        self.stolen_slices = 0
        self.redispatches = 0
        self.sigs = 0
        self.hash_bytes = 0
        self.sigs_per_s = 0.0  # EWMA over per-slice verify rates

    @property
    def breaker(self):
        from tendermint_tpu.ops import gateway

        return gateway.devd_breaker(self.path)

    def note_success(self, lanes: int, n_bytes: int, dt_s: float,
                     stolen: bool, sigs: bool) -> None:
        with self.mtx:
            self.dispatched_slices += 1
            if stolen:
                self.stolen_slices += 1
            if sigs:
                self.sigs += lanes
                if dt_s > 0:
                    rate = lanes / dt_s
                    self.sigs_per_s = (
                        0.8 * self.sigs_per_s + 0.2 * rate
                    ) if self.sigs_per_s else rate
            else:
                self.hash_bytes += n_bytes


_endpoints: dict[str, _Endpoint] = {}
_eps_mtx = threading.Lock()


def _fleet() -> list[_Endpoint]:
    """Endpoint objects for the CURRENT configuration, created on first
    sight (a client dials lazily, so an unreachable entry costs nothing
    until dispatched to)."""
    out = []
    with _eps_mtx:
        for path in endpoint_paths():
            ep = _endpoints.get(path)
            if ep is None:
                ep = _Endpoint(path)
                _endpoints[path] = ep
            out.append(ep)
    return out


def reset() -> None:
    """Drop the endpoint table — fresh clients and counters after env or
    socket churn (tests, benches). The breakers live in gateway's
    registry; drop those with gateway.reset_devd_breaker()."""
    with _eps_mtx:
        eps = list(_endpoints.values())
        _endpoints.clear()
    for ep in eps:
        try:
            ep.client.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass


def reset_endpoint_latches(path: str) -> None:
    """Re-arm one endpoint's version-skew latches (the breaker's
    on_close hook: its daemon came back, possibly upgraded)."""
    with _eps_mtx:
        ep = _endpoints.get(path)
    if ep is not None:
        ep.stream_ok = True
        ep.hash_stream_ok = True


def plane_allow() -> bool:
    """True while ANY endpoint's breaker admits work — the whole plane
    falls to the CPU floor only when every breaker is open. allow() may
    run a bounded half-open probe inline; a probe that re-closes a
    breaker makes the dispatcher's own allow() check free right after."""
    return any(ep.breaker.allow() for ep in _fleet())


# -- slicing ------------------------------------------------------------------


def _verify_floor() -> int:
    try:
        return max(1, int(os.environ.get("TENDERMINT_TPU_MIN_BATCH", "32")))
    except ValueError:  # a typo'd knob must not kill the hot path
        return 32


def _hash_floor() -> int:
    try:
        return max(1, int(
            os.environ.get("TENDERMINT_TPU_HASH_MIN_BATCH", "16")
        ))
    except ValueError:
        return 16


def _plan_slices(n: int, workers: int, floor: int) -> list[tuple[int, int]]:
    """Contiguous (start, stop) slices: ~2 per worker so there is
    residual work to steal, never more than the floor allows (each slice
    stays at or above the min-batch floor — the same width gate the
    single-socket plane applies to whole batches), never fewer than 1."""
    floor = max(1, floor)
    k = max(1, min(workers * 2, n // floor))
    base, rem = divmod(n, k)
    out, start = [], 0
    for i in range(k):
        size = base + (1 if i < rem else 0)
        out.append((start, start + size))
        start += size
    return out


def _plan_slices_weighted(
    n: int, weights: list[float], floor: int,
) -> list[tuple[int, int, int]] | None:
    """Endpoint-weighted planning (round 22, ROADMAP follow-on from
    PR 21): (start, stop, home) slices whose widths are proportional to
    each endpoint's recorded ``sigs_per_s`` EWMA, so a PERMANENTLY
    slower chip gets proportionally narrower slices up front instead of
    relying on steals every batch. Endpoints with no history yet take
    the fleet's mean recorded rate (a new chip is assumed average until
    measured). Returns None when no endpoint has history or the batch is
    too narrow to split — the caller falls back to the equal-width
    planner. Each home still gets ~2 slices when its share allows, so
    the steal tail keeps absorbing TRANSIENT slowness."""
    floor = max(1, floor)
    known = [w for w in weights if w > 0]
    if not known or n < 2 * floor:
        return None
    fill = sum(known) / len(known)
    w = [wi if wi > 0 else fill for wi in weights]
    total = sum(w)
    # largest-remainder apportionment of the n lanes over the workers
    raw = [n * wi / total for wi in w]
    shares = [int(r) for r in raw]
    short = n - sum(shares)
    for i in sorted(
        range(len(w)), key=lambda j: raw[j] - shares[j], reverse=True,
    )[:short]:
        shares[i] += 1
    out, start = [], 0
    for i, q in enumerate(shares):
        if q <= 0:
            continue
        parts = 2 if q >= 2 * floor else 1
        base, rem = divmod(q, parts)
        for j in range(parts):
            size = base + (1 if j < rem else 0)
            out.append((start, start + size, i))
            start += size
    return out or None


# -- the dispatcher -----------------------------------------------------------

# bound on full re-dispatch rounds: within a round, surviving workers
# steal a failed slice immediately; a fresh round only happens when every
# worker of the previous one exited (failed or drained), so 3 rounds is
# already "the fleet failed repeatedly" — the gateway's retry + breaker
# thresholds own anything past that
_MAX_ROUNDS = 3


def _dispatch(items: list, run, floor: int, sigs: bool) -> list:
    """Shard `items` across healthy endpoints; merge per-slice results
    back at their original offsets (per-lane attribution survives
    slicing AND re-dispatch by construction). `run(ep, sub)` executes
    one slice on one endpoint and returns len(sub) results."""
    n = len(items)
    out: list = [None] * n
    cond = threading.Condition()
    last_exc: list[BaseException] = []

    # slice records: [start, stop, home_worker_index]
    pending: list[list[int]] = []
    inflight = [0]

    for round_ in range(_MAX_ROUNDS):
        eps = [ep for ep in _fleet() if ep.breaker.allow()]
        if not eps:
            raise DevdShardError(
                "all devd endpoint breakers are open"
            ) from (last_exc[-1] if last_exc else None)
        if not pending:
            if round_ == 0:
                weighted = _plan_slices_weighted(
                    n, [ep.sigs_per_s for ep in eps], floor,
                ) if sigs else None
                if weighted is not None:
                    pending = [[s, e, h] for s, e, h in weighted]
                else:
                    pending = [
                        [s, e, i % len(eps)]
                        for i, (s, e) in enumerate(
                            _plan_slices(n, len(eps), floor)
                        )
                    ]
            else:  # everything completed in a prior round
                break
        else:
            # re-home surviving slices onto the new worker set
            for i, rec in enumerate(pending):
                rec[2] = i % len(eps)

        def take(idx: int):
            """Own-home slices first, then steal from the shared tail.
            A drained queue with slices still IN FLIGHT is not done —
            an in-flight slice may fail and re-queue, and a worker that
            exited early would strand it for a whole re-dispatch round —
            so idle workers wait for either new work or fleet idle."""
            with cond:
                while True:
                    if pending:
                        for j, rec in enumerate(pending):
                            if rec[2] == idx:
                                inflight[0] += 1
                                return pending.pop(j), False
                        inflight[0] += 1
                        return pending.pop(), True  # steal from the tail
                    if inflight[0] == 0:
                        return None, False
                    cond.wait(0.05)

        def worker(idx: int, ep: _Endpoint) -> None:
            while True:
                rec, stolen = take(idx)
                if rec is None:
                    return
                start, stop = rec[0], rec[1]
                sub = items[start:stop]
                with ep.mtx:
                    ep.outstanding += 1
                t0 = time.monotonic()
                try:
                    res = list(run(ep, sub))
                except Exception as exc:  # noqa: BLE001 — per-endpoint
                    # breaker accounting; the slice re-dispatches
                    ep.breaker.record_failure()
                    with ep.mtx:
                        ep.outstanding -= 1
                        ep.redispatches += 1
                    with cond:
                        pending.append(rec)
                        last_exc.append(exc)
                        inflight[0] -= 1
                        cond.notify_all()
                    logger.warning(
                        "devd endpoint %s failed a %d-lane slice (%s); "
                        "re-dispatching to a healthy endpoint",
                        ep.path, len(sub), exc,
                    )
                    return  # this endpoint sits out the rest of the batch
                ep.breaker.record_success()
                with ep.mtx:
                    ep.outstanding -= 1
                n_bytes = 0 if sigs else sum(len(x) for x in sub)
                ep.note_success(
                    len(sub), n_bytes, time.monotonic() - t0, stolen, sigs,
                )
                with cond:
                    out[start:stop] = res
                    inflight[0] -= 1
                    cond.notify_all()

        threads = [
            threading.Thread(
                target=worker, args=(i, ep), daemon=True,
                name=f"devd-shard-{i}",
            )
            for i, ep in enumerate(eps)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with cond:
            if not pending:
                return out
    raise DevdShardError(
        f"sharded dispatch exhausted {_MAX_ROUNDS} rounds with slices "
        "unserved"
    ) from (last_exc[-1] if last_exc else None)


# -- verify plane -------------------------------------------------------------


def _stream_min() -> int:
    from tendermint_tpu.ops import devd_backend

    return devd_backend._stream_min()


def _verify_slice(ep: _Endpoint, sub: list) -> list:
    """One verify slice on one endpoint: streamed transport at or above
    the stream floor (per-endpoint version-skew latch), single-shot
    below it — the same policy ops/devd_backend applies per batch."""
    if ep.stream_ok and len(sub) >= _stream_min():
        try:
            return list(ep.client.verify_stream(sub))
        except devd.DevdError as exc:
            if "too old" not in str(exc):
                raise
            ep.stream_ok = False
    return list(ep.client.verify_batch(sub))


def verify_batch(items) -> list[bool]:
    """Sharded verify_batch: same contract as devd_backend.verify_batch
    (per-lane bool verdicts, order preserved), fleet-wide."""
    items = list(items)
    if not items:
        return []
    return [bool(b) for b in
            _dispatch(items, _verify_slice, _verify_floor(), sigs=True)]


def verify_batch_async(items):
    """Sharded verify_batch_async: dispatch runs on a background thread
    NOW; the returned zero-arg resolver joins it. The gateway's
    _PendingBatch / prime_cache_async / pop_primed plumbing rides this
    unchanged — it only ever sees a resolver."""
    items = list(items)
    if not items:
        return lambda: []
    box: dict = {}
    evt = threading.Event()

    def run() -> None:
        try:
            box["res"] = verify_batch(items)
        except BaseException as exc:  # noqa: BLE001 — re-raised at resolve
            box["exc"] = exc
        finally:
            evt.set()

    threading.Thread(
        target=run, daemon=True, name="devd-shard-async"
    ).start()

    def resolve():
        evt.wait()
        if "exc" in box:
            raise box["exc"]
        return box["res"]

    return resolve


# -- aggregate plane ----------------------------------------------------------


def agg_batch(terms) -> list[tuple[int, int]]:
    """Sharded dual-scalar-mul lanes for the aggregate-commit verify
    (the 'agg' op; docs/upgrade.md): contiguous lane slices across the
    fleet, results offset-merged back — per-lane attribution survives
    slicing and re-dispatch exactly as the verify plane's does. A lane
    is one [a]P + [b]Q term, so the verify floor is the right width
    gate (each lane costs one Straus ladder, same as a signature)."""
    terms = [tuple(t) for t in terms]
    if not terms:
        return []
    return [tuple(p) for p in _dispatch(
        terms, lambda ep, sub: ep.client.agg_batch(sub),
        _verify_floor(), sigs=True,
    )]


# -- hash plane ---------------------------------------------------------------


def _hash_slice(ep: _Endpoint, sub: list, mode: str) -> list:
    """One hash slice on one endpoint: streamed chunk frames when the
    slice is wide or fat enough (per-endpoint latch), single-shot
    otherwise — devd_backend's per-batch policy, per slice."""
    from tendermint_tpu.ops import devd_backend

    total = sum(len(b) for b in sub)
    if ep.hash_stream_ok and (
        len(sub) >= devd_backend._stream_min()
        or total >= devd_backend._hash_stream_min_bytes()
    ):
        try:
            return list(ep.client.hash_stream(
                sub, mode=mode, chunk=devd_backend._hash_chunk(mode)
            ))
        except devd.DevdError as exc:
            if "too old" not in str(exc):
                raise
            ep.hash_stream_ok = False
    return list(ep.client.hash_batch(sub, mode=mode))


def hash_batch(items, mode: str = "part") -> list[bytes]:
    """Sharded hash_batch: leaf digests in order, fleet-wide."""
    items = [bytes(b) for b in items]
    if not items:
        return []
    return _dispatch(
        items, lambda ep, sub: _hash_slice(ep, sub, mode),
        _hash_floor(), sigs=False,
    )


def hash_tree(items, mode: str = "part") -> tuple[list, list]:
    """Sharded (leaf digests, postorder internal nodes). Leaf hashing —
    the expensive term (64 KB parts, tx blobs) — shards across the
    fleet; the internal tree builds host-side from the gathered digests
    with the same builder devd's hashers use
    (merkle.simple.flat_tree_from_leaf_digests), so the node buffer is
    byte-identical to a single daemon's tree frame. Internal nodes hash
    64-byte digest pairs — well under 1% of the leaf work at production
    part shapes — so a second device round trip per level would cost
    more in transport than it saves in compute."""
    digests = hash_batch(items, mode)
    from tendermint_tpu.merkle.simple import flat_tree_from_leaf_digests

    tree = flat_tree_from_leaf_digests(digests)
    return digests, tree.internal_nodes()


# -- observability ------------------------------------------------------------


def stream_stats() -> dict:
    """Verify-transport counters summed across endpoint clients (same
    key set as one DevdClient's stream_stats)."""
    return _sum_stats("stream_stats")


def hash_stream_stats() -> dict:
    """Hash-transport counters summed across endpoint clients."""
    return _sum_stats("hash_stream_stats")


def _sum_stats(method: str) -> dict:
    out: dict = {}
    for ep in _fleet():
        for k, v in getattr(ep.client, method)().items():
            if isinstance(v, (int, float)):
                out[k] = out.get(k, 0) + v
            else:
                out.setdefault(k, v)
    return out


def endpoint_stats() -> dict[str, dict]:
    """Per-endpoint dispatch counters + breaker state, keyed by socket
    path. node/telemetry.py exports these as the labeled
    gateway_endpoint_* families; `breaker_state` reads the registry
    breaker (0 closed / 1 half-open / 2 open) without probing it."""
    out: dict[str, dict] = {}
    for ep in _fleet():
        with ep.mtx:
            d = {
                "outstanding": ep.outstanding,
                "dispatched_slices": ep.dispatched_slices,
                "stolen_slices": ep.stolen_slices,
                "redispatches": ep.redispatches,
                "sigs": ep.sigs,
                "sigs_per_s": round(ep.sigs_per_s, 1),
                "hash_bytes": ep.hash_bytes,
            }
        d["breaker_state"] = ep.breaker.state
        out[ep.path] = d
    return out


def plane_stats() -> dict:
    """Flat fleet aggregates for the legacy metrics map (stable key set;
    in single-socket mode the dispatch counters sit at zero and `count`
    is 1 — the plane is observable either way)."""
    eps = endpoint_stats()
    vals = list(eps.values())
    return {
        "count": len(vals),
        "healthy": sum(1 for d in vals if d["breaker_state"] != 2),
        "dispatched_slices": sum(d["dispatched_slices"] for d in vals),
        "stolen_slices": sum(d["stolen_slices"] for d in vals),
        "redispatches": sum(d["redispatches"] for d in vals),
        "outstanding": sum(d["outstanding"] for d in vals),
    }
