"""Operator CLI: dump a node's per-height consensus traces.

    python -m tendermint_tpu.ops.trace --home ~/.tendermint --last 5
    python -m tendermint_tpu.ops.trace --url 127.0.0.1:46657 --json

Pulls the `consensus_trace` RPC (consensus/trace.py ring) and renders
each committed height's wall time as named segments — where a slow
height actually spent it — plus the height's device-vs-CPU verify/hash
attribution and breaker state. `--home` resolves the RPC address from
the node's config.toml; `--url` talks to any reachable node directly.
"""

from __future__ import annotations

import argparse
import json
import sys

from tendermint_tpu.consensus.trace import SEGMENTS


def _resolve_url(args) -> str:
    if args.url:
        return args.url
    from tendermint_tpu.config.toml import load_config

    cfg = load_config(args.home)
    laddr = cfg.rpc.laddr
    if not laddr:
        raise SystemExit(f"node at {args.home} has no rpc.laddr configured")
    addr = laddr.split("://", 1)[-1]
    if addr.startswith("unix") or "/" in addr.split(":", 1)[0]:
        return f"unix://{addr.split('://', 1)[-1]}"
    host, _, port = addr.rpartition(":")
    if host in ("", "0.0.0.0", "::"):
        host = "127.0.0.1"  # listen-anywhere means dial loopback locally
    return f"{host}:{port}"


def _bar(frac: float, width: int = 24) -> str:
    n = max(0, min(width, round(frac * width)))
    return "#" * n + "." * (width - n)


def render(traces: list[dict], out=sys.stdout) -> None:
    if not traces:
        print("no completed heights traced yet", file=out)
        return
    for t in traces:
        wall = t.get("wall_s", 0.0) or 0.0
        dev = t.get("device", {})
        print(
            f"height {t['height']}  wall {wall:.3f}s  "
            f"rounds {t.get('rounds', 1)}  "
            f"(segments sum {t.get('total_s', 0.0):.3f}s)",
            file=out,
        )
        segs = t.get("segments", {})
        order = [s for s in SEGMENTS if s in segs] + [
            s for s in segs if s not in SEGMENTS
        ]
        for name in order:
            v = segs[name]
            frac = (v / wall) if wall > 0 else 0.0
            print(f"  {name:<14} {v:>9.4f}s  {_bar(frac)} {frac * 100:5.1f}%",
                  file=out)
        aux = t.get("aux", {})
        if "overlap_apply_s" in aux:
            # pipelined execution (round 14): the deferred apply of the
            # PREVIOUS height ran against this height's propose/prevote
            # segments — split it into the part consensus never waited
            # for (hidden) vs the join wait it actually paid (idle)
            apply_s = aux["overlap_apply_s"]
            wait_s = aux.get("pipeline_join_wait_s", 0.0)
            hidden = max(0.0, apply_s - wait_s)
            frac = (hidden / apply_s) if apply_s > 0 else 0.0
            print(
                f"  = apply(H-1)   {apply_s:>9.4f}s  {_bar(frac)} "
                f"{frac * 100:5.1f}% hidden / {wait_s:.4f}s join wait",
                file=out,
            )
        for k, v in sorted(aux.items()):
            if k == "overlap_apply_s":
                continue  # rendered as the split line above
            print(f"  ~ {k:<12} {v:>9.4f}s  (overlaps segments)", file=out)
        vt, vc = dev.get("verify_tpu_sigs", 0), dev.get("verify_cpu_sigs", 0)
        ht, hc = dev.get("hash_tpu_leaves", 0), dev.get("hash_cpu_leaves", 0)
        br = dev.get("breaker_state_end", -1)
        br_s = {-1: "n/a (no devd)", 0: "closed", 1: "half-open",
                2: "OPEN (CPU fallback)"}.get(br, str(br))
        print(
            f"  device: verify {vt} sigs on-device / {vc} cpu; "
            f"hash {ht} leaves on-device / {hc} cpu; breaker {br_s}",
            file=out,
        )
        print(file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="dump per-height consensus wall-time traces",
    )
    ap.add_argument("--home", default=None,
                    help="node home (reads rpc.laddr from config.toml)")
    ap.add_argument("--url", default=None,
                    help="RPC address (host:port or unix:///path.sock); "
                         "overrides --home")
    ap.add_argument("--last", type=int, default=10,
                    help="how many recent heights (default 10)")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON instead of the rendered table")
    args = ap.parse_args(argv)
    if not args.url and not args.home:
        ap.error("one of --home or --url is required")

    from tendermint_tpu.rpc.client import HTTPClient

    client = HTTPClient(_resolve_url(args))
    traces = client.consensus_trace(last=args.last)["traces"]
    try:
        if args.json:
            print(json.dumps(traces, indent=2))
        else:
            render(traces)
    except BrokenPipeError:
        # piped into `head` etc. — a closed pager is a clean exit, not
        # a traceback
        sys.stderr.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
