from tendermint_tpu.mempool.mempool import Mempool, TxInCacheError

__all__ = ["Mempool", "TxInCacheError"]
