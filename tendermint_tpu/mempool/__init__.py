from tendermint_tpu.mempool.mempool import (
    LANES,
    Mempool,
    MempoolFullError,
    MempoolSourceLimitError,
    TxInCacheError,
)

__all__ = [
    "LANES",
    "Mempool",
    "MempoolFullError",
    "MempoolSourceLimitError",
    "TxInCacheError",
]
