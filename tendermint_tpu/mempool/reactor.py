"""Mempool tx gossip on channel 0x30 (reference: mempool/reactor.go).

Per-peer broadcast thread walks the mempool CList with blocking
next_wait (reactor.go:114-152), waiting until the peer's height is at
least tx height - 1 before sending, so peers that are far behind aren't
flooded with txs they can't check yet.
"""

from __future__ import annotations

import json
import threading

from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.conn import ChannelDescriptor
from tendermint_tpu.p2p.switch import Reactor

MEMPOOL_CHANNEL = 0x30
PEER_CATCHUP_SLEEP = 0.1


def _encode_tx(tx: bytes) -> bytes:
    return json.dumps({"type": "tx", "tx": tx.hex()}, sort_keys=True).encode()


class MempoolReactor(Reactor, BaseService):
    def __init__(self, config, mempool):
        BaseService.__init__(self, name="mempool.reactor")
        self.config = config
        self.mempool = mempool
        self._peer_threads: dict[str, threading.Thread] = {}
        self._peer_stops: dict[str, threading.Event] = {}
        self._mtx = threading.Lock()

    # -- Reactor interface -------------------------------------------------

    def get_channels(self) -> list[ChannelDescriptor]:
        from tendermint_tpu.codec import jsonval as jv

        return [
            ChannelDescriptor(
                id=MEMPOOL_CHANNEL, priority=5, send_queue_capacity=64,
                # largest legal frame: one MAX_TX_BYTES tx, hex-doubled
                # inside the JSON envelope (round-18 right-sizing — the
                # 21 MiB block default gave flooders 2.5x headroom)
                recv_message_capacity=2 * jv.MAX_TX_BYTES + 4096,
            )
        ]

    def add_peer(self, peer) -> None:
        if getattr(self.config, "broadcast", True) is False:
            return
        stop = threading.Event()
        t = threading.Thread(
            target=self._broadcast_tx_routine,
            args=(peer, stop),
            daemon=True,
            name=f"mempool.bcast:{peer.id()[:8]}",
        )
        with self._mtx:
            self._peer_stops[peer.id()] = stop
            self._peer_threads[peer.id()] = t
        t.start()

    def remove_peer(self, peer, reason) -> None:
        with self._mtx:
            stop = self._peer_stops.pop(peer.id(), None)
            self._peer_threads.pop(peer.id(), None)
        if stop:
            stop.set()

    @staticmethod
    def _peer_height(peer) -> int | None:
        """The peer's consensus height, from the consensus reactor's
        PeerState mirror when both reactors are wired (the reference reads
        the same shared PeerState, mempool/reactor.go:133-135)."""
        ps = peer.get("ConsensusReactor.peerState")
        return ps.get_height() if ps is not None else None

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        from tendermint_tpu.codec import jsonval as jv

        try:
            msg = json.loads(msg_bytes.decode())
            if not isinstance(msg, dict) or msg.get("type") != "tx":
                raise ValueError("unknown mempool msg")
            tx_hex = jv.str_field(msg, "tx", 2 * jv.MAX_TX_BYTES)
            tx = bytes.fromhex(tx_hex)
        except (ValueError, KeyError, UnicodeDecodeError) as exc:
            self.switch.stop_peer_for_error(peer, exc)
            return
        try:
            # peer id keys the mempool's per-source admission accounting
            # (round 23): one flooding peer exhausts ITS budget, not the
            # lanes other sources share
            self.mempool.check_tx(tx, source="peer", source_id=str(peer.id()))
        except Exception:  # noqa: BLE001 — dup/full/source-limit/app reject: fine
            pass

    # -- gossip ------------------------------------------------------------

    def _broadcast_tx_routine(self, peer, stop: threading.Event) -> None:
        element = None
        while self.is_running() and not stop.is_set():
            if element is None:
                element = self.mempool.txs_front_wait(timeout=0.5)
                if element is None:
                    continue
            mem_tx = element.value
            # don't send txs the peer can't process yet (reactor.go:132-143)
            peer_h = self._peer_height(peer)
            if peer_h is not None and 0 < peer_h < mem_tx.height - 1:
                stop.wait(PEER_CATCHUP_SLEEP)
                continue
            if not peer.send(MEMPOOL_CHANNEL, _encode_tx(mem_tx.tx)):
                # full queue / slow peer: retry while it's still connected
                # (the reference blocks in Send; exiting would silence
                # mempool gossip to this peer forever)
                if not self.switch.peers.has(peer.id()):
                    return
                stop.wait(PEER_CATCHUP_SLEEP)
                continue
            rec = self.mempool.txtrace
            if rec is not None:
                # lifecycle mark: first successful gossip send of this
                # tx to ANY peer (keep-first stamp semantics)
                rec.stamp(mem_tx.tx, "p2p_broadcast")
            # advance strictly once per sent tx
            while self.is_running() and not stop.is_set():
                nxt = element.next_wait(timeout=0.5)
                if nxt is not None:
                    element = nxt
                    break
                if element.removed:
                    element = None  # re-fetch front; cache dedups re-sends
                    break
