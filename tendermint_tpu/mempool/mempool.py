"""Concurrent transaction pool (reference: mempool/mempool.go).

Good txs live in a CList walked concurrently by the reactor's per-peer
broadcast routines; an LRU cache (100k entries, mempool/mempool.go:51)
dedups everything ever seen; CheckTx goes to the app over the async ABCI
mempool connection; after each commit the surviving txs are re-checked
(mempool/mempool.go:331-357,379); `txs_available` fires once per height
when the pool first becomes non-empty (no-empty-blocks mode).

Consensus holds lock()/unlock() around app-Commit + update so no CheckTx
interleaves with state transition (state/execution.py commit path).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict

from tendermint_tpu.abci.types import (
    CODE_MEMPOOL_FULL,
    CODE_UNAUTHORIZED,
    ResponseCheckTx,
)
from tendermint_tpu.libs.autofile import Group
from tendermint_tpu.libs.clist import CList
from tendermint_tpu.libs.envknob import env_number

CACHE_SIZE = 100_000

# Priority lanes (round 23, docs/serving.md): reap drains in this order,
# FIFO within a lane. Gossip stays lane-blind — one CList in arrival
# order is what the reactor walks, so the wire format is unchanged and
# byte-identical blocks stay byte-identical.
LANES = ("priority", "default", "bulk")
# load-shed ladder levels (mirrored in node/health.py; duplicated here so
# the mempool has no node-package import)
PRESSURE_SHED_WRITES = 2


def lane_for_priority(priority: int) -> str:
    """App CheckTx priority hint -> lane name (>0 priority, <0 bulk)."""
    if priority > 0:
        return LANES[0]
    if priority < 0:
        return LANES[2]
    return LANES[1]

logger = logging.getLogger("mempool")


class SigBatcher:
    """Batch signature pre-verification gate ahead of app CheckTx
    (BASELINE config 5). The reference mempool hands every tx straight to
    the app, which verifies one signature at a time on CPU
    (mempool/mempool.go:166-205); here a CheckTx burst's sig-carrying txs
    accumulate for up to `max_wait_s` (or `max_batch`), the collected
    signatures verify in ONE gateway batch — the TPU kernel when wide —
    and only txs whose signature held are dispatched to the app at all.

    `parse(tx) -> (pubkey, msg, sig) | None`; txs parsing to None bypass
    the gate (the app decides). Runs its own drain thread; submit() is
    called under the mempool lock and never blocks on the device.

    Results are delivered BATCHED: `on_results([(ctx, ok), ...])` is
    called once per verified batch on the drain thread, so the consumer
    can amortize its own per-item costs (the mempool admits a whole
    batch through one app-lock round trip — check_tx_many_async; per-tx
    callbacks measured ~15us each, capping a 4k burst at ~67k tx/s
    regardless of verify speed). `on_results` defaults unset; the
    Mempool wires itself in at construction.

    The intake queue is BOUNDED (`max_backlog`): a peer flooding unique
    signed txs faster than the verifier drains must get refusals, not an
    unbounded in-memory backlog — the same end-to-end-bound rule the
    consensus peer ingress follows (consensus/state._enqueue_peer_msg;
    the tx cache's FIFO eviction means fresh floods are never refused
    there). submit() returns False on overflow and the caller rejects
    the tx retriably."""

    def __init__(self, verifier, parse, max_batch: int = 512,
                 max_wait_s: float = 0.002, max_backlog: int = 8192,
                 on_results=None, max_inflight: int = 2):
        self.verifier = verifier
        self.parse = parse
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.max_backlog = max_backlog
        self.on_results = on_results
        # pipelined pre-verify (round 6): up to max_inflight batches are
        # dispatched via verify_batch_async — batch k's verdicts resolve
        # while batch k+1's txs are already marshaling toward the device
        # (streamed chunks on the devd backend), so intake never idles
        # behind one synchronous verify round trip
        self.max_inflight = max(1, max_inflight)
        self.dropped = 0
        # exactly-once accounting (round 8 chaos coverage): every
        # submitted item is delivered to on_results exactly once — on
        # daemon death between the in-flight batches the verifier's
        # fallback re-verifies (or the gate fails open), but an item is
        # never dropped or double-delivered. delivered counts results
        # handed to the sink; the chaos tests assert
        # delivered == submitted - refused.
        self.delivered = 0
        self.fail_open = 0  # batches delivered un-verified (see _deliver)
        # round 18: gate verdicts that failed — the mempool-flood
        # adversary's garbage signatures, shed here without ever
        # reaching the app (p2p_adversary_flood_txs_rejected)
        self.bad_sigs = 0
        # round 11: per-batch gate latency distribution (dispatch ->
        # verdicts delivered) — scrape-only; the flat mempool_sig_gate_*
        # gauges stay the legacy metrics-RPC surface. One observe per
        # BATCH, so the burst hot path pays nothing per tx (the <2%
        # overhead floor benches/bench_telemetry.py asserts).
        from tendermint_tpu.libs import telemetry

        self._batch_hist = telemetry.default_registry().histogram(
            "mempool_sig_gate_batch_seconds",
            "sig-gate batch wall time: verify dispatch to verdicts "
            "delivered",
        )
        # Intake is a plain list under a condition variable, swapped out
        # wholesale by the drain thread — NOT a queue.Queue: at burst
        # rates the per-item timed gets (one condition wait each) cost
        # more than the verification they feed (measured ~40 ms of a
        # 119 ms 4k-tx gated burst). submit() is one append under the
        # lock; the drain thread takes the whole buffer in one swap and
        # sleeps at most once per linger window.
        self._buf: list = []
        self._cv = threading.Condition()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="mempool.sigbatch"
        )
        self._thread.start()

    def submit(self, item, ctx) -> bool:
        """Enqueue for the next batch (ctx rides to on_results with the
        verdict); False if the gate is saturated (caller must reject the
        tx without app dispatch)."""
        with self._cv:
            if len(self._buf) >= self.max_backlog:
                self.dropped += 1
                return False
            self._buf.append((item, ctx))
            # wake the drain thread when work appears or a full batch is
            # ready; intermediate appends don't pay a notify
            if len(self._buf) == 1 or len(self._buf) == self.max_batch:
                self._cv.notify()
        return True

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify()

    def _take_batch(self, wait: bool = True) -> list | None:
        """Swap out up to max_batch items. wait=True blocks until work or
        stop, lingering up to max_wait_s for the burst to fill a batch;
        wait=False (a verify batch is already in flight) grabs whatever
        accumulated during the last device round trip and returns [] if
        nothing did. None means stopped AND drained."""
        with self._cv:
            if wait:
                while not self._buf and not self._stopped:
                    self._cv.wait()
            if not self._buf:
                return None if self._stopped else []
            if wait and len(self._buf) < self.max_batch and not self._stopped:
                deadline = time.monotonic() + self.max_wait_s
                while len(self._buf) < self.max_batch and not self._stopped:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
            batch = self._buf[: self.max_batch]
            del self._buf[: self.max_batch]
            return batch

    def _run(self) -> None:
        from collections import deque

        pending: deque = deque()  # (batch, resolver|None) FIFO
        while True:
            batch = self._take_batch(wait=not pending)
            if batch is None and not pending:
                return
            if batch:
                try:
                    resolver = self.verifier.verify_batch_async(
                        [b[0] for b in batch]
                    )
                except Exception:  # noqa: BLE001 — fail OPEN at delivery
                    # (see _deliver); dispatch failures must not stall
                    # the intake side of the pipeline
                    logger.exception("sig gate dispatch failed")
                    resolver = None
                pending.append((batch, resolver))
            if pending and (not batch or len(pending) >= self.max_inflight):
                self._deliver(*pending.popleft())

    def _deliver(self, batch: list, resolver) -> None:
        t0 = time.perf_counter()
        try:
            oks = resolver() if resolver is not None else None
        except Exception:  # noqa: BLE001 — fail OPEN (round-8 latch
            # sweep: genuinely unconditional, NOT breaker business — the
            # verifier underneath already did the breaker accounting and
            # its own CPU re-verify; only a bug that escapes ALL of that
            # lands here). The gate is an optimization, not the security
            # boundary (DeliverTx re-verifies unconditionally —
            # apps/signedkv.py), so a verifier bug may admit junk to the
            # pool but never to a block; failing closed would drop valid
            # txs instead
            logger.exception("sig gate resolve failed; delivering un-verified")
            oks = None
        if oks is None:
            self.fail_open += 1
        results = [
            (ctx, bool(ok))
            for (_item, ctx), ok in zip(
                batch, oks if oks is not None else [True] * len(batch)
            )
        ]
        self._batch_hist.observe(time.perf_counter() - t0)
        self.delivered += len(results)
        self.bad_sigs += sum(1 for _ctx, ok in results if not ok)
        try:
            self.on_results(results)
        except Exception:  # noqa: BLE001 — a bad sink must not stall the gate
            logger.exception("sig gate result sink failed")


class TxInCacheError(Exception):
    """Tx already seen (mempool/mempool.go:162)."""


class MempoolFullError(Exception):
    """Pool at the sum of its lane caps: shed at intake, before any app
    dispatch (round 23). Stable reason string for the RPC layer."""


class MempoolSourceLimitError(Exception):
    """One source (rpc IP / peer id) holds its full in-pool tx budget —
    shed ITS txs so it can't crowd out other clients' lanes (round 23)."""


class MemTx:
    """A good tx in the pool, tagged with the height it was checked at
    (mempool/mempool.go:407-410) plus its lane and admitting source
    (round 23 accounting)."""

    __slots__ = ("counter", "height", "tx", "lane", "source")

    def __init__(self, counter: int, height: int, tx: bytes,
                 lane: str = "default", source: str = ""):
        self.counter = counter
        self.height = height
        self.tx = tx
        self.lane = lane
        self.source = source


class TxCache:
    """Bounded FIFO-evicting dedup set (mempool/mempool.go:412-471)."""

    def __init__(self, size: int = CACHE_SIZE):
        self._size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()
        self._mtx = threading.Lock()

    def exists(self, tx: bytes) -> bool:
        with self._mtx:
            return tx in self._map

    def push(self, tx: bytes) -> bool:
        with self._mtx:
            if tx in self._map:
                return False
            if len(self._map) >= self._size:
                self._map.popitem(last=False)
            self._map[tx] = None
            return True

    def remove(self, tx: bytes) -> None:
        with self._mtx:
            self._map.pop(tx, None)

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()


class Mempool:
    def __init__(self, config, proxy_app_conn, sig_batcher: SigBatcher | None = None):
        self.config = config
        self.proxy_app_conn = proxy_app_conn
        self.sig_batcher = sig_batcher
        if sig_batcher is not None and sig_batcher.on_results is None:
            # the mempool is the gate's result sink: whole batches admit
            # through one lock round trip (see SigBatcher docstring)
            sig_batcher.on_results = self._sig_gate_results
        self.txs = CList()
        self.counter = 0
        self.height = 0
        self.cache = TxCache()
        # round 18: already-seen txs shed at the dedup cache — the
        # valid-but-DUPLICATE arm of a mempool flood (one int += on the
        # dup path only; the clean path pays nothing)
        self.cache_dups = 0
        # -- priority lanes + per-source accounting (round 23) ----------
        # lane caps from config with TENDERMINT_MEMPOOL_LANE_* env twins
        # (env wins — the DeviceConfig precedence rule)
        self.lane_caps: dict[str, tuple[int, int]] = {}
        for lane in LANES:
            self.lane_caps[lane] = (
                int(env_number(
                    f"TENDERMINT_MEMPOOL_LANE_{lane.upper()}_MAX_TXS",
                    getattr(config, f"lane_{lane}_max_txs", 0), cast=int)),
                int(env_number(
                    f"TENDERMINT_MEMPOOL_LANE_{lane.upper()}_MAX_BYTES",
                    getattr(config, f"lane_{lane}_max_bytes", 0), cast=int)),
            )
        # whole-pool intake cap = sum of lane tx caps; any uncapped
        # (0) lane uncaps the pool too — 0 always means "no limit"
        caps = [c for c, _b in self.lane_caps.values()]
        self.pool_cap = sum(caps) if all(caps) else 0
        self.source_max_txs = int(env_number(
            "TENDERMINT_MEMPOOL_SOURCE_MAX_TXS",
            getattr(config, "source_max_txs", 0), cast=int))
        self.lane_counts = {lane: 0 for lane in LANES}
        self.lane_bytes = {lane: 0 for lane in LANES}
        self.lane_full = {lane: 0 for lane in LANES}  # rejects per lane
        self.pool_full_rejects = 0
        self.source_limited = 0
        self.shed_writes = 0
        # in-pool txs per source key ("rpc:<ip>" / "peer:<id>"); entries
        # drop at 0 so cardinality is bounded by pool size
        self.source_counts: dict[str, int] = {}
        # tx -> source for in-flight CheckTx (popped at every terminal)
        self._pending_source: dict[bytes, str] = {}
        # load-shed ladder probe, wired by the node to
        # OverloadMonitor.level; None (bare harnesses) = never shed
        self.pressure_fn = None
        self.wal: Group | None = None
        # recheck cursor: txs in [recheck_cursor, recheck_end] are being
        # re-validated post-commit (mempool/mempool.go:72-75)
        self.recheck_cursor = None
        self.recheck_end = None
        self.notified_txs_available = False
        self._txs_available_cb = None
        # tx-lifecycle tracing (round 17, libs/txtrace.py): the node
        # wires one recorder across mempool/reactor/consensus; None in
        # bare harnesses — every stamp site guards it. _admit_rec is the
        # precomputed per-tx admit-stamp seam: only the UNGATED path
        # stamps admit from the per-tx response callback (the sig-gate
        # path stamps it batch-granularly in _sig_gate_results), so the
        # gated burst hot path pays zero per-tx tracing there.
        self._txtrace = None
        self._admit_rec = None
        # the recorder-bound sampling countdown (libs/txtrace.bind_tick):
        # check_tx's fast path is a pure local-attribute decrement; with
        # no recorder it counts down from 2^60 — never fires
        self._trace_tick = 1 << 60
        self._mtx = threading.RLock()  # the proxy mtx (mempool/mempool.go:58)
        proxy_app_conn.set_response_callback(self._res_cb)

    @property
    def txtrace(self):
        return self._txtrace

    @txtrace.setter
    def txtrace(self, rec) -> None:
        self._txtrace = rec
        self._admit_rec = rec if self.sig_batcher is None else None
        if rec is not None:
            rec.bind_tick(self)

    # -- wal ---------------------------------------------------------------

    def init_wal(self) -> None:
        """Append-only log of every tx entering CheckTx
        (mempool/mempool.go:111-124)."""
        import os

        path = self.config.wal_dir()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.wal = Group(path)

    def close_wal(self) -> None:
        with self._mtx:
            if self.wal is not None:
                self.wal.close()
                self.wal = None

    # -- locking around commit --------------------------------------------

    def lock(self) -> None:
        self._mtx.acquire()

    def unlock(self) -> None:
        self._mtx.release()

    def size(self) -> int:
        return len(self.txs)

    def flush_app_conn(self) -> None:
        self.proxy_app_conn.flush_sync()

    def flush(self) -> None:
        """Drop everything (unsafe_flush_mempool RPC)."""
        with self._mtx:
            self.cache.reset()
            el = self.txs.front()
            while el is not None:
                nxt = el.next()
                self.txs.remove(el)
                el = nxt
            self.lane_counts = {lane: 0 for lane in LANES}
            self.lane_bytes = {lane: 0 for lane in LANES}
            self.source_counts.clear()

    def txs_front(self):
        return self.txs.front()

    def txs_front_wait(self, timeout: float | None = None):
        return self.txs.front_wait(timeout)

    # -- checktx -----------------------------------------------------------

    def check_tx(self, tx: bytes, cb=None, source: str = "rpc",
                 source_id: str = "") -> None:
        """Validate tx against the app; good txs enter the pool when the
        async response lands (mempool/mempool.go:166-205). With a
        SigBatcher wired, sig-carrying txs first pass the batched
        signature gate — invalid signatures are rejected here without
        ever reaching the app. `source` tags the tx-lifecycle trace
        (round 17): "rpc" for a client submit, "peer" for gossip.
        `source_id` (round 23) narrows it to the specific client IP /
        peer id for per-source admission accounting; intake sheds raise
        typed errors (MempoolFullError / MempoolSourceLimitError) with
        stable reason strings the RPC layer forwards verbatim."""
        src_key = f"{source}:{source_id}" if source_id else source
        with self._mtx:
            if not self.cache.push(tx):
                self.cache_dups += 1
                raise TxInCacheError(tx.hex()[:16])
            if self.pool_cap and len(self.txs) >= self.pool_cap:
                # pool at the sum of its lane caps: fail fast at intake,
                # before WAL/gate/app work. Cache entry dropped so the tx
                # can resubmit once the pool drains.
                self.pool_full_rejects += 1
                self.cache.remove(tx)
                raise MempoolFullError(
                    f"mempool_full: {len(self.txs)} txs >= cap {self.pool_cap}")
            if (self.source_max_txs
                    and self.source_counts.get(src_key, 0) >= self.source_max_txs):
                self.source_limited += 1
                self.cache.remove(tx)
                raise MempoolSourceLimitError(
                    f"mempool_source_limit: {src_key} holds "
                    f">={self.source_max_txs} txs")
            self._pending_source[tx] = src_key
            # lifecycle ingress, inlined (the <2% discipline): an
            # untraced tx pays ONE local-attribute countdown decrement;
            # only the sampled tx enters the recorder (which re-arms
            # this tick through the bind_tick mirror)
            self._trace_tick -= 1
            if self._trace_tick <= 0:
                if self._txtrace is not None:
                    self._txtrace.ingress(tx, source)
                else:
                    self._trace_tick = 1 << 60
            if self.wal is not None:
                self.wal.write_line(tx.hex())
                self.wal.flush()
            if self.sig_batcher is not None:
                item = self.sig_batcher.parse(tx)
                if item is not None:
                    if not self.sig_batcher.submit(item, (tx, cb)):
                        # gate saturated: refuse retriably, never grow an
                        # unbounded backlog off a peer-driven path
                        self.cache.remove(tx)
                        self._pending_source.pop(tx, None)
                        if self._txtrace is not None:
                            # a traced tx leaving the lifecycle here
                            # must seal, not linger as a false PARKED
                            self._txtrace.reject(tx, "gate_saturated")
                        if cb is not None:
                            cb(ResponseCheckTx(
                                code=CODE_UNAUTHORIZED,
                                log="signature gate saturated; retry",
                            ))
                    return
                if self._txtrace is not None and tx in self._txtrace._active:
                    # gate-BYPASSING traced tx (no parseable signature,
                    # off the gated hot path): the batch-granular admit
                    # stamp won't cover it — stamp on its own response
                    rec, orig_cb = self._txtrace, cb

                    def cb(res, _tx=tx, _orig=orig_cb, _rec=rec):
                        if res.is_ok:
                            _rec.stamp(_tx, "mempool_admit")
                        else:
                            _rec.reject(_tx, "checktx_reject")
                        if _orig is not None:
                            _orig(res)
            reqres = self.proxy_app_conn.check_tx_async(tx)
            if cb is not None:
                reqres.set_callback(lambda res: cb(res))

    def _sig_gate_results(self, results) -> None:
        """Gate verdicts for one verified batch (batcher thread).
        Signature-held txs admit to the app in ONE grouped dispatch
        (check_tx_many_async — one mempool-lock and one app-lock round
        trip for the whole batch); failures reject without app dispatch,
        same cache semantics as an app-rejected tx
        (mempool/mempool.go:231)."""
        rec = self._txtrace
        ok_entries = [ctx for ctx, ok in results if ok]
        if rec is not None and rec._active:
            # batch-granular stamping (the <2% discipline): one set
            # build for the whole verdict batch, zero per-tx calls
            rec.stamp_gate_batch(ok_entries)
        for tx, cb in (ctx for ctx, ok in results if not ok):
            if rec is not None:
                rec.reject(tx, "bad_sig")
            try:
                self._reject_bad_sig(tx, cb)
            except Exception:  # noqa: BLE001 — one raising reject callback
                # (e.g. a dead RPC response writer) must not abort the
                # batch: the remaining verdicts still have to be
                # delivered or their txs are stranded in the dedup cache
                logger.exception("bad-sig reject callback failed")
        if not ok_entries:
            return
        with self._mtx:
            rrs = self.proxy_app_conn.check_tx_many_async(
                [tx for tx, _cb in ok_entries]
            )
        for (_tx, cb), rr in zip(ok_entries, rrs):
            if cb is not None:
                try:
                    rr.set_callback(cb)
                except Exception:  # noqa: BLE001 — same isolation rule
                    logger.exception("check_tx callback failed")

    def _reject_bad_sig(self, tx: bytes, cb) -> None:
        """Signature failed the batch gate: reject without app dispatch —
        same cache semantics as an app-rejected tx (allow resubmission,
        mempool/mempool.go:231)."""
        self.cache.remove(tx)
        self._pending_source.pop(tx, None)
        if cb is not None:
            cb(ResponseCheckTx(code=CODE_UNAUTHORIZED,
                               log="invalid signature (batch pre-verify)"))

    def _res_cb(self, req_type: str, tx, res) -> None:
        """Routed to normal or recheck mode by cursor state
        (mempool/mempool.go:208-214)."""
        if req_type != "check_tx":
            return
        if self.recheck_cursor is None:
            self._res_cb_normal(tx, res)
        else:
            self._res_cb_recheck(tx, res)

    def _res_cb_normal(self, tx: bytes, res: ResponseCheckTx) -> None:
        src = self._pending_source.pop(tx, "")
        if res.is_ok:
            # lane admission (round 23): the app's priority hint picks
            # the lane; a full lane or a shed-writes ladder level rejects
            # by MUTATING the response — the ABCI clients fire this
            # global callback before per-request completion, so every
            # broadcast_tx waiter sees the typed rejection.
            lane = lane_for_priority(getattr(res, "priority", 0))
            cap_txs, cap_bytes = self.lane_caps[lane]
            if (cap_txs and self.lane_counts[lane] >= cap_txs) or (
                    cap_bytes and self.lane_bytes[lane] + len(tx) > cap_bytes):
                self.lane_full[lane] += 1
                self.cache.remove(tx)
                if self._txtrace is not None:
                    self._txtrace.reject(tx, "lane_full")
                res.code = CODE_MEMPOOL_FULL
                res.log = f"mempool_lane_full:{lane}"
                return
            pressure = self.pressure_fn() if self.pressure_fn is not None else 0
            if pressure >= PRESSURE_SHED_WRITES and lane != LANES[0]:
                # ladder at shed-writes: only the priority lane still
                # admits (reads were already shed at the RPC edge)
                self.shed_writes += 1
                self.cache.remove(tx)
                if self._txtrace is not None:
                    self._txtrace.reject(tx, "shed_writes")
                res.code = CODE_MEMPOOL_FULL
                res.log = f"mempool_shed_writes:{lane}"
                return
            if self._admit_rec is not None:
                # ungated path only: the sig-gate path already stamped
                # admit batch-granularly (_sig_gate_results)
                self._admit_rec.stamp(tx, "mempool_admit")
            self.counter += 1
            self.txs.push_back(MemTx(self.counter, self.height, tx, lane, src))
            self.lane_counts[lane] += 1
            self.lane_bytes[lane] += len(tx)
            if src:
                self.source_counts[src] = self.source_counts.get(src, 0) + 1
            self._notify_txs_available()
        else:
            # bad tx: allow future resubmission (mempool/mempool.go:231)
            if self._txtrace is not None:
                self._txtrace.reject(tx, "checktx_reject")
            self.cache.remove(tx)

    def _res_cb_recheck(self, tx: bytes, res: ResponseCheckTx) -> None:
        cursor = self.recheck_cursor
        assert cursor is not None
        memtx: MemTx = cursor.value
        if memtx.tx != tx:
            raise RuntimeError(
                f"recheck response for unexpected tx {tx.hex()[:16]} != {memtx.tx.hex()[:16]}"
            )
        if not res.is_ok:
            # tx invalidated by the last block: evict from the pool AND the
            # cache — it might become good again later (mempool.go:258-259)
            self.txs.remove(cursor)
            self._forget(memtx)
            self.cache.remove(tx)
        if cursor is self.recheck_end:
            self.recheck_cursor = None
            self.recheck_end = None
            if self.size() > 0:
                self._notify_txs_available()
        else:
            self.recheck_cursor = cursor.next()

    # -- txs-available signal ---------------------------------------------

    def enable_txs_available(self, cb) -> None:
        """cb() fires at most once per height when the pool goes non-empty
        (mempool/mempool.go:280-297)."""
        self._txs_available_cb = cb

    def _notify_txs_available(self) -> None:
        if self._txs_available_cb is not None and not self.notified_txs_available:
            self.notified_txs_available = True
            self._txs_available_cb()

    # -- consensus interface ----------------------------------------------

    def reap(self, max_txs: int) -> list[bytes]:
        """Up to max_txs good txs, lanes drained in priority order
        (priority -> default -> bulk, FIFO within a lane; -1 = all).
        With every tx in the default lane this is exactly the reference's
        FIFO reap (mempool/mempool.go:300-327). Waits for outstanding
        CheckTx responses first."""
        with self._mtx:
            if self.height > 0:
                self.proxy_app_conn.flush_sync()
            by_lane: dict[str, list[bytes]] = {lane: [] for lane in LANES}
            el = self.txs.front()
            while el is not None:
                # unknown lane tag (hand-built MemTx) rides the default lane
                by_lane.get(el.value.lane, by_lane["default"]).append(el.value.tx)
                el = el.next()
            out: list[bytes] = []
            for lane in LANES:
                out.extend(by_lane[lane])
            if max_txs >= 0:
                del out[max_txs:]
            return out

    def update(self, height: int, txs: list[bytes]) -> None:
        """Remove committed txs; recheck survivors against the new app
        state. Caller must hold lock() (mempool/mempool.go:331-357)."""
        self.proxy_app_conn.flush_sync()
        self.height = height
        self.notified_txs_available = False
        committed = set(txs)
        good = self._filter_txs(committed)
        # Recheck && (RecheckEmpty || block had txs) — mempool/mempool.go:351
        if good and self.config.recheck and (self.config.recheck_empty or txs):
            self._recheck_txs(good)
            # fires _res_cb_recheck for each in-flight response
            self.proxy_app_conn.flush_async()

    def _forget(self, memtx: MemTx) -> None:
        """Reverse the lane/source accounting of one pool departure."""
        lane = memtx.lane
        if lane in self.lane_counts:
            self.lane_counts[lane] = max(0, self.lane_counts[lane] - 1)
            self.lane_bytes[lane] = max(0, self.lane_bytes[lane] - len(memtx.tx))
        src = memtx.source
        if src:
            left = self.source_counts.get(src, 0) - 1
            if left > 0:
                self.source_counts[src] = left
            else:
                # entries drop at zero: per-source cardinality stays
                # bounded by the pool, not by client-IP churn
                self.source_counts.pop(src, None)

    def _filter_txs(self, block_txs: set[bytes]) -> list:
        good = []
        el = self.txs.front()
        while el is not None:
            nxt = el.next()
            if el.value.tx in block_txs:
                self.txs.remove(el)
                self._forget(el.value)
            else:
                good.append(el)
            el = nxt
        return good

    def _recheck_txs(self, good_elements: list) -> None:
        self.recheck_cursor = good_elements[0]
        self.recheck_end = good_elements[-1]
        # grouped dispatch: one app-lock round trip for the whole
        # survivor set; responses arrive in order, which the recheck
        # cursor depends on (both the local client's many-path and the
        # base per-tx loop preserve submission order)
        self.proxy_app_conn.check_tx_many_async(
            [el.value.tx for el in good_elements]
        )
