"""Typed configuration tree (reference: config/config.go).

One Config struct per subsystem — Base, RPC, P2P, Mempool, Consensus —
with defaults mirroring the reference's (config/config.go:10-19 structs,
367-385 consensus timeout schedule) and faster "test" presets. Consensus-
critical parameters (block size limits etc.) do NOT live here; they travel
in the genesis doc (types/params.py), exactly as in the reference.

Durations are seconds as floats (the reference uses milliseconds — values
converted, not renamed). Timeouts follow the reference's linear round
schedule: timeout_X + round * timeout_X_delta (config/config.go:338-357).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, replace


@dataclass
class BaseConfig:
    """Top-level node options (config/config.go:57-135)."""

    root_dir: str = ""
    chain_id: str = ""
    genesis: str = "genesis.json"
    priv_validator: str = "priv_validator.json"
    moniker: str = "anonymous"
    proxy_app: str = "tcp://127.0.0.1:46658"
    abci: str = "socket"  # socket | grpc (in-proc apps use names: kvstore, ...)
    log_level: str = "info"
    prof_laddr: str = ""
    fast_sync: bool = True
    filter_peers: bool = False
    tx_index: str = "kv"  # kv | null
    # sqlite (bounded-RAM persistent store, the LevelDB-default
    # equivalent) so a restarted node resumes its chain AND steady-state
    # RSS stays flat as the chain grows — the round-5 soak measured
    # filedb's in-memory key index growing ~90 KB/min at test cadence
    # (libs/db.py SqliteDB docstring). filedb (crash-safe journal,
    # offset-indexed, r4 default) remains selectable; memdb is for tests
    # (the kill_all localnet scenario catches a non-persistent default).
    # NOTE: homes initialized before this default changed carry the OLD
    # explicit backend in config.toml and must edit it by hand — the
    # loader honors whatever the file says.
    db_backend: str = "sqlite"  # sqlite | filedb | memdb
    db_path: str = "data"

    def genesis_file(self) -> str:
        return _root_join(self.root_dir, self.genesis)

    def priv_validator_file(self) -> str:
        return _root_join(self.root_dir, self.priv_validator)

    def db_dir(self) -> str:
        return _root_join(self.root_dir, self.db_path)


@dataclass
class RPCConfig:
    """RPC server options (config/config.go:163-193)."""

    root_dir: str = ""
    laddr: str = "tcp://0.0.0.0:46657"
    grpc_laddr: str = ""
    unsafe: bool = False
    # -- ingress admission (round 23, docs/serving.md) ------------------
    # every knob here has a TENDERMINT_RPC_* env twin (env wins, read
    # per request — live-tunable under fire). 0 disables a limit.
    max_connections: int = 512  # concurrent HTTP/WS connections
    max_inflight: int = 256  # concurrently-executing requests
    rate_limit: float = 0.0  # per-client-IP requests/s (unix peers exempt)
    rate_burst: float = 0.0  # bucket depth; 0 -> 2x rate_limit
    deadline_s: float = 0.0  # per-request budget; waits inside handlers obey it
    ws_send_queue: int = 256  # per-WS-client bounded event queue
    ws_max_clients: int = 200  # concurrent WS subscribers


@dataclass
class P2PConfig:
    """Peer-to-peer options (config/config.go:199-253)."""

    root_dir: str = ""
    laddr: str = "tcp://0.0.0.0:46656"
    seeds: str = ""  # comma-separated host:port
    skip_upnp: bool = False
    addr_book_file: str = "addrbook.json"
    addr_book_strict: bool = True
    pex_reactor: bool = False
    max_num_peers: int = 50
    flush_throttle_timeout: float = 0.100
    max_msg_packet_payload_size: int = 1024
    send_rate: int = 512_000  # bytes/sec (p2p/connection.go:33-34)
    recv_rate: int = 512_000

    def addr_book(self) -> str:
        return _root_join(self.root_dir, self.addr_book_file)


@dataclass
class MempoolConfig:
    """Mempool options (config/config.go:267-291)."""

    root_dir: str = ""
    recheck: bool = True
    recheck_empty: bool = True
    broadcast: bool = True
    wal_path: str = "data/mempool.wal"
    # -- priority lanes (round 23, docs/serving.md) ---------------------
    # per-lane count/byte caps; reap drains priority -> default -> bulk.
    # TENDERMINT_MEMPOOL_LANE_<LANE>_MAX_TXS / _MAX_BYTES env twins win.
    lane_priority_max_txs: int = 10_000
    lane_priority_max_bytes: int = 32 * 1024 * 1024
    lane_default_max_txs: int = 50_000
    lane_default_max_bytes: int = 64 * 1024 * 1024
    lane_bulk_max_txs: int = 20_000
    lane_bulk_max_bytes: int = 32 * 1024 * 1024
    # per-source in-pool tx cap (source = rpc client IP or peer id);
    # 0 disables. TENDERMINT_MEMPOOL_SOURCE_MAX_TXS wins.
    source_max_txs: int = 0

    def wal_dir(self) -> str:
        return _root_join(self.root_dir, self.wal_path)


@dataclass
class ConsensusConfig:
    """Consensus timeouts + policies (config/config.go:295-385).

    Defaults match DefaultConsensusConfig (config/config.go:367-385):
    3s propose (+0.5s/round), 1s prevote/precommit (+0.5s/round),
    1s commit; empty blocks on, 0s empty-blocks interval.
    """

    root_dir: str = ""
    wal_path: str = "data/cs.wal/wal"
    wal_light: bool = False
    # group-commit durability window (round 9, docs/crash-recovery.md):
    # non-ENDHEIGHT records are fsynced at most this many seconds after
    # they buffer; #ENDHEIGHT markers always fsync synchronously
    wal_flush_interval_s: float = 0.1
    # True restores the pre-round-9 fsync-per-record bound (10-40x slower
    # commit hot path; benches/bench_wal.py measures the gap)
    wal_sync_every_write: bool = False

    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0
    skip_timeout_commit: bool = False

    max_block_size_txs: int = 10000
    max_block_size_bytes: int = 1  # unused in reference too (config/config.go:309)

    create_empty_blocks: bool = True
    create_empty_blocks_interval: float = 0.0

    # pipelined execution plane (round 14, docs/execution-pipeline.md):
    # defer apply(H) + snapshot hook + events to the ordered executor
    # while consensus advances to H+1; False restores the fully serial
    # finalize_commit (benches/bench_pipeline.py measures the gap)
    pipeline_apply: bool = True

    peer_gossip_sleep_duration: float = 0.100
    peer_query_maj23_sleep_duration: float = 2.0

    # has-vote-aware gossip dedup (round 20, docs/localnet.md): feed the
    # per-peer vote bit-arrays from STATE-channel HasVote announcements
    # (arrays ensured on arrival, last-commit heights accepted),
    # broadcast HasBlockPart part announcements so peers skip votes and
    # parts we already hold, and hold RE-pushes of a just-received vote
    # for one gossip tick so those announcements win the relay race
    # (reactor.VOTE_RELAY_DELAY). False restores the pre-round-20
    # gossip (benches/bench_localnet.py measures the duplicate-ratio
    # gap — ~30% fewer duplicate votes at n=10 real processes).
    gossip_dedup: bool = True

    def wal_file(self) -> str:
        return _root_join(self.root_dir, self.wal_path)

    # -- round-indexed timeout schedule (config/config.go:338-357) --------

    def propose(self, round_: int) -> float:
        return self.timeout_propose + self.timeout_propose_delta * round_

    def prevote(self, round_: int) -> float:
        return self.timeout_prevote + self.timeout_prevote_delta * round_

    def precommit(self, round_: int) -> float:
        return self.timeout_precommit + self.timeout_precommit_delta * round_

    def commit(self, wall_time: float, block_time: float) -> float:
        """Absolute deadline for starting the next height: block time +
        timeout_commit, as a delay from wall_time (config/config.go:353-357)."""
        return max(0.0, block_time + self.timeout_commit - wall_time)


@dataclass
class StateSyncConfig:
    """State-sync snapshot subsystem (round 10, docs/state-sync.md).
    Both sides of the protocol live here: producing snapshots at height
    intervals, and restoring from peers' snapshots on a cold start."""

    root_dir: str = ""
    # restore side: on an empty node, discover peer snapshots, light-
    # verify + restore the newest, then fast-sync only the tail
    enable: bool = False
    # comma-separated RPC endpoints the light client verifies headers
    # against during restore (empty + enable=True is a config error the
    # node reports at startup)
    rpc_servers: str = ""
    # operator-pinned trust anchor; 0 walks trust from genesis
    trust_height: int = 0
    # producer side: snapshot every N committed heights (0 = off)
    snapshot_interval: int = 0
    snapshot_keep_recent: int = 2
    chunk_size: int = 65536
    # every K-th snapshot is FULL; the ones between are deltas against
    # the previous snapshot (round 13, state-tree apps only; 1 = always
    # full). keep_recent is clamped to cover the chain.
    snapshot_full_every: int = 4

    def snapshot_dir(self) -> str:
        return _root_join(self.root_dir, "data/snapshots")


@dataclass
class PruningConfig:
    """Bounded-retention lifecycle (round 19, docs/state-sync.md §
    Retention): automatic block-store + WAL pruning so disk is bounded
    by retention, not chain length. Off by default — archive nodes keep
    everything.

    The configured `retain_blocks` is an OPERATOR TARGET, not the
    effective retention: the coordinator (node/retention.py) prunes to
    the MINIMUM of this target, the oldest published snapshot height
    (the statesync producer must stay serviceable), the oldest pending
    evidence height, and the app state tree's oldest retained version —
    whichever plane needs the deepest history wins."""

    root_dir: str = ""
    # keep at least the newest N blocks (0 = pruning disabled). Values
    # below 2 are clamped: consensus always needs the head block's seen
    # commit and last-commit linkage.
    retain_blocks: int = 0
    # run the retention check every N committed heights (the prune
    # itself rides the apply executor's tail, off the consensus
    # critical path)
    interval_heights: int = 10


@dataclass
class DeviceConfig:
    """Device plane topology (round 21, docs/device-daemon.md § Sharded
    device plane): which devd daemon socket(s) the gateway dispatches
    verify/hash batches to. Empty = the TENDERMINT_DEVD_SOCK/default
    single-socket behavior, unchanged."""

    root_dir: str = ""
    # comma-separated devd socket paths. One entry behaves byte-for-byte
    # like setting TENDERMINT_DEVD_SOCK; two or more arm the sharded
    # dispatcher (ops/devd_shard: slice sharding, work stealing,
    # per-endpoint circuit breakers). Node assembly exports this as
    # TENDERMINT_DEVD_SOCKS unless the env var is already set (the env
    # wins — it is the operator's per-process override).
    socks: str = ""


@dataclass
class ReplicaConfig:
    """Verified read-replica daemon (round 24, docs/serving.md § Read
    replicas): a stateless, proof-carrying read cache that follows an
    upstream node's RPC with the light client and serves the read
    surface. Every knob has a TENDERMINT_REPLICA_* env twin (env wins,
    read per use — live-tunable)."""

    root_dir: str = ""
    # upstream RPC endpoint ("host:port" or "unix:///path.sock"). May
    # itself be a replica — tiered fan-out; proofs compose unchanged.
    upstream: str = ""
    # the replica's own read listener (same transports as a node's RPC)
    laddr: str = "tcp://0.0.0.0:46659"
    # bounded staleness: a latest-height read is served from cache only
    # while the cached proof sits within this many heights of the
    # replica's verified head, and refused entirely when the replica
    # itself lags its upstream by more than this
    max_lag_heights: int = 10
    # proof-carrying cache entry cap (LRU over (path, key, height))
    cache_entries: int = 10_000
    # verified block/commit responses kept for block / blockchain_info /
    # commit serving and downstream replica chaining (also sizes the
    # light client's verified-header memo)
    keep_blocks: int = 64
    # upstream WS resubscribe backoff: initial seconds, doubling per
    # consecutive failure up to the max
    reconnect_backoff_s: float = 0.25
    reconnect_backoff_max_s: float = 4.0


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    pruning: PruningConfig = field(default_factory=PruningConfig)
    device: DeviceConfig = field(default_factory=DeviceConfig)
    replica: ReplicaConfig = field(default_factory=ReplicaConfig)

    def set_root(self, root: str) -> "Config":
        self.base.root_dir = root
        self.rpc.root_dir = root
        self.p2p.root_dir = root
        self.mempool.root_dir = root
        self.consensus.root_dir = root
        self.statesync.root_dir = root
        self.pruning.root_dir = root
        self.device.root_dir = root
        self.replica.root_dir = root
        return self

    def copy(self) -> "Config":
        return Config(
            replace(self.base),
            replace(self.rpc),
            replace(self.p2p),
            replace(self.mempool),
            replace(self.consensus),
            replace(self.statesync),
            replace(self.pruning),
            replace(self.device),
            replace(self.replica),
        )


def _root_join(root: str, path: str) -> str:
    if os.path.isabs(path) or not root:
        return path
    return os.path.join(root, path)


def default_config() -> Config:
    return Config()


def test_config() -> Config:
    """Fast preset for tests (Test*Config variants in config/config.go):
    10x-shorter consensus timeouts, skip timeout-commit, ephemeral ports,
    in-memory db."""
    cfg = Config()
    cfg.base.chain_id = "tendermint_test"
    cfg.base.proxy_app = "kvstore"
    cfg.base.fast_sync = False
    cfg.base.db_backend = "memdb"
    cfg.rpc.laddr = "tcp://0.0.0.0:36657"
    cfg.p2p.laddr = "tcp://0.0.0.0:36656"
    cfg.p2p.skip_upnp = True
    c = cfg.consensus
    c.wal_light = True
    c.timeout_propose = 0.1
    c.timeout_propose_delta = 0.001
    c.timeout_prevote = 0.01
    c.timeout_prevote_delta = 0.001
    c.timeout_precommit = 0.01
    c.timeout_precommit_delta = 0.001
    c.timeout_commit = 0.01
    c.skip_timeout_commit = True
    return cfg
