from tendermint_tpu.config.config import (
    BaseConfig,
    Config,
    ConsensusConfig,
    MempoolConfig,
    P2PConfig,
    RPCConfig,
    default_config,
    test_config,
)
from tendermint_tpu.config.toml import ensure_root, load_config, reset_test_root

__all__ = [
    "Config",
    "BaseConfig",
    "RPCConfig",
    "P2PConfig",
    "MempoolConfig",
    "ConsensusConfig",
    "default_config",
    "test_config",
    "ensure_root",
    "load_config",
    "reset_test_root",
]
