"""Multi-process integration tier (reference test/p2p/* scenarios over
real node processes + TCP; see test/p2p/README.md). Slow-marked: each
scenario boots a 4-process testnet."""

from __future__ import annotations

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "test", "p2p"))

from localnet import Localnet  # noqa: E402
from scenarios import SCENARIOS  # noqa: E402


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario(name, tmp_path_factory):
    # tmp_path_factory roots get pruned across runs — raw mkdtemp homes
    # would accumulate filedb journals in the system temp dir forever
    net = Localnet(
        4,
        str(tmp_path_factory.mktemp(f"localnet-{name}")),
        base_port=47900 + 20 * sorted(SCENARIOS).index(name),
    )
    try:
        SCENARIOS[name](net)
    finally:
        net.stop_all()
