"""Light-client trust-transition unit tests (rpc/light.py). The live-node
integration path is tests/test_node_rpc.py::test_light_client_*; here a
stub client serves crafted chain data so the validator-change rule —
adopt a new set only when the OLD trusted set still signed > 2/3 of its
power on the transition commit — can be tested for both the accept and
the forged-set-attack cases (code-review r3 finding)."""

from __future__ import annotations

import pytest

from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.rpc.light import LightClient, LightClientError
from tendermint_tpu.types import PrivValidatorFS
from tendermint_tpu.types.block import Header
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, Vote

CHAIN = "light-test-chain"


def _pv():
    return PrivValidatorFS(gen_priv_key_ed25519(), None)


def _commit_for(header: Header, vset: ValidatorSet, privs: dict):
    """Sign a +2/3 commit over `header` by every validator of `vset`
    that has a priv key in `privs` (address -> pv)."""
    from tendermint_tpu.types.block import Commit

    block_id = BlockID(header.hash(), PartSetHeader(1, b"\x01" * 20))
    precommits: list = [None] * vset.size()
    for i in range(vset.size()):
        addr, val = vset.get_by_index(i)
        pv = privs.get(addr)
        if pv is None:
            continue
        vote = Vote(
            validator_address=addr,
            validator_index=i,
            height=header.height,
            round_=0,
            type_=VOTE_TYPE_PRECOMMIT,
            block_id=block_id,
        )
        precommits[i] = pv.sign_vote(CHAIN, vote)
    return Commit(block_id, precommits)


def _header(height: int, vset: ValidatorSet, last_block_id=None) -> Header:
    return Header(
        chain_id=CHAIN,
        height=height,
        time_ns=height * 1000,
        num_txs=0,
        last_block_id=last_block_id or BlockID(),
        last_commit_hash=b"\x02" * 20,
        data_hash=b"\x03" * 20,
        validators_hash=vset.hash(),
        app_hash=b"",
    )


class StubClient:
    def __init__(self):
        self.commits: dict = {}  # height -> {"header":..., "commit":...}
        self.valsets: dict = {}  # height -> ValidatorSet

    def add_height(self, header, commit, vset):
        self.commits[header.height] = {
            "header": header.to_json(),
            "commit": commit.to_json(),
        }
        self.valsets[header.height] = vset

    def commit(self, height):
        return self.commits[height]

    def validators(self, height=0):
        return {
            "block_height": height,
            "validators": self.valsets[height].to_json(),
        }


def _chain_with_change(old_signs_transition: bool):
    """Heights 1-2 under set {v1}; at height 3 the set becomes {v1, v2}
    (or {attacker} when old_signs_transition is False)."""
    pv1, pv2 = _pv(), _pv()
    v1 = Validator.new(pv1.get_pub_key(), 2)
    v2 = Validator.new(pv2.get_pub_key(), 1)
    old_set = ValidatorSet([v1.copy()])
    privs = {pv1.get_address(): pv1, pv2.get_address(): pv2}

    stub = StubClient()
    prev_id = None
    for h in (1, 2):
        hd = _header(h, old_set, prev_id)
        stub.add_height(hd, _commit_for(hd, old_set, privs), old_set)
        prev_id = BlockID(hd.hash(), PartSetHeader(1, b"\x01" * 20))

    if old_signs_transition:
        new_set = ValidatorSet([v1.copy(), v2.copy()])
    else:
        atk = _pv()
        privs[atk.get_address()] = atk
        new_set = ValidatorSet([Validator.new(atk.get_pub_key(), 5)])
    hd3 = _header(3, new_set, prev_id)
    stub.add_height(hd3, _commit_for(hd3, new_set, privs), new_set)
    return stub, old_set


def test_advance_accepts_overlapping_validator_change():
    stub, old_set = _chain_with_change(old_signs_transition=True)
    lc = LightClient(stub, CHAIN, old_set.copy())
    lc.advance(3)
    assert lc.height == 3
    assert lc.validators.size() == 2


def test_advance_rejects_forged_validator_set():
    """A self-consistent forged set + commit (signed only by attacker
    keys) must NOT be adopted: the trusted set signed none of its
    power on the transition."""
    stub, old_set = _chain_with_change(old_signs_transition=False)
    lc = LightClient(stub, CHAIN, old_set.copy())
    with pytest.raises(LightClientError, match="trusted set signed only"):
        lc.advance(3)


def test_advance_rejects_replayed_precommit_stuffing():
    """ADVICE r3 (high): condition (d) must only credit old-set power for
    precommits over THIS commit's block. A vote's sign-bytes exclude the
    validator index/address, so an attacker can re-wrap genuine old-set
    precommits replayed from the real chain (same height/round, the REAL
    block) into a forged commit over a forged block; without the block_id
    filter those replays satisfy (d) with zero old-set endorsement."""
    from tendermint_tpu.types.block import Commit

    pv1 = _pv()
    v1 = Validator.new(pv1.get_pub_key(), 2)
    old_set = ValidatorSet([v1.copy()])
    privs = {pv1.get_address(): pv1}
    stub = StubClient()
    prev_id = None
    for h in (1, 2):
        hd = _header(h, old_set, prev_id)
        stub.add_height(hd, _commit_for(hd, old_set, privs), old_set)
        prev_id = BlockID(hd.hash(), PartSetHeader(1, b"\x01" * 20))

    # the REAL height-3 block the honest chain committed — the source of
    # the replayable precommit material
    real_hd3 = _header(3, old_set, prev_id)
    real_block_id = BlockID(real_hd3.hash(), PartSetHeader(1, b"\x01" * 20))

    # the forged chain: {v1, attacker} with the attacker holding +2/3 of
    # the NEW set, so the new-set tally passes on attacker signatures alone
    atk = _pv()
    new_set = ValidatorSet([v1.copy(), Validator.new(atk.get_pub_key(), 100)])
    forged_hd3 = _header(3, new_set, prev_id)
    forged_block_id = BlockID(forged_hd3.hash(), PartSetHeader(1, b"\x01" * 20))
    precommits: list = [None] * new_set.size()
    for i in range(new_set.size()):
        addr, _ = new_set.get_by_index(i)
        if addr == pv1.get_address():
            # replayed genuine precommit: v1's signature covers only
            # (block_id, height, round, type), so index/address re-wrap
            # is free for the attacker
            vote = Vote(addr, i, 3, 0, VOTE_TYPE_PRECOMMIT, real_block_id)
            precommits[i] = pv1.sign_vote(CHAIN, vote)
        else:
            vote = Vote(addr, i, 3, 0, VOTE_TYPE_PRECOMMIT, forged_block_id)
            precommits[i] = atk.sign_vote(CHAIN, vote)
    stub.add_height(forged_hd3, Commit(forged_block_id, precommits), new_set)

    lc = LightClient(stub, CHAIN, old_set.copy())
    with pytest.raises(LightClientError, match="trusted set signed only"):
        lc.advance(3)
    assert lc.validators.hash() == old_set.hash()


def test_failed_advance_does_not_install_candidate_set():
    """ADVICE r3 (medium): if verify_header rejects the transition commit
    AFTER the old-set-overlap check passed, the candidate set must not be
    left installed as trusted — a catching caller would otherwise verify
    all later headers against the attacker's set."""
    pv1, pv2, pv3 = _pv(), _pv(), _pv()
    v1 = Validator.new(pv1.get_pub_key(), 3)
    old_set = ValidatorSet([v1.copy()])
    privs = {pv1.get_address(): pv1, pv2.get_address(): pv2}  # pv3 never signs
    stub = StubClient()
    prev_id = None
    for h in (1, 2):
        hd = _header(h, old_set, prev_id)
        stub.add_height(hd, _commit_for(hd, old_set, privs), old_set)
        prev_id = BlockID(hd.hash(), PartSetHeader(1, b"\x01" * 20))
    # transition commit signed by v1+v2 only: the OLD-set overlap passes
    # (v1 is 100% of old power) but the NEW set's +2/3 tally fails (pv3
    # holds most of the new power and did not sign)
    new_set = ValidatorSet([
        v1.copy(),
        Validator.new(pv2.get_pub_key(), 1),
        Validator.new(pv3.get_pub_key(), 100),
    ])
    hd3 = _header(3, new_set, prev_id)
    stub.add_height(hd3, _commit_for(hd3, new_set, privs), new_set)

    lc = LightClient(stub, CHAIN, old_set.copy())
    with pytest.raises(LightClientError, match="commit verification failed"):
        lc.advance(3)
    assert lc.validators.hash() == old_set.hash()
    assert lc.height == 2
    # and the client still works against the honest chain from there
    lc.verify_header(2)


def test_set_change_at_trust_anchor_cannot_skip_chain_link():
    """ADVICE r3 (low): a validator-set change landing on the FIRST height
    an advance() call processes used to skip the last_block_id chain-link
    check (prev_header was None). Out-of-band trust anchors are now
    verified before the walk, so a change at the anchor height cannot
    bypass chain linkage."""
    pv1, pv2 = _pv(), _pv()
    v1 = Validator.new(pv1.get_pub_key(), 2)
    old_set = ValidatorSet([v1.copy()])
    privs = {pv1.get_address(): pv1, pv2.get_address(): pv2}
    stub = StubClient()
    prev_id = None
    for h in (1, 2):
        hd = _header(h, old_set, prev_id)
        stub.add_height(hd, _commit_for(hd, old_set, privs), old_set)
        prev_id = BlockID(hd.hash(), PartSetHeader(1, b"\x01" * 20))
    # height 3 changes the set AND chains to garbage; its commit is
    # self-consistent and v1 signs it, so both the overlap and the
    # new-set tally would pass — only the chain link betrays it
    new_set = ValidatorSet([v1.copy(), Validator.new(pv2.get_pub_key(), 1)])
    bad_link = BlockID(b"\xee" * 20, PartSetHeader(1, b"\x01" * 20))
    hd3 = _header(3, new_set, bad_link)
    stub.add_height(hd3, _commit_for(hd3, new_set, privs), new_set)

    lc = LightClient(stub, CHAIN, old_set.copy(), trusted_height=3)
    with pytest.raises(LightClientError):
        lc.advance(3)
    assert lc.validators.hash() == old_set.hash()


def test_advance_rejects_unchained_header():
    """A validator change whose header does not chain to the verified
    previous header is rejected (the chain-link check runs before any
    commit verification)."""
    stub, old_set = _chain_with_change(old_signs_transition=True)
    hj = dict(stub.commits[3]["header"])
    hj["last_block_id"] = BlockID(
        b"\xee" * 20, PartSetHeader(1, b"\x01" * 20)
    ).to_json()
    stub.commits[3] = {"header": hj, "commit": stub.commits[3]["commit"]}
    lc = LightClient(stub, CHAIN, old_set.copy())
    with pytest.raises(LightClientError, match="does not chain"):
        lc.advance(3)


# -- >1/3 validator-set turnover (the statesync restore trust path) ----------
#
# A snapshot restore light-walks from its trust anchor to the snapshot
# height, so it must survive validator-set changes where MORE THAN A
# THIRD of the set turns over in one height — beyond the classic
# bisection skip-verify limit, fine for the sequential rule as long as
# the surviving old validators still carry > 2/3 of the OLD set's power
# on the transition commit (rpc/light.py _check_old_set_overlap).


def test_advance_accepts_over_one_third_turnover():
    """Old set {v1:7, v2:2}; the new set keeps only v1 and adds two
    newcomers holding 40/47 of the new power — way past 1/3 turnover.
    v1 alone carries 7/9 > 2/3 of the OLD power, so the sequential rule
    adopts the set; the walk then continues under it."""
    pv1, pv2, pv3, pv4 = _pv(), _pv(), _pv(), _pv()
    v1 = Validator.new(pv1.get_pub_key(), 7)
    old_set = ValidatorSet([v1.copy(), Validator.new(pv2.get_pub_key(), 2)])
    privs = {pv.get_address(): pv for pv in (pv1, pv2, pv3, pv4)}

    stub = StubClient()
    prev_id = None
    for h in (1, 2):
        hd = _header(h, old_set, prev_id)
        stub.add_height(hd, _commit_for(hd, old_set, privs), old_set)
        prev_id = BlockID(hd.hash(), PartSetHeader(1, b"\x01" * 20))

    new_set = ValidatorSet([
        v1.copy(),
        Validator.new(pv3.get_pub_key(), 20),
        Validator.new(pv4.get_pub_key(), 20),
    ])
    hd3 = _header(3, new_set, prev_id)
    stub.add_height(hd3, _commit_for(hd3, new_set, privs), new_set)
    prev_id = BlockID(hd3.hash(), PartSetHeader(1, b"\x01" * 20))
    # one more height under the NEW set: trust must keep walking
    hd4 = _header(4, new_set, prev_id)
    stub.add_height(hd4, _commit_for(hd4, new_set, privs), new_set)

    lc = LightClient(stub, CHAIN, old_set.copy())
    lc.advance(4)
    assert lc.height == 4
    assert lc.validators.hash() == new_set.hash()


def test_advance_rejects_exactly_two_thirds_old_overlap():
    """The overlap rule is STRICTLY greater than 2/3: a transition where
    the surviving old validators carry exactly 2/3 of the old power must
    be refused (the boundary an attacker holding 1/3 of the old keys
    would otherwise exploit)."""
    pv1, pv2 = _pv(), _pv()
    v1 = Validator.new(pv1.get_pub_key(), 2)
    old_set = ValidatorSet([v1.copy(), Validator.new(pv2.get_pub_key(), 1)])
    privs = {pv1.get_address(): pv1, pv2.get_address(): pv2}

    stub = StubClient()
    prev_id = None
    for h in (1, 2):
        hd = _header(h, old_set, prev_id)
        stub.add_height(hd, _commit_for(hd, old_set, privs), old_set)
        prev_id = BlockID(hd.hash(), PartSetHeader(1, b"\x01" * 20))

    # v2 (1/3 of old power) is dropped; only v1 (exactly 2/3) survives to
    # sign. The attacker dominates the new set so ITS +2/3 tally passes.
    atk = _pv()
    privs[atk.get_address()] = atk
    new_set = ValidatorSet([v1.copy(), Validator.new(atk.get_pub_key(), 100)])
    hd3 = _header(3, new_set, prev_id)
    stub.add_height(hd3, _commit_for(hd3, new_set, privs), new_set)

    lc = LightClient(stub, CHAIN, old_set.copy())
    with pytest.raises(LightClientError, match="signed only 2/3"):
        lc.advance(3)
    assert lc.validators.hash() == old_set.hash()
    assert lc.height == 2


# -- pruned-source horizon jump (round 19, bounded retention) -----------------


class PrunedStubClient(StubClient):
    """A source that pruned history below `base`: commits below it
    error exactly like the live RPC handler, and /status attests the
    earliest retained height."""

    def __init__(self, base: int):
        super().__init__()
        self.base = base
        self.commit_calls: list[int] = []

    def commit(self, height):
        self.commit_calls.append(height)
        if height < self.base:
            raise RuntimeError(
                f"height {height} is below the store's base {self.base}"
            )
        return super().commit(height)

    def status(self):
        return {
            "latest_block_height": max(self.commits, default=0),
            "earliest_block_height": self.base,
        }


def _pruned_chain(n: int, base: int, change_at: int | None = None,
                  old_signs_transition: bool = True):
    """n heights under {v1} (power 2), optionally switching sets at
    `change_at`; the stub only SERVES heights >= base."""
    pv1, pv2 = _pv(), _pv()
    v1 = Validator.new(pv1.get_pub_key(), 2)
    v2 = Validator.new(pv2.get_pub_key(), 1)
    genesis_set = ValidatorSet([v1.copy()])
    privs = {pv1.get_address(): pv1, pv2.get_address(): pv2}
    stub = PrunedStubClient(base)
    prev_id = None
    cur_set = genesis_set
    for h in range(1, n + 1):
        if change_at is not None and h == change_at:
            if old_signs_transition:
                cur_set = ValidatorSet([v1.copy(), v2.copy()])
            else:
                atk = _pv()
                privs[atk.get_address()] = atk
                cur_set = ValidatorSet([Validator.new(atk.get_pub_key(), 5)])
        hd = _header(h, cur_set, prev_id)
        stub.add_height(hd, _commit_for(hd, cur_set, privs), cur_set)
        prev_id = BlockID(hd.hash(), PartSetHeader(1, b"\x01" * 20))
    return stub, genesis_set


def test_advance_jumps_pruned_gap_same_set():
    """Genesis trust against a source whose base is 8: the sequential
    walk cannot fetch 1..7, but the trusted set's +2/3 signature on the
    horizon commit carries trust across the gap directly."""
    stub, genesis_set = _pruned_chain(12, base=8)
    lc = LightClient(stub, CHAIN, genesis_set.copy())
    lc.advance(12)
    assert lc.height == 12
    # exactly one failed probe below the base, then the jump
    assert stub.commit_calls[0] == 1
    assert 2 not in stub.commit_calls, "walk retried inside the pruned gap"
    assert stub.commit_calls[1] == 8


def test_advance_jumps_pruned_gap_with_overlapping_set_change():
    """The set changed INSIDE the pruned gap but the old trusted set
    still carries > 2/3 of its power on the horizon commit: rule (d)
    transfers trust without the (unknowable) chain linkage."""
    stub, genesis_set = _pruned_chain(12, base=8, change_at=5)
    lc = LightClient(stub, CHAIN, genesis_set.copy())
    lc.advance(12)
    assert lc.height == 12
    assert lc.validators.size() == 2


def test_advance_rejects_forged_set_across_pruned_gap():
    """A forged set past the pruned gap (zero old-set power on the
    horizon commit) must NOT be adopted — lying about the prune horizon
    weakens nothing."""
    stub, genesis_set = _pruned_chain(12, base=8, change_at=5,
                                      old_signs_transition=False)
    lc = LightClient(stub, CHAIN, genesis_set.copy())
    with pytest.raises(LightClientError, match="trusted set signed only"):
        lc.advance(12)
    assert lc.height == 0  # trust never moved


def test_advance_reraises_when_no_pruned_gap_attested():
    """A commit fetch failure WITHOUT a pruned-gap attestation (status
    shows the height should exist) re-raises: real transport errors must
    not silently skip verification."""
    stub, genesis_set = _pruned_chain(12, base=1)

    real_commit = stub.commit

    def flaky(height):
        if height == 3:
            raise RuntimeError("connection reset")
        return real_commit(height)

    stub.commit = flaky
    lc = LightClient(stub, CHAIN, genesis_set.copy())
    with pytest.raises(RuntimeError, match="connection reset"):
        lc.advance(12)
    assert lc.height == 2  # trust stopped exactly before the failure
