"""Chaos suite for the devd device plane (round 8 — ISSUE 3).

The consensus critical path rides a socket to a separate daemon process
on both hot planes (verify stream, hash stream); these tests prove the
plane DEGRADES AND RECOVERS instead of latching dead: faults injected on
a deterministic seeded schedule (ops/faults.FaultPlan — no internals
monkeypatched), the shared circuit breaker opening to the CPU fallback
and re-closing when the daemon returns, and consensus committing blocks
throughout.

Fast tier-1 subset (unmarked): schedule determinism, breaker trial
mode, in-process and out-of-process (FaultProxy — real wire bytes)
injection with verdict/digest parity, SigBatcher exactly-once delivery
across a daemon death, writer abandonment accounting, and a short
consensus-under-churn run. The slow-marked soak is the acceptance run:
>= 20 committed blocks under a kill/restart + frame-corruption schedule
with the committed tx sequence, part-set roots, and final app hash
byte-identical to a fault-free run, and the breaker demonstrably
re-closed.

Commit-hash fidelity note: block HEADER hashes embed wall-clock propose
times, so two separate runs can never be compared header-for-header;
the deterministic commit fingerprints are the committed tx sequence,
the per-block part-set root (recomputed on pure CPU against the root
the devd-routed hasher produced under faults), and the app-hash chain
they imply. All sim daemons here hash with REAL digests
(devd._SimHasher), so those comparisons are real parity, not tautology.
"""

from __future__ import annotations

import threading
import time

import pytest

from tendermint_tpu import devd
from tendermint_tpu.crypto import ed25519 as ed
from tendermint_tpu.ops import faults
from tendermint_tpu.ops.faults import (
    DaemonFleet,
    DaemonSupervisor,
    Fault,
    FaultPlan,
    FaultProxy,
)

SIM_ENV = {"TENDERMINT_DEVD_SIM_RATE": "200000"}


@pytest.fixture()
def chaos_env(monkeypatch, tmp_path):
    """devd-routed gateway with fast breaker windows and clean shared
    state (breaker, backend client, skew latches, avail cache); yields
    the per-test daemon socket path."""
    sock = str(tmp_path / "devd.sock")
    monkeypatch.setenv("TENDERMINT_DEVD_SOCK", sock)
    monkeypatch.delenv("TENDERMINT_DEVD_SOCKS", raising=False)
    monkeypatch.setenv("TENDERMINT_TPU_KERNEL", "devd")
    monkeypatch.setenv("TENDERMINT_TPU_BREAKER_BACKOFF_S", "0.05")
    monkeypatch.setenv("TENDERMINT_TPU_BREAKER_BACKOFF_CAP_S", "0.25")
    monkeypatch.setenv("TENDERMINT_DEVD_STREAM_MIN", "8")
    monkeypatch.setenv("TENDERMINT_DEVD_CLAIM_TIMEOUT_S", "10")
    monkeypatch.setenv("TENDERMINT_DEVD_STREAM_TIMEOUT_S", "10")
    import tendermint_tpu.ops.devd_backend as backend
    from tendermint_tpu.ops import devd_shard, gateway

    monkeypatch.setattr(backend, "_client", None)
    # the module-level default gateway instances are process-global;
    # monkeypatch restores whatever existed before the test, so a
    # devd-routed default built against this test's throwaway daemon
    # can never leak into later tests
    monkeypatch.setattr(gateway, "_default_verifier", None)
    monkeypatch.setattr(gateway, "_default_hasher", None)
    backend.reset_stream_latches()
    gateway.reset_devd_breaker()
    devd_shard.reset()
    devd.bust_avail_cache()
    yield sock
    devd.set_socket_wrapper(None)
    gateway.reset_devd_breaker()
    devd_shard.reset()
    backend.reset_stream_latches()
    devd.bust_avail_cache()


def _items(n: int, tag: bytes = b"chaos"):
    seeds = [bytes([7, k]) + b"\x07" * 30 for k in range(8)]
    out = []
    for i in range(n):
        seed = seeds[i % 8]
        msg = tag + b"-%d" % i
        out.append((ed.public_key(seed), msg, ed.sign(seed, msg)))
    return out


def _wait_breaker_closed(verify_once, breaker, deadline_s: float = 10.0):
    """Drive traffic until a probe re-closes the breaker (bounded)."""
    deadline = time.monotonic() + deadline_s
    while breaker.state != breaker.CLOSED:
        assert time.monotonic() < deadline, "breaker never re-closed"
        verify_once()
        time.sleep(0.05)


# -- schedule + breaker units (no daemon) -------------------------------------


def test_fault_plan_schedule_is_deterministic():
    plan = FaultPlan(seed=7).add("corrupt", "s2c", first=3, every=3, limit=2)
    fired = [plan.pick("s2c") is not None for _ in range(10)]
    assert fired == [False, False, True, False, False, True,
                     False, False, False, False]
    assert plan.stats()["faults_corrupt"] == 2
    assert plan.stats()["faults_total"] == 2
    # unrelated event streams never trip the rule
    assert all(plan.pick("c2s") is None for _ in range(10))
    # content randomness is seed-deterministic
    a, b = FaultPlan(seed=9), FaultPlan(seed=9)
    assert [a.corrupt_offset(0, 100) for _ in range(8)] == \
        [b.corrupt_offset(0, 100) for _ in range(8)]
    with pytest.raises(ValueError):
        Fault("melt", "s2c")
    with pytest.raises(ValueError):
        Fault("corrupt", "sideways")
    # a due fault the injection point cannot inject is skipped — neither
    # consumed nor counted, so faults_* only ever report real injections
    p2 = FaultPlan(seed=1).add("truncate", "s2c", first=1, every=1, limit=3)
    assert p2.pick("s2c", supported=("stall", "drop")) is None
    assert p2.stats()["faults_truncate"] == 0
    assert p2.wants("truncate", "s2c")
    assert p2.pick("s2c") is not None  # injectable point: fires + counts
    assert p2.stats()["faults_truncate"] == 1


def test_breaker_trial_mode_backoff_and_stats():
    from tendermint_tpu.ops.gateway import CircuitBreaker

    br = CircuitBreaker(threshold=2, base_backoff_s=0.05,
                        max_backoff_s=0.2, probe=None, seed=3)
    assert br.allow() and br.state == br.CLOSED
    br.record_failure()
    assert br.state == br.CLOSED  # below threshold
    br.record_failure()
    assert br.state == br.OPEN
    assert not br.allow()  # probe not due yet
    time.sleep(0.3)  # past max jittered window
    assert br.allow()  # trial request admitted (half-open)
    assert br.state == br.HALF_OPEN
    br.record_failure()  # trial failed -> reopen, backoff doubled
    assert br.state == br.OPEN
    time.sleep(0.45)
    assert br.allow()
    br.record_success()  # trial passed -> closed
    assert br.state == br.CLOSED
    st = br.stats()
    assert st["breaker_opens"] == 1 and st["breaker_closes"] == 1
    assert st["breaker_probes"] == 2 and st["breaker_probe_failures"] == 1
    assert st["breaker_fallback_s"] > 0
    assert st["breaker_state"] == 0


def test_writer_abandonment_counts_fault_and_closes_conn(monkeypatch):
    """Satellite fix: a writer thread that outlives its reap budget is
    counted (`writer_abandoned` in stream_* stats) and its connection
    closed — never silently walked away from, never re-pooled."""
    monkeypatch.setattr(devd, "WRITER_REAP_S", 0.05)
    client = devd.DevdClient("/nonexistent/sock")
    gate = threading.Event()
    writer = threading.Thread(target=gate.wait, daemon=True)
    writer.start()
    closed = []

    class Conn:
        def shutdown(self, how):
            closed.append("shutdown")

        def close(self):
            closed.append("close")

    try:
        assert client._reap_writer(writer, client._stream_stats, Conn())
        assert client.stream_stats()["writer_abandoned"] == 1
        # shutdown BEFORE close: close() alone never wakes the wedged
        # sendall (the syscall pins the file description)
        assert closed == ["shutdown", "close"]
        # a promptly-exiting writer is NOT abandonment
        gate.set()
        assert not client._reap_writer(writer, client._stream_stats, Conn())
        assert client.stream_stats()["writer_abandoned"] == 1
    finally:
        gate.set()


# -- in-process injection -----------------------------------------------------


def test_inprocess_faults_gateway_serves_correct_verdicts(chaos_env):
    """Corrupt/drop/refuse faults on the production client path: every
    batch still answers the correct verdicts (reconnect-once, breaker,
    CPU re-verify), the plan's counters prove the schedule fired, and
    the faults_* gauges surface through Verifier.stats()."""
    from tendermint_tpu.ops import gateway

    sup = DaemonSupervisor(chaos_env, SIM_ENV)
    sup.start()
    plan = FaultPlan(seed=11)
    plan.add("corrupt", "c2s", first=3, every=7, limit=3)
    plan.add("drop", "s2c", first=5, every=0, limit=1)
    plan.add("refuse", "connect", first=2, every=0, limit=1)
    try:
        faults.install_client_faults(plan)
        v = gateway.Verifier(min_tpu_batch=1)
        items = _items(64)
        for _ in range(12):
            assert v.verify_batch(items) == [True] * 64
        st = plan.stats()
        assert st["faults_corrupt"] >= 1
        assert st["faults_total"] >= 3, st
        # visible alongside the stream_* gauges
        vstats = v.stats()
        assert vstats["faults_corrupt"] == st["faults_corrupt"]
        assert {"breaker_state", "breaker_opens"} <= set(vstats)
        # drive recovery: the breaker (if it opened) must re-close
        # against the healthy daemon once the harness is uninstalled
        faults.uninstall_client_faults(plan)
        br = gateway.devd_breaker()
        _wait_breaker_closed(
            lambda: v.verify_batch(items), br
        )
        before = v.stats()["tpu_sigs"]
        assert v.verify_batch(items) == [True] * 64
        assert v.stats()["tpu_sigs"] == before + 64  # devd-routed again
    finally:
        faults.uninstall_client_faults(plan)
        sup.stop()


def test_stalled_daemon_hits_stream_budget_not_io_timeout(chaos_env,
                                                          tmp_path,
                                                          monkeypatch):
    """Deadline budgets: a read starved on an active stream (daemon-side
    stall, injected by the proxy holding every result frame for 5 s)
    surfaces within the per-frame STREAM budget, not the flat 300 s io
    timeout the resolver used to block on. A timeout is deliberately
    not a reconnect (live-but-slow daemon — see DevdClient.request), so
    it raises to the caller's fallback fast."""
    monkeypatch.setenv("TENDERMINT_DEVD_STREAM_TIMEOUT_S", "0.5")
    upstream = str(tmp_path / "real.sock")
    sup = DaemonSupervisor(upstream, SIM_ENV)
    sup.start()
    plan = FaultPlan(seed=2)
    proxy = FaultProxy(chaos_env, upstream, plan).start()
    try:
        client = devd.DevdClient(chaos_env)
        assert client.stream_timeout == 0.5
        # warm the full relay path (proxy accept thread + upstream dial)
        # BEFORE arming the stall: under suite load the first accept can
        # lag past the 0.5 s stream budget, making the client raise with
        # zero frames relayed — faults_stall would read 0 (tier-1 flake).
        # The rule is every=1 from first=1, so arming late loses nothing.
        client.ping()
        plan.add("stall", "s2c", first=1, every=1, limit=1 << 30, stall_s=5.0)
        t0 = time.monotonic()
        with pytest.raises(Exception):
            client.verify_stream(_items(32), chunk=8)
        elapsed = time.monotonic() - t0
        assert elapsed < 8.0, f"stalled read took {elapsed:.1f}s to surface"
        assert plan.stats()["faults_stall"] >= 1
        client.close()
    finally:
        proxy.stop()
        sup.stop()


def test_proxy_skew_latches_single_shot_until_breaker_reset(chaos_env,
                                                            tmp_path):
    """Version skew: the proxy answers stream headers the way a
    pre-streaming daemon would (pickle {"ok": False}); the backend
    latches the single-shot path (verdicts stay correct) and the latch
    re-arms through reset_stream_latches — the hook the breaker's
    re-close fires, since a returned daemon may be a different build."""
    import tendermint_tpu.ops.devd_backend as backend
    from tendermint_tpu.ops import gateway

    upstream = str(tmp_path / "real.sock")
    sup = DaemonSupervisor(upstream, SIM_ENV)
    sup.start()
    plan = FaultPlan(seed=4)
    plan.add("skew", "c2s", first=1, every=1, limit=1 << 30)
    proxy = FaultProxy(chaos_env, upstream, plan).start()
    try:
        v = gateway.Verifier(min_tpu_batch=1)
        items = _items(32)
        assert v.verify_batch(items) == [True] * 32  # wide: tries stream
        assert backend._stream_ok is False, "skew must latch single-shot"
        assert plan.stats()["faults_skew"] >= 1
        # latched but serving: still correct, still devd-routed
        assert v.verify_batch(items) == [True] * 32
        backend.reset_stream_latches()
        assert backend._stream_ok and backend._hash_stream_ok
    finally:
        proxy.stop()
        sup.stop()


# -- out-of-process injection (real wire bytes) -------------------------------


def test_proxy_faults_both_planes_parity_and_skew(chaos_env, tmp_path,
                                                  monkeypatch):
    """FaultProxy in front of a real daemon: chunk/digest frames relay
    byte-for-byte and the schedule corrupts/truncates them on the wire.
    The gateway's verdicts and digests stay byte-identical to CPU
    throughout, and the plan counters prove the schedule fired."""
    from tendermint_tpu.crypto.hashing import ripemd160
    from tendermint_tpu.ops import gateway

    upstream = str(tmp_path / "real.sock")
    sup = DaemonSupervisor(upstream, SIM_ENV)
    sup.start()
    plan = FaultPlan(seed=5)
    plan.add("corrupt", "s2c", first=4, every=6, limit=4)
    plan.add("truncate", "c2s", first=9, every=0, limit=1)
    proxy = FaultProxy(chaos_env, upstream, plan).start()
    try:
        devd.bust_avail_cache()
        monkeypatch.setenv("TENDERMINT_TPU_HASHES", "1")
        v = gateway.Verifier(min_tpu_batch=1)
        h = gateway.Hasher(min_tpu_batch=1, use_tpu=True)
        assert h._route == "devd"
        items = _items(48)
        parts = [bytes([i]) * 700 for i in range(24)]
        want_digests = [ripemd160(p) for p in parts]
        for _ in range(10):
            assert v.verify_batch(items) == [True] * 48
            assert h.part_leaf_hashes(parts) == want_digests
        st = plan.stats()
        assert st["faults_corrupt"] >= 2, st
        assert st["faults_truncate"] >= 1, st
        hs = h.stats()
        assert hs["faults_corrupt"] == st["faults_corrupt"]
    finally:
        proxy.stop()
        sup.stop()


def test_proxy_blackout_opens_breaker_then_recovers(chaos_env, tmp_path):
    """Daemon-death emulation via proxy blackout: connects refuse and
    live conns drop for the window; the breaker opens, the CPU fallback
    serves correct verdicts, and the end of the blackout re-closes it —
    no daemon process was harmed (the shared-daemon chaos mode)."""
    from tendermint_tpu.ops import gateway

    upstream = str(tmp_path / "real.sock")
    sup = DaemonSupervisor(upstream, SIM_ENV)
    sup.start()
    proxy = FaultProxy(chaos_env, upstream).start()
    try:
        devd.bust_avail_cache()
        v = gateway.Verifier(min_tpu_batch=1)
        items = _items(32)
        assert v.verify_batch(items) == [True] * 32
        proxy.blackout(0.6)
        br = gateway.devd_breaker()
        deadline = time.monotonic() + 5.0
        while br.state != br.OPEN and time.monotonic() < deadline:
            assert v.verify_batch(items) == [True] * 32
        assert br.state == br.OPEN
        assert proxy.plan.stats()["faults_kill"] == 1
        time.sleep(0.7)  # blackout over
        _wait_breaker_closed(lambda: v.verify_batch(items), br)
        before = v.stats()["tpu_sigs"]
        assert v.verify_batch(items) == [True] * 32
        assert v.stats()["tpu_sigs"] == before + 32
    finally:
        proxy.stop()
        sup.stop()


# -- mempool sig gate: exactly-once across daemon death -----------------------


def test_sigbatcher_exactly_once_across_daemon_death(chaos_env):
    """Satellite coverage: the daemon dying between the gate's 2
    in-flight chunks must not drop or double-deliver a single tx
    verdict. Every accepted submission is delivered exactly once; valid
    signatures are never reported invalid (fallback re-verifies; the
    gate fails open only on total verifier loss)."""
    from tendermint_tpu.mempool.mempool import SigBatcher
    from tendermint_tpu.ops import gateway

    sup = DaemonSupervisor(chaos_env, SIM_ENV)
    sup.start()
    delivered: list = []
    dmtx = threading.Lock()

    def on_results(results):
        with dmtx:
            delivered.extend(results)

    v = gateway.Verifier(min_tpu_batch=1)
    sb = SigBatcher(v, parse=lambda tx: tx, max_batch=64,
                    max_wait_s=0.001, on_results=on_results, max_inflight=2)
    items = _items(512, tag=b"gate")
    try:
        accepted = []
        for i, it in enumerate(items):
            if sb.submit(it, i):
                accepted.append(i)
            if i == 128:
                sup.kill()  # mid-burst, chunks in flight
            elif i == 320:
                sup.restart()
            if i % 64 == 0:
                time.sleep(0.01)  # let batches go in-flight mid-churn
    finally:
        sb.stop()
        sb._thread.join(timeout=30.0)
        sup.stop()
    assert not sb._thread.is_alive()
    with dmtx:
        got = sorted(ctx for ctx, _ok in delivered)
        oks = {ctx: ok for ctx, ok in delivered}
    assert got == accepted, "dropped or duplicated tx verdicts"
    assert sb.delivered == len(accepted)
    # all submissions were validly signed: none may be reported invalid
    assert all(oks.values())


# -- consensus liveness under churn -------------------------------------------


def _run_consensus_run(n_blocks: int, txs: list[bytes], hasher=None,
                       budget_s: float = 20.0, during=None, until=None):
    """Commit `n_blocks` on a single-validator KVStore chain, feeding
    txs strictly sequentially (tx k+1 enters the pool only after tx k
    committed, so the committed ORDER is deterministic across runs).
    Returns (new-block event list, consensus state). `during(height_events)`
    runs once after start (chaos hookup)."""
    import tendermint_tpu.types.events as tev
    from consensus_common import EventCollector, make_cs_and_stubs
    from tendermint_tpu.abci.apps.kvstore import KVStoreApp

    cs, _stubs, _ = make_cs_and_stubs(1, app=KVStoreApp())
    if hasher is not None:
        cs.part_hasher = hasher
    blocks = EventCollector(cs.evsw, tev.EVENT_NEW_BLOCK)
    cs.start()
    try:
        if during is not None:
            during(blocks)
        next_tx = 0
        if txs:
            cs.mempool.check_tx(txs[0])
            next_tx = 1
        deadline = time.monotonic() + budget_s + 1.5 * n_blocks
        while True:
            events = list(blocks.items)
            # done: enough blocks AND every tx landed AND one block
            # after the last tx's block (so its app-hash effect is
            # bound into a committed header — app_hash lags one height)
            if len(events) >= n_blocks and next_tx == len(txs) and (
                not txs or _fingerprint_ready(events, txs)
            ) and (until is None or until()):
                return events, cs
            assert time.monotonic() < deadline, (
                f"liveness lost: {len(events)} blocks, tx {next_tx}/"
                f"{len(txs)} (height_seconds_max="
                f"{cs.height_seconds_max:.2f})"
            )
            if next_tx < len(txs):
                landed = {t for d in events for t in d.block.data.txs}
                if txs[next_tx - 1] in landed:
                    cs.mempool.check_tx(txs[next_tx])
                    next_tx += 1
            time.sleep(0.02)
    finally:
        cs.stop()


def _last_tx_height(block_events, txs) -> int | None:
    for d in block_events:
        if txs[-1] in d.block.data.txs:
            return d.block.header.height
    return None


def _fingerprint_ready(block_events, txs) -> bool:
    h = _last_tx_height(block_events, txs)
    return h is not None and any(
        d.block.header.height == h + 1 for d in block_events
    )


def _committed_fingerprint(block_events, txs):
    """(ordered committed txs, app hash with every tx applied) — the
    deterministic commit fingerprint two runs of the same sequential tx
    schedule must share. header.app_hash lags one height, so the
    post-all-txs hash is read from the block AFTER the one carrying the
    last tx (heights may differ across runs; the hash may not)."""
    committed = [t for d in block_events for t in d.block.data.txs]
    if not txs:
        return committed, b""
    h = _last_tx_height(block_events, txs)
    post = next(
        d.block.header.app_hash for d in block_events
        if d.block.header.height == h + 1
    )
    return committed, post


def _assert_partset_parity(cs, block_events) -> int:
    """Every committed block's part-set root (produced by the devd-routed
    hasher, possibly under faults) must equal a pure-CPU recompute —
    the 'zero digests wrong' assertion. Returns blocks checked."""
    checked = 0
    for d in block_events:
        blk = d.block
        meta = cs.block_store.load_block_meta(blk.header.height)
        if meta is None:
            continue
        cpu = blk.make_part_set(65536).header()
        assert meta.block_id.parts_header == cpu, (
            f"height {blk.header.height}: part-set root diverged"
        )
        checked += 1
    return checked


def _chaos_hasher(sock: str):
    from tendermint_tpu.ops import gateway

    devd.bust_avail_cache()
    h = gateway.Hasher(min_tpu_batch=1, use_tpu=True)
    assert h._route == "devd", "hasher must ride the daemon for the soak"
    return h


def test_consensus_commits_through_daemon_churn(chaos_env):
    """Fast tier-1 chaos subset: a single-validator chain keeps
    committing while the daemon serving its part-set hash plane is
    SIGKILLed and restarted; the commit fingerprint matches a fault-free
    run, part-set roots recompute byte-identically on CPU, and the
    breaker re-closes with devd routing restored."""
    from tendermint_tpu.ops import gateway

    n_blocks, txs = 6, [b"k%d=v%d" % (i, i) for i in range(4)]
    sup = DaemonSupervisor(chaos_env, SIM_ENV, plan=FaultPlan(seed=3))
    sup.start()
    try:
        # fault-free reference run first (daemon healthy throughout)
        ref_blocks, ref_cs = _run_consensus_run(
            n_blocks, txs, hasher=_chaos_hasher(chaos_env)
        )
        ref_print = _committed_fingerprint(ref_blocks, txs)
        assert ref_print[0] == txs, "reference run must commit every tx"

        # chaos run: kill/restart churn while committing
        hasher = _chaos_hasher(chaos_env)

        def start_churn(_blocks):
            sup.churn(down_s=0.5, up_s=1.0, cycles=2)

        chaos_blocks, chaos_cs = _run_consensus_run(
            n_blocks, txs, hasher=hasher, during=start_churn,
        )
        sup.stop_churn(ensure_up=True)
        assert sup.kills >= 1 and sup.plan.stats()["faults_kill"] >= 1
        assert _committed_fingerprint(chaos_blocks, txs) == ref_print
        assert _assert_partset_parity(chaos_cs, chaos_blocks) >= n_blocks - 1
        # liveness: no height stalled past its budget
        assert chaos_cs.height_seconds_max < 10.0, chaos_cs.height_seconds_max
        # recovery: breaker closed against the healthy daemon, and the
        # hash plane demonstrably routes devd again
        br = gateway.devd_breaker()
        parts = [bytes([i]) * 512 for i in range(16)]
        _wait_breaker_closed(lambda: hasher.part_leaf_hashes(parts), br)
        before = hasher.stats()["tpu_part_batches"]
        hasher.part_leaf_hashes(parts)
        assert hasher.stats()["tpu_part_batches"] == before + 1

        # round 11: breaker-open heights visibly attribute their hash
        # work to the CPU fallback in the per-height traces
        # (consensus/trace.py) — kill the daemon FOR GOOD and commit a
        # few more heights on the open breaker
        dead_hasher = _chaos_hasher(chaos_env)  # resolved while serving
        dead_blocks, dead_cs = _run_consensus_run(
            3, [], hasher=dead_hasher, during=lambda _blocks: sup.kill(),
        )
        assert len(dead_blocks) >= 3
        newest = dead_cs.trace.last(1)[0].to_json()
        dev = newest["device"]
        assert dev["hash_cpu_leaves"] > 0, dev
        assert dev["hash_tpu_leaves"] == 0, dev
        assert dev["breaker_state_end"] != gateway.CircuitBreaker.CLOSED, dev
        # the segment partition holds under chaos too
        tol = max(0.05 * newest["wall_s"], 0.005)
        total = sum(newest["segments"].values())
        assert abs(total - newest["wall_s"]) <= tol, (total, newest["wall_s"])
        # the same attribution is scrape-visible: the supervisor's churn
        # registered into the telemetry plane (ops/faults satellite),
        # asserting on metrics instead of reaching into the harness
        from tendermint_tpu.libs import telemetry

        fams = {
            f.name: f for f in telemetry.default_registry().collect()
        }
        assert fams["faults_supervisor_kills"].samples[0][2] >= 1
        assert fams["faults_supervisor_restarts"].samples[0][2] >= 1
    finally:
        sup.stop()


@pytest.mark.slow
def test_chaos_soak_20_blocks_with_corruption(chaos_env, tmp_path):
    """The acceptance soak: >= 20 blocks commit while a seeded schedule
    SIGKILLs/restarts the daemon AND corrupts wire frames through the
    FaultProxy, with a concurrent streamed verify load sharing the same
    breaker. Asserts: commit fingerprint byte-identical to a fault-free
    run, per-block part-set roots CPU-identical, zero wrong verify
    verdicts, no height past its timeout budget, breaker re-closed with
    devd routing restored, and the fault counters prove the schedule
    actually fired."""
    from tendermint_tpu.ops import gateway

    n_blocks, txs = 22, [b"s%d=w%d" % (i, i) for i in range(12)]
    upstream = str(tmp_path / "real.sock")
    plan = FaultPlan(seed=17)
    plan.add("corrupt", "s2c", first=6, every=9, limit=1 << 30)
    plan.add("corrupt", "c2s", first=11, every=13, limit=1 << 30)
    sup = DaemonSupervisor(upstream, SIM_ENV, plan=plan)
    sup.start()
    proxy = FaultProxy(chaos_env, upstream, plan).start()
    try:
        ref_blocks, _ref_cs = _run_consensus_run(
            n_blocks, txs, hasher=_chaos_hasher(chaos_env), budget_s=40.0
        )
        ref_print = _committed_fingerprint(ref_blocks, txs)
        assert ref_print[0] == txs

        hasher = _chaos_hasher(chaos_env)
        v = gateway.Verifier(min_tpu_batch=1)
        load_stop = threading.Event()
        wrong = []

        def verify_load():
            items = _items(96, tag=b"soak")
            while not load_stop.is_set():
                try:
                    if v.verify_batch(items) != [True] * 96:
                        wrong.append("wrong verdicts")
                        return
                except Exception as exc:  # noqa: BLE001 — must not happen:
                    # the gateway's contract is fallback, never raise
                    wrong.append(f"verify raised: {exc}")
                    return
                time.sleep(0.05)

        load = threading.Thread(target=verify_load, daemon=True)
        load.start()

        def start_churn(_blocks):
            sup.churn(down_s=0.6, up_s=1.6, cycles=4)

        # keep committing past n_blocks until the kill schedule really
        # ran (a fast chain otherwise outruns the churn and the
        # faults_kill assertion goes timing-dependent)
        chaos_blocks, chaos_cs = _run_consensus_run(
            n_blocks, txs, hasher=hasher, during=start_churn, budget_s=60.0,
            until=lambda: sup.kills >= 3,
        )
        sup.stop_churn(ensure_up=True)
        load_stop.set()
        load.join(timeout=30.0)

        assert not wrong, wrong
        assert _committed_fingerprint(chaos_blocks, txs) == ref_print
        assert _assert_partset_parity(chaos_cs, chaos_blocks) >= n_blocks - 1
        assert chaos_cs.height_seconds_max < 15.0, chaos_cs.height_seconds_max
        st = plan.stats()
        assert st["faults_kill"] >= 3, st      # churn really killed it
        assert st["faults_corrupt"] >= 2, st   # frames really corrupted
        br = gateway.devd_breaker()
        assert br.stats()["breaker_opens"] >= 1  # degradation was real
        parts = [bytes([i % 251]) * 600 for i in range(20)]
        _wait_breaker_closed(lambda: hasher.part_leaf_hashes(parts), br,
                             deadline_s=20.0)
        # routing restored: devd-routed batches flow again on BOTH
        # planes within a bounded window. Retry-loop, not next-batch:
        # the proxy's corruption schedule never stops, so any single
        # batch may legitimately eat a fault and take the CPU fallback
        # for that batch — recovery means the plane keeps coming back
        deadline = time.monotonic() + 20.0
        before = hasher.stats()["tpu_part_batches"]
        while hasher.stats()["tpu_part_batches"] == before:
            assert time.monotonic() < deadline, "hash plane never re-routed"
            hasher.part_leaf_hashes(parts)
        vbefore = v.stats()["tpu_sigs"]
        while v.stats()["tpu_sigs"] == vbefore:
            assert time.monotonic() < deadline, "verify plane never re-routed"
            assert v.verify_batch(_items(16)) == [True] * 16
    finally:
        proxy.stop()
        sup.stop()


# -- sharded device plane chaos matrix (round 21 — ISSUE 17) ------------------
#
# Wrong-LENGTH signatures mark the forged lanes (sim daemons verify
# structurally; the CPU fallback agrees they are invalid), and the
# stream floor is raised so slices ride the single-shot op — the
# streamed protocol's fixed-width frames reject malformed lanes with an
# error instead of a verdict.


def _forge_len(items, idx):
    for i in idx:
        p, m, s = items[i]
        items[i] = (p, m, s[:10])
    return items


def test_shard_kill_one_of_n_mid_burst(chaos_env, tmp_path, monkeypatch):
    """Matrix row: SIGKILL one of 3 endpoints during a verify burst.
    Every batch in the burst answers exact per-lane verdicts (the dead
    endpoint's slices re-dispatch to healthy ones), the redispatch
    counter moves, and the plane never falls to the CPU floor."""
    from tendermint_tpu.ops import devd_shard, gateway

    monkeypatch.setenv("TENDERMINT_DEVD_STREAM_MIN", "100000")
    monkeypatch.setenv("TENDERMINT_TPU_MIN_BATCH", "8")
    fleet = DaemonFleet(3, sock_dir=str(tmp_path), extra_env=SIM_ENV)
    fleet.start()
    monkeypatch.setenv("TENDERMINT_DEVD_SOCKS", fleet.socks_env)
    try:
        items = _forge_len(_items(96, tag=b"kill1"), [13, 71])
        want = [i not in (13, 71) for i in range(96)]
        for _ in range(3):
            assert devd_shard.verify_batch(items) == want
        fleet.kill(0)
        dead = fleet.sock_paths[0]
        for _ in range(6):  # the burst continues across the death
            assert devd_shard.verify_batch(items) == want
        st = devd_shard.endpoint_stats()
        assert st[dead]["redispatches"] >= 1, st
        # capacity degraded, plane alive: the two healthy endpoints
        # absorbed the work and no breaker but the dead one's moved
        assert gateway.devd_plane_allow()
        for path in fleet.sock_paths[1:]:
            assert st[path]["breaker_state"] == 0, st
            assert st[path]["dispatched_slices"] >= 1, st
    finally:
        fleet.stop()


def test_shard_all_breakers_open_falls_to_host_floor(chaos_env, tmp_path,
                                                     monkeypatch):
    """Matrix row: the plane serves sharded, then the WHOLE fleet dies
    -> every breaker opens -> the hash plane serves byte-identical host
    digests and the verify plane correct CPU verdicts; counters prove
    both the open breakers and the fallback actually happened."""
    from tendermint_tpu.crypto.hashing import ripemd160
    from tendermint_tpu.ops import devd_shard, gateway

    monkeypatch.setenv("TENDERMINT_DEVD_STREAM_MIN", "100000")
    monkeypatch.setenv("TENDERMINT_TPU_MIN_BATCH", "8")
    monkeypatch.setenv("TENDERMINT_TPU_BREAKER_FAILURES", "1")
    monkeypatch.setenv("TENDERMINT_TPU_HASHES", "1")
    monkeypatch.delenv("TENDERMINT_DEVD_SOCK", raising=False)
    fleet = DaemonFleet(2, sock_dir=str(tmp_path), extra_env=SIM_ENV)
    fleet.start()
    monkeypatch.setenv("TENDERMINT_DEVD_SOCKS", fleet.socks_env)
    devd.bust_avail_cache()
    try:
        v = gateway.Verifier(min_tpu_batch=1)
        h = gateway.Hasher(min_tpu_batch=1, use_tpu=True)
        assert h._route == "devd"
        items = _forge_len(_items(24, tag=b"floor"), [4])
        parts = [bytes([i]) * 600 for i in range(20)]
        want_digests = [ripemd160(p) for p in parts]
        assert v.verify_batch(items) == [i != 4 for i in range(24)]
        assert h.part_leaf_hashes(parts) == want_digests
        assert devd_shard.plane_stats()["dispatched_slices"] >= 1

        fleet.kill(0)
        fleet.kill(1)
        # first post-death batches eat the endpoint failures (threshold
        # 1 -> both breakers open) and fall back; verdicts stay exact
        assert v.verify_batch(items) == [i != 4 for i in range(24)]
        assert h.part_leaf_hashes(parts) == want_digests
        states = gateway.devd_breaker_states()
        assert all(states[s] == 2 for s in fleet.sock_paths), states
        assert not gateway.devd_plane_allow()
        # the floor is the steady state now — still correct, still counted
        assert v.verify_batch(items) == [i != 4 for i in range(24)]
        assert v.stats()["cpu_sigs"] >= 24
        assert h.part_leaf_hashes(parts) == want_digests
        assert h.stats()["cpu_leaves"] >= len(parts)
    finally:
        fleet.stop()


def test_shard_flapping_endpoint_breaker_storm(chaos_env, tmp_path,
                                               monkeypatch):
    """Matrix row: one endpoint flaps (kill/restart churn) beside a
    healthy one, with tight breaker windows forcing a half-open probe
    storm. Verdicts stay exact through every flap; the flapper's breaker
    demonstrably opened AND probed; once the flapping stops the breaker
    re-closes and the endpoint serves slices again."""
    from tendermint_tpu.ops import devd_shard, gateway

    monkeypatch.setenv("TENDERMINT_DEVD_STREAM_MIN", "100000")
    monkeypatch.setenv("TENDERMINT_TPU_MIN_BATCH", "8")
    monkeypatch.setenv("TENDERMINT_TPU_BREAKER_FAILURES", "1")
    fleet = DaemonFleet(2, sock_dir=str(tmp_path), extra_env=SIM_ENV)
    fleet.start()
    monkeypatch.setenv("TENDERMINT_DEVD_SOCKS", fleet.socks_env)
    flapper = fleet.sock_paths[0]
    try:
        items = _forge_len(_items(64, tag=b"flap"), [31])
        want = [i != 31 for i in range(64)]
        assert devd_shard.verify_batch(items) == want
        fleet.supervisors[0].churn(down_s=0.15, up_s=0.25, cycles=3)
        deadline = time.monotonic() + 20.0
        br = gateway.devd_breaker(flapper)
        while fleet.supervisors[0].kills < 3:
            assert time.monotonic() < deadline, "churn never completed"
            assert devd_shard.verify_batch(items) == want
            time.sleep(0.02)
        fleet.supervisors[0].stop_churn(ensure_up=True)
        st = br.stats()
        assert st["breaker_opens"] >= 1, st
        assert st["breaker_probes"] >= 1, st
        # recovery: the flapper re-closes and takes work again
        deadline = time.monotonic() + 10.0
        while br.state != br.CLOSED:
            assert time.monotonic() < deadline, "flapper never re-closed"
            assert devd_shard.verify_batch(items) == want
            time.sleep(0.05)
        before = devd_shard.endpoint_stats()[flapper]["dispatched_slices"]
        deadline = time.monotonic() + 10.0
        while devd_shard.endpoint_stats()[flapper][
                "dispatched_slices"] == before:
            assert time.monotonic() < deadline, "flapper never re-served"
            assert devd_shard.verify_batch(items) == want
    finally:
        fleet.stop()


def test_labeled_reconnect_counters_split_paths(chaos_env):
    """Satellite: the two reconnect paths count separately —
    `reconnects_connect` (stale pooled socket found at first use) vs
    `reconnects_midstream` (died under an active exchange) — and the
    total stays backward-compatible."""
    sup = DaemonSupervisor(chaos_env, SIM_ENV)
    sup.start()
    client = devd.DevdClient(chaos_env)
    items = _items(32)
    try:
        assert all(client.verify_stream(items, chunk=8))
        sup.restart()  # pool now full of dead sockets
        assert all(client.verify_stream(items, chunk=8))
        st = client.stream_stats()
        assert st["reconnects"] >= 1
        assert st["reconnects"] == (
            st["reconnects_connect"] + st["reconnects_midstream"]
        )
        assert st["writer_abandoned"] == 0
    finally:
        client.close()
        sup.stop()
